"""Closed-loop runtime controller tests (docs/controller.md): the
decision-ledger schema and its stdlib pins, the policy decision matrix
over synthetic signals, the audited apply_override seam, the guardrail
trip -> crash-bundle dump -> auto-revert round trip, off-is-
structurally-absent, config validation, the fleet merger's controller
section + ds_fleet DECISIONS table on a jax-less box, and the DSL012
knob-write lint."""
import importlib.util
import json
import os
import subprocess
import sys

import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis import astlint
from deepspeed_tpu.runtime.config import DeepSpeedConfigError, \
    get_controller
from deepspeed_tpu.runtime.controller import (
    CONTROLLER_EVENT_TYPES, CONTROLLER_EVENTS_JSONL, CONTROLLER_KNOBS,
    CONTROLLER_POLICIES, DECISION_KEYS, DecisionLedger,
    KIND_CONTROLLER_EVENT, POLICY_REGISTRY, RuntimeController,
    make_controller_event, unreverted_regressions,
    validate_controller_event)
from deepspeed_tpu.runtime.controller.policies import (
    LaunchAheadPolicy, PrefillBucketsPolicy, QuantizedCollectivesPolicy,
    SpeculationPolicy)
from deepspeed_tpu.telemetry import record as record_mod
from deepspeed_tpu.telemetry.config import DeepSpeedTelemetryConfig
from deepspeed_tpu.telemetry.fleet import aggregate
from deepspeed_tpu.telemetry.fleet.aggregate import write_host_manifest
from deepspeed_tpu.telemetry.recorder import FlightRecorder
from deepspeed_tpu.telemetry.watchdog import Watchdog

pytestmark = pytest.mark.controller

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_bin(name):
    path = os.path.join(_REPO, "bin", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------------ pins
def test_ledger_schema_pinned_across_stdlib_copies():
    """One schema, three stdlib copies: the ledger module (source of
    truth), the jax-free fleet merger, and the bin/ checker."""
    checker = _load_bin("check_bench_schema")
    assert tuple(DECISION_KEYS) == tuple(aggregate.DECISION_KEYS)
    assert tuple(DECISION_KEYS) == tuple(checker.DECISION_KEYS)
    assert tuple(CONTROLLER_EVENT_TYPES) == \
        tuple(aggregate.CONTROLLER_EVENT_TYPES)
    assert tuple(CONTROLLER_EVENT_TYPES) == \
        tuple(checker.CONTROLLER_EVENT_TYPES)
    assert CONTROLLER_EVENTS_JSONL == aggregate.CONTROLLER_EVENTS_JSONL
    assert CONTROLLER_EVENTS_JSONL == checker.CONTROLLER_EVENTS_JSONL
    assert KIND_CONTROLLER_EVENT == aggregate.KIND_CONTROLLER_EVENT
    assert KIND_CONTROLLER_EVENT == checker.KIND_CONTROLLER_EVENT
    assert tuple(CONTROLLER_KNOBS) == tuple(checker.CONTROLLER_KNOBS)
    assert tuple(record_mod.CONTROLLER_SNAPSHOT_KEYS) == \
        tuple(checker.CONTROLLER_SNAPSHOT_KEYS)
    # every configurable policy is registered, and the registry names
    # ARE the config vocabulary
    assert tuple(sorted(POLICY_REGISTRY)) == tuple(CONTROLLER_POLICIES)


def test_dsl012_attr_set_covers_every_knob():
    """The lint's attribute vocabulary is the static twin of the knob
    table: each CONTROLLER_KNOBS entry actuates through at least one
    attribute DSL012 watches (adapters.py is the mapping)."""
    attrs = astlint._DSL012_KNOB_ATTRS
    assert attrs == frozenset({
        "spec_k", "prefill_chunk_tokens", "prefill_buckets", "windows",
        "_h2d_bucket_elems", "_qwz_enabled", "_qgz_enabled"})
    covered = {
        "launch_ahead_window": "windows",
        "h2d_bucket_elems": "_h2d_bucket_elems",
        "spec_k": "spec_k",
        "prefill_chunk_tokens": "prefill_chunk_tokens",
        "quantized_collectives": "_qwz_enabled",
        "prefill_buckets": "prefill_buckets",
    }
    assert set(covered) == set(CONTROLLER_KNOBS)
    assert set(covered.values()) <= attrs


# ------------------------------------------------------- event schema
def test_controller_event_schema_matrix():
    ev = make_controller_event(
        event="decision", decision_id="train-0000", policy="speculation",
        knob="spec_k", old=3, new=4, signal={"acceptance_rate": 0.9},
        predicted_win_s=0.01, reason="acceptance high")
    assert validate_controller_event(ev) == []
    assert sorted(ev) == sorted(DECISION_KEYS)
    # missing key
    bad = dict(ev)
    del bad["signal"]
    assert any("missing" in p for p in validate_controller_event(bad))
    # extra key
    bad = dict(ev, freelance=1)
    assert any("unexpected" in p for p in validate_controller_event(bad))
    # unknown event / knob vocabulary
    assert validate_controller_event(dict(ev, event="ponder")) != []
    assert validate_controller_event(dict(ev, knob="warp_drive")) != []
    # a decision must cite its signal
    assert any("signal" in p for p in validate_controller_event(
        dict(ev, signal=None)))
    # an outcome/revert must carry the measurement
    out = make_controller_event(
        event="outcome", decision_id="train-0000", policy="speculation",
        knob="spec_k", measured_win_s=0.004)
    assert validate_controller_event(out) == []
    assert any("measured_win_s" in p for p in validate_controller_event(
        dict(out, measured_win_s=None)))


def test_ledger_appends_schema_valid_jsonl(tmp_path):
    led = DecisionLedger(str(tmp_path))
    led.emit(event="decision", decision_id="t-0", policy="speculation",
             knob="spec_k", old=3, new=4, signal={"step": 1})
    led.emit(event="outcome", decision_id="t-0", policy="speculation",
             knob="spec_k", measured_win_s=0.002)
    assert led.path == os.path.join(str(tmp_path),
                                    CONTROLLER_EVENTS_JSONL)
    lines = [json.loads(ln) for ln in open(led.path)]
    assert len(lines) == 2
    for ev in lines:
        assert validate_controller_event(ev) == []
    assert [ev["seq"] for ev in lines] == [0, 1]   # monotone
    assert led.tally() == {"decision": 1, "outcome": 1}
    # the bin/ checker accepts the file as-is
    checker = _load_bin("check_bench_schema")
    assert checker.check_file(led.path) == []
    # ...and names the first bad line when one is torn in
    with open(led.path, "a") as fh:
        fh.write(json.dumps({"kind": KIND_CONTROLLER_EVENT}) + "\n")
    assert checker.check_file(led.path) != []


def test_unreverted_regressions_from_ledger_alone():
    def outcome(did, win, base=0.1):
        return make_controller_event(
            event="outcome", decision_id=did, policy="p", knob="spec_k",
            measured_win_s=win, signal={"baseline_s": base})

    revert = make_controller_event(
        event="revert", decision_id="t-1", policy="p", knob="spec_k",
        measured_win_s=-0.05)
    events = [outcome("t-0", 0.01), outcome("t-1", -0.05),
              outcome("t-2", -0.04), revert]
    # t-1 regressed but was reverted; t-2 regressed and was NOT
    assert unreverted_regressions(events) == ["t-2"]
    # the guardrail floor filters sub-threshold regressions
    assert unreverted_regressions(events, guardrail_pct=0.45) == []


# ------------------------------------------------------ policy matrix
def test_launch_ahead_policy_widens_waitiest_kind():
    pol = LaunchAheadPolicy()
    sig0 = {"exec_per_kind": {"h2d": {"wait_s": 0.0},
                              "compute": {"wait_s": 0.0}},
            "exec_busy_s": 0.0, "exec_waits_s": 0.0,
            "windows": {"h2d": 2, "compute": 1}}
    assert pol.propose(sig0) == []          # first tick only baselines
    sig1 = {"exec_per_kind": {"h2d": {"wait_s": 0.30},
                              "compute": {"wait_s": 0.01}},
            "exec_busy_s": 1.0, "exec_waits_s": 0.31,
            "windows": {"h2d": 2, "compute": 1}}
    moves = pol.propose(sig1)
    assert len(moves) == 1
    mv = moves[0]
    assert mv["knob"] == "launch_ahead_window" and mv["target"] == "h2d"
    assert mv["new"] == 3
    assert mv["predicted_win_s"] == pytest.approx(0.15)
    assert mv["signal"]["wait_frac"] > 0.2   # the citation is measured


def test_launch_ahead_policy_grows_h2d_bucket_at_max_window():
    pol = LaunchAheadPolicy(max_window=2)
    pol.propose({"exec_per_kind": {"h2d": {"wait_s": 0.0}},
                 "exec_busy_s": 0.0, "exec_waits_s": 0.0,
                 "windows": {"h2d": 2}})
    moves = pol.propose(
        {"exec_per_kind": {"h2d": {"wait_s": 0.4}},
         "exec_busy_s": 1.0, "exec_waits_s": 0.4,
         "windows": {"h2d": 2}, "h2d_bucket_elems": 1 << 20})
    assert [m["knob"] for m in moves] == ["h2d_bucket_elems"]
    assert moves[0]["new"] == 2 << 20


def test_launch_ahead_policy_decays_idle_windows():
    pol = LaunchAheadPolicy()
    pol.propose({"exec_per_kind": {"h2d": {"wait_s": 0.0}},
                 "exec_busy_s": 0.0, "exec_waits_s": 0.0,
                 "windows": {"h2d": 4}})
    moves = pol.propose({"exec_per_kind": {"h2d": {"wait_s": 0.0}},
                         "exec_busy_s": 1.0, "exec_waits_s": 0.0,
                         "windows": {"h2d": 4}})
    assert [(m["knob"], m["target"], m["new"]) for m in moves] == \
        [("launch_ahead_window", "h2d", 3)]


def test_speculation_policy_matrix():
    pol = SpeculationPolicy()
    up = pol.propose({"acceptance_rate": 0.9, "spec_k": 3,
                      "step_time_s": 0.1})
    assert [(m["knob"], m["new"]) for m in up] == [("spec_k", 4)]
    down = pol.propose({"acceptance_rate": 0.2, "spec_k": 3})
    assert [(m["knob"], m["new"]) for m in down] == [("spec_k", 2)]
    # k floor / ceiling
    assert pol.propose({"acceptance_rate": 0.2, "spec_k": 1}) == []
    assert pol.propose({"acceptance_rate": 0.95, "spec_k": 8}) == []
    # burning TTFT SLO halves the prefill chunk; a green one grows it
    # back toward (never past) the base
    burn = pol.propose({"ttft_burn_rate": 1.5,
                        "prefill_chunk_tokens": 256})
    assert [(m["knob"], m["new"]) for m in burn] == \
        [("prefill_chunk_tokens", 128)]
    back = pol.propose({"ttft_burn_rate": 0.1,
                        "prefill_chunk_tokens": 128})
    assert [(m["knob"], m["new"]) for m in back] == \
        [("prefill_chunk_tokens", 256)]
    assert pol.propose({"ttft_burn_rate": 0.1,
                        "prefill_chunk_tokens": 256}) == []
    # absent signals = no moves (policies tolerate every absence)
    assert pol.propose({}) == []


def test_quantized_collectives_policy_needs_health_and_positive_win():
    pol = QuantizedCollectivesPolicy()
    base = {"ici_health": {"h0:reduce_scatter": 0.4},
            "quantized": {"gradients": False},
            "wire_win_s": {"gradients": 0.02}}
    moves = pol.propose(base)
    assert [(m["knob"], m["target"], m["new"]) for m in moves] == \
        [("quantized_collectives", "gradients", True)]
    assert moves[0]["predicted_win_s"] == pytest.approx(0.02)
    assert moves[0]["signal"]["worst_health"] == pytest.approx(0.4)
    # degraded link but no predicted win: no move
    assert pol.propose(dict(base, wire_win_s={})) == []
    # healthy links un-quantize
    off = pol.propose({"ici_health": {"h0:reduce_scatter": 0.98},
                       "quantized": {"gradients": True}})
    assert [(m["target"], m["new"]) for m in off] == [("gradients",
                                                       False)]
    # mid-band: hysteresis, no move either way
    assert pol.propose({"ici_health": {"h0:reduce_scatter": 0.75},
                        "quantized": {"gradients": False},
                        "wire_win_s": {"gradients": 0.02}}) == []


def test_prefill_buckets_policy_coarsens_once_per_storm():
    pol = PrefillBucketsPolicy()
    sig = {"storm_flags": ["recompile_storm:prefill"],
           "prefill_buckets": [8, 16, 32, 64, 128], "step_time_s": 0.2}
    moves = pol.propose(sig)
    assert len(moves) == 1
    # every other bucket, largest always kept (admission correctness)
    assert moves[0]["new"] == [8, 32, 128]
    assert moves[0]["knob"] == "prefill_buckets"
    # the same storm flag set never re-fires (act once)
    assert pol.propose(sig) == []
    # no storm, no move
    assert pol.propose({"prefill_buckets": [8, 16]}) == []


# ----------------------------------------------------- the seam + loop
def _cfg(**over):
    base = {"enabled": True, "interval_steps": 2, "eval_steps": 2,
            "cooldown_steps": 4, "guardrail_pct": 0.2,
            "max_moves_per_tick": 1, "policies": ["speculation"]}
    base.update(over)
    return base


class _Box:
    """A registered-knob target: one mutable value."""

    def __init__(self, value):
        self.value = value


def _bind(ctrl, knob, box):
    ctrl.register_knob(knob, lambda target: box.value,
                       lambda target, value: setattr(box, "value",
                                                     value))


def test_apply_override_is_the_only_actuation_and_always_ledgers(
        tmp_path):
    ctrl = RuntimeController(_cfg(), output_dir=str(tmp_path))
    box = _Box(3)
    _bind(ctrl, "spec_k", box)
    # unbound knob: refused, no ledger event, no mutation
    assert ctrl.apply_override(policy="manual", knob="prefill_buckets",
                               new=[8], signal={}) is None
    assert ctrl.ledger.events == []
    ev = ctrl.apply_override(policy="manual", knob="spec_k", new=5,
                             signal={"why": "test"}, step=10,
                             predicted_win_s=0.01, reason="manual move")
    assert box.value == 5
    assert ev["event"] == "decision" and ev["old"] == 3 and \
        ev["new"] == 5
    assert ev["signal"]["step"] == 10       # the citation carries step
    assert validate_controller_event(ev) == []
    # cooldown: the same knob refuses a second move inside the window
    assert ctrl.apply_override(policy="manual", knob="spec_k", new=6,
                               signal={}, step=12) is None
    assert box.value == 5
    # ...and accepts one after it expires
    assert ctrl.apply_override(policy="manual", knob="spec_k", new=6,
                               signal={}, step=15) is not None
    # no-op moves (old == new) never ledger
    n = len(ctrl.ledger.events)
    assert ctrl.apply_override(policy="manual", knob="spec_k", new=6,
                               signal={}, step=40) is None
    assert len(ctrl.ledger.events) == n
    snap = ctrl.snapshot()
    assert record_mod.validate_controller_snapshot(snap) == []
    assert snap["decisions"] == 2 and snap["pending"] == 2


def test_outcome_measures_win_and_drift(tmp_path):
    ctrl = RuntimeController(_cfg(), output_dir=str(tmp_path))
    box = _Box(3)
    _bind(ctrl, "spec_k", box)
    for step in range(4):                    # baseline: 0.1 s steps
        ctrl.on_step(step, 0.1)
    ctrl.apply_override(policy="manual", knob="spec_k", new=5,
                        signal={}, step=3, predicted_win_s=0.02)
    for step in range(4, 8):                 # after: 0.06 s steps
        ctrl.on_step(step, 0.06)
    outs = [e for e in ctrl.ledger.events if e["event"] == "outcome"]
    assert len(outs) == 1
    out = outs[0]
    assert out["measured_win_s"] == pytest.approx(0.04)
    assert out["signal"]["baseline_s"] == pytest.approx(0.1)
    assert ctrl.drift == pytest.approx(0.5)  # predicted 0.02 / won 0.04
    assert box.value == 5                    # an improvement stays
    assert unreverted_regressions(ctrl.ledger.events,
                                  guardrail_pct=0.2) == []


def test_guardrail_trip_dumps_ledger_and_reverts(tmp_path):
    """The whole episode: a bad move regresses past the guardrail, the
    controller watchdog trips, the crash bundle carries the full
    ledger (every decision replayable from the dump alone), the knob
    reverts through the same seam, and the revert is a ledger event."""

    class _Tel:
        output_dir = str(tmp_path)
        recorder = FlightRecorder(str(tmp_path), job_name="t")
        watchdog = None
        metrics = None

    tel = _Tel()
    tel.watchdog = Watchdog({"controller": {"action": "dump"}},
                            recorder=tel.recorder, job_name="t")
    ctrl = RuntimeController(_cfg(), telemetry=tel, role="serve")
    box = _Box(3)
    _bind(ctrl, "spec_k", box)
    for step in range(4):
        ctrl.on_step(step, 0.1)
    ctrl.apply_override(policy="manual", knob="spec_k", new=8,
                        signal={"why": "deliberately bad"}, step=3,
                        predicted_win_s=0.01)
    for step in range(4, 8):                 # 2x regression: 0.2 s
        ctrl.on_step(step, 0.2)
    # reverted through the seam, counted, cooled down
    assert box.value == 3
    assert ctrl.reverts == 1
    events = ctrl.ledger.snapshot()
    assert [e["event"] for e in events] == ["decision", "outcome",
                                            "revert"]
    revert = events[-1]
    assert revert["decision_id"] == events[0]["decision_id"]
    assert revert["old"] == 8 and revert["new"] == 3   # the undo
    assert revert["measured_win_s"] == pytest.approx(-0.1)
    # the ledger itself proves the regression was handled
    assert unreverted_regressions(events, guardrail_pct=0.2) == []
    # the watchdog tripped and dumped
    trips = list(tel.watchdog.trips)
    assert [t["watchdog"] for t in trips] == ["controller"]
    bundles = [os.path.join(str(tmp_path), n)
               for n in sorted(os.listdir(str(tmp_path)))
               if n.startswith("bundle_") and n.endswith(".json")]
    assert len(bundles) == 1
    bundle = json.load(open(bundles[0]))
    assert bundle["reason"] == "watchdog:controller"
    state = bundle["state"]["controller"]
    # the bundle snapshot is from BEFORE the revert (the trip fires
    # first, so the dump shows the regressing override still applied)
    assert state["enabled"] is True and state["role"] == "serve"
    assert [e["event"] for e in state["events"]] == ["decision",
                                                     "outcome"]
    checker = _load_bin("check_bench_schema")
    for i, ev in enumerate(state["events"]):
        assert checker.check_controller_event(ev, "ev[{}]".format(i)) \
            == []
    # the on-disk ledger has all three events and validates
    assert checker.check_file(ctrl.ledger.path) == []
    assert len([json.loads(ln) for ln in open(ctrl.ledger.path)]) == 3


def test_policy_exception_never_kills_the_tick(tmp_path):
    class _Bomb:
        name = "bomb"

        def propose(self, signals):
            raise RuntimeError("boom")

    ctrl = RuntimeController(_cfg(policies=["speculation"]),
                             output_dir=str(tmp_path))
    ctrl.policies.insert(0, _Bomb())
    box = _Box(3)
    _bind(ctrl, "spec_k", box)
    # the bomb fires first, the speculation policy still runs
    ctrl.on_step(0, 0.1, {"acceptance_rate": 0.95, "spec_k": 3})
    assert box.value == 4
    assert ctrl.decisions == 1


# --------------------------------------------------- config validation
def test_controller_config_matrix():
    assert get_controller({}) is None
    assert get_controller({"controller": False}) is None
    assert get_controller({"controller": {"enabled": False}}) is None
    cfg = get_controller({"controller": True})
    assert cfg == {"enabled": True, "interval_steps": 20,
                   "eval_steps": 20, "cooldown_steps": 40,
                   "guardrail_pct": 0.2, "max_moves_per_tick": 1,
                   "policies": list(CONTROLLER_POLICIES)}
    cfg = get_controller({"controller": {
        "interval_steps": 5, "policies": ["speculation"]}})
    assert cfg["interval_steps"] == 5 and \
        cfg["policies"] == ["speculation"]
    with pytest.raises(DeepSpeedConfigError, match="unknown key"):
        get_controller({"controller": {"intervall_steps": 5}})
    with pytest.raises(DeepSpeedConfigError, match="interval_steps"):
        get_controller({"controller": {"interval_steps": 0}})
    with pytest.raises(DeepSpeedConfigError, match="guardrail_pct"):
        get_controller({"controller": {"guardrail_pct": -0.5}})
    with pytest.raises(DeepSpeedConfigError, match="unknown policy"):
        get_controller({"controller": {"policies": ["warp_drive"]}})
    with pytest.raises(DeepSpeedConfigError, match="policies"):
        get_controller({"controller": {"policies": []}})


# ------------------------------------------- serving engine integration
def _serve_engine(tmp_path, controller=None, drafter=False):
    from deepspeed_tpu.models import gpt2
    cfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=32, n_layers=1,
                          n_heads=2, d_model=16,
                          use_flash_attention=False, remat=False)
    inf = {"max_batch_size": 2, "prefill_buckets": [8, 16],
           "dtype": "fp32", "greedy": True, "max_new_tokens": 3,
           "kv_layout": "paged", "kv_block_size": 4}
    if drafter:
        inf["speculative"] = {"enabled": True, "method": "ngram",
                              "num_draft_tokens": 3}
    config = {"inference": inf,
              "telemetry": {"enabled": True,
                            "output_path": str(tmp_path)}}
    if controller is not None:
        config["controller"] = controller
    return deepspeed_tpu.init_inference(
        model=gpt2.make_gpt2_model(config=cfg), config=config)


def test_controller_off_is_structurally_absent(tmp_path):
    engine = _serve_engine(tmp_path)
    try:
        assert engine.controller is None
        snap = engine.telemetry.snapshot()
        assert "controller" not in snap
        assert "controller" not in engine.telemetry.healthz()
        assert not os.path.exists(os.path.join(
            engine.telemetry.output_dir, CONTROLLER_EVENTS_JSONL))
    finally:
        engine.telemetry.close()


def test_serving_controller_attaches_and_surfaces_snapshot(tmp_path):
    engine = _serve_engine(tmp_path, controller=True, drafter=True)
    try:
        ctrl = engine.controller
        assert ctrl is not None and ctrl.role == "serve"
        assert ctrl.knobs == ["prefill_buckets", "spec_k"]
        from deepspeed_tpu.inference.scheduler import \
            ContinuousBatchingScheduler
        sched = ContinuousBatchingScheduler(engine)
        sched.submit([2, 3, 5, 7])
        while sched.has_work:
            sched.step()
        assert sched.results                 # the request retired
        # the controller ticked from the scheduler step path
        assert ctrl._objective
        snap = engine.telemetry.snapshot()
        assert record_mod.validate_controller_snapshot(
            snap["controller"]) == []
        assert snap["controller"]["role"] == "serve"
        assert engine.telemetry.healthz()["controller"]["enabled"]
        # a forced move through the seam actuates the live engine knob
        old_k = engine.spec_k
        ctrl.apply_override(policy="manual", knob="spec_k",
                            new=old_k + 1, signal={}, step=999)
        assert engine.spec_k == old_k + 1
    finally:
        engine.telemetry.close()


# ------------------------------------------------- fleet merge + CLI
def _host_with_controller_events(root, name, events):
    d = os.path.join(str(root), name)
    os.makedirs(d, exist_ok=True)
    write_host_manifest(d, job_name=name)
    with open(os.path.join(d, aggregate.JSONL_NAME), "w") as fh:
        fh.write(json.dumps({"kind": "train_step", "step": 0,
                             "wall": 1000.0}) + "\n")
    with open(os.path.join(d, CONTROLLER_EVENTS_JSONL), "w") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")
    return d


def _episode(role, *, revert):
    dec = make_controller_event(
        event="decision", decision_id=role + "-0000", policy="manual",
        knob="spec_k", old=3, new=8, signal={"step": 3}, wall=1001.0)
    out = make_controller_event(
        event="outcome", decision_id=role + "-0000", policy="manual",
        knob="spec_k", old=3, new=8, measured_win_s=-0.1,
        signal={"baseline_s": 0.1}, wall=1002.0, seq=1)
    events = [dec, out]
    if revert:
        events.append(make_controller_event(
            event="revert", decision_id=role + "-0000", policy="manual",
            knob="spec_k", old=8, new=3, measured_win_s=-0.1,
            wall=1003.0, seq=2))
    return events


def test_merge_run_controller_section_and_checker(tmp_path):
    _host_with_controller_events(tmp_path, "h0",
                                 _episode("serve", revert=True))
    _host_with_controller_events(tmp_path, "h1",
                                 _episode("train", revert=False))
    report = aggregate.merge_run(str(tmp_path))
    ctrl = report["controller"]
    assert ctrl["count"] == 5
    assert ctrl["tally"] == {"decision": 2, "outcome": 2, "revert": 1}
    # h1's regression was never undone; h0's was
    assert ctrl["unreverted"] == ["train-0000"]
    # wall-ordered union with host attribution
    assert [ev["source"] for ev in ctrl["events"]].count("h0") == 3
    assert ctrl["events"][0]["wall"] <= ctrl["events"][-1]["wall"]
    # the checker accepts the merged report artifact
    checker = _load_bin("check_bench_schema")
    rpath = os.path.join(str(tmp_path), "fleet_report.json")
    with open(rpath, "w") as fh:
        json.dump(report, fh)
    assert checker.check_file(rpath) == []
    # ...and rejects one missing the section
    del report["controller"]
    with open(rpath, "w") as fh:
        json.dump(report, fh)
    assert checker.check_file(rpath) != []


def test_ds_fleet_decisions_table_and_strict_without_jax(tmp_path):
    """The DECISIONS table + --strict unreverted-regression exit must
    run on a jax-less box (the stdlib doctoring contract)."""
    _host_with_controller_events(tmp_path, "h0",
                                 _episode("serve", revert=False))
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise ImportError('no jax on this box (test_controller)')\n")
    env = dict(os.environ, PYTHONPATH=str(poison))
    cmd = [sys.executable, os.path.join(_REPO, "bin", "ds_fleet.py"),
           str(tmp_path), "--strict"]
    out = subprocess.run(cmd, capture_output=True, text=True, env=env)
    assert out.returncode == 2, (out.stdout, out.stderr)
    assert "CONTROLLER DECISIONS" in out.stdout
    assert "UNREVERTED REGRESSIONS: serve-0000" in out.stdout
    assert "manual/spec_k" in out.stdout
    # with the revert in the ledger, strict passes
    _host_with_controller_events(tmp_path, "h0",
                                 _episode("serve", revert=True))
    out = subprocess.run(cmd, capture_output=True, text=True, env=env)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "1 revert" in out.stdout


# ----------------------------------------------------------- DSL012
_KNOB_WRITE_SRC = """
class Engine:
    def retune(self):
        self.spec_k = 5
        self.plan_executor().windows["h2d"] = 4

    def grow(self):
        self.prefill_chunk_tokens += 64
"""


def test_dsl012_fires_outside_controller_dir(tmp_path):
    src = tmp_path / "rogue.py"
    src.write_text(_KNOB_WRITE_SRC)
    found = astlint.lint_file(str(src),
                              relpath="deepspeed_tpu/inference/rogue.py")
    rules = [(rule, line) for rule, _, line, _ in found
             if rule == "DSL012"]
    assert len(rules) == 3                  # attr, subscript, augassign
    # unrelated attribute names stay silent
    quiet = tmp_path / "quiet.py"
    quiet.write_text("class A:\n    def f(self):\n"
                     "        self.windows_completed = 1\n")
    assert astlint.lint_file(
        str(quiet), relpath="deepspeed_tpu/inference/quiet.py") == []


def test_dsl012_inert_in_controller_and_config_parsers(tmp_path):
    src = tmp_path / "adapters.py"
    src.write_text(_KNOB_WRITE_SRC)
    for rel in ("deepspeed_tpu/runtime/controller/adapters.py",
                "deepspeed_tpu/runtime/config.py",
                "deepspeed_tpu/inference/config.py"):
        found = astlint.lint_file(str(src), relpath=rel)
        assert [f for f in found if f[0] == "DSL012"] == [], rel


def test_repo_self_lint_is_baseline_clean():
    """Every knob write in the tree is either inside the controller
    seam or a reviewed construction-time baseline entry."""
    findings = astlint.lint_paths(
        [os.path.join(_REPO, "deepspeed_tpu")], base=_REPO)
    baseline = astlint.load_baseline(
        os.path.join(_REPO, "bin", "ds_lint_baseline.json"))
    new, _stale = astlint.diff_baseline(findings, baseline)
    dsl012 = [f for f in new if f.rule == "DSL012"]
    assert dsl012 == [], [f.message for f in dsl012]


# ------------------------------------------------- trace_id satellite
def test_page_slice_carries_trace_id_across_the_wire():
    np = pytest.importorskip("numpy")
    from deepspeed_tpu.inference.fleet.handoff import (PageSlice,
                                                       deserialize_slice,
                                                       serialize_slice)
    k = np.arange(2 * 1 * 2 * 4 * 3, dtype=np.float32).reshape(
        2, 1, 2, 4, 3)
    sl = PageSlice(k, k + 1, page_size=4, length=5, pending_token=7,
                   context=[1, 2, 3, 4, 5], trace_id="serve-9-12")
    back = deserialize_slice(serialize_slice(sl))
    assert back.trace_id == "serve-9-12"
    # absence stays None (older slices, spans off)
    sl2 = PageSlice(k, k, page_size=4, length=5, pending_token=7,
                    context=[1])
    assert deserialize_slice(serialize_slice(sl2)).trace_id is None


def test_span_tracer_continues_a_carried_trace_id():
    from deepspeed_tpu.telemetry.spans import SpanTracer
    tracer = SpanTracer([])
    cont = tracer.begin("serving_request", trace_id="prefill-1-0")
    assert cont.trace_id == "prefill-1-0"
    minted = tracer.begin("serving_request")
    assert minted.trace_id != "prefill-1-0"


def test_merged_trace_rehomes_cross_host_requests():
    ev = lambda pid, tid_arg: {"name": "s", "ph": "X", "ts": 1.0,
                               "dur": 1.0, "pid": pid, "tid": 0,
                               "args": {"trace_id": tid_arg}}
    merged = [ev(0, "req-a"), ev(1, "req-a"),    # crosses hosts
              ev(0, "req-b"),                    # single-host: stays
              {"name": "x", "ph": "X", "ts": 0.0, "dur": 1.0,
               "pid": 1, "tid": 3}]              # no trace_id: stays
    aggregate._rehome_cross_host_requests(merged, req_pid=2)
    assert [e["pid"] for e in merged[:4]] == [2, 2, 0, 1]
    assert merged[0]["tid"] == merged[1]["tid"]
    names = [e for e in merged if e.get("ph") == "M"]
    assert {(m["name"], m["args"]["name"]) for m in names} == \
        {("process_name", "requests"), ("thread_name", "req-a")}
