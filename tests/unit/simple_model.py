"""Tiny model fixtures (mirrors reference tests/unit/simple_model.py)."""
import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.model import Model


def make_simple_model(hidden_dim, nlayers=2, seed=0):
    """Two-layer MLP; apply(params, x, y) -> MSE loss (the reference's
    SimpleModel + CrossEntropyLoss analogue, returning loss from forward)."""
    rng = np.random.RandomState(seed)
    params = {}
    for i in range(nlayers):
        params["layer_{}".format(i)] = {
            "w": jnp.asarray(rng.randn(hidden_dim, hidden_dim) * 0.1,
                             dtype=jnp.float32),
            "b": jnp.zeros((hidden_dim,), dtype=jnp.float32),
        }

    def apply_fn(params, x, y):
        h = x
        for i in range(nlayers):
            layer = params["layer_{}".format(i)]
            h = h @ layer["w"].astype(h.dtype) + layer["b"].astype(h.dtype)
            if i < nlayers - 1:
                h = jax.nn.relu(h)
        return jnp.mean((h - y) ** 2)

    return Model(apply_fn, params, name="SimpleModel")


class SimpleDataset:
    """Random (x, y) regression pairs with a learnable linear target."""

    def __init__(self, total_samples, hidden_dim, seed=0, dtype=np.float32):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(total_samples, hidden_dim).astype(dtype)
        w_true = rng.randn(hidden_dim, hidden_dim).astype(dtype) * 0.1
        self.y = (self.x @ w_true).astype(dtype)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]


def random_dataloader(model=None, total_samples=64, hidden_dim=8, device=None,
                      dtype=np.float32):
    dataset = SimpleDataset(total_samples, hidden_dim, dtype=dtype)
    return dataset


def base_config(world, micro_batch=4, gas=1, **overrides):
    cfg = {
        "train_micro_batch_size_per_gpu": micro_batch,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(overrides)
    return cfg
