"""zero.Init / GatheredParameters semantics (reference
tests/unit/test_zero_context.py)."""
import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import build_mesh
from deepspeed_tpu.runtime.model import Model


def _apply(params, x, y):
    return jnp.mean((x @ params["w"] - y) ** 2)


def test_init_shards_params_at_construction():
    mesh = build_mesh(data=8)
    with deepspeed_tpu.zero.Init(mesh=mesh, param_persistence_threshold=64):
        model = Model(_apply, {"w": jnp.zeros((128, 16)),
                               "b": jnp.zeros((4,))})
    assert getattr(model, "ds_sharded", False)
    w_spec = model.params["w"].sharding.spec
    assert "data" in str(w_spec)
    # small param below persistence threshold stays replicated
    b_spec = model.params["b"].sharding.spec
    assert "data" not in str(b_spec)


def test_init_restores_model_ctor():
    mesh = build_mesh(data=8)
    with deepspeed_tpu.zero.Init(mesh=mesh):
        pass
    model = Model(_apply, {"w": jnp.zeros((16, 4))})
    assert not getattr(model, "ds_sharded", False)


def test_init_disabled_is_noop():
    mesh = build_mesh(data=8)
    with deepspeed_tpu.zero.Init(mesh=mesh, enabled=False):
        model = Model(_apply, {"w": jnp.zeros((128, 16))})
    assert not getattr(model, "ds_sharded", False)


def test_gathered_parameters_read_and_modify():
    mesh = build_mesh(data=8)
    with deepspeed_tpu.zero.Init(mesh=mesh, param_persistence_threshold=0):
        model = Model(_apply, {"w": jnp.ones((64, 8))})
    with deepspeed_tpu.zero.GatheredParameters(model, modifier_rank=0) as full:
        np.testing.assert_allclose(full["w"], np.ones((64, 8)))
        full["w"][:] = 7.0
    # modification written back, sharding preserved
    assert float(model.params["w"][0, 0]) == 7.0
    assert "data" in str(model.params["w"].sharding.spec)


def test_gathered_parameters_no_modifier_discards():
    mesh = build_mesh(data=8)
    with deepspeed_tpu.zero.Init(mesh=mesh, param_persistence_threshold=0):
        model = Model(_apply, {"w": jnp.ones((64, 8))})
    with deepspeed_tpu.zero.GatheredParameters(model) as full:
        full["w"][:] = 3.0
    assert float(model.params["w"][0, 0]) == 1.0


def test_init_model_trains_through_engine():
    mesh = build_mesh(data=8)
    with deepspeed_tpu.zero.Init(mesh=mesh, param_persistence_threshold=0):
        model = Model(_apply, {"w": jnp.zeros((32, 8))})
    config = {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config_params=config)
    rs = np.random.RandomState(0)
    W = rs.randn(32, 8).astype(np.float32)
    x = jnp.asarray(rs.randn(16, 32).astype(np.float32))
    y = x @ jnp.asarray(W)
    losses = []
    for _ in range(60):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < 0.2 * losses[0], losses


def test_gathered_parameters_plain_numpy_tree():
    """Raw (unsharded) trees must not crash on exit (pytree None trap)."""
    tree = {"w": np.ones((4, 4), dtype=np.float32)}
    with deepspeed_tpu.zero.GatheredParameters(tree, modifier_rank=0) as full:
        full["w"][:] = 2.0
    np.testing.assert_allclose(np.asarray(tree["w"]), 2.0)


def test_init_remote_device_cpu_keeps_shard_layout():
    mesh = build_mesh(data=8)
    with deepspeed_tpu.zero.Init(mesh=mesh, remote_device="cpu",
                                 param_persistence_threshold=0):
        model = Model(_apply, {"w": jnp.ones((64, 8))})
    w = model.params["w"]
    # on the CPU test mesh the host mesh mirrors the device mesh: the
    # offloaded param keeps the 1/N sharded layout
    assert "data" in str(w.sharding.spec)
    assert all(d.platform == "cpu" for d in w.sharding.device_set)


def test_register_external_parameter_noop():
    deepspeed_tpu.zero.register_external_parameter(object(), object())
