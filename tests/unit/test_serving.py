"""Production serving: paged KV cache, prefix sharing, speculative decode.

The acceptance spec for ISSUE 7:

  * paged decode is bit-compatible with the slot-cache oracle (logits
    atol 1e-5 across mixed lengths and page boundaries);
  * the page allocator's refcount/free-on-retire invariants hold,
    including copy-on-write forks of shared prefix pages;
  * greedy speculative decode emits the byte-identical token stream of
    the greedy autoregressive baseline (ngram AND model drafters);
  * stale K/V beyond a sequence's live length can never leak into
    attention in either layout (NaN-poison tests);
  * chunked prefill interleaves with the decode batch instead of
    stalling it; pool exhaustion preempts-and-recomputes correctly.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.inference.paging import (GARBAGE_PAGE, PageAllocator,
                                            PagePoolExhausted, PrefixCache,
                                            plan_chunks)
from deepspeed_tpu.models import gpt2

pytestmark = pytest.mark.serving

TINY = dict(vocab_size=128, max_seq_len=64, n_layers=2, n_heads=2,
            d_model=32, use_flash_attention=False, remat=False)
PS = 8                                   # page size used throughout


def tiny_model(seed=0, **over):
    cfg = gpt2.GPT2Config(**{**TINY, **over})
    return gpt2.make_gpt2_model(config=cfg, seed=seed)


def make_engine(model, **inference):
    inference.setdefault("max_batch_size", 3)
    inference.setdefault("prefill_buckets", [8, 16, 32])
    inference.setdefault("dtype", "fp32")
    inference.setdefault("greedy", True)
    return deepspeed.init_inference(model=model,
                                    config={"inference": inference})


def paged_engine(model, **inference):
    inference.setdefault("kv_layout", "paged")
    inference.setdefault("kv_block_size", PS)
    return make_engine(model, **inference)


@pytest.fixture(scope="module")
def model():
    return tiny_model()


@pytest.fixture(scope="module")
def oracle(model):
    """The slot-layout engine: every paged/spec result is judged
    against its streams."""
    return make_engine(model)


def greedy_chain(model, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        ids = jnp.asarray(np.asarray(seq, np.int32)[None])
        hidden = gpt2.forward_hidden(model.params, ids, model.config,
                                     train=False)
        seq.append(int(np.asarray(hidden[0, -1] @ model.params["wte"].T)
                       .argmax()))
    return seq[len(prompt):]


# ------------------------------------------------------- paged == slot


def test_paged_decode_logits_match_slot_across_page_boundaries(model,
                                                               oracle):
    """Mixed prompt lengths straddling page boundaries (PS-1, PS, PS+5):
    per-step decode LOGITS from the paged engine match the slot oracle
    within 1e-5 while sequences cross page boundaries as they grow."""
    eng = paged_engine(model)
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, 128, size=n).tolist()
               for n in (PS - 1, PS, PS + 5)]

    def run(engine):
        logits = []
        for slot, p in enumerate(prompts):
            engine.prefill(slot, p)
        for _ in range(2 * PS + 3):      # decode across >= 2 boundaries
            if engine.kv_layout == "paged":
                for slot in range(len(prompts)):
                    assert engine.ensure_pages(
                        slot, int(engine.lengths[slot]) + 1)
            greedy, top_k, t, tp = engine._sampling_key(None)
            fn = engine._get_decode_fn(greedy, top_k)
            tokens = jnp.asarray(
                np.full((engine.num_slots, 1), 5, np.int32))
            args = [engine.params, engine.kv.k, engine.kv.v, tokens,
                    jnp.asarray(engine.lengths)]
            if engine.kv_layout == "paged":
                args.append(jnp.asarray(engine.page_tables))
            k, v, _, step_logits = fn(*args, jax.random.PRNGKey(0),
                                      jnp.float32(t), jnp.float32(tp))
            engine.kv.update((k, v))
            logits.append(np.asarray(step_logits)[:, 0])
            for slot in range(len(prompts)):
                engine.advance(slot)
        for slot in range(len(prompts)):
            engine.free_slot(slot)
        return logits

    got, want = run(eng), run(oracle)
    for step, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_allclose(g, w, atol=1e-5,
                                   err_msg="step {}".format(step))


def test_paged_generate_matches_slot_streams(model, oracle):
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 128, size=n).tolist() for n in (5, 11, 14, 26)]
    eng = paged_engine(model)
    assert eng.generate(prompts, max_new_tokens=12) == \
        oracle.generate(prompts, max_new_tokens=12)
    # free-on-retire: every page back in the pool
    assert eng.allocator.pages_in_use == 0


# ------------------------------------------------- allocator invariants


def test_page_allocator_refcounts_and_exhaustion():
    alloc = PageAllocator(4)
    pages = [alloc.alloc() for _ in range(4)]
    assert sorted(pages) == [1, 2, 3, 4]       # page 0 never handed out
    assert alloc.pages_in_use == 4 and not alloc.can_alloc(1)
    with pytest.raises(PagePoolExhausted):
        alloc.alloc()
    alloc.ref(pages[0])                         # share it
    alloc.free(pages[0])
    assert alloc.refcount(pages[0]) == 1        # still held by the sharer
    alloc.free(pages[0])
    assert alloc.refcount(pages[0]) == 0 and alloc.can_alloc(1)
    with pytest.raises(AssertionError, match="double free"):
        alloc.free(pages[0])
    # garbage-page ops are inert / rejected
    alloc.free(GARBAGE_PAGE)                    # no-op
    with pytest.raises(AssertionError):
        alloc.ref(GARBAGE_PAGE)


def test_page_allocator_cow_fork():
    alloc = PageAllocator(4)
    page = alloc.alloc()
    same, forked = alloc.fork(page)
    assert same == page and not forked          # unshared: no fork
    alloc.ref(page)                             # refcount 2 (shared)
    new, forked = alloc.fork(page)
    assert forked and new != page
    assert alloc.refcount(page) == 1 and alloc.refcount(new) == 1


def test_engine_cow_forks_shared_partial_page(model, oracle):
    """Two slots sharing a PARTIAL page (a forked sequence): the first
    decode write into it must fork, not corrupt the sibling."""
    eng = paged_engine(model)
    prompt = list(range(1, PS + 5))             # 12 tokens: 1 full + 1 partial page
    eng.prefill(0, prompt)
    # fork slot 0 -> slot 1: share its pages, bump refcounts
    n_pages = int(eng.page_counts[0])
    for j in range(n_pages):
        page = int(eng.page_tables[0, j])
        eng.page_tables[1, j] = page
        eng.allocator.ref(page)
    eng.page_counts[1] = n_pages
    eng.lengths[1] = eng.lengths[0]
    shared_partial = int(eng.page_tables[0, 1])
    assert eng.allocator.refcount(shared_partial) == 2

    # both slots decode at position 12 — INSIDE the shared partial page
    first = int(oracle.prefill(0, prompt))
    oracle.free_slot(0)
    tokens = np.zeros(eng.num_slots, np.int32)
    tokens[0] = tokens[1] = first
    nxt = eng.decode_step(tokens)
    eng.advance(0), eng.advance(1)
    # the write forked the page: tables diverged, refcounts back to 1
    assert eng.page_tables[0, 1] != eng.page_tables[1, 1]
    assert eng.allocator.refcount(int(eng.page_tables[0, 1])) == 1
    assert eng.allocator.refcount(int(eng.page_tables[1, 1])) == 1
    # and both slots decode the true greedy continuation
    want = greedy_chain(model, prompt + [first], 1)[0]
    assert int(nxt[0]) == want and int(nxt[1]) == want
    eng.free_slot(0), eng.free_slot(1)
    assert eng.allocator.pages_in_use == 0


# ------------------------------------------------------- prefix sharing


def test_prefix_sharing_hits_and_matches_baseline(model, oracle):
    eng = paged_engine(model, prefix_caching=True)
    rs = np.random.RandomState(7)
    system = rs.randint(0, 128, size=2 * PS + 3).tolist()   # 2 full pages
    tails = [rs.randint(0, 128, size=n).tolist() for n in (4, 7, 2)]
    prompts = [system + t for t in tails]
    outs = [eng.generate([p], max_new_tokens=5)[0] for p in prompts]
    stats = eng.prefix_stats()
    assert stats["hits"] >= 2                  # 2nd and 3rd prompt hit
    assert stats["shared_pages"] >= 4 and stats["tokens_saved"] >= 4 * PS
    assert outs == [oracle.generate([p], max_new_tokens=5)[0]
                    for p in prompts]
    # retired sequences released their refs; only cache-held pages remain
    held = eng.allocator.pages_in_use
    assert held == eng.prefix_stats()["entries"]
    eng.prefix_cache.clear()
    assert eng.allocator.pages_in_use == 0


def test_prefix_sharing_within_one_burst(model, oracle):
    """N requests with one system prompt arriving in the SAME
    generate() call share its pages: matching runs at first-chunk time
    and registration happens per chunk, so the burst's first member
    seeds the rest one loop iteration later."""
    eng = paged_engine(model, max_batch_size=4, prefix_caching=True)
    rs = np.random.RandomState(9)
    system = rs.randint(0, 128, size=2 * PS).tolist()    # 2 full pages
    prompts = [system + rs.randint(0, 128, size=n).tolist()
               for n in (3, 6, 2, 5)]
    outs = eng.generate(prompts, max_new_tokens=4)
    stats = eng.prefix_stats()
    assert stats["hits"] >= 3, stats          # members 2..4 all hit
    assert outs == oracle.generate(prompts, max_new_tokens=4)


def test_prefix_cache_register_match_evict():
    alloc = PageAllocator(8)
    cache = PrefixCache(alloc, page_size=4)
    tokens = list(range(11))                    # 2 full pages + partial
    pages = [alloc.alloc(), alloc.alloc()]
    cache.register(tokens, pages)
    assert alloc.refcount(pages[0]) == 2        # owner + cache
    # full match capped below the whole prompt
    got, n = cache.match(tokens, len(tokens) - 1)
    assert got == pages and n == 8
    for p in got:
        alloc.free(p)                           # caller returns its refs
    # a diverging second page breaks the chain after page 1
    other = tokens[:4] + [99, 98, 97, 96, 95]
    got, n = cache.match(other, len(other) - 1)
    assert got == pages[:1] and n == 4
    alloc.free(got[0])
    # eviction under pressure releases the cache's refs LRU-first
    for p in pages:
        alloc.free(p)                           # owner retires
    assert alloc.pages_in_use == 2              # cache refs keep them
    cache.evict(alloc.num_pages)                # demand everything
    assert alloc.pages_in_use == 0


# -------------------------------------------------- speculative decode


def test_spec_greedy_ngram_byte_identical(model, oracle):
    eng = paged_engine(model, speculative={
        "enabled": True, "method": "ngram", "num_draft_tokens": 4})
    rs = np.random.RandomState(1)
    prompts = [([3, 7, 9] * 6)[:14],                       # repetitive
               rs.randint(0, 128, size=9).tolist(),        # random
               rs.randint(0, 128, size=17).tolist()]
    assert eng.generate(prompts, max_new_tokens=11) == \
        oracle.generate(prompts, max_new_tokens=11)
    spec = eng.serving_metrics.spec_dist()
    assert spec is not None and spec["proposed"] > 0
    assert 0.0 <= spec["acceptance_rate"] <= 1.0


def test_spec_greedy_model_drafter_byte_identical(model, oracle):
    """Draft model == target model: every draft accepted (rate 1.0) and
    the stream is byte-identical; a DIFFERENT tiny drafter still yields
    the identical stream (greedy acceptance is draft-agnostic)."""
    same = deepspeed.init_inference(
        model=model, draft_model=tiny_model(),
        config={"inference": {
            "max_batch_size": 2, "prefill_buckets": [8, 16, 32],
            "dtype": "fp32", "greedy": True, "kv_layout": "paged",
            "kv_block_size": PS,
            "speculative": {"enabled": True, "method": "model",
                            "num_draft_tokens": 3}}})
    rs = np.random.RandomState(2)
    prompts = [rs.randint(0, 128, size=n).tolist() for n in (6, 13)]
    want = oracle.generate(prompts, max_new_tokens=9)
    assert same.generate(prompts, max_new_tokens=9) == want
    assert same.serving_metrics.spec_dist()["acceptance_rate"] == 1.0

    other = deepspeed.init_inference(
        model=model, draft_model=tiny_model(seed=123, n_layers=1),
        config={"inference": {
            "max_batch_size": 2, "prefill_buckets": [8, 16, 32],
            "dtype": "fp32", "greedy": True, "kv_layout": "paged",
            "kv_block_size": PS,
            "speculative": {"enabled": True, "method": "model",
                            "num_draft_tokens": 3}}})
    assert other.generate(prompts, max_new_tokens=9) == want


def test_spec_respects_eos_and_budget(model, oracle):
    """EOS inside an accepted draft run truncates exactly like the
    baseline, and max_new_tokens never overshoots."""
    eng = paged_engine(model, speculative={
        "enabled": True, "method": "ngram", "num_draft_tokens": 4})
    prompt = [7, 7, 7]
    free_run = oracle.generate([prompt], max_new_tokens=8)[0]
    eos = free_run[2]
    assert eng.generate([prompt], max_new_tokens=8,
                        eos_token_id=eos)[0] == \
        free_run[:free_run.index(eos) + 1]
    out = eng.generate([prompt], max_new_tokens=5)[0]
    assert out == free_run[:5]
    assert eng.lengths.tolist() == [0] * eng.num_slots


def test_spec_slot_layout_and_cache_end(model, oracle):
    """Speculation composes with the SLOT layout too, and k_eff clamps
    near the cache ceiling (no write past max_seq)."""
    eng = make_engine(model, prefill_buckets=[8, 16, 32, 64],
                      speculative={
                          "enabled": True, "method": "ngram",
                          "num_draft_tokens": 4})
    long_prompt = list(range(30)) * 2                   # 60 of 64
    out = eng.generate([long_prompt], max_new_tokens=50)[0]
    # the oracle fixture's buckets stop at 32; judge against the dense
    # greedy chain instead (decode stops when the cache fills: 60 -> 64
    # leaves 4 writes + the final sampled-but-not-embedded token)
    n_new = TINY["max_seq_len"] - len(long_prompt) + 1
    assert out == greedy_chain(model, long_prompt, n_new)
    assert len(out) == n_new


def test_model_drafter_survives_plain_decode_interludes(model, oracle):
    """While any slot sits near the cache ceiling, steps run plain
    decode (k_eff 0) — the model drafter must still embed each
    committed token into ITS cache, or speculation resumes over a
    stale hole once the near-ceiling slot retires (acceptance would
    collapse below the target-as-drafter 1.0 invariant)."""
    eng = deepspeed.init_inference(
        model=model, draft_model=model,
        config={"inference": {
            "max_batch_size": 2, "prefill_buckets": [8, 16, 32, 64],
            "dtype": "fp32", "greedy": True, "kv_layout": "paged",
            "kv_block_size": PS,
            "speculative": {"enabled": True, "method": "model",
                            "num_draft_tokens": 3}}})
    near_ceiling = list(range(1, 59))             # 58 of 64: forces k_eff 0
    short = [5, 3, 8, 1]
    from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
    sched = ContinuousBatchingScheduler(eng)
    u_long = sched.submit(near_ceiling, max_new_tokens=10)   # caps at 7
    u_short = sched.submit(short, max_new_tokens=25)
    res = sched.run()
    assert res[u_short] == greedy_chain(model, short, 25)
    assert len(res[u_long]) == 64 - 58 + 1
    # speculation resumed after the long request retired, and every
    # draft kept matching the target (no stale drafter hole)
    spec = eng.serving_metrics.spec_dist()
    assert spec is not None and spec["acceptance_rate"] == 1.0, spec


def test_spec_sampled_acceptance_reproducible(model):
    """Non-greedy speculative decode: same seed -> same stream, right
    lengths (sequential-sampling semantics through the verify pass)."""
    kw = dict(max_batch_size=1, prefill_buckets=[8], greedy=False,
              top_k=8, temperature=0.9, kv_layout="paged",
              kv_block_size=PS,
              speculative={"enabled": True, "method": "ngram",
                           "num_draft_tokens": 3})
    a = make_engine(model, **kw)
    b = make_engine(model, **kw)
    prompt = [3, 1, 4, 1, 5]
    out = a.generate([prompt], max_new_tokens=6)
    assert out == b.generate([prompt], max_new_tokens=6)
    assert len(out[0]) == 6


# ------------------------------------------------------ chunked prefill


def test_chunked_prefill_matches_unchunked(model, oracle):
    eng = paged_engine(model, prefill_chunk_tokens=8,
                       prefill_buckets=[8, 16, 32])
    rs = np.random.RandomState(4)
    prompts = [rs.randint(0, 128, size=n).tolist() for n in (29, 5, 18)]
    assert eng.generate(prompts, max_new_tokens=6) == \
        oracle.generate(prompts, max_new_tokens=6)


def test_chunked_prefill_does_not_stall_decode(model):
    """A decoding request keeps emitting tokens on every scheduler step
    while a long prompt prefills chunk by chunk next to it."""
    from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
    eng = paged_engine(model, max_batch_size=2, prefill_chunk_tokens=8)
    sched = ContinuousBatchingScheduler(eng)
    short = sched.submit([1, 2, 3], max_new_tokens=20)
    sched.step()                                # short admitted + decoding
    req_short = sched.slots[0]
    long_uid = sched.submit(list(range(1, 30)), max_new_tokens=2)
    grew = []
    for _ in range(3):                          # 29 tokens = 4 chunks
        before = len(req_short.generated)
        sched.step()
        grew.append(len(req_short.generated) - before)
        long_req = sched.slots[1]
        assert long_req is not None and long_req.state == "prefill"
    assert all(g == 1 for g in grew), grew      # decode never stalled
    results = sched.run()
    assert len(results[short]) == 20 and len(results[long_uid]) == 2


def test_plan_chunks_covers_and_respects_bounds():
    bucket_for = lambda n: min(b for b in (8, 16, 32) if n >= 0 and b >= n)
    assert plan_chunks(29, 8, bucket_for, 64) == \
        [(0, 8), (8, 8), (16, 8), (24, 5)]
    assert plan_chunks(5, 8, bucket_for, 64) == [(0, 5)]
    assert plan_chunks(20, None, bucket_for, 64) == [(0, 20)]
    # a chunk whose padded bucket would overrun max_seq merges back
    # into one unchunked prefill (slot-layout write safety): with
    # max_seq 60, the final chunk (48, 11) pads to bucket 16 -> 64 > 60
    assert plan_chunks(59, 16, bucket_for, 60) == [(0, 59)]
    # ... while max_seq 64 fits every padded chunk and stays chunked
    assert plan_chunks(60, 16, bucket_for, 64) == \
        [(0, 16), (16, 16), (32, 16), (48, 12)]


# ------------------------------------------------- stale-KV poisoning


@pytest.mark.parametrize("layout", ["slot", "paged"])
def test_stale_kv_beyond_length_never_leaks(model, oracle, layout):
    """Freed slots/pages are reused WITHOUT clearing: poison everything
    past the live lengths with NaN and decode must be unaffected — the
    absolute-position mask (models/gpt2.py _attend_cache_rows) is the
    only thing standing between stale K/V and the softmax, for both
    layouts."""
    eng = make_engine(model) if layout == "slot" else paged_engine(model)
    prompt = [9, 4, 2, 8, 1]
    first = eng.prefill(0, prompt)
    if layout == "slot":
        # poison every position past the live length in every slot
        k, v = eng.kv.buffers()
        k = k.at[:, :, :, len(prompt):, :].set(jnp.nan)
        v = v.at[:, :, :, len(prompt):, :].set(jnp.nan)
    else:
        # poison every UNALLOCATED page (incl. garbage page 0) and the
        # allocated tail beyond the live length
        k, v = eng.kv.buffers()
        live = [int(eng.page_tables[0, j])
                for j in range(int(eng.page_counts[0]))]
        dead = [p for p in range(eng.kv.k.shape[0]) if p not in live]
        k = k.at[jnp.asarray(dead)].set(jnp.nan)
        v = v.at[jnp.asarray(dead)].set(jnp.nan)
        off = len(prompt) % PS
        k = k.at[live[-1], :, :, off:, :].set(jnp.nan)
        v = v.at[live[-1], :, :, off:, :].set(jnp.nan)
    eng.kv.update((k, v))
    tokens = np.zeros(eng.num_slots, np.int32)
    tokens[0] = first
    nxt = eng.decode_step(tokens)
    want = greedy_chain(model, prompt + [first], 1)[0]
    assert int(nxt[0]) == want
    eng.free_slot(0)


def test_paged_prefill_into_poisoned_pool_is_clean(model):
    """Bucket-padded paged prefill redirects pad writes to the garbage
    page, so a freshly-allocated page's tail keeps its recycled content
    INSIDE the bucket span — poison the whole pool with NaN before any
    prefill and generation must still be exact (V is zeroed beyond each
    row's true valid length, not the padded width)."""
    eng = paged_engine(model, max_batch_size=2)
    k, v = eng.kv.buffers()
    eng.kv.update((k.at[:].set(jnp.nan), v.at[:].set(jnp.nan)))
    prompt = [9, 4, 2, 8, 1]                  # pads to bucket 8 > 5
    out = eng.generate([prompt], max_new_tokens=4)[0]
    assert out == greedy_chain(model, prompt, 4)


def test_prefix_hit_admits_with_suffix_only_pages(model, oracle):
    """Admission charges only the UNMATCHED suffix against the pool: a
    second user of a cached long system prompt admits even when the
    pool could not hold the whole prompt fresh."""
    eng = paged_engine(model, max_batch_size=2, prefix_caching=True,
                       max_seq_len=48, num_pages=6)   # 48 tokens total
    rs = np.random.RandomState(11)
    system = rs.randint(0, 128, size=3 * PS).tolist()    # 3 full pages
    first = system + rs.randint(0, 128, size=3).tolist()
    out1 = eng.generate([first], max_new_tokens=3)[0]
    # 3 pages now live in the prefix cache; only 3 remain free — the
    # second prompt needs 4 pages, so without the match crediting its
    # 3 shared pages admission would have to EVICT the cached prefix
    assert eng.allocator.free_pages == 3
    second = system + rs.randint(0, 128, size=2).tolist()
    out2 = eng.generate([second], max_new_tokens=3)[0]
    assert eng.prefix_stats()["hits"] >= 1
    # no eviction happened: the cached prefix survived the admission
    assert eng.prefix_stats()["entries"] == 3
    assert [out1, out2] == [
        oracle.generate([p], max_new_tokens=3)[0] for p in (first, second)]


# ------------------------------------------------ preemption + pressure


def test_pool_exhaustion_preempts_and_recovers(model, oracle):
    """A pool too small for all concurrent sequences preempts the
    youngest decoder (recompute discipline) and still produces the
    byte-identical greedy streams."""
    # 3 slots x up to ~40 tokens each, but only 9 pages (72 tokens)
    eng = paged_engine(model, max_batch_size=3, num_pages=9)
    rs = np.random.RandomState(5)
    prompts = [rs.randint(0, 128, size=n).tolist() for n in (12, 14, 10)]
    out = eng.generate(prompts, max_new_tokens=24)
    assert out == oracle.generate(prompts, max_new_tokens=24)
    assert eng.allocator.pages_in_use == 0


# ----------------------------------------------------------- sharding


def test_paged_cache_sharded_over_heads_decode_parity(model, oracle):
    """TP mesh: the paged pool shards its heads axis like the slot
    cache (one KV_CACHE_SPEC serves both) and paged+spec decode on the
    mesh still matches the unsharded slot oracle."""
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.inference.kv_cache import KV_CACHE_SPEC
    mesh = build_mesh(data=4, model=2)
    eng = deepspeed.init_inference(model=model, mesh=mesh, config={
        "inference": {"max_batch_size": 2, "prefill_buckets": [16, 32],
                      "dtype": "fp32", "greedy": True,
                      "kv_layout": "paged", "kv_block_size": PS,
                      "prefix_caching": True,
                      "speculative": {"enabled": True, "method": "ngram",
                                      "num_draft_tokens": 3}}})
    assert eng.kv.k.sharding.spec == KV_CACHE_SPEC
    rs = np.random.RandomState(8)
    prompts = [rs.randint(0, 128, size=n).tolist() for n in (7, 12)]
    assert eng.generate(prompts, max_new_tokens=5) == \
        oracle.generate(prompts, max_new_tokens=5)


# ------------------------------------------------------ config surface


def test_paged_config_validation():
    from deepspeed_tpu.inference.config import (DeepSpeedInferenceConfig,
                                                DeepSpeedInferenceConfigError)
    ic = DeepSpeedInferenceConfig({"inference": {
        "kv_layout": "paged", "kv_block_size": 8, "num_pages": 32,
        "prefix_caching": True, "prefill_chunk_tokens": 64,
        "speculative": {"enabled": True, "method": "ngram",
                        "num_draft_tokens": 5}}})
    assert ic.kv_layout == "paged" and ic.resolve_num_pages(4, 64) == 32
    # fraction-of-slot-footprint sizing (default fraction 1.0)
    frac = DeepSpeedInferenceConfig({"inference": {
        "kv_layout": "paged", "kv_block_size": 8,
        "kv_pool_fraction": 0.5}})
    assert frac.resolve_num_pages(4, 64) == 16      # 0.5 * 4*64 / 8
    for bad in ({"kv_layout": "blocked"},
                {"prefix_caching": True},                    # needs paged
                {"kv_block_size": 0},
                {"num_pages": 4, "kv_pool_fraction": 0.5},   # pick one
                {"prefill_chunk_tokens": 0},
                {"speculative": {"enabled": True, "method": "oracle"}},
                {"speculative": {"num_draft_tokens": 0}},
                {"speculative": {"drafts": 4}}):             # unknown key
        with pytest.raises(DeepSpeedInferenceConfigError):
            DeepSpeedInferenceConfig({"inference": bad})
    with pytest.raises(DeepSpeedInferenceConfigError, match="cannot hold"):
        DeepSpeedInferenceConfig({"inference": {
            "kv_layout": "paged", "kv_block_size": 8,
            "num_pages": 2}}).resolve_num_pages(4, 64)


def test_model_drafter_requires_draft_model(model):
    with pytest.raises(AssertionError, match="draft_model"):
        make_engine(model, speculative={"enabled": True,
                                        "method": "model"})


# ----------------------------------------------------------- telemetry


def test_serving_records_carry_new_fields(model, tmp_path):
    """One serving_step record per scheduler step with schema-valid
    ttft/tpot/page_pool/prefix/speculative fields (bin/check_bench_schema
    and the dryrun leg read the same contract)."""
    import json
    from deepspeed_tpu.telemetry.record import validate_step_record
    eng = deepspeed.init_inference(
        model=model,
        config={"inference": {
            "max_batch_size": 2, "prefill_buckets": [8, 16, 32],
            "dtype": "fp32", "greedy": True, "kv_layout": "paged",
            "kv_block_size": PS, "prefix_caching": True,
            "speculative": {"enabled": True, "method": "ngram",
                            "num_draft_tokens": 3}},
            "telemetry": {"enabled": True,
                          "output_path": str(tmp_path)}})
    shared = [5, 6, 7] * 6
    # two calls: prefix registration happens at prefill completion, so
    # the second request must ARRIVE after the first prefilled to hit
    eng.generate([shared[:14]], max_new_tokens=6)
    eng.generate([shared[:17]], max_new_tokens=6)
    with open(eng.telemetry.jsonl_path) as fh:
        recs = [json.loads(line) for line in fh]
    assert recs
    for rec in recs:
        assert not validate_step_record(rec), validate_step_record(rec)
    last = recs[-1]
    assert last["ttft"]["count"] == 2 and last["ttft"]["p95_s"] > 0
    assert last["tpot"]["count"] == 2
    assert last["page_pool"]["num_pages"] == eng.allocator.num_pages
    assert 0 <= last["page_pool"]["occupancy"] <= 1
    assert last["prefix"]["lookups"] == 2 and last["prefix"]["hits"] >= 1
    assert last["speculative"]["proposed"] > 0
    assert 0 < last["speculative"]["acceptance_rate"] <= 1
    snap = eng.telemetry_snapshot()["serving"]
    for key in ("ttft", "tpot", "page_pool", "prefix", "speculative"):
        assert key in snap, key


def test_bench_schema_checker_table_matches_record_schema():
    """bin/check_bench_schema.py keeps a LOCAL copy of the serving
    sub-dict key table (it must stay a bare stdlib script — no jax
    import from bin/); pin the copy to telemetry/record.py so the two
    cannot drift."""
    import importlib.util
    import os
    from deepspeed_tpu.telemetry.record import SERVING_SUBDICT_KEYS
    path = os.path.join(os.path.dirname(__file__), "..", "..", "bin",
                        "check_bench_schema.py")
    spec = importlib.util.spec_from_file_location("_cbs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.SERVING_SUBDICT_KEYS == SERVING_SUBDICT_KEYS
