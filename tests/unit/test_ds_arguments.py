"""add_config_arguments tests (reference tests/unit/test_ds_arguments.py)."""
import argparse

import pytest

import deepspeed_tpu


def basic_parser():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_epochs", type=int)
    return parser


def test_no_ds_arguments():
    parser = basic_parser()
    args = parser.parse_args(["--num_epochs", "2"])
    assert args.num_epochs == 2
    assert not hasattr(args, "deepspeed")


def test_no_ds_enable_argument():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--num_epochs", "2", "--deepspeed_config",
                              "foo.json"])
    assert args.num_epochs == 2
    assert args.deepspeed is False
    assert args.deepspeed_config == "foo.json"


def test_full_ds_arguments():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--num_epochs", "2", "--deepspeed",
                              "--deepspeed_config", "foo.json",
                              "--deepspeed_mpi"])
    assert args.deepspeed is True
    assert args.deepspeed_mpi is True
    assert args.deepspeed_config == "foo.json"


def test_core_deepspeed_arguments_defaults():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--num_epochs", "1"])
    assert args.deepspeed is False
    assert args.deepspeed_config is None
    assert args.deepspeed_mpi is False
