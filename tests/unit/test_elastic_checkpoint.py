"""Elastic checkpoint resharding tests.

Reference: ZeRO stage-1 elastic checkpoints re-shard optimizer state across
different DP world sizes on load (stage1.py:848-1107); pipeline per-layer
files allow stage re-partitioning. Here the state dict stores full gathered
trees and load re-places them with the current mesh's plan, so resharding
across dp sizes — and across ZeRO stages — is exercised end-to-end.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.model import Model


def _apply(params, x, y):
    return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)


def _fresh_params():
    return {"w": jnp.zeros((32, 8)), "b": jnp.zeros((8,))}


def _config(stage=1):
    return {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": stage},
    }


def _train(engine, steps=5, seed=0):
    rs = np.random.RandomState(seed)
    W = rs.randn(32, 8).astype(np.float32)
    x = jnp.asarray(rs.randn(16, 32).astype(np.float32))
    y = x @ jnp.asarray(W)
    for _ in range(steps):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    return x, y, float(loss)


@pytest.mark.parametrize("from_dp,to_dp", [(8, 4), (4, 8), (8, 2)])
def test_elastic_resharding_across_dp_sizes(tmp_path, from_dp, to_dp):
    engine = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                             config_params=_config(stage=2),
                             mesh=build_mesh(data=from_dp))
    x, y, last = _train(engine)
    engine.save_checkpoint(str(tmp_path))

    engine2 = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                              config_params=_config(stage=2),
                              mesh=build_mesh(data=to_dp))
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.loaded_checkpoint_dp_world_size == from_dp
    # same loss on the same batch after resharding (up to psum
    # reassociation across the different mesh partitionings)
    np.testing.assert_allclose(float(engine2(x, y)), float(engine(x, y)),
                               rtol=1e-5)
    # optimizer state landed on the new mesh's plan
    m_leaf = engine2.state["opt"]["exp_avg"]["w"]
    assert "data" in str(m_leaf.sharding.spec)
    assert len(m_leaf.sharding.device_set) == to_dp
    # training continues without error at the new size
    _train(engine2, steps=2)


def test_elastic_resharding_across_zero_stages(tmp_path):
    """dp=8 stage-2 checkpoint -> stage-3 engine (and back)."""
    engine = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                             config_params=_config(stage=2),
                             mesh=build_mesh(data=8))
    x, y, _ = _train(engine)
    engine.save_checkpoint(str(tmp_path))

    engine3 = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                              config_params=_config(stage=3),
                              mesh=build_mesh(data=8))
    engine3.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(float(engine3(x, y)), float(engine(x, y)),
                               rtol=1e-5)


def test_load_from_fp32_weights_toggle(tmp_path):
    engine = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                             config_params=_config(stage=1),
                             mesh=build_mesh(data=8))
    _train(engine)
    # skew master away from params so the two load modes differ
    engine.state["master"] = jax.tree_util.tree_map(
        lambda m: m + 0.001, engine.state["master"])
    engine.save_checkpoint(str(tmp_path))

    exact = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                            config_params=_config(stage=1),
                            mesh=build_mesh(data=8))
    exact.load_checkpoint(str(tmp_path), load_from_fp32_weights=True)
    recast = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                             config_params=_config(stage=1),
                             mesh=build_mesh(data=8))
    recast.load_checkpoint(str(tmp_path), load_from_fp32_weights=False)

    m_exact = np.asarray(exact.state["master"]["w"])
    m_recast = np.asarray(recast.state["master"]["w"])
    assert not np.allclose(m_exact, m_recast)
    # recast master equals the bf16 params upcast
    np.testing.assert_allclose(
        m_recast, np.asarray(recast.state["params"]["w"], dtype=np.float32))


def test_counters_and_scheduler_roundtrip(tmp_path):
    config = _config(stage=1)
    config["scheduler"] = {"type": "WarmupLR",
                           "params": {"warmup_min_lr": 0.0,
                                      "warmup_max_lr": 1e-2,
                                      "warmup_num_steps": 100}}
    engine = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                             config_params=config, mesh=build_mesh(data=8))
    _train(engine, steps=7)
    engine.save_checkpoint(str(tmp_path), client_state={"epoch": 3})

    engine2 = DeepSpeedEngine(model=Model(_apply, _fresh_params()),
                              config_params=config, mesh=build_mesh(data=4))
    _, client = engine2.load_checkpoint(str(tmp_path))
    assert engine2.global_steps == 7
    assert client["epoch"] == 3
    assert engine2.lr_scheduler.state_dict() == \
        engine.lr_scheduler.state_dict()
