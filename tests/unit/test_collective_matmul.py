"""Collective matmul: ring-decomposed all-gather/reduce-scatter GEMMs
vs the unfused XLA oracle — forward, custom_vjp backward, the
qwZ-composed int8 ZeRO-3 ring gather, wire pricing, and the config
gate — on sub-meshes of the 8-device CPU mesh (world sizes 1/2/4).

Tolerances: fp32 is near-bit (the column op's per-block GEMMs contract
identically to the monolithic dot; the row op re-orders the n-way
partial-sum reduction); bf16 engine runs inherit the usual half-width
drift (documented in docs/collective_matmul.md).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.collective_matmul import (
    CollectiveMatmulBinding, make_zero3_gather_fn, tp_column_matmul,
    tp_row_matmul, zero3_ring_gather)
from deepspeed_tpu.parallel.ring import even_chunk_count, ring_perm

pytestmark = pytest.mark.comm

# one mesh per world size, shared across tests so the lru-cached jitted
# shard_map wrappers compile once per (mesh, options)
_MESHES = {}


def _model_mesh(n):
    if n not in _MESHES:
        _MESHES[n] = Mesh(np.array(jax.devices()[:n]).reshape(n),
                          ("model",))
    return _MESHES[n]


def _binding(n, **kw):
    return CollectiveMatmulBinding(mesh=_model_mesh(n), axis="model", **kw)


def _xw(rng, b, s, d, f, dtype=np.float32):
    x = jnp.asarray(rng.randn(b, s, d).astype(dtype))
    w = jnp.asarray(rng.randn(d, f).astype(dtype))
    return x, w


TOL_F32 = dict(atol=5e-6, rtol=5e-6)
TOL_GRAD = dict(atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("n", [1, 2, 4])
def test_column_forward_matches_unfused(n):
    rng = np.random.RandomState(0)
    x, w = _xw(rng, 2, 8, 16, 8 * max(n, 1))
    out = tp_column_matmul(x, w, _binding(n))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w),
                               **TOL_F32)


@pytest.mark.parametrize("n", [1, 2, 4])
def test_row_forward_matches_unfused(n):
    rng = np.random.RandomState(1)
    f = 8 * max(n, 1)
    x, w = _xw(rng, 2, 8, f, 16)
    out = tp_row_matmul(x, w, _binding(n))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w),
                               **TOL_F32)


@pytest.mark.parametrize("n", [2, 4])
@pytest.mark.parametrize("kind", ["column", "row"])
def test_backward_matches_unfused(n, kind):
    rng = np.random.RandomState(2)
    if kind == "column":
        x, w = _xw(rng, 1, 8, 8, 8 * n)
        fused = lambda x, w: tp_column_matmul(x, w, _binding(n))
    else:
        x, w = _xw(rng, 1, 8, 8 * n, 8)
        fused = lambda x, w: tp_row_matmul(x, w, _binding(n))
    gf = jax.grad(lambda x, w: jnp.sum(fused(x, w) ** 2),
                  argnums=(0, 1))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2),
                  argnums=(0, 1))(x, w)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   **TOL_GRAD)


def test_chunked_rotation_bit_matches_single_hop():
    # chunks only changes ppermute granularity, never the math
    rng = np.random.RandomState(3)
    x, w = _xw(rng, 2, 8, 16, 16)
    one = tp_column_matmul(x, w, _binding(4, chunks=1))
    many = tp_column_matmul(x, w, _binding(4, chunks=3))
    np.testing.assert_array_equal(np.asarray(one), np.asarray(many))


def test_bf16_wire_policy_tolerance():
    # "bf16" casts the rotated payload only: lossy at half-width drift,
    # not a rounding catastrophe
    rng = np.random.RandomState(4)
    x, w = _xw(rng, 2, 8, 16, 16)
    lossy = tp_column_matmul(x, w, _binding(4, dtype="bf16"))
    np.testing.assert_allclose(np.asarray(lossy), np.asarray(x @ w),
                               atol=0.3, rtol=0.05)


def test_shape_fallback_is_plain_matmul():
    # indivisible seq -> one loud fallback, bitwise the unfused product
    rng = np.random.RandomState(5)
    x, w = _xw(rng, 2, 7, 16, 16)
    out = tp_column_matmul(x, w, _binding(4))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x @ w))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("n", [2, 4])
def test_zero3_ring_gather_roundtrip(n, dtype):
    rng = np.random.RandomState(6)
    mesh = Mesh(np.array(jax.devices()[:n]).reshape(n), ("data",))
    p = jnp.asarray(rng.randn(8 * n, 8), dtype=dtype)
    p_sh = jax.device_put(p, NamedSharding(mesh, P("data", None)))
    out = jax.jit(lambda q: zero3_ring_gather(
        q, mesh, P("data", None), P(None, None), "data", 0, 2, False,
        256))(p_sh)
    # an unquantized ring gather of the shards IS the original array
    np.testing.assert_array_equal(np.asarray(out, np.float32),
                                  np.asarray(p, np.float32))


@pytest.mark.parametrize("n", [1, 2, 4])
def test_zero3_ring_gather_quantized_matches_per_shard_codec(n):
    from deepspeed_tpu.runtime.comm.quantize import (dequantize_param,
                                                     quantize_param)
    rng = np.random.RandomState(7)
    mesh = Mesh(np.array(jax.devices()[:n]).reshape(n), ("data",))
    p = jnp.asarray(rng.randn(8 * n, 16).astype(np.float32))
    p_sh = jax.device_put(p, NamedSharding(mesh, P("data", None)))
    out = jax.jit(lambda q: zero3_ring_gather(
        q, mesh, P("data", None), P(None, None), "data", 0, 1, True,
        256))(p_sh)
    # the wire carries each SHARD's int8 blocks + scales: the gathered
    # result is exactly the concat of per-shard codec round-trips
    ref = jnp.concatenate(
        [dequantize_param(*quantize_param(p[i * 8:(i + 1) * 8]),
                          jnp.float32) for i in range(n)], axis=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_zero3_ring_gather_backward_is_straight_through():
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]).reshape(n), ("data",))
    p = jnp.asarray(np.random.RandomState(8).randn(8 * n, 8)
                    .astype(np.float32))
    p_sh = jax.device_put(p, NamedSharding(mesh, P("data", None)))
    c = jnp.asarray(np.random.RandomState(9).randn(8 * n, 8)
                    .astype(np.float32))

    def loss(q):
        return jnp.sum(zero3_ring_gather(
            q, mesh, P("data", None), P(None, None), "data", 0, 1,
            False, 256) * c)

    g = jax.jit(jax.grad(loss))(p_sh)
    np.testing.assert_allclose(np.asarray(g), np.asarray(c), atol=1e-6)


def test_make_zero3_gather_fn_skips_persistent_leaves():
    from deepspeed_tpu.runtime.zero.partition import ZeroShardingPlan
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("data",))
    plan = ZeroShardingPlan(mesh, stage=3, param_persistence_threshold=0)
    gather = make_zero3_gather_fn(plan, mesh, chunks=1)
    tree = {"w": jnp.ones((8, 8), jnp.float32),
            "tiny": jnp.ones((3,), jnp.float32)}   # no dp-divisible dim
    placed = {
        "w": jax.device_put(tree["w"],
                            plan.param_sharding("w", (8, 8))),
        "tiny": jax.device_put(tree["tiny"],
                               plan.param_sharding("tiny", (3,))),
    }
    out = jax.jit(gather)(placed)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(tree["w"]))
    np.testing.assert_array_equal(np.asarray(out["tiny"]),
                                  np.asarray(tree["tiny"]))


# ------------------------------------------------------------ ring helper
def test_ring_perm_shapes():
    assert ring_perm(4) == [(0, 1), (1, 2), (2, 3), (3, 0)]
    assert ring_perm(4, reverse=True) == [(0, 3), (1, 0), (2, 1), (3, 2)]
    assert even_chunk_count(12, 5) == 4     # largest divisor <= 5
    assert even_chunk_count(7, 3) == 1


def test_ring_attention_still_matches_dense():
    # the refactor onto parallel/ring.py must not move ring attention
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.parallel.ring_attention import (
        _dense_reference_attention, sequence_parallel_attention)
    rng = np.random.RandomState(10)
    mk = lambda: jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    mesh = build_mesh(sequence=4)
    out = sequence_parallel_attention(q, k, v, mesh, impl="ring")
    ref = _dense_reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------- wire pricing
def test_ring_decomposition_prices_as_one_collective():
    from deepspeed_tpu.runtime.comm.wire import (
        decomposed_collective_bytes, _ring_factor)
    payload = 4 * 1024 * 1024
    one = decomposed_collective_bytes(payload, group=8, chunks=1)
    for chunks in (2, 3, 16):
        assert decomposed_collective_bytes(payload, 8, chunks) == one
    assert one == int(round(payload * _ring_factor(8)))
    assert decomposed_collective_bytes(payload, group=1) == 0


def test_overlap_report_classes():
    from deepspeed_tpu.runtime.comm.wire import overlap_report
    est = {"allgather_bytes_per_step": 10 ** 9,
           "reduce_bytes_per_step": 10 ** 9}
    unfused = overlap_report(est, 1.0, {}, "cpu")
    fused = overlap_report(est, 1.0,
                           {"allgather": True, "reduce": True}, "cpu")
    for cls in ("allgather", "reduce"):
        assert 0 < unfused[cls]["overlap_efficiency"] < 1
        assert fused[cls]["overlap_efficiency"] == 1.0
        assert fused[cls]["bytes"] == unfused[cls]["bytes"]
        assert fused[cls]["exposed_s"] == 0.0
    assert overlap_report(None, 1.0, {}, "cpu") is None
    assert overlap_report(est, 0.0, {}, "cpu") is None


# ------------------------------------------------------------ config gate
def test_config_parses_and_validates():
    from deepspeed_tpu.runtime.comm.config import DeepSpeedCommConfig
    cc = DeepSpeedCommConfig({"comm": {"collective_matmul": {
        "enabled": True, "chunks": 4, "dtype": "bf16"}}})
    cm = cc.collective_matmul
    assert cm.enabled and cm.chunks == 4 and cm.dtype == "bf16"
    assert cm.tensor_parallel and cm.zero_gather     # defaults
    off = DeepSpeedCommConfig({}).collective_matmul
    assert not off.enabled

    with pytest.raises(ValueError):
        DeepSpeedCommConfig({"comm": {"collective_matmul": {
            "enabled": True, "chunks": 0}}})
    with pytest.raises(ValueError):
        DeepSpeedCommConfig({"comm": {"collective_matmul": {
            "enabled": True, "dtype": "fp8"}}})
    # unknown keys: warn by default, raise under strict (PR 4/5 policy)
    DeepSpeedCommConfig({"comm": {"collective_matmul": {
        "enabled": True, "bogus": 1}}})
    with pytest.raises(ValueError):
        DeepSpeedCommConfig({"comm": {"collective_matmul": {
            "enabled": True, "strict": True, "bogus": 1}}})


def test_transformer_flash_attention_key():
    from deepspeed_tpu.runtime.config import (
        DeepSpeedConfigError, get_transformer_flash_attention)
    assert get_transformer_flash_attention({}) is None
    # legacy bools parse onto the tri-state: true -> auto, false -> xla
    assert get_transformer_flash_attention(
        {"transformer": {"flash_attention": True}}) == "auto"
    assert get_transformer_flash_attention(
        {"transformer": {"flash_attention": False}}) == "xla"
    for mode in ("auto", "pallas", "xla", "PALLAS"):
        assert get_transformer_flash_attention(
            {"transformer": {"flash_attention": mode}}) == mode.lower()
    with pytest.raises(DeepSpeedConfigError):
        get_transformer_flash_attention(
            {"transformer": {"flash_attention": "yes"}})


def test_engine_applies_transformer_and_cm_gates():
    """Engine wiring: transformer.flash_attention flips the model
    config; comm.collective_matmul attaches a binding on a TP mesh and
    the fused loss tracks the unfused oracle."""
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    def engine(cm, flash=None):
        conf = {
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3,
                                  "stage3_param_persistence_threshold": 0},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 10 ** 9,
        }
        if cm:
            conf["comm"] = {"collective_matmul": {"enabled": True,
                                                  "chunks": 2}}
        if flash is not None:
            conf["transformer"] = {"flash_attention": flash}
        cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=16, n_layers=1,
                              n_heads=2, d_model=32,
                              use_flash_attention=False, remat=False,
                              loss_chunk=0)
        return DeepSpeedEngine(model=gpt2.make_gpt2_model(config=cfg),
                               mesh=build_mesh(data=2, model=2),
                               config_params=conf)

    e_on = engine(cm=True, flash=True)
    assert e_on._cm_tp and e_on._cm_zero3
    assert e_on.model.config.collective_matmul is not None
    # legacy true parses as "auto": off-TPU that RESOLVES to the XLA
    # oracle — explicitly, not via a silent in-kernel fallback — and the
    # resolution is observable on the engine
    assert e_on.flash_attention_backend == "xla"
    assert e_on.model.config.flash_attention_backend == "xla"
    assert e_on.model.config.use_flash_attention is False

    # forced "pallas" off-TPU runs the kernel under the interpreter
    # (loud warning), never silently dense
    e_forced = engine(cm=False, flash="pallas")
    assert e_forced.flash_attention_backend == "interpret"
    assert e_forced.model.config.flash_attention_backend == "interpret"
    assert e_forced.model.config.use_flash_attention is True

    e_off = engine(cm=False)
    assert not e_off._cm_tp and not e_off._cm_zero3
    ids = np.random.RandomState(0).randint(
        0, 128, size=(1, 4, 16)).astype(np.int32)
    loss_on = float(e_on.train_batch(batch=(ids, ids.copy())))
    loss_off = float(e_off.train_batch(batch=(ids, ids.copy())))
    assert np.isfinite(loss_on)
    assert abs(loss_on - loss_off) / abs(loss_off) < 1e-2


def test_engine_cm_noop_without_site_warns_and_strict_raises():
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.parallel.topology import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    def conf(strict):
        return {
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},   # no stage-3 gathers
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "comm": {"collective_matmul": {"enabled": True,
                                           "strict": strict}},
        }

    def build(strict):
        cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=16, n_layers=1,
                              n_heads=2, d_model=32,
                              use_flash_attention=False, remat=False,
                              loss_chunk=0)
        # DP-only mesh: no model axis, no stage-3 -> no fusion site
        return DeepSpeedEngine(model=gpt2.make_gpt2_model(config=cfg),
                               mesh=build_mesh(data=2),
                               config_params=conf(strict))

    eng = build(strict=False)     # warns, engine still comes up
    assert not eng._cm_tp and not eng._cm_zero3
    with pytest.raises(ValueError):
        build(strict=True)
