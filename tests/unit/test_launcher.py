"""Launcher CLI + env report tests (reference tests for runner.py parsing
live in its users; the grammar is locked here)."""
import io
import json
import os
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.launcher import (fetch_hostfile,
                                    parse_inclusion_exclusion,
                                    encode_world_info, decode_world_info)
from deepspeed_tpu.launcher.launch import build_env, parse_args as launch_args


def _hostfile(tmp_path, text):
    p = tmp_path / "hostfile"
    p.write_text(textwrap.dedent(text))
    return str(p)


def test_fetch_hostfile(tmp_path):
    path = _hostfile(tmp_path, """\
        worker-0 slots=4
        worker-1 slots=4
    """)
    pool = fetch_hostfile(path)
    assert pool == {"worker-0": 4, "worker-1": 4}


def test_fetch_hostfile_bad_format(tmp_path):
    path = _hostfile(tmp_path, "worker-0 slots=x\n")
    with pytest.raises(ValueError):
        fetch_hostfile(path)


def test_fetch_hostfile_duplicate(tmp_path):
    path = _hostfile(tmp_path, "w0 slots=2\nw0 slots=4\n")
    with pytest.raises(ValueError):
        fetch_hostfile(path)


def test_fetch_hostfile_missing():
    assert fetch_hostfile("/nonexistent/hostfile") is None


def test_include_filtering():
    pool = {"w0": 4, "w1": 4, "w2": 4}
    active = parse_inclusion_exclusion(pool, "w0:0,1@w2", "")
    assert active == {"w0": [0, 1], "w2": [0, 1, 2, 3]}


def test_exclude_filtering():
    pool = {"w0": 4, "w1": 4}
    active = parse_inclusion_exclusion(pool, "", "w1:2,3")
    assert active == {"w0": [0, 1, 2, 3], "w1": [0, 1]}
    active = parse_inclusion_exclusion(pool, "", "w1")
    assert active == {"w0": [0, 1, 2, 3]}


def test_include_exclude_mutually_exclusive():
    with pytest.raises(ValueError):
        parse_inclusion_exclusion({"w0": 1}, "w0", "w0")


def test_include_unknown_host():
    with pytest.raises(ValueError, match="not found"):
        parse_inclusion_exclusion({"w0": 1}, "w9", "")


def test_world_info_roundtrip():
    info = {"w0": [0, 1], "w1": [0]}
    assert decode_world_info(encode_world_info(info)) == info


def test_launch_env_build():
    info = {"w0": [0, 1, 2, 3], "w1": [0, 1, 2, 3]}
    from deepspeed_tpu.launcher.runner import encode_world_info as enc
    args = launch_args(["--world_info", enc(info), "--node_rank", "1",
                        "--master_addr", "10.0.0.1", "--master_port",
                        "29501", "train.py"])
    env = build_env(args, decode_world_info(args.world_info))
    assert env["MASTER_ADDR"] == "10.0.0.1"
    assert env["MASTER_PORT"] == "29501"
    assert env["RANK"] == "1" and env["WORLD_SIZE"] == "2"
    assert env["DS_TPU_SLOTS"] == "4"


def test_single_node_launch_end_to_end(tmp_path):
    """deepspeed CLI -> launch.py -> user script, env propagated."""
    out_file = tmp_path / "env.json"
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""\
        import json, os, sys
        json.dump({k: os.environ.get(k) for k in
                   ("RANK", "WORLD_SIZE", "MASTER_ADDR", "DS_TPU_SLOTS")},
                  open(sys.argv[1], "w"))
    """))
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, PYTHONPATH=repo_root)
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--hostfile", "/nonexistent", "--num_gpus", "2",
         str(script), str(out_file)],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 0, proc.stderr
    result = json.load(open(out_file))
    assert result["RANK"] == "0"
    assert result["WORLD_SIZE"] == "1"
    assert result["DS_TPU_SLOTS"] == "2"


def test_pdsh_cmd_assembly():
    from deepspeed_tpu.launcher.runner import parse_args
    from deepspeed_tpu.launcher.multinode_runner import PDSHRunner
    args = parse_args(["--master_addr", "10.0.0.1", "train.py", "--lr",
                       "0.1"])
    world = encode_world_info({"w0": [0], "w1": [0]})
    os.environ["JAX_TEST_EXPORT_VAR"] = "1"
    runner = PDSHRunner(args, world, {"w0": [0], "w1": [0]})
    try:
        cmd = runner.get_cmd(runner.export_envs(), {"w0": [0], "w1": [0]})
    finally:
        del os.environ["JAX_TEST_EXPORT_VAR"]
    joined = " ".join(cmd)
    assert cmd[0] == "pdsh"
    assert "-w w0,w1" in joined
    assert "--node_rank=%n" in joined
    assert "JAX_TEST_EXPORT_VAR" in joined
    assert "train.py" in joined


def test_ds_report_smoke():
    from deepspeed_tpu.env_report import main
    buf = io.StringIO()
    main(out=buf)
    text = buf.getvalue()
    assert "op report" in text
    assert "cpu_adam" in text
    assert "flash_attention" in text
    assert "jax version" in text
