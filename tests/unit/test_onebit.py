"""Compressed (1-bit) collectives + 1-bit Adam tests.

Mirrors reference tests/onebit/test_nccl_backend.py: the compressed
allreduce is validated against the exact allreduce (error-feedback keeps the
long-run average unbiased), and OnebitAdam trains end-to-end through the
engine across its freeze_step boundary.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import build_mesh
from deepspeed_tpu.runtime.comm import (CompressedBackend, pack_signs,
                                        unpack_signs)
from deepspeed_tpu.runtime.model import Model


def test_pack_unpack_roundtrip():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(256).astype(np.float32))
    packed = pack_signs(x)
    assert packed.dtype == jnp.uint8 and packed.size == 32
    signs = unpack_signs(packed, jnp.float32(1.0))
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_compressed_allreduce_single_shot_error_bounded():
    mesh = build_mesh(data=8)
    backend = CompressedBackend(mesh)
    rs = np.random.RandomState(1)
    values = jnp.asarray(rs.randn(8, 1024).astype(np.float32))
    out, we, se = backend.compressed_allreduce(values)
    true_mean = np.asarray(values).mean(axis=0)
    # every rank gets the same result
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out[3]),
                               atol=1e-6)
    # 1-bit quantization: correlation with the true mean, not equality
    corr = np.corrcoef(np.asarray(out[0]), true_mean)[0, 1]
    assert corr > 0.5, corr


def test_error_feedback_makes_average_unbiased():
    """sum of outputs telescopes: mean over T iterations -> true mean."""
    mesh = build_mesh(data=8)
    backend = CompressedBackend(mesh)
    rs = np.random.RandomState(2)
    values = jnp.asarray(rs.randn(8, 512).astype(np.float32))
    true_mean = np.asarray(values).mean(axis=0)
    we = se = None
    acc = np.zeros(512, dtype=np.float64)
    T = 200
    for _ in range(T):
        out, we, se = backend.compressed_allreduce(values, we, se)
        acc += np.asarray(out[0], dtype=np.float64)
    err = np.abs(acc / T - true_mean).mean() / np.abs(true_mean).mean()
    assert err < 0.05, err


def test_error_feedback_unbiased_with_padding():
    """Non-divisible sizes: pad lanes must not bias the telescoping."""
    mesh = build_mesh(data=8)
    backend = CompressedBackend(mesh)
    rs = np.random.RandomState(7)
    n = 1000  # padded to 1024: 24 pad lanes
    values = jnp.asarray(rs.randn(8, n).astype(np.float32))
    true_mean = np.asarray(values).mean(axis=0)
    we = se = None
    acc = np.zeros(n, dtype=np.float64)
    T = 200
    for _ in range(T):
        out, we, se = backend.compressed_allreduce(values, we, se)
        acc += np.asarray(out[0], dtype=np.float64)
    err = np.abs(acc / T - true_mean).mean() / np.abs(true_mean).mean()
    assert err < 0.05, err
    # pad-lane error feedback stays exactly zero
    np.testing.assert_array_equal(np.asarray(we[:, n:]), 0.0)


def test_compressed_allreduce_padding():
    mesh = build_mesh(data=8)
    backend = CompressedBackend(mesh)
    rs = np.random.RandomState(3)
    n = 1000  # not divisible by 64
    values = jnp.asarray(rs.randn(8, n).astype(np.float32))
    out, we, se = backend.compressed_allreduce(values)
    assert out.shape == (8, n)
    assert we.shape[-1] == backend.padded_size(n)


def test_onebit_adam_rejects_zero3():
    """Stages 0-2 are supported since the compressed-comm tier (the
    exchange needs replicated compute params in the local-grad body);
    stage 3 stays a loud rejection."""
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
    }
    with pytest.raises(ValueError, match="not compatible with ZeRO"):
        deepspeed_tpu.initialize(
            model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                        {"w": jnp.zeros((16, 4))}),
            config_params=config)


def test_onebit_small_buffer_quantization_unbiased():
    """Pad lanes must not deflate the scale for tiny buffers (2 real
    lanes padded to the 8-lane sign-pack width): the worker+server
    two-stage compression (the degenerate all-equal-workers pipeline,
    built directly on masked_compress) telescopes to the true value."""
    from deepspeed_tpu.runtime.comm.onebit import masked_compress

    def two_stage(x, we, se):
        n = x.size
        padded = we.size
        flat = jnp.pad(x.reshape(-1), (0, padded - n))
        mask = (jnp.arange(padded) < n).astype(jnp.float32)
        _, _, worker_q, nwe = masked_compress(flat + we, mask,
                                              jnp.float32(n))
        _, _, server_q, nse = masked_compress(worker_q + se, mask,
                                              jnp.float32(n))
        return server_q[:n], nwe, nse

    x = jnp.asarray([0.5, -0.3], dtype=jnp.float32)
    we = jnp.zeros(8, dtype=jnp.float32)
    se = jnp.zeros(8, dtype=jnp.float32)
    acc = np.zeros(2)
    for _ in range(50):
        out, we, se = two_stage(x, we, se)
        acc += np.asarray(out)
    avg = acc / 50
    np.testing.assert_allclose(avg, [0.5, -0.3], atol=0.05)
    # pad lanes of error feedback stay zero
    np.testing.assert_array_equal(np.asarray(we[2:]), 0.0)


def test_onebit_adam_through_engine():
    rs = np.random.RandomState(0)
    W_true = rs.randn(16, 4).astype(np.float32)

    def apply_fn(params, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    config = {
        "train_batch_size": 32,
        "steps_per_print": 100,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-2, "freeze_step": 10}},
        "bf16": {"enabled": True},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Model(apply_fn, {"w": jnp.zeros((16, 4))}),
        config_params=config)
    x = jnp.asarray(rs.randn(32, 16).astype(np.float32))
    y = x @ jnp.asarray(W_true)
    losses = []
    for i in range(60):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    # must keep converging after freeze_step (compression engaged at 10;
    # 1-bit quantization error is large at 64 params, so the bar is steady
    # descent, not rate — the reference only unit-tests the backend)
    assert losses[-1] < 0.7 * losses[0], losses
    assert losses[-1] < 0.8 * losses[12], losses
    # error-feedback state is live once frozen
    werr = jax.tree_util.tree_leaves(
        engine.state["opt"]["worker_error"])[0]
    assert float(jnp.abs(werr).sum()) > 0.0
