"""LR schedule tests (reference tests/unit/test_lr_schedulers.py)."""
import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle,
                                                WarmupLR, WarmupDecayLR,
                                                SCHEDULE_CLASSES,
                                                get_lr_schedule_class)
from deepspeed_tpu.runtime.model import Model


def test_schedule_registry():
    assert set(SCHEDULE_CLASSES) == {"LRRangeTest", "OneCycle", "WarmupLR",
                                     "WarmupDecayLR"}
    assert get_lr_schedule_class("WarmupLR") is WarmupLR
    with pytest.raises(ValueError):
        get_lr_schedule_class("Nope")


def test_lr_range_test_continuous():
    opt = FusedAdam(lr=1e-3)
    sched = LRRangeTest(opt, lr_range_test_min_lr=1e-4,
                        lr_range_test_step_size=10,
                        lr_range_test_step_rate=1.0)
    lrs = []
    for _ in range(20):
        sched.step()
        lrs.append(opt.lr)
    # monotonic growth from min_lr
    assert lrs[0] >= 1e-4 and all(b >= a for a, b in zip(lrs, lrs[1:]))
    np.testing.assert_allclose(lrs[9], 1e-4 * 2.0, rtol=1e-6)


def test_lr_range_test_staircase():
    opt = FusedAdam(lr=1e-3)
    sched = LRRangeTest(opt, lr_range_test_min_lr=1e-4,
                        lr_range_test_step_size=5,
                        lr_range_test_step_rate=1.0,
                        lr_range_test_staircase=True)
    lrs = []
    for _ in range(10):
        sched.step()
        lrs.append(opt.lr)
    # interval boundary: floor((i+1)/5) bumps at i=4 and i=9
    assert len(set(np.round(lrs[:4], 10))) == 1
    assert len(set(np.round(lrs[4:9], 10))) == 1
    assert lrs[4] > lrs[0] and lrs[9] > lrs[4]


def test_one_cycle_up_down():
    opt = FusedAdam(lr=1e-3)
    sched = OneCycle(opt, cycle_min_lr=1e-4, cycle_max_lr=1e-2,
                     cycle_first_step_size=10)
    lrs = []
    for _ in range(20):
        sched.step()
        lrs.append(opt.lr)
    peak = int(np.argmax(lrs))
    assert 8 <= peak <= 11
    np.testing.assert_allclose(max(lrs), 1e-2, rtol=1e-5)
    assert lrs[-1] < 1e-2


def test_one_cycle_momentum_cycle():
    opt = FusedAdam(lr=1e-3)
    sched = OneCycle(opt, cycle_min_lr=1e-4, cycle_max_lr=1e-2,
                     cycle_first_step_size=10, cycle_min_mom=0.85,
                     cycle_max_mom=0.99)
    moms = []
    for _ in range(20):
        sched.step()
        moms.append(sched.get_mom()[0][0])  # beta1 of group 0
    # momentum cycles inversely to lr: falls then rises
    trough = int(np.argmin(moms))
    assert 8 <= trough <= 11


def test_warmup_lr_then_constant():
    opt = FusedAdam(lr=1e-3)
    sched = WarmupLR(opt, warmup_min_lr=0.0, warmup_max_lr=1e-2,
                     warmup_num_steps=10)
    lrs = []
    for _ in range(15):
        sched.step()
        lrs.append(opt.lr)
    assert lrs[0] < lrs[5] < lrs[9]
    np.testing.assert_allclose(lrs[10:], 1e-2, rtol=1e-6)


def test_warmup_decay_lr():
    opt = FusedAdam(lr=1e-3)
    sched = WarmupDecayLR(opt, total_num_steps=20, warmup_min_lr=0.0,
                          warmup_max_lr=1e-2, warmup_num_steps=10)
    lrs = []
    for _ in range(20):
        sched.step()
        lrs.append(opt.lr)
    assert int(np.argmax(lrs)) in (9, 10)
    assert lrs[-1] < lrs[10]


def test_state_dict_roundtrip():
    opt = FusedAdam(lr=1e-3)
    sched = WarmupLR(opt, warmup_max_lr=1e-2, warmup_num_steps=10)
    for _ in range(4):
        sched.step()
    sd = sched.state_dict()
    opt2 = FusedAdam(lr=1e-3)
    sched2 = WarmupLR(opt2, warmup_max_lr=1e-2, warmup_num_steps=10)
    sched2.load_state_dict(sd)
    sched.step()
    sched2.step()
    assert sched.get_last_lr() == sched2.get_last_lr()


@pytest.mark.parametrize("name,params", [
    ("LRRangeTest", {"lr_range_test_min_lr": 1e-4}),
    ("OneCycle", {"cycle_min_lr": 1e-4, "cycle_max_lr": 1e-2}),
    ("WarmupLR", {"warmup_max_lr": 1e-2, "warmup_num_steps": 5}),
    ("WarmupDecayLR", {"warmup_max_lr": 1e-2, "warmup_num_steps": 5,
                       "total_num_steps": 20}),
])
def test_schedulers_through_engine(name, params):
    """Scheduler selected from config json steps per batch
    (reference engine.py:465-480)."""
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "scheduler": {"type": name, "params": params},
    }
    engine, _, _, sched = deepspeed_tpu.initialize(
        model=Model(lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2),
                    {"w": jnp.zeros((4, 2))}),
        config_params=config)
    assert type(sched).__name__ == name
    x = jnp.ones((8, 4))
    y = jnp.ones((8, 2))
    for _ in range(3):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    assert engine.lr_scheduler.last_batch_iteration == 2
