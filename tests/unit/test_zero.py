"""ZeRO stage correctness: every stage must produce the same training
trajectory as plain DP (the sharding only changes placement, not math).

Mirrors reference tests/unit/test_zero.py + test_fp16.py zero combos.
"""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel.topology import DATA_AXIS
from simple_model import make_simple_model, SimpleDataset, base_config

HIDDEN = 16
WORLD = 8


def make_engine(config, seed=0):
    model = make_simple_model(HIDDEN, seed=seed)
    engine, _, _, _ = deepspeed.initialize(model=model, config_params=config)
    return engine


def run_steps(engine, dataset, steps):
    mb = engine.train_micro_batch_size_per_gpu() * WORLD
    losses = []
    for s in range(steps):
        x = np.stack([dataset[(s * mb + i) % len(dataset)][0]
                      for i in range(mb)])
        y = np.stack([dataset[(s * mb + i) % len(dataset)][1]
                      for i in range(mb)])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def zero_cfg(stage, **zero_overrides):
    cfg = base_config(WORLD)
    cfg["bf16"] = {"enabled": True}
    if stage > 0:
        z = {"stage": stage}
        z.update(zero_overrides)
        cfg["zero_optimization"] = z
    return cfg


@pytest.fixture(scope="module")
def baseline():
    dataset = SimpleDataset(512, HIDDEN, seed=11)
    engine = make_engine(zero_cfg(0), seed=2)
    losses = run_steps(engine, dataset, 6)
    params = jax.tree_util.tree_map(np.asarray, engine.get_params())
    return dataset, losses, params


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_matches_dp(stage, baseline):
    dataset, ref_losses, ref_params = baseline
    engine = make_engine(
        zero_cfg(stage, stage3_param_persistence_threshold=0), seed=2)
    losses = run_steps(engine, dataset, 6)
    np.testing.assert_allclose(np.array(losses), np.array(ref_losses),
                               rtol=5e-3, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ref_params),
                    jax.tree_util.tree_leaves(engine.get_params())):
        np.testing.assert_allclose(a, np.asarray(b), rtol=5e-3, atol=1e-5)


def test_zero1_master_is_sharded():
    engine = make_engine(zero_cfg(1), seed=2)
    master_leaves = jax.tree_util.tree_leaves(engine.state["master"])
    specs = [leaf.sharding.spec for leaf in master_leaves
             if hasattr(leaf, "sharding")]
    # at least the weight matrices (16x16, divisible by 8) must be sharded
    assert any(DATA_AXIS in str(s) for s in specs), specs
    # compute params stay replicated at stage 1
    for leaf in jax.tree_util.tree_leaves(engine.state["params"]):
        assert leaf.sharding.spec == P() or \
            DATA_AXIS not in str(leaf.sharding.spec)


def test_zero2_grads_sharded():
    engine = make_engine(zero_cfg(2), seed=2)
    specs = [leaf.sharding.spec for leaf in
             jax.tree_util.tree_leaves(engine.state["acc_grads"])]
    assert any(DATA_AXIS in str(s) for s in specs), specs


def test_zero3_params_sharded():
    engine = make_engine(
        zero_cfg(3, stage3_param_persistence_threshold=0), seed=2)
    specs = [leaf.sharding.spec for leaf in
             jax.tree_util.tree_leaves(engine.state["params"])]
    assert any(DATA_AXIS in str(s) for s in specs), specs


def test_zero3_persistence_threshold_keeps_small_replicated():
    engine = make_engine(
        zero_cfg(3, stage3_param_persistence_threshold=10 ** 9), seed=2)
    for leaf in jax.tree_util.tree_leaves(engine.state["params"]):
        assert DATA_AXIS not in str(leaf.sharding.spec)


def test_zero_requires_half_precision():
    cfg = base_config(WORLD)
    cfg["zero_optimization"] = {"stage": 1}
    with pytest.raises(AssertionError):
        make_engine(cfg)


def test_zero_unbalanced_shapes():
    """Shapes not divisible by dp fall back to replication but still train
    (reference test_zero unbalanced gradients)."""
    from deepspeed_tpu.runtime.model import Model
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    params = {
        "w_odd": jnp.asarray(rng.randn(7, 5) * 0.1, jnp.float32),  # 35 elems
        "w_even": jnp.asarray(rng.randn(16, 16) * 0.1, jnp.float32),
    }

    def apply_fn(params, x, y):
        h = x @ params["w_even"].astype(x.dtype)
        h2 = h[:, :7] @ params["w_odd"].astype(x.dtype)
        return jnp.mean((h2 - y[:, :5]) ** 2)

    model = Model(apply_fn, params)
    cfg = zero_cfg(2)
    engine, _, _, _ = deepspeed.initialize(model=model, config_params=cfg)
    mb = engine.train_micro_batch_size_per_gpu() * WORLD
    x = rng.randn(mb, 16).astype(np.float32)
    y = rng.randn(mb, 16).astype(np.float32)
    for _ in range(3):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    assert np.isfinite(float(loss))
