"""Elasticity config keys/defaults (reference: deepspeed/elasticity/constants.py)."""

ELASTICITY = "elasticity"

LATEST_ELASTICITY_VERSION = 0.1

ENABLED = "enabled"
ENABLED_DEFAULT = False

MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000

MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]

MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1
MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000

MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0

PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True

IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False

VERSION = "version"
VERSION_DEFAULT = LATEST_ELASTICITY_VERSION

# --- runtime elasticity keys (ISSUE 16, runtime/elastic/): how the
# ElasticRunner detects, retries, and gates a rescale. These do NOT
# enter the immutable solver fingerprint (ensure_immutable_elastic_config
# compares only the batch-math keys) — an operator may tune retry or
# eviction policy mid-campaign without invalidating the schedule.
RESCALE_RETRIES = "rescale_retries"
RESCALE_RETRIES_DEFAULT = 2

RESCALE_BACKOFF_SECONDS = "rescale_backoff_seconds"
RESCALE_BACKOFF_SECONDS_DEFAULT = 0.5

EVICTION_SEVERITY = "eviction_severity"
EVICTION_SEVERITY_DEFAULT = 2.0

EVICTION_WINDOWS = "eviction_windows"
EVICTION_WINDOWS_DEFAULT = 3

PREEMPTION_NOTICE_FILE = "preemption_notice_file"
PREEMPTION_NOTICE_FILE_DEFAULT = None

FINGERPRINT_GATE = "fingerprint_gate"
FINGERPRINT_GATE_DEFAULT = False

MINIMUM_DEEPSPEED_VERSION = "0.1.0"

DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"
