from .elasticity import (compute_elastic_config, elasticity_enabled,
                         ensure_immutable_elastic_config,
                         get_candidate_batch_sizes, get_valid_gpus,
                         get_best_candidates, _get_compatible_gpus_v01, HCN_LIST)
from .config import (ElasticityConfig, ElasticityError, ElasticityConfigError,
                     ElasticityIncompatibleWorldSize)
from .constants import (ELASTICITY, ENABLED, DEEPSPEED_ELASTICITY_CONFIG,
                        MINIMUM_DEEPSPEED_VERSION, LATEST_ELASTICITY_VERSION,
                        IGNORE_NON_ELASTIC_BATCH_INFO,
                        IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
