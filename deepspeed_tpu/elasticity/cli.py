"""``ds_elastic``: elasticity config explorer (reference bin/ds_elastic)."""
import argparse
import json

from . import compute_elastic_config
from ..version import __version__


def main(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("-c", "--config", type=str, required=True,
                        help="DeepSpeed config json")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="Intended/current world size (chips)")
    args = parser.parse_args(args=args)
    with open(args.config, "r") as fd:
        ds_config = json.load(fd)
    print("Config:", json.dumps(ds_config.get("elasticity", {}), indent=2))
    if args.world_size > 0:
        batch, valid_chips, micro = compute_elastic_config(
            ds_config, __version__, world_size=args.world_size)
        print("Final batch size: {}".format(batch))
        print("Valid chip counts: {}".format(valid_chips))
        print("Micro batch size: {}".format(micro))
        print("Grad accum steps: {}".format(
            batch // (micro * args.world_size)))
    else:
        batch, valid_chips = compute_elastic_config(ds_config, __version__)
        print("Final batch size: {}".format(batch))
        print("Valid chip counts: {}".format(valid_chips))
    return 0


if __name__ == "__main__":
    main()
