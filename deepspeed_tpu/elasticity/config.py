"""Elasticity config object (reference: deepspeed/elasticity/config.py)."""
import json

from .constants import (
    ENABLED, ENABLED_DEFAULT, MAX_ACCEPTABLE_BATCH_SIZE,
    MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT, MICRO_BATCHES, MICRO_BATCHES_DEFAULT,
    MIN_GPUS, MIN_GPUS_DEFAULT, MAX_GPUS, MAX_GPUS_DEFAULT, MIN_TIME,
    MIN_TIME_DEFAULT, VERSION, VERSION_DEFAULT, PREFER_LARGER_BATCH,
    PREFER_LARGER_BATCH_DEFAULT, IGNORE_NON_ELASTIC_BATCH_INFO,
    IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT, RESCALE_RETRIES,
    RESCALE_RETRIES_DEFAULT, RESCALE_BACKOFF_SECONDS,
    RESCALE_BACKOFF_SECONDS_DEFAULT, EVICTION_SEVERITY,
    EVICTION_SEVERITY_DEFAULT, EVICTION_WINDOWS,
    EVICTION_WINDOWS_DEFAULT, PREEMPTION_NOTICE_FILE,
    PREEMPTION_NOTICE_FILE_DEFAULT, FINGERPRINT_GATE,
    FINGERPRINT_GATE_DEFAULT)


class ElasticityError(Exception):
    """Base exception for elasticity errors."""


class ElasticityConfigError(ElasticityError):
    """Bad elasticity configuration."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size not in the valid device-count list for the elastic config."""


class ElasticityConfig:
    """Typed view of the ``"elasticity"`` config block.

    When enabled, ``max_train_batch_size`` and ``micro_batch_sizes`` are
    required; device-count bounds, min_time, version, and batch preference are
    optional.
    """

    def __init__(self, param_dict):
        self.enabled = param_dict.get(ENABLED, ENABLED_DEFAULT)
        if self.enabled:
            for required in (MAX_ACCEPTABLE_BATCH_SIZE, MICRO_BATCHES):
                if required not in param_dict:
                    raise ElasticityConfigError(
                        "Elasticity config missing {}".format(required))
            self.max_acceptable_batch_size = param_dict[MAX_ACCEPTABLE_BATCH_SIZE]
            self.micro_batches = param_dict[MICRO_BATCHES]
        else:
            self.max_acceptable_batch_size = param_dict.get(
                MAX_ACCEPTABLE_BATCH_SIZE, MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
            self.micro_batches = param_dict.get(MICRO_BATCHES, MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                "micro_batch_sizes must be a list, got {}: {}".format(
                    type(self.micro_batches), self.micro_batches))
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                "micro_batch_sizes must be positive integers, got {}".format(
                    self.micro_batches))

        self.min_gpus = param_dict.get(MIN_GPUS, MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(MAX_GPUS, MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError(
                "min/max device counts must be > 0, got min={} max={}".format(
                    self.min_gpus, self.max_gpus))
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                "min_gpus cannot exceed max_gpus, got min={} max={}".format(
                    self.min_gpus, self.max_gpus))

        self.min_time = param_dict.get(MIN_TIME, MIN_TIME_DEFAULT)
        if self.min_time < 0:
            raise ElasticityConfigError(
                "min_time must be >= 0, got {}".format(self.min_time))

        self.version = param_dict.get(VERSION, VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(PREFER_LARGER_BATCH,
                                                       PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            IGNORE_NON_ELASTIC_BATCH_INFO, IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

        # runtime rescale policy (ISSUE 16, runtime/elastic/) — outside
        # the immutable solver fingerprint, tunable between runs
        self.rescale_retries = int(param_dict.get(
            RESCALE_RETRIES, RESCALE_RETRIES_DEFAULT))
        if self.rescale_retries < 0:
            raise ElasticityConfigError(
                "rescale_retries must be >= 0, got {}".format(
                    self.rescale_retries))
        self.rescale_backoff_seconds = float(param_dict.get(
            RESCALE_BACKOFF_SECONDS, RESCALE_BACKOFF_SECONDS_DEFAULT))
        if self.rescale_backoff_seconds < 0:
            raise ElasticityConfigError(
                "rescale_backoff_seconds must be >= 0, got {}".format(
                    self.rescale_backoff_seconds))
        self.eviction_severity = float(param_dict.get(
            EVICTION_SEVERITY, EVICTION_SEVERITY_DEFAULT))
        self.eviction_windows = int(param_dict.get(
            EVICTION_WINDOWS, EVICTION_WINDOWS_DEFAULT))
        if self.eviction_windows < 1:
            raise ElasticityConfigError(
                "eviction_windows must be >= 1, got {}".format(
                    self.eviction_windows))
        self.preemption_notice_file = param_dict.get(
            PREEMPTION_NOTICE_FILE, PREEMPTION_NOTICE_FILE_DEFAULT)
        self.fingerprint_gate = bool(param_dict.get(
            FINGERPRINT_GATE, FINGERPRINT_GATE_DEFAULT))

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
