"""Schedule-time elasticity: batch-size / device-count co-design.

Reference behavior: deepspeed/elasticity/elasticity.py (v0.1 algorithm).
Given acceptable micro-batch sizes and a max global batch, pick the global
batch size divisible by the largest number of device counts, so the job can
be scaled across that device-count list without changing convergence (the
global batch decomposes as micro_batch * grad_accum * world_size).

Pure math — identical on TPU; "gpus" in names kept for config parity, they
mean accelerator chips here.
"""
import os
import json
import re
from functools import reduce
from math import gcd

from .config import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                     ElasticityIncompatibleWorldSize)
from .constants import (ELASTICITY, ENABLED, ENABLED_DEFAULT,
                        LATEST_ELASTICITY_VERSION, MINIMUM_DEEPSPEED_VERSION,
                        DEEPSPEED_ELASTICITY_CONFIG)
from ..utils.logging import logger

# Highly composite numbers: each has more divisors than any smaller positive
# integer, which maximizes the number of compatible device counts per batch
# size. Enough entries to cover ~720K global batch.
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280,
    720720
]


def _lcm(values):
    return reduce(lambda a, b: a * b // gcd(a, b), values)


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """For each base, the largest base*HCN not exceeding the cap."""
    candidates = set()
    for base in base_list:
        best = base
        for hcn in HCN_LIST:
            scaled = base * hcn
            if scaled > max_acceptable_batch_size:
                break
            best = scaled
        candidates.add(best)
    return list(candidates)


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """All device counts w for which some micro-batch evenly tiles batch_size/w."""
    valid = set()
    for micro_batch in micro_batches:
        if batch_size % micro_batch != 0:
            continue
        max_count = batch_size // micro_batch
        # every divisor of max_count is a valid world size for this micro batch
        divisors = [max_count] + [i for i in range(1, max_count // 2 + 1)
                                  if max_count % i == 0]
        for count in divisors:
            if min_valid_gpus <= count <= max_valid_gpus:
                valid.add(count)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus, max_gpus,
                        prefer_larger):
    """Pick the candidate with the most valid device counts (ties broken by
    batch-size preference)."""
    best_num_valid = 0
    best_valid_gpus = None
    best_batch_size = int(min(micro_batches))

    for batch_size in candidate_batch_sizes:
        valid_gpus = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        better_tie = (len(valid_gpus) == best_num_valid and
                      ((prefer_larger and batch_size > best_batch_size) or
                       (not prefer_larger and batch_size < best_batch_size)))
        if len(valid_gpus) > best_num_valid or better_tie:
            best_num_valid = len(valid_gpus)
            best_valid_gpus = valid_gpus
            best_batch_size = batch_size
    return best_batch_size, best_valid_gpus


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                             min_gpus=None, max_gpus=None, prefer_larger=True):
    """v0.1 heuristic: candidate bases are each micro-batch plus their LCM,
    each scaled by the largest HCN fitting under the cap; the winner is the
    candidate compatible with the most device counts in [min_gpus, max_gpus]."""
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or int(max_acceptable_batch_size / min(micro_batches))

    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ValueError(
            "All micro batches must be <= max_acceptable_batch_size={}".format(
                max_acceptable_batch_size))

    base_list = list(micro_batches) + [_lcm(micro_batches)]
    candidates = get_candidate_batch_sizes(base_list, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus,
                               prefer_larger)


def _parse_version(version_str):
    matched = re.search(r"^(\d+)\.(\d+)(?:\.(\d+))?", version_str)
    if matched is None:
        raise ValueError(
            "Expecting major.minor[.patch] version format, got {}".format(
                version_str))
    return (int(matched.group(1)), int(matched.group(2)),
            int(matched.group(3) or 0))


def _compatible_ds_version_check(target_version):
    if _parse_version(target_version) < _parse_version(MINIMUM_DEEPSPEED_VERSION):
        raise ElasticityError(
            "Target version {} is below minimum {} supporting elasticity".format(
                target_version, MINIMUM_DEEPSPEED_VERSION))
    return True


def elasticity_enabled(ds_config):
    if ELASTICITY not in ds_config:
        return False
    return ds_config[ELASTICITY].get(ENABLED, ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Verify the resource scheduler saw the same elastic config we run with."""
    if DEEPSPEED_ELASTICITY_CONFIG not in os.environ:
        logger.warning(
            "DEEPSPEED_ELASTICITY_CONFIG env var not found; cannot guarantee "
            "the resource scheduler will scale this job with compatible counts.")
        return
    scheduler_config = ElasticityConfig(
        json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG]))
    runtime_config = ElasticityConfig(runtime_elastic_config_dict)
    for field in ("max_acceptable_batch_size", "micro_batches", "version"):
        sched_val = getattr(scheduler_config, field)
        run_val = getattr(runtime_config, field)
        if sched_val != run_val:
            raise ElasticityConfigError(
                "Elastic config {}={} seen by scheduler does not match runtime "
                "{}={}".format(field, sched_val, field, run_val))


def compute_elastic_config(ds_config, target_deepspeed_version, world_size=0):
    """Compute (final_batch_size, valid_gpus[, micro_batch]) for an elastic job.

    Deterministic for a given config; callable both from scheduling
    infrastructure and from the runtime (DeepSpeedConfig calls this when the
    elasticity block is enabled).
    """
    if not isinstance(ds_config, dict):
        raise ValueError(
            "Expected ds_config dict, got {}: {}".format(type(ds_config), ds_config))
    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(
            "'{}' is missing from config json".format(ELASTICITY))
    elastic_config_dict = ds_config[ELASTICITY]
    if not elastic_config_dict.get(ENABLED, ENABLED_DEFAULT):
        raise ElasticityConfigError("Elasticity is disabled ('enabled': false)")

    elastic_config = ElasticityConfig(elastic_config_dict)

    if float(elastic_config.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            "Elasticity version {} > latest supported {}".format(
                elastic_config.version, LATEST_ELASTICITY_VERSION))
    _compatible_ds_version_check(target_deepspeed_version)

    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size)
        final_batch_size = int(final_batch_size)
    else:
        raise NotImplementedError(
            "No elastic logic for version: {}".format(elastic_config.version))

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                "World size ({}) not in valid device counts: {}".format(
                    world_size, valid_gpus))
        micro_batch_size = None
        for mbsz in sorted(set(elastic_config.micro_batches), reverse=True):
            if final_batch_size // world_size % mbsz == 0:
                micro_batch_size = mbsz
                break
        if micro_batch_size is None:
            raise ElasticityError(
                "No divisible micro batch for world_size={}, batch={}, "
                "micro_batches={}".format(world_size, final_batch_size,
                                          elastic_config.micro_batches))
        return final_batch_size, valid_gpus, micro_batch_size

    return final_batch_size, valid_gpus
