"""``analysis`` ds_config section.

Validated with the telemetry section's no-silent-no-ops policy: unknown
keys warn, and raise under ``analysis.strict``. Shape::

    "analysis": {
      "strict": false,            // unsuppressed findings RAISE after an audit
      "report_path": null,        // write the JSON analysis report here
      "suppressions": null,       // path to the baseline-suppression file
      "hlo": false,               // audits also compile + census the HLO
      "donation_min_bytes": 1048576,   // donation findings below this stay quiet
      "census_min_bytes": 1024,        // collectives below this are noise
      "fp32_allowlist": [],       // GEMM prims allowed to run fp32 off bf16
      "concurrency": {            // ISSUE 15 sanitizer (dict | true | false)
        "enabled": false,         //   instrument the runtime's locks
        "stack_depth": 12,        //   frames kept per first-seen edge/finding
        "fingerprint": true       //   engine.audit() publishes the program
      }                           //   fingerprint into the host manifest
    }

The sharding/recompile thresholds are NOT duplicated here: the auditor
reads ``telemetry.programs.replicated_leaf_bytes`` and
``telemetry.programs.recompile_storm_threshold`` — the runtime compile
observatory and the ahead-of-time auditor share one rule
implementation (analysis/rules.py) and one threshold config, so the
two paths cannot drift.
"""
from .rules import (CENSUS_MIN_BYTES_DEFAULT, DONATION_MIN_BYTES_DEFAULT,
                    RECOMPILE_STORM_THRESHOLD_DEFAULT,
                    REPLICATED_LEAF_BYTES_DEFAULT)

ANALYSIS = "analysis"

KNOWN_ANALYSIS_KEYS = {
    "strict", "report_path", "suppressions", "hlo",
    "donation_min_bytes", "census_min_bytes", "fp32_allowlist",
    "concurrency",
}

KNOWN_CONCURRENCY_KEYS = {"enabled", "stack_depth", "fingerprint"}

CONCURRENCY_STACK_DEPTH_DEFAULT = 12


class DeepSpeedAnalysisConfig(object):
    """Typed view of the ``analysis`` section of a ds_config dict.

    ``telemetry_config`` (a ``DeepSpeedTelemetryConfig``) supplies the
    shared observatory thresholds when given; otherwise the shared
    defaults from ``analysis/rules.py`` apply."""

    def __init__(self, param_dict, telemetry_config=None):
        d = (param_dict or {}).get(ANALYSIS, {})
        if d is None:
            d = {}
        if not isinstance(d, dict):
            raise ValueError("analysis section must be a dict, got "
                             "{}".format(type(d).__name__))
        self.strict = bool(d.get("strict", False))
        unknown = sorted(k for k in d if k not in KNOWN_ANALYSIS_KEYS)
        if unknown:
            from ..telemetry.config import warn_or_raise_noop
            warn_or_raise_noop(
                "analysis.{} has NO effect: unknown key(s) in the "
                "'analysis' section (accepted: {})".format(
                    ", ".join(unknown), sorted(KNOWN_ANALYSIS_KEYS)),
                self.strict, flag="analysis.strict")

        self.report_path = d.get("report_path") or None
        self.suppressions = d.get("suppressions") or None
        self.hlo = bool(d.get("hlo", False))
        self.donation_min_bytes = self._pos_int(
            d, "donation_min_bytes", DONATION_MIN_BYTES_DEFAULT)
        self.census_min_bytes = self._pos_int(
            d, "census_min_bytes", CENSUS_MIN_BYTES_DEFAULT)
        allow = d.get("fp32_allowlist", [])
        if not isinstance(allow, (list, tuple)) or \
                not all(isinstance(x, str) for x in allow):
            raise ValueError(
                "analysis.fp32_allowlist must be a list of primitive "
                "names, got {!r}".format(allow))
        self.fp32_allowlist = tuple(allow)

        # concurrency sanitizer (docs/concurrency.md): dict | true |
        # false like the watchdog sub-keys — true enables with defaults
        conc = d.get("concurrency", False)
        if conc is True:
            conc = {}
        if conc is False or conc is None:
            self.concurrency_enabled = False
            self.concurrency_stack_depth = CONCURRENCY_STACK_DEPTH_DEFAULT
            self.concurrency_fingerprint = True
        elif isinstance(conc, dict):
            unknown = sorted(k for k in conc
                             if k not in KNOWN_CONCURRENCY_KEYS)
            if unknown:
                from ..telemetry.config import warn_or_raise_noop
                warn_or_raise_noop(
                    "analysis.concurrency.{} has NO effect: unknown "
                    "key(s) (accepted: {})".format(
                        ", ".join(unknown),
                        sorted(KNOWN_CONCURRENCY_KEYS)),
                    self.strict, flag="analysis.strict")
            self.concurrency_enabled = bool(conc.get("enabled", True))
            depth = conc.get("stack_depth",
                             CONCURRENCY_STACK_DEPTH_DEFAULT)
            if isinstance(depth, bool) or not isinstance(depth, int) \
                    or depth < 1:
                raise ValueError(
                    "analysis.concurrency.stack_depth must be an int "
                    ">= 1, got {!r}".format(depth))
            self.concurrency_stack_depth = depth
            self.concurrency_fingerprint = bool(
                conc.get("fingerprint", True))
        else:
            raise ValueError(
                "analysis.concurrency must be a dict or a bool, got "
                "{!r}".format(conc))

        # shared observatory thresholds (one config — see module doc)
        self.storm_threshold = getattr(
            telemetry_config, "programs_storm_threshold",
            RECOMPILE_STORM_THRESHOLD_DEFAULT)
        self.replicated_leaf_bytes = getattr(
            telemetry_config, "programs_replicated_leaf_bytes",
            REPLICATED_LEAF_BYTES_DEFAULT)

    @staticmethod
    def _pos_int(d, key, default):
        val = d.get(key, default)
        if isinstance(val, bool) or not isinstance(val, int) or val < 0:
            raise ValueError(
                "analysis.{} must be an int >= 0, got {!r}".format(
                    key, val))
        return val
