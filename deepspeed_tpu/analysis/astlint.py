"""Repo-wide AST hot-path linter (the ``bin/ds_lint.py`` core).

Static rules for the anti-patterns that degrade step time without ever
failing a test — each is a hazard the runtime telemetry can only see
AFTER the cost is paid:

  * **DSL001 time-in-traced-fn** — ``time.time()`` /
    ``time.monotonic()`` / ``time.perf_counter()`` inside a function
    NESTED in a ``*_fn`` builder (the repo's traced-program
    convention). Wall-clock reads trace as constants: the timing is a
    lie and the closure re-traces on nothing.
  * **DSL002 device-put-in-loop** — ``jax.device_put`` inside a
    ``for``/``while`` body: one un-jitted dispatch per leaf per
    iteration (the T3 finding the coalesced H2D batcher exists to
    kill; runtime/zero/transfer.py).
  * **DSL003 telemetry-gate-missing** — a ``<x>.telemetry.<attr>``
    read in a function with no ``telemetry``-None guard: the telemetry
    object is None whenever the config section is off, so the ungated
    access is a latent AttributeError on every production path.
  * **DSL004 jit-in-loop** — ``jax.jit(...)`` called inside a loop
    body: a fresh jit wrapper (and trace) per iteration; hoist the jit
    (or cache by key, the ``_get_jit`` pattern).
  * **DSL005 pallas-call-outside-ops** — a ``pl.pallas_call`` site
    outside ``deepspeed_tpu/ops/``: hand-written kernels live in ONE
    place (ops/pallas and the op packages; docs/pallas_kernels.md is
    the inventory), so dispatch layers import kernels rather than
    inlining them.
  * **DSL007 metric-name-outside-catalog** — a string-literal metric
    name passed to a ``.counter()``/``.gauge()``/``.histogram()``
    registry call that does not appear in docs/fleet.md's metric
    catalog: every exported series must be documented (name + labels)
    before it ships, or scrapers chase undocumented gauges
    (docs/fleet.md; the rule is inert when the catalog file is absent).
  * **DSL006 step-scheduling-outside-executor** — hand-written step
    scheduling outside ``deepspeed_tpu/runtime/executor/``: an async
    transfer issue (``copy_to_host_async``), a worker pool
    (``ThreadPoolExecutor`` / ``make_upload_pool``), or a donation
    declaration (a ``donate_argnums=`` call keyword). Since ISSUE 13
    the segment executor owns overlap construction, phase timing and
    donation for every step path; the surviving legacy sites (pipe
    engine, jit caches, the transfer batcher internals, the audit
    layer reading declarations) are baselined — NEW occurrences fail
    CI so new paths lower onto the executor instead of growing a
    seventh bespoke scheduler (docs/executor.md).

  * **DSL008 guarded-mutation-outside-lock** — a mutating call /
    subscript assign on a ``self.<attr>`` the class declares in its
    ``_GUARDED_BY`` map, with no enclosing ``with self.<lock>:`` for
    the declared lock. The static twin of the dynamic guarded-state
    checker (analysis/concurrency/locksan.py): the AST rule catches
    sites a run never exercised, the runtime proxy catches the threads
    the AST cannot see (``__init__`` is exempt — construction
    happens-before publication).
  * **DSL009 thread-without-daemon-story** — ``threading.Thread(...)``
    constructed without a ``daemon=`` keyword: the thread's lifetime is
    undeclared, and a non-daemon thread with no join/close path holds
    the interpreter open on every crash (docs/concurrency.md).
  * **DSL011 pallas-call-without-cost-estimate** — a ``pl.pallas_call``
    under ``deepspeed_tpu/ops/`` with no ``cost_estimate=`` keyword: a
    custom call XLA prices at zero flops silently corrupts MFU
    accounting and the bench scoreboard's regression gate the moment
    the kernel lands on a hot path. Every kernel declares its
    ``pl.CostEstimate`` (docs/pallas_kernels.md).
  * **DSL010 serving-field-outside-schema** — a dict literal tagged
    ``"kind": "serving_step"`` carrying a string key that is NOT in
    telemetry/record.py's pinned ``SERVING_STEP_KEYS`` /
    ``SERVING_SUBDICT_KEYS`` tables: a hand-rolled serving record with
    a freelance field ships a schema drift the validators then chase
    (record.py itself is exempt — it IS the schema; the rule is inert
    when the schema file is absent, so partial checkouts never
    false-fail).
  * **DSL012 knob-write-outside-controller** — an assignment to one of
    the closed-loop controller's managed tunables (``spec_k``,
    ``prefill_chunk_tokens``, ``prefill_buckets``, ``windows``,
    ``_h2d_bucket_elems``, ``_qwz_enabled``, ``_qgz_enabled``) outside
    ``deepspeed_tpu/runtime/controller/`` and the config parsers: a
    live retune that bypasses ``RuntimeController.apply_override``
    never lands in the decision ledger, so the run's behavior stops
    being replayable from ``controller_events.jsonl``
    (docs/controller.md). Construction-time sites are baselined.

Violations key as ``DSL###:<relpath>::<qualname>`` and count per key —
the committed baseline file maps keys to accepted counts, so existing
(reviewed) occurrences stay green while any NEW occurrence fails.
"""
import ast
import json
import os

from .findings import Finding

LINT_RULES = {
    "DSL001": "time-in-traced-fn",
    "DSL002": "device-put-in-loop",
    "DSL003": "telemetry-gate-missing",
    "DSL004": "jit-in-loop",
    "DSL005": "pallas-call-outside-ops",
    "DSL006": "step-scheduling-outside-executor",
    "DSL007": "metric-name-outside-catalog",
    "DSL008": "guarded-mutation-outside-lock",
    "DSL009": "thread-without-daemon-story",
    "DSL010": "serving-field-outside-schema",
    "DSL011": "pallas-call-without-cost-estimate",
    "DSL012": "knob-write-outside-controller",
}

# DSL008: mutating container methods (the static twin of the dynamic
# checker in concurrency/locksan.py — the AST rule catches the sites a
# run never exercised, the proxy catches the threads the AST cannot
# see)
_DSL008_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "add", "update",
    "setdefault", "sort", "reverse", "rotate",
})
# the class-level declaration both checkers read
_GUARDED_BY_NAME = "_GUARDED_BY"

# DSL007: registry-call method names + the metric-name literal shape
_METRIC_METHODS = {"counter", "gauge", "histogram"}
_METRIC_NAME_RE = None          # compiled lazily (module stays light)


def _looks_like_metric_name(text):
    global _METRIC_NAME_RE
    if _METRIC_NAME_RE is None:
        import re
        _METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
    return bool(_METRIC_NAME_RE.match(text))


def load_metric_catalog(base):
    """docs/fleet.md's text, the DSL007 catalog — None (rule inert)
    when the file is absent so partial checkouts never false-fail."""
    path = os.path.join(base or ".", "docs", "fleet.md")
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return fh.read()


# DSL010: the module that IS the serving-record schema (exempt from
# the rule), and the two pinned tables the rule reads out of it
_SERVING_SCHEMA_MODULE = "deepspeed_tpu/telemetry/record.py"


def load_serving_schema(base):
    """The serving-record field vocabulary (SERVING_STEP_KEYS +
    SERVING_SUBDICT_KEYS keys), AST-read from telemetry/record.py —
    None (DSL010 inert) when the schema file is absent or unreadable
    so partial checkouts never false-fail."""
    path = os.path.join(base or ".", *_SERVING_SCHEMA_MODULE.split("/"))
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        try:
            tree = ast.parse(fh.read(), filename=path)
        except SyntaxError:
            return None
    fields = set()
    for node in ast.iter_child_nodes(tree):
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if "SERVING_STEP_KEYS" in names and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            fields.update(
                elt.value for elt in node.value.elts
                if isinstance(elt, ast.Constant) and
                isinstance(elt.value, str))
        if "SERVING_SUBDICT_KEYS" in names and \
                isinstance(node.value, ast.Dict):
            fields.update(
                k.value for k in node.value.keys
                if isinstance(k, ast.Constant) and
                isinstance(k.value, str))
    return frozenset(fields) or None

# DSL005: the one directory kernels may live in
_OPS_PREFIX = "deepspeed_tpu/ops/"
# DSL006: the one directory step-scheduling machinery may live in
_EXECUTOR_PREFIX = "deepspeed_tpu/runtime/executor/"
# DSL012: the one directory live knob mutations may live in (the
# audited apply_override seam), plus the config parsers that SET the
# tunables at construction time
_CONTROLLER_PREFIX = "deepspeed_tpu/runtime/controller/"
_DSL012_CONFIG_MODULES = frozenset({
    "deepspeed_tpu/runtime/config.py",
    "deepspeed_tpu/inference/config.py",
})
# the controller-managed tunables' attribute names (the static twin of
# runtime/controller/ledger.py CONTROLLER_KNOBS — attribute spelling,
# not knob spelling; pinned by tests/unit/test_controller.py)
_DSL012_KNOB_ATTRS = frozenset({
    "spec_k", "prefill_chunk_tokens", "prefill_buckets", "windows",
    "_h2d_bucket_elems", "_qwz_enabled", "_qgz_enabled",
})

_TIME_FNS = {"time", "monotonic", "perf_counter"}


def _attr_chain(node):
    """Attribute node -> dotted string tail ('self.telemetry.spans')."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class _FunctionLint(ast.NodeVisitor):
    """Per-function-body state: loop depth, telemetry guards/uses,
    enclosing ``with <lock>`` scopes (DSL008)."""

    def __init__(self, linter, qualname, in_builder, guarded=None):
        self.linter = linter
        self.qualname = qualname
        self.in_builder = in_builder       # nested under a *_fn builder
        self.loop_depth = 0
        self.telemetry_guarded = False
        self.telemetry_aliases = set()
        self.telemetry_uses = []           # [lineno]
        # DSL008 state: the owning class's _GUARDED_BY map and the
        # stack of lock attr names entered via `with self.<lock>:`
        self.guarded = guarded or {}
        self.with_locks = []

    # ---- nested functions delegate back to the linter (fresh state)
    def visit_FunctionDef(self, node):
        self.linter.visit_function(
            node, self.qualname,
            self.in_builder or self.qualname.endswith("_fn"),
            guarded=self.guarded)

    visit_AsyncFunctionDef = visit_FunctionDef

    # ------------------------------------------------------------ DSL008
    def visit_With(self, node):
        entered = set()
        for item in node.items:
            expr = item.context_expr
            chain = _attr_chain(expr) if isinstance(expr, ast.Attribute) \
                else ""
            if chain.startswith("self."):
                entered.add(chain.split(".")[-1])
        self.with_locks.append(entered)
        self.generic_visit(node)
        self.with_locks.pop()

    visit_AsyncWith = visit_With

    def _held_locks(self):
        held = set()
        for scope in self.with_locks:
            held |= scope
        return held

    def _guarded_attr_of(self, node):
        """'attr' when ``node`` is ``self.<attr>`` and the class
        declares it _GUARDED_BY; None otherwise."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and node.attr in self.guarded:
            return node.attr
        return None

    def _check_guarded_mutation(self, attr, lineno, how):
        # __init__ builds the structure before any thread can see it
        if attr is None or self.qualname.endswith("__init__"):
            return
        lock = self.guarded[attr]
        if lock in self._held_locks():
            return
        self.linter.report(
            "DSL008", self.qualname, lineno,
            "self.{} mutated ({}) outside `with self.{}` — the class "
            "declares it _GUARDED_BY that lock "
            "(docs/concurrency.md)".format(attr, how, lock))

    def visit_AugAssign(self, node):
        tgt = node.target
        if isinstance(tgt, ast.Subscript):
            self._check_guarded_mutation(
                self._guarded_attr_of(tgt.value), node.lineno,
                "augmented subscript assign")
        self._check_knob_write(tgt, node.lineno)
        self.generic_visit(node)

    # ------------------------------------------------------------ DSL012
    def _check_knob_write(self, tgt, lineno):
        if self.linter.knob_exempt:
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._check_knob_write(elt, lineno)
            return
        node = tgt
        if isinstance(node, ast.Subscript):
            node = node.value      # windows["h2d"] = 4 writes `windows`
        if isinstance(node, ast.Attribute) and \
                node.attr in _DSL012_KNOB_ATTRS:
            self.linter.report(
                "DSL012", self.qualname, lineno,
                "controller-managed tunable .{} written outside "
                "runtime/controller/ — a live retune must go through "
                "RuntimeController.apply_override so the move lands in "
                "the decision ledger (docs/controller.md)".format(
                    node.attr))

    def visit_For(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_While = visit_For

    def visit_Assign(self, node):
        # alias: tel = self.telemetry (guards on the alias count)
        if isinstance(node.value, ast.Attribute) and \
                node.value.attr == "telemetry":
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.telemetry_aliases.add(tgt.id)
        # DSL008: self.<guarded>[k] = v outside the declared lock
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                self._check_guarded_mutation(
                    self._guarded_attr_of(tgt.value), node.lineno,
                    "subscript assign")
            self._check_knob_write(tgt, node.lineno)
        self.generic_visit(node)

    def _guards_telemetry(self, expr):
        """Whether ``expr`` mentions telemetry (or an alias), through
        ``not`` and boolean composition — a truthiness test like
        ``if self.telemetry:`` IS a None-gate in idiomatic Python."""
        if isinstance(expr, (ast.Attribute, ast.Name)):
            chain = _attr_chain(expr)
            return "telemetry" in chain or \
                chain in self.telemetry_aliases
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
            return self._guards_telemetry(expr.operand)
        if isinstance(expr, ast.BoolOp):
            return any(self._guards_telemetry(v) for v in expr.values)
        return False

    def visit_Compare(self, node):
        # <expr> is [not] None where <expr> mentions telemetry/an alias
        if any(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            for operand in [node.left] + list(node.comparators):
                if self._guards_telemetry(operand):
                    self.telemetry_guarded = True
        self.generic_visit(node)

    def visit_If(self, node):
        if self._guards_telemetry(node.test):
            self.telemetry_guarded = True
        self.generic_visit(node)

    def visit_IfExp(self, node):
        if self._guards_telemetry(node.test):
            self.telemetry_guarded = True
        self.generic_visit(node)

    def visit_Attribute(self, node):
        # <x>.telemetry.<attr> read
        if isinstance(node.value, ast.Attribute) and \
                node.value.attr == "telemetry":
            self.telemetry_uses.append(node.lineno)
        self.generic_visit(node)

    # ------------------------------------------------------------ DSL010
    def visit_Dict(self, node):
        schema = self.linter.serving_schema
        if schema is not None and not self.linter.is_serving_schema:
            keys = {}
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    keys[k.value] = v
            kind = keys.get("kind")
            if isinstance(kind, ast.Constant) and \
                    kind.value == "serving_step":
                for name in sorted(set(keys) - set(schema)):
                    self.linter.report(
                        "DSL010", self.qualname, node.lineno,
                        "serving_step record literal carries field "
                        "{!r} outside telemetry/record.py's pinned "
                        "SERVING_STEP_KEYS/SERVING_SUBDICT_KEYS — "
                        "extend the schema tables (and their stdlib "
                        "copies) instead of freelancing a "
                        "field".format(name))
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = node.func
        chain = _attr_chain(fn) if isinstance(fn, ast.Attribute) else ""
        if chain.startswith("time.") and \
                chain.split(".")[-1] in _TIME_FNS and self.in_builder:
            self.linter.report("DSL001", self.qualname, node.lineno,
                               "{}() inside a traced-fn builder body "
                               "traces as a constant".format(chain))
        if chain.endswith(".device_put") and self.loop_depth > 0:
            self.linter.report("DSL002", self.qualname, node.lineno,
                               "jax.device_put inside a loop body — one "
                               "un-jitted dispatch per iteration "
                               "(coalesce via the H2D batcher)")
        if chain == "jax.jit" and self.loop_depth > 0:
            self.linter.report("DSL004", self.qualname, node.lineno,
                               "jax.jit inside a loop body — a fresh "
                               "trace per iteration (hoist or cache by "
                               "key)")
        is_pallas_call = chain.endswith(".pallas_call") or (
            isinstance(fn, ast.Name) and fn.id == "pallas_call")
        catalog = self.linter.metric_catalog
        if catalog is not None and isinstance(fn, ast.Attribute) and \
                fn.attr in _METRIC_METHODS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str) and \
                    _looks_like_metric_name(arg.value) and \
                    arg.value not in catalog:
                self.linter.report(
                    "DSL007", self.qualname, node.lineno,
                    "metric {!r} is not in docs/fleet.md's catalog — "
                    "document every exported series (name + labels) "
                    "before shipping it".format(arg.value))
        if is_pallas_call and not self.linter.in_ops:
            self.linter.report("DSL005", self.qualname, node.lineno,
                               "pl.pallas_call outside deepspeed_tpu/"
                               "ops/ — kernels live in one place "
                               "(ops/pallas; docs/pallas_kernels.md)")
        # DSL011: every kernel in ops/ must declare its price — a
        # custom call without a CostEstimate reads as zero flops to
        # XLA's cost model, silently corrupting MFU and the scoreboard
        # regression gate the moment the kernel lands on a hot path.
        if is_pallas_call and self.linter.in_ops and \
                not any(kw.arg == "cost_estimate" for kw in node.keywords):
            self.linter.report(
                "DSL011", self.qualname, node.lineno,
                "pl.pallas_call without cost_estimate= — a zero-flop "
                "custom call corrupts MFU pricing and the scoreboard "
                "gate (pass pl.CostEstimate(flops=..., "
                "bytes_accessed=..., transcendentals=...); "
                "docs/pallas_kernels.md)")
        # DSL008: mutating-method call on a declared-guarded attribute
        if isinstance(fn, ast.Attribute) and fn.attr in _DSL008_MUTATORS:
            self._check_guarded_mutation(
                self._guarded_attr_of(fn.value), node.lineno,
                ".{}()".format(fn.attr))
        # DSL009: a thread constructed with no daemon story — a
        # non-daemon thread with no declared join/close path holds the
        # interpreter open on every crash (the repo's threads are
        # daemon + joined-with-timeout in close(); a reviewed baseline
        # entry is how a deliberate non-daemon thread ships)
        if chain == "threading.Thread" and \
                not any(kw.arg == "daemon" for kw in node.keywords):
            self.linter.report(
                "DSL009", self.qualname, node.lineno,
                "threading.Thread(...) without daemon= — declare the "
                "thread's lifetime (daemon=True, or daemon=False with "
                "a reviewed join/close story; docs/concurrency.md)")
        if not self.linter.in_executor:
            name_id = fn.id if isinstance(fn, ast.Name) else ""
            sched = None
            # split-tail match: a subscripted receiver
            # (bufs[0].copy_to_host_async()) truncates the chain to the
            # bare attribute name
            if chain.split(".")[-1] == "copy_to_host_async":
                sched = "async transfer issue (copy_to_host_async)"
            elif chain.endswith("ThreadPoolExecutor") or \
                    name_id == "ThreadPoolExecutor":
                sched = "worker pool (ThreadPoolExecutor)"
            elif chain.endswith("make_upload_pool") or \
                    name_id == "make_upload_pool":
                sched = "upload worker (make_upload_pool)"
            elif any(kw.arg == "donate_argnums"
                     for kw in node.keywords):
                sched = "donation declaration (donate_argnums=)"
            if sched:
                self.linter.report(
                    "DSL006", self.qualname, node.lineno,
                    "{} outside deepspeed_tpu/runtime/executor/ — "
                    "step scheduling lowers onto the segment executor "
                    "(docs/executor.md)".format(sched))
        self.generic_visit(node)

    def finish(self):
        if self.telemetry_uses and not self.telemetry_guarded:
            self.linter.report(
                "DSL003", self.qualname, self.telemetry_uses[0],
                "reads .telemetry.<attr> with no is-None gate in the "
                "function — telemetry is None whenever the config "
                "section is off")


class FileLinter:
    def __init__(self, relpath, metric_catalog=None,
                 serving_schema=None):
        self.relpath = relpath
        norm = relpath.replace(os.sep, "/")
        self.in_ops = norm.startswith(_OPS_PREFIX)
        self.in_executor = norm.startswith(_EXECUTOR_PREFIX)
        self.metric_catalog = metric_catalog
        self.serving_schema = serving_schema
        self.is_serving_schema = norm == _SERVING_SCHEMA_MODULE
        self.knob_exempt = norm.startswith(_CONTROLLER_PREFIX) or \
            norm in _DSL012_CONFIG_MODULES
        self.violations = []       # [(rule, qualname, lineno, message)]

    def report(self, rule, qualname, lineno, message):
        self.violations.append((rule, qualname, lineno, message))

    def visit_function(self, node, parent_qual, in_builder,
                       guarded=None):
        qual = "{}.{}".format(parent_qual, node.name) if parent_qual \
            else node.name
        state = _FunctionLint(self, qual, in_builder, guarded=guarded)
        for stmt in node.body:
            state.visit(stmt)
        state.finish()

    @staticmethod
    def _guarded_decl(class_node):
        """The class's ``_GUARDED_BY`` literal ({attr: lock_attr}), or
        {} — the DSL008 declaration (shared with the dynamic checker,
        concurrency/locksan.py)."""
        for stmt in class_node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and
                       t.id == _GUARDED_BY_NAME for t in stmt.targets):
                continue
            if not isinstance(stmt.value, ast.Dict):
                return {}
            decl = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str) and \
                        isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    decl[k.value] = v.value
            return decl
        return {}

    def run(self, tree):
        # walk module/class levels; functions get per-body state
        def top(node, prefix, guarded):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    self.visit_function(child, prefix, False,
                                        guarded=guarded)
                elif isinstance(child, ast.ClassDef):
                    name = "{}.{}".format(prefix, child.name) if prefix \
                        else child.name
                    top(child, name, self._guarded_decl(child))
        top(tree, "", {})
        return self.violations


def lint_file(path, relpath=None, metric_catalog=None,
              serving_schema=None):
    relpath = relpath or path
    with open(path) as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as err:
        return [("DSL000", "<module>", getattr(err, "lineno", 0),
                 "unparseable: {}".format(err))]
    return FileLinter(relpath, metric_catalog=metric_catalog,
                      serving_schema=serving_schema).run(tree)


def lint_paths(paths, base=None, metric_catalog=None,
               serving_schema=None):
    """-> {key: [Finding, ...]} over every .py file under ``paths``
    (key = 'RULE:relpath::qualname'; ``base`` anchors the relpaths —
    pass the repo root so baseline keys are stable under any cwd).
    ``metric_catalog``: DSL007's documented-name text; defaults to
    ``base``/docs/fleet.md when present. ``serving_schema``: DSL010's
    field vocabulary; defaults to the tables AST-read from
    ``base``/deepspeed_tpu/telemetry/record.py when present."""
    findings = {}
    files = []
    for root in paths:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, _, names in os.walk(root):
            files += [os.path.join(dirpath, n) for n in sorted(names)
                      if n.endswith(".py")]
    base = base or os.getcwd()
    if metric_catalog is None:
        metric_catalog = load_metric_catalog(base)
    if serving_schema is None:
        serving_schema = load_serving_schema(base)
    for path in sorted(files):
        rel = os.path.relpath(path, base)
        for rule, qual, lineno, message in lint_file(
                path, rel, metric_catalog=metric_catalog,
                serving_schema=serving_schema):
            key = "{}:{}::{}".format(rule, rel.replace(os.sep, "/"), qual)
            findings.setdefault(key, []).append(Finding(
                rule=rule, check=LINT_RULES.get(rule, rule),
                program=rel.replace(os.sep, "/"),
                message="{}:{} [{}] {}".format(rel, lineno, rule, message),
                key=key,
                details={"line": lineno, "qualname": qual}))
    return findings


def load_baseline(path):
    if path is None or not os.path.exists(path):
        return {}
    with open(path) as fh:
        payload = json.load(fh)
    if not isinstance(payload, dict) or \
            not isinstance(payload.get("violations"), dict):
        raise ValueError(
            "{}: baseline must be an object with a 'violations' "
            "map".format(path))
    return {str(k): int(v) for k, v in payload["violations"].items()}


def write_baseline(path, findings):
    payload = {
        "comment": "ds_lint baseline: accepted (reviewed) hot-path lint "
                   "occurrences by key; regenerate with "
                   "bin/ds_lint.py --write-baseline",
        "violations": {k: len(v) for k, v in sorted(findings.items())},
    }
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def diff_baseline(findings, baseline):
    """-> (new, stale): findings above their baselined count, and
    baseline keys no longer observed (candidates to prune)."""
    new = []
    for key, items in sorted(findings.items()):
        allowed = baseline.get(key, 0)
        if len(items) > allowed:
            new.extend(items[allowed:])
    stale = sorted(k for k in baseline if k not in findings)
    return new, stale
