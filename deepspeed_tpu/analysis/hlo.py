"""Post-optimization HLO collective census.

Parses the compiled module text of a jitted program (``jax.jit(fn)
.lower(*structs).compile().as_text()`` — the per-device SPMD program
AFTER XLA's partitioner ran) and prices every collective instruction
with the same ring formulas ``runtime/comm/wire.py`` uses, so the
analytic wire estimator can finally be ground-truthed against what XLA
actually emits:

  * ``all-gather``          result_bytes * (g-1)/g
  * ``all-reduce``          result_bytes * 2(g-1)/g
  * ``reduce-scatter``      result_bytes * (g-1)      (input = g*result)
  * ``collective-permute``  result_bytes              (one ring hop)
  * ``all-to-all``          result_bytes * (g-1)/g

Each op is attributed to the mesh axis (or axis set) its replica groups
span — ``parallel.topology.mesh_axis_groups`` computes the ground-truth
device groupings per axis — so ZeRO's data-axis wire classes separate
cleanly from tensor-parallel (model-axis) traffic the estimator never
prices. ``reconcile_wire`` then diffs the census against
``estimate_step_comm_bytes``'s classes: collectives in the HLO the
estimator did not price (and vice versa) become findings.
"""
import re

import numpy as np

from .findings import Finding
from .rules import CENSUS_MIN_BYTES_DEFAULT

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

COLLECTIVE_OPS = (
    "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")
# tuple result shapes stop at the first ')' — long tuples carry
# '/*index=N*/' comments (so '[^=]*' would reject them), but never
# nested parens
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\([^()]*\)|[a-z]+[0-9]*\[[0-9,]*\]"
    r"(?:\{[^}]*\})?)\s+([a-z0-9\-]+)(?:-start)?\(", re.M)
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[\d,{}\s]*\}\}|\[[\d,]+\]<=\[[\d,]+\]"
    r"(?:T\(([\d,]+)\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([\d,{}\s]*)\}")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')


def _element_bytes(shape_text):
    """One HLO shape (or tuple-of-shapes) -> per-element byte sizes."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue                       # token/opaque/etc
        numel = 1
        if dims:
            numel = int(np.prod([int(d) for d in dims.split(",")],
                                dtype=np.int64))
        sizes.append(numel * _DTYPE_BYTES[dtype])
    return sizes


def _shape_bytes(shape_text):
    """One HLO shape (or tuple-of-shapes) -> total bytes per device."""
    return sum(_element_bytes(shape_text))


def _result_bytes(shape_text, opcode, is_async):
    """The RESULT size of one collective instruction. Async ``-start``
    ops carry tuple shapes bundling operand + result (+ u32 scratch):
    summing them would overprice the wire (operand + result per op).
    The result is the LARGEST element for gather-like ops (output >=
    input) and the SMALLEST for reduce-scatter (output = input / g);
    sync single-shape ops pass through unchanged."""
    sizes = _element_bytes(shape_text)
    if not sizes:
        return 0
    if not is_async:
        return sum(sizes)
    return min(sizes) if opcode == "reduce-scatter" else max(sizes)


def _parse_replica_groups(text):
    """replica_groups attribute -> list of frozenset(device ids)."""
    m = _GROUPS_RE.search(text)
    if not m:
        return None
    body = m.group(1)
    if body.startswith("{{") or body.startswith("{"):
        groups = []
        for grp in re.findall(r"\{([\d,\s]*)\}", body):
            ids = [int(x) for x in grp.replace(" ", "").split(",") if x]
            if ids:
                groups.append(frozenset(ids))
        return groups
    # iota form: [G,S]<=[dims] or [G,S]<=[dims]T(perm)
    m2 = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", body)
    if not m2:
        return None
    out_dims = [int(x) for x in m2.group(1).split(",")]
    src_dims = [int(x) for x in m2.group(2).split(",")]
    ids = np.arange(int(np.prod(src_dims, dtype=np.int64)))
    ids = ids.reshape(src_dims)
    if m2.group(3):
        perm = [int(x) for x in m2.group(3).split(",")]
        ids = ids.transpose(perm)
    ids = ids.reshape(out_dims)
    return [frozenset(int(d) for d in row) for row in ids]


def _parse_permute_groups(text):
    """source_target_pairs -> connected components (the ring groups)."""
    m = _PAIRS_RE.search(text)
    if not m:
        return None
    pairs = re.findall(r"\{(\d+)\s*,\s*(\d+)\}", m.group(0))
    if not pairs:
        return None
    parent = {}

    def find(x):
        parent.setdefault(x, x)
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in pairs:
        ra, rb = find(int(a)), find(int(b))
        if ra != rb:
            parent[ra] = rb
    comps = {}
    for node in list(parent):
        comps.setdefault(find(node), set()).add(node)
    return [frozenset(c) for c in comps.values()]


def _wire_bytes(opcode, result_bytes, group_size):
    g = max(int(group_size), 1)
    ring = (g - 1) / g if g > 1 else 0.0
    if opcode == "all-gather":
        return result_bytes * ring
    if opcode == "all-reduce":
        return result_bytes * 2 * ring
    if opcode == "reduce-scatter":
        return result_bytes * (g - 1)
    if opcode == "collective-permute":
        return float(result_bytes)
    if opcode == "all-to-all":
        return result_bytes * ring
    return 0.0


def classify_groups(groups, axis_groups):
    """Match an op's replica groups against the mesh's per-axis(-set)
    ground truth. ``axis_groups``: {label: [frozenset(ids), ...]}."""
    if not groups:
        return "unknown"
    got = set(groups)
    for label, truth in axis_groups.items():
        if got <= set(truth):
            return label
    all_ids = frozenset().union(*groups)
    if len(groups) == 1 and all(len(g) > 1 for g in groups):
        return "world" if len(all_ids) > 1 else "self"
    return "other"


def collective_census(hlo_text, axis_groups=None,
                      min_bytes=CENSUS_MIN_BYTES_DEFAULT):
    """-> {"ops": [...], "by_axis": {...}, "total_bytes": int}.

    ``ops`` lists every collective instruction at/above ``min_bytes``
    wire volume with its opcode, per-device wire bytes (ring pricing),
    group size and mesh-axis attribution; smaller ops aggregate into
    ``below_threshold_bytes`` so nothing silently disappears.
    """
    axis_groups = axis_groups or {}
    ops = []
    below = 0.0
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        shape_text, opcode = m.group(1), m.group(2)
        is_async = opcode.endswith("-start")
        if is_async:
            opcode = opcode[:-len("-start")]
        if opcode not in COLLECTIVE_OPS:
            continue
        if opcode == "collective-permute":
            groups = _parse_permute_groups(line)
        else:
            groups = _parse_replica_groups(line)
        gsize = max((len(g) for g in groups), default=1) if groups else 1
        result_bytes = _result_bytes(shape_text, opcode, is_async)
        wire = _wire_bytes(opcode, result_bytes, gsize)
        axis = classify_groups(groups, axis_groups)
        in_loop = "while" in line or "body" in line.split("=")[0]
        if wire < min_bytes:
            below += wire
            continue
        name_m = _OP_NAME_RE.search(line)
        op_name = name_m.group(1) if name_m else ""
        ops.append({
            "opcode": opcode,
            "wire_bytes": int(round(wire)),
            "result_bytes": int(result_bytes),
            "group_size": int(gsize),
            "axis": axis,
            "in_loop": bool(in_loop),
            # hand-written shard_map collectives (the quantized/1-bit
            # exchanges, ring bodies) — deterministic bytes the compiler
            # cannot reshape, vs GSPMD-inserted resharding it can
            "explicit": "shmap_body" in op_name,
            "op_name": op_name[-80:],
        })
    by_axis = {}
    for op in ops:
        slot = by_axis.setdefault(op["axis"], {"ops": 0, "wire_bytes": 0})
        slot["ops"] += 1
        slot["wire_bytes"] += op["wire_bytes"]
    return {
        "ops": ops,
        "by_axis": by_axis,
        "total_bytes": int(sum(op["wire_bytes"] for op in ops)),
        "below_threshold_bytes": int(round(below)),
    }


def census_classes(census, data_labels, normalize_allreduce=False):
    """Fold one census into the wire estimator's class vocabulary for
    the DATA-axis labels: explicit gathers -> allgather, reductions ->
    reduce, ring ppermute hops -> ring (our own decompositions — the
    caller knows whether its rings serve gathers, reductions or both).

    ``normalize_allreduce``: price data-axis all-reduces at their
    reduce-scatter ring equivalent (half). Backends without XLA's
    ReduceScatterCreator pass (the CPU rung) leave GSPMD's
    all-reduce+dynamic-slice unrewritten where the TPU target emits a
    true reduce-scatter; pass True when the plan shards the gradients
    (stage >= 2) so the CPU census compares against the TPU-target
    model. The raw per-op list keeps the unnormalized bytes.
    """
    out = {"allgather_bytes": 0, "reduce_bytes": 0, "ring_bytes": 0,
           "data_other_bytes": 0, "other_axis_bytes": 0,
           "explicit_bytes": 0}
    for op in census["ops"]:
        if op.get("explicit") and op["axis"] in data_labels:
            # our hand-written shard_map collectives (quantized / 1-bit
            # exchange bodies): tallied separately — their bytes are
            # deterministic and must equal the estimator EXACTLY
            out["explicit_bytes"] += op["wire_bytes"]
        if op["axis"] not in data_labels:
            out["other_axis_bytes"] += op["wire_bytes"]
            continue
        if op["opcode"] == "all-gather":
            out["allgather_bytes"] += op["wire_bytes"]
        elif op["opcode"] in ("all-reduce", "reduce-scatter"):
            wire = op["wire_bytes"]
            if normalize_allreduce and op["opcode"] == "all-reduce":
                wire //= 2
            out["reduce_bytes"] += wire
        elif op["opcode"] == "collective-permute":
            out["ring_bytes"] += op["wire_bytes"]
        else:
            # a data-axis collective in NO wire class (e.g. a GSPMD
            # resharding all-to-all) is exactly the "unplanned
            # collective behind your back" this census exists to catch
            # — it must count toward the reconciled total
            out["data_other_bytes"] += op["wire_bytes"]
    out["data_total_bytes"] = (out["allgather_bytes"] +
                               out["reduce_bytes"] + out["ring_bytes"] +
                               out["data_other_bytes"])
    return out


def reconcile_wire(census_list, wire_est, data_labels, program="step",
                   min_bytes=CENSUS_MIN_BYTES_DEFAULT,
                   normalize_allreduce=False):
    """Diff the summed HLO census of one optimizer step's programs
    against the wire estimator's per-step classes.

    Returns (payload, findings): the payload embeds both sides and the
    per-class deltas; findings flag collectives the estimator did not
    price (census > estimate) and estimates the HLO does not back
    (estimate > census). ``normalize_allreduce``: see
    :func:`census_classes` — pass True when the plan shards the grads
    (stage >= 2) and the backend lacks the all-reduce->reduce-scatter
    rewrite.
    """
    classes = {"allgather_bytes": 0, "reduce_bytes": 0, "ring_bytes": 0,
               "data_other_bytes": 0, "other_axis_bytes": 0,
               "explicit_bytes": 0, "data_total_bytes": 0}
    for census in census_list:
        part = census_classes(census, data_labels,
                              normalize_allreduce=normalize_allreduce)
        for key in classes:
            classes[key] += part[key]
    est_ag = int(wire_est.get("allgather_bytes_per_step",
                              wire_est.get("allgather_bytes", 0)) or 0)
    est_rs = int(wire_est.get("reduce_bytes_per_step",
                              wire_est.get("reduce_bytes", 0)) or 0)
    # the compressed-comm tier's classes (wire.py): the in-collective
    # quantized gradient exchange reprices the reduce class (flat or the
    # hierarchical two-level formula — quantized_allreduce_bytes); the
    # 1-bit momentum exchange is its own class. Census-side these land
    # as data-axis collective-permutes (ring hops -> ring_bytes),
    # all-to-alls (the sign exchange -> data_other_bytes) and
    # all-gathers, so only the TOTAL reconciles class-exactly.
    est_opt = int(wire_est.get("optimizer_bytes_per_step", 0) or 0)
    est_total = est_ag + est_rs + est_opt
    payload = {
        "program": program,
        "estimator": {"allgather_bytes": est_ag, "reduce_bytes": est_rs,
                      "optimizer_bytes": est_opt,
                      "total_bytes": est_total},
        "quantized": bool(est_opt or
                          wire_est.get("quantized_collectives")),
        "hlo": classes,
        "delta_total_bytes": classes["data_total_bytes"] - est_total,
        "match_total": classes["data_total_bytes"] == est_total,
        # per-class comparison is only meaningful when no ring hops blur
        # the attribution (a ppermute ring can serve either class)
        "match_classes": (classes["ring_bytes"] == 0 and
                          classes["allgather_bytes"] == est_ag and
                          classes["reduce_bytes"] == est_rs),
        # the explicitly-decomposed class: when the program's stage-3
        # gathers run as OUR ppermute rings (collective_matmul), the
        # ring bytes are deterministic and must equal the estimator's
        # allgather class exactly — the byte-for-byte census contract
        # the dryrun analysis leg pins (None when no rings ran, or when
        # the rings serve the QUANTIZED reduce class instead)
        "match_ring_allgather": (classes["ring_bytes"] == est_ag
                                 if classes["ring_bytes"] and not est_opt
                                 and not wire_est.get(
                                     "quantized_collectives") else None),
        # the compressed-comm contract: the hand-written shard_map
        # exchanges (1-bit momentum + in-collective quantized reduce,
        # incl. the hierarchical two-level decomposition) have
        # deterministic instruction-level bytes — the census must equal
        # the estimator's exchange classes EXACTLY. None when no
        # quantized exchange is priced.
        "match_exchange": (
            classes["explicit_bytes"] == est_opt +
            (est_rs if wire_est.get("quantized_collectives") else 0)
            if (est_opt or wire_est.get("quantized_collectives"))
            else None),
    }
    findings = []
    if classes["data_total_bytes"] > est_total and \
            classes["data_total_bytes"] - est_total >= min_bytes:
        findings.append(Finding(
            rule="sharding_drift", check="unpriced_collective",
            program=program,
            message="the lowered step moves {:,} data-axis collective "
                    "bytes but the wire estimator prices {:,} — XLA "
                    "inserted {:,} bytes of collectives the plan did not "
                    "anticipate (an unplanned all-gather behind your "
                    "back)".format(classes["data_total_bytes"], est_total,
                                   classes["data_total_bytes"] - est_total),
            key="unpriced_collective:{}".format(program),
            details=payload))
    elif est_total > classes["data_total_bytes"] and \
            est_total - classes["data_total_bytes"] >= min_bytes:
        findings.append(Finding(
            rule="sharding_drift", check="overpriced_estimate",
            program=program,
            message="the wire estimator prices {:,} data-axis collective "
                    "bytes but the lowered step only moves {:,} — the "
                    "estimator books collectives XLA never emits (its "
                    "model has drifted from the program)".format(
                        est_total, classes["data_total_bytes"]),
            key="overpriced_estimate:{}".format(program),
            details=payload))
    return payload, findings
