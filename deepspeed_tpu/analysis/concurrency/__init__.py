"""Concurrency sanitizer + SPMD divergence auditor (ISSUE 15;
docs/concurrency.md).

Two halves, both reporting through the PR 10 ``Finding``/suppression
machinery:

* :mod:`locksan` — an opt-in instrumented shim over the ``threading``
  locks the runtime already creates: lock-order cycles, locks held
  across blocking calls, signal-handler acquisition of non-reentrant
  locks, and declared-guarded state accessed without its lock
  (``_GUARDED_BY`` — the one declaration per class the DSL008 AST rule
  also reads). Off = structurally absent.
* :mod:`divergence` — per-host program fingerprints over the fleet's
  collective order, derived from the shard-lint IR walk + lowered
  segment plans, published in the host manifest, verified across hosts
  by ``telemetry/fleet/aggregate.py`` + ``bin/ds_fleet.py`` (which
  stay stdlib-only; this package supplies derivation + findings).
"""
from .divergence import (FINGERPRINT_KEYS, FINGERPRINT_VERSION,
                         audit_fleet, canonical_fingerprint,
                         collective_tokens, divergence_findings,
                         fingerprint_engine, plan_tokens,
                         publish_fingerprint, validate_fingerprint)
from .locksan import (GUARDED_BY_ATTR, LockSanitizer, SanLock, current,
                      guarded, install, instrument_collector, new_lock,
                      new_rlock, note_blocking, signal_scope, uninstall)

__all__ = [
    "LockSanitizer", "SanLock", "GUARDED_BY_ATTR", "current", "install",
    "uninstall", "new_lock", "new_rlock", "guarded", "note_blocking",
    "signal_scope", "instrument_collector",
    "FINGERPRINT_KEYS", "FINGERPRINT_VERSION", "canonical_fingerprint",
    "collective_tokens", "plan_tokens", "fingerprint_engine",
    "publish_fingerprint", "divergence_findings", "audit_fleet",
    "validate_fingerprint",
]
