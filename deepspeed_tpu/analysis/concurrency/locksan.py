"""Lock sanitizer: an opt-in instrumented shim over the ``threading``
locks the runtime already creates (docs/concurrency.md is the lock
inventory and rule catalog).

The runtime is genuinely concurrent — the PlanExecutor worker pools,
the H2D upload worker, the watchdog deadline thread, the recorder's
signal-handler dumps, the metrics exporter's HTTP handler threads —
and every concurrency bug so far (the SIGTERM RLock self-deadlock, the
deque-mutated-during-dump race, the histogram aliased mid-scrape) was
caught by human review after the fact. This module makes three whole
bug classes observable AHEAD of the hang:

* **lock_order_cycle** — the global lock-order graph (edge A->B when B
  is acquired while A is held, acquisition stack kept for the first
  observation of each edge) contains a cycle: two threads taking the
  same pair of locks in opposite orders WILL deadlock under the right
  interleaving, whether or not this run hit it.
* **held_blocking** — a sanitized lock was held across a declared
  blocking call (a worker-future wait, a device sync, an H2D drain, a
  crash-bundle file write): every other thread touching that lock
  stalls behind IO it has no part in, and a blocked dump can wedge the
  dying process.
* **signal_unsafe** — a signal handler acquired a NON-reentrant
  sanitized lock: the signal can land while the interrupted main-thread
  frame already holds it, and the handler self-deadlocks the process
  (the exact bug that forced the recorder ring onto an RLock).
* **guarded_race** — a structure declared ``_GUARDED_BY`` its class was
  mutated (or iterated) without its guarding lock held by the current
  thread (the deque-mutated-during-dump class, caught at the racy
  access instead of the crashed iteration).

OFF = structurally absent: :func:`new_lock`/:func:`new_rlock` return
plain ``threading`` locks, :func:`guarded` returns the container
unchanged, and :func:`note_blocking` is one module-global ``is None``
check — the PR 8 subsystem contract. ON (``analysis.concurrency`` in
the ds_config, or an explicit :func:`install`), every acquisition costs
a thread-local list append; stacks are captured only on the FIRST
observation of an edge or finding, so the steady state stays inside
the telemetry <5% budget (measured by the dryrun concurrency leg).

Findings ride the PR 10 machinery: :meth:`LockSanitizer.report`
returns :class:`~..findings.Finding` objects that route through the
usual suppression file and raise under ``analysis.strict``
(docs/concurrency.md documents the suppression policy).

Stdlib-only by construction (``from ..findings import Finding`` is the
only sibling import), so the sanitizer itself can never drag jax into
a thread it instruments.
"""
import threading
import time
import traceback

from ..findings import Finding

RULE = "concurrency"

# findings the sanitizer can produce (docs/concurrency.md rule catalog)
CHECKS = ("lock_order_cycle", "held_blocking", "signal_unsafe",
          "guarded_race")

STACK_DEPTH_DEFAULT = 12

# class-level declaration read by the dynamic checker AND the DSL008
# AST rule: {attr_name: lock_attr_name}
GUARDED_BY_ATTR = "_GUARDED_BY"

# the process-global active sanitizer; None = off = every seam below is
# a single is-None check (the zero-overhead-off contract)
_ACTIVE = None


# ------------------------------------------------------------- seams
def current():
    """The installed :class:`LockSanitizer`, or None (off)."""
    return _ACTIVE


def install(sanitizer):
    """Install ``sanitizer`` process-globally (idempotent when the same
    instance is already active). Locks created via :func:`new_lock` /
    :func:`new_rlock` AFTER this point are instrumented."""
    global _ACTIVE
    if _ACTIVE is not None and _ACTIVE is not sanitizer:
        raise RuntimeError(
            "a lock sanitizer is already installed — uninstall() it "
            "first (the lock-order graph is process-global by design)")
    _ACTIVE = sanitizer
    return sanitizer


def uninstall():
    """Remove the active sanitizer (tests; already-wrapped locks keep
    working — they hold their own sanitizer reference — but new locks
    come out plain)."""
    global _ACTIVE
    san = _ACTIVE
    _ACTIVE = None
    return san


def new_lock(name):
    """A ``threading.Lock`` — instrumented under ``name`` when the
    sanitizer is active, plain otherwise."""
    if _ACTIVE is None:
        return threading.Lock()
    return _ACTIVE.lock(name)


def new_rlock(name):
    """A ``threading.RLock`` — instrumented under ``name`` when the
    sanitizer is active, plain otherwise."""
    if _ACTIVE is None:
        return threading.RLock()
    return _ACTIVE.rlock(name)


def guarded(owner, attr, container):
    """Wrap ``container`` (deque/list/dict/set) in a guarded-access
    checker when the sanitizer is active and ``type(owner)`` declares
    ``attr`` in its ``_GUARDED_BY`` map; returns ``container`` itself
    otherwise. Call at the CREATION site so every alias (e.g. the log
    handler's ring reference) shares the checked object."""
    if _ACTIVE is None:
        return container
    decl = getattr(type(owner), GUARDED_BY_ATTR, None)
    if not decl or attr not in decl:
        return container
    return _ACTIVE.guard(container, owner, attr, decl[attr])


def note_blocking(desc):
    """Declare the calling frame is about to BLOCK (a future wait, a
    device sync, a file write on a shared path). No-op when off; a
    ``held_blocking`` finding when any sanitized lock is held."""
    if _ACTIVE is not None:
        _ACTIVE.note_blocking(desc)


class signal_scope:
    """Context manager marking the dynamic extent of a signal handler:
    non-reentrant sanitized acquisitions inside it become
    ``signal_unsafe`` findings. No-op (but still a valid context
    manager) when the sanitizer is off."""

    def __enter__(self):
        if _ACTIVE is not None:
            _ACTIVE._tls_state().in_signal += 1
        return self

    def __exit__(self, *exc):
        if _ACTIVE is not None:
            state = _ACTIVE._tls_state()
            state.in_signal = max(state.in_signal - 1, 0)
        return False


# ----------------------------------------------------------- wrappers
class _LockInfo:
    __slots__ = ("name", "reentrant")

    def __init__(self, name, reentrant):
        self.name = name
        self.reentrant = reentrant


class SanLock:
    """Instrumented lock: delegates to the wrapped ``threading`` lock,
    reporting every acquisition/release to the owning sanitizer. Usable
    anywhere the plain lock was (``with``, ``acquire``/``release``,
    ``logging`` handler locks)."""

    __slots__ = ("_san", "_info", "_inner")

    def __init__(self, san, info, inner):
        self._san = san
        self._info = info
        self._inner = inner

    def acquire(self, blocking=True, timeout=-1):
        self._san.before_acquire(self._info)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san.after_acquire(self._info)
        return got

    def release(self):
        self._inner.release()
        self._san.after_release(self._info)

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def held_by_current_thread(self):
        """Whether THIS thread holds the lock (from the sanitizer's
        thread-local held list — a plain Lock cannot answer this)."""
        return any(info is self._info
                   for info, _ in self._san._tls_state().held)

    @property
    def name(self):
        return self._info.name

    @property
    def reentrant(self):
        return self._info.reentrant


# mutating method names checked by the guarded proxies, per operation
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "add", "update",
    "setdefault", "sort", "reverse", "rotate",
})
# read operations that are UNSAFE concurrent with mutation (the
# deque-mutated-during-dump / dict-changed-size-during-render class):
# snapshotting must hold the lock too
_CHECKED_READS = frozenset({"__iter__", "copy", "items", "keys",
                            "values"})


class GuardedProxy:
    """Transparent wrapper over a declared-guarded container: mutating
    calls (and iteration) verify the declared lock is held by the
    current thread, else record ONE ``guarded_race`` finding per
    (class.attr, method) site. Non-checked attributes delegate."""

    __slots__ = ("_obj", "_san", "_owner_name", "_attr", "_lock_attr",
                 "_owner_ref")

    def __init__(self, obj, san, owner, attr, lock_attr):
        object.__setattr__(self, "_obj", obj)
        object.__setattr__(self, "_san", san)
        object.__setattr__(self, "_owner_name", type(owner).__name__)
        object.__setattr__(self, "_attr", attr)
        object.__setattr__(self, "_lock_attr", lock_attr)
        object.__setattr__(self, "_owner_ref", owner)

    # ------------------------------------------------------------ checks
    def _check(self, op):
        san = self._san
        lock = getattr(self._owner_ref, self._lock_attr, None)
        held = isinstance(lock, SanLock) and lock.held_by_current_thread()
        if not held:
            san.record_guarded_race(self._owner_name, self._attr,
                                    self._lock_attr, op)

    def __getattr__(self, name):
        val = getattr(self._obj, name)
        if name in _MUTATORS or name in _CHECKED_READS:
            def checked(*args, **kwargs):
                self._check(name)
                return val(*args, **kwargs)
            return checked
        return val

    # dunder lookups bypass __getattr__ — spell the checked ones out
    def __iter__(self):
        self._check("__iter__")
        return iter(self._obj)

    def __setitem__(self, key, value):
        self._check("__setitem__")
        self._obj[key] = value

    def __delitem__(self, key):
        self._check("__delitem__")
        del self._obj[key]

    def __getitem__(self, key):
        return self._obj[key]

    def __len__(self):
        return len(self._obj)

    def __contains__(self, item):
        return item in self._obj

    def __bool__(self):
        return bool(self._obj)

    def __repr__(self):
        return "GuardedProxy({!r})".format(self._obj)

    @property
    def maxlen(self):            # deque passthrough
        return getattr(self._obj, "maxlen", None)


class _TlsState(threading.local):
    def __init__(self):
        self.held = []              # [(info, count)] acquisition order
        self.in_signal = 0


class LockSanitizer:
    """Owns the instrumentation state: the lock registry, the per-thread
    held stacks, the lock-order edge graph, and the raw finding events.
    One instance per process (``install()``); thread-safe — its own
    internal lock is a PLAIN lock (never itself sanitized)."""

    def __init__(self, stack_depth=STACK_DEPTH_DEFAULT):
        self.stack_depth = int(stack_depth)
        self._tls = _TlsState()
        self._state_lock = threading.Lock()     # guards the tables below
        self._locks = []                        # [_LockInfo]
        self._edges = {}      # (held_name, acq_name) -> edge dict
        self._events = {}     # finding key -> event dict (fire once)
        self.acquisitions = 0

    # ----------------------------------------------------------- factory
    def lock(self, name):
        return self._wrap_new(threading.Lock(), name, reentrant=False)

    def rlock(self, name):
        return self._wrap_new(threading.RLock(), name, reentrant=True)

    def wrap(self, lock, name):
        """Instrument an EXISTING lock object (the post-construction
        seam for the stdlib-only fleet modules, which cannot import this
        package themselves). Already-sanitized locks pass through."""
        if isinstance(lock, SanLock):
            return lock
        reentrant = "RLock" in type(lock).__name__
        return self._wrap_new(lock, name, reentrant=reentrant)

    def _wrap_new(self, inner, name, reentrant):
        name = str(name)
        with self._state_lock:
            # the order graph keys edges by NAME — two distinct locks
            # sharing one name (a second engine's "recorder.ring")
            # would conflate into self-edges reporting a deadlock that
            # cannot exist, so a reused name gets a #n suffix and every
            # _LockInfo stays a unique graph node
            taken = sum(1 for i in self._locks
                        if i.name == name or
                        i.name.startswith(name + "#"))
            if taken:
                name = "{}#{}".format(name, taken + 1)
            info = _LockInfo(name, bool(reentrant))
            self._locks.append(info)
        return SanLock(self, info, inner)

    def guard(self, container, owner, attr, lock_attr):
        if isinstance(container, GuardedProxy):
            return container
        return GuardedProxy(container, self, owner, attr, lock_attr)

    # ------------------------------------------------------ acquire hooks
    def _tls_state(self):
        return self._tls

    def _stack(self):
        # drop the innermost frames (sanitizer internals) — the caller
        # wants to see ITS acquisition site
        return traceback.format_stack(limit=self.stack_depth + 2)[:-2]

    def before_acquire(self, info):
        state = self._tls
        if state.in_signal and not info.reentrant:
            held_here = any(i is info for i, _ in state.held)
            self._record_event(
                "signal_unsafe:{}".format(info.name),
                check="signal_unsafe",
                message="signal handler acquires NON-reentrant lock "
                        "{!r}{} — a signal landing while the "
                        "interrupted frame holds it self-deadlocks the "
                        "dying process (use an RLock, or move the work "
                        "off the handler)".format(
                            info.name,
                            " it already holds" if held_here else ""),
                details={"lock": info.name,
                         "held_by_this_thread": held_here})

    def after_acquire(self, info):
        state = self._tls
        with self._state_lock:
            # under the state lock: a bare += from every acquiring
            # thread is the exact lost-increment race this tool exists
            # to flag
            self.acquisitions += 1
        for held_info, _count in state.held:
            if held_info is info:
                # reentrant re-acquisition: bump the count, no edge
                for i, (hi, c) in enumerate(state.held):
                    if hi is info:
                        state.held[i] = (hi, c + 1)
                        return
        # nesting edge from every currently-held lock (the order graph)
        for held_info, _count in state.held:
            key = (held_info.name, info.name)
            with self._state_lock:
                edge = self._edges.get(key)
                if edge is None:
                    self._edges[key] = {
                        "count": 1,
                        "stack": self._stack(),
                        "thread": threading.current_thread().name,
                    }
                else:
                    edge["count"] += 1
        state.held.append((info, 1))

    def after_release(self, info):
        state = self._tls
        for i in range(len(state.held) - 1, -1, -1):
            held_info, count = state.held[i]
            if held_info is info:
                if count > 1:
                    state.held[i] = (held_info, count - 1)
                else:
                    del state.held[i]
                return

    # ------------------------------------------------------ blocking note
    def note_blocking(self, desc):
        state = self._tls
        if not state.held:
            return
        names = [info.name for info, _ in state.held]
        self._record_event(
            "held_blocking:{}:{}".format(names[-1], desc),
            check="held_blocking",
            message="lock(s) {} held across blocking call {!r} — every "
                    "thread touching them stalls behind IO/waits they "
                    "have no part in (move the blocking work outside "
                    "the critical section)".format(names, desc),
            details={"locks": names, "blocking": str(desc)})

    # ------------------------------------------------------- guarded race
    def record_guarded_race(self, owner, attr, lock_attr, op):
        self._record_event(
            "guarded_race:{}.{}:{}".format(owner, attr, op),
            check="guarded_race",
            message="{}.{} accessed via {!r} WITHOUT {} held by this "
                    "thread — the structure is declared _GUARDED_BY "
                    "that lock (racy mutation/iteration; the "
                    "deque-mutated-during-dump class)".format(
                        owner, attr, op, lock_attr),
            details={"class": owner, "attr": attr,
                     "lock": lock_attr, "op": op})

    def _record_event(self, key, check, message, details):
        with self._state_lock:
            if key in self._events:
                self._events[key]["count"] += 1
                return
            self._events[key] = {
                "key": key, "check": check, "message": message,
                "details": dict(details), "count": 1,
                "stack": self._stack(),
                "thread": threading.current_thread().name,
                "wall": time.time(),
            }

    # ------------------------------------------------------------- cycles
    def _cycles(self):
        """Elementary cycles of the lock-order graph, canonicalized
        (each reported once, rotation-invariant)."""
        with self._state_lock:
            edges = {k: dict(v, stack=list(v["stack"]))
                     for k, v in self._edges.items()}
        graph = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        seen = set()
        cycles = []

        def dfs(start, node, path, on_path):
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cycle = tuple(path)
                    # canonical rotation: start at the lexicographically
                    # smallest lock so A->B->A and B->A->B are ONE cycle
                    pivot = cycle.index(min(cycle))
                    canon = cycle[pivot:] + cycle[:pivot]
                    if canon not in seen:
                        seen.add(canon)
                        cycles.append((canon, edges))
                elif nxt not in on_path and nxt > start:
                    # only walk nodes > start: each cycle found exactly
                    # once from its smallest node
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for start in sorted(graph):
            dfs(start, start, [start], {start})
        return cycles

    # ------------------------------------------------------------- report
    def report(self):
        """-> [Finding]: the lock-order cycles plus every recorded
        event, in the PR 10 shape (route through a Suppressions file /
        ``dispose`` for the strict behavior)."""
        findings = []
        for canon, edges in self._cycles():
            chain = " -> ".join(canon + (canon[0],))
            stacks = {}
            for i, a in enumerate(canon):
                b = canon[(i + 1) % len(canon)]
                edge = edges.get((a, b))
                if edge is not None:
                    stacks["{}->{}".format(a, b)] = {
                        "count": edge["count"],
                        "thread": edge["thread"],
                        "stack": edge["stack"],
                    }
            findings.append(Finding(
                rule=RULE, check="lock_order_cycle", program="runtime",
                severity="error",
                message="lock-order cycle {} — two threads taking these "
                        "locks in opposite orders WILL deadlock under "
                        "the right interleaving (acquisition stacks in "
                        "details)".format(chain),
                key="lock_order_cycle:{}".format(":".join(canon)),
                details={"cycle": list(canon), "edges": stacks}))
        with self._state_lock:
            events = list(self._events.values())
        for ev in events:
            findings.append(Finding(
                rule=RULE, check=ev["check"], program="runtime",
                severity="error" if ev["check"] == "signal_unsafe"
                else "warn",
                message=ev["message"],
                key=ev["key"],
                details=dict(ev["details"], count=ev["count"],
                             thread=ev["thread"], stack=ev["stack"])))
        return findings

    def snapshot(self):
        """Cheap counters for telemetry/dryrun printing."""
        with self._state_lock:
            return {
                "locks": len(self._locks),
                "acquisitions": self.acquisitions,
                "edges": len(self._edges),
                "events": len(self._events),
            }


# ------------------------------------------------- collector instrument
def instrument_collector(collector):
    """Post-construction instrumentation of a TelemetryCollector's
    STDLIB-ONLY fleet objects (they cannot import this package under
    the ``bin/ds_fleet.py`` synthetic mount, so their plain locks are
    wrapped from outside): the metrics registry + every metric family
    + the exporter's state lock. The recorder/watchdog locks are
    already sanitized at creation (telemetry/recorder.py,
    telemetry/watchdog.py use :func:`new_lock`/:func:`new_rlock`).
    No-op when the sanitizer is off."""
    san = current()
    if san is None or collector is None:
        return
    metrics = getattr(collector, "metrics", None)
    if metrics is not None:
        reg = metrics.registry
        reg._lock = san.wrap(reg._lock, "metrics.registry")
        for name, metric in list(reg._metrics.items()):
            metric._lock = san.wrap(metric._lock,
                                    "metrics.family:{}".format(name))
            metric._samples = san.guard(metric._samples, metric,
                                        "_samples", "_lock")
        reg._metrics = san.guard(reg._metrics, reg, "_metrics", "_lock")
    exporter = getattr(collector, "exporter", None)
    if exporter is not None and hasattr(exporter, "_lock"):
        exporter._lock = san.wrap(exporter._lock, "metrics.exporter")
