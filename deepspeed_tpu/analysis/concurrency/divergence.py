"""SPMD divergence auditor: per-host program fingerprints over the
fleet's collective order (docs/concurrency.md "Program fingerprints").

On a real pod a single host that lowers a DIFFERENT program — an extra
collective from host-dependent control flow, a reordered gather from a
config drift, a segment plan built against a stale topology — hangs
the whole mesh with no diagnosis: every other host sits in a
collective the divergent host never enters. GSPMD-style whole-program
partitioning (2105.04663) makes the collective SEQUENCE a per-program
invariant, so we can canonicalize it ahead of time and compare across
hosts BEFORE the first step:

* each program family's collective sequence is derived from the
  existing shard-lint IR walk (``analysis/ir.py`` classification — the
  same records every other rule reads) and, for lowered step paths,
  from the executed :class:`SegmentPlan` topology;
* the sequences canonicalize into one JSON payload + sha256 digest
  (:func:`canonical_fingerprint`) published in the PR 14 host manifest
  (``program_fingerprint``; ``telemetry/fleet/aggregate.py`` owns the
  cross-host comparison so ``bin/ds_fleet.py`` stays jax-less);
* :func:`divergence_findings` turns a mismatched comparison into
  ``fleet_divergence`` findings through the PR 10 machinery (warn,
  raise under ``analysis.strict``) — "the pod hung" becomes "host 3
  lowered a different plan at step 0".

The derivation half (this module's jax-touching functions) runs only
in-process on an engine; the comparison half is stdlib and lives with
the fleet merger.
"""
import hashlib
import json

from ..findings import AnalysisReport, Finding

FINGERPRINT_VERSION = 1

# every published fingerprint carries exactly these keys (the manifest
# extension bin/check_bench_schema.py validates)
FINGERPRINT_KEYS = ("version", "digest", "families")


# ------------------------------------------------------- canonical form
def collective_tokens(walk_result, structure=True):
    """The ordered collective sequence of one walked program: one token
    per collective-classified op record — primitive name, the mesh axes
    it moves over, and its static trip count (``xN`` for scan bodies;
    ``x?`` under a dynamic-trip ``while``). Two hosts executing the
    same program produce the same token list BY CONSTRUCTION; any
    divergence in collective order/kind/axis shows as a token diff.

    ``structure=True`` appends one ``#ops:...`` summary token (op /
    GEMM / host-call counts): GSPMD programs carry NO explicit
    collective primitives — the partitioner inserts them post-lowering,
    deterministically from the program structure — so the structural
    census is what diverges when two hosts lower different GSPMD
    programs (2105.04663)."""
    tokens = []
    n_ops = n_gemm = n_host = 0
    for info in walk_result.eqns:
        n_ops += 1
        if info.prim in ("dot_general", "conv_general_dilated"):
            n_gemm += 1
        if info.kind == "host":
            n_host += 1
        if info.kind != "collective":
            continue
        axes = info.eqn.params.get("axes",
                                   info.eqn.params.get("axis_name"))
        if isinstance(axes, (list, tuple)):
            axes = ",".join(str(a) for a in axes)
        token = info.prim
        if axes is not None:
            token += "@{}".format(axes)
        trips = info.trips
        if trips is None:
            token += "x?"
        elif trips != 1:
            token += "x{}".format(int(trips))
        tokens.append(token)
    if structure:
        tokens.append("#ops:{}/gemm:{}/host:{}".format(
            n_ops, n_gemm, n_host))
    return tokens


def plan_tokens(plan):
    """The ordered byte-moving segment sequence of one lowered
    :class:`SegmentPlan`: collective/transfer segments in plan
    (insertion = serial-oracle) order. Segment names are deterministic
    functions of the config/topology, so equal configs fingerprint
    equal and a host that lowered a different plan diffs at the first
    divergent segment."""
    return ["{}:{}".format(seg.kind, seg.name)
            for seg in plan.segments
            if seg.kind in ("collective", "transfer")]


def canonical_fingerprint(families):
    """``{family: [token, ...]}`` -> the fingerprint payload published
    in the host manifest: a version, the canonical-JSON sha256 digest
    (16 hex chars — collision is a non-goal, diffability is), and the
    family map itself (kept so a mismatch can name the first divergent
    family/token instead of just "digests differ")."""
    fams = {str(k): [str(t) for t in v]
            for k, v in sorted(families.items())}
    payload = json.dumps({"version": FINGERPRINT_VERSION,
                          "families": fams},
                         sort_keys=True, separators=(",", ":"))
    return {
        "version": FINGERPRINT_VERSION,
        "digest": hashlib.sha256(payload.encode("utf-8")).hexdigest()[:16],
        "families": fams,
    }


def validate_fingerprint(payload):
    """-> list of problems with one program_fingerprint payload."""
    problems = []
    if not isinstance(payload, dict):
        return ["fingerprint is not a dict"]
    for key in FINGERPRINT_KEYS:
        if key not in payload:
            problems.append("missing key {!r}".format(key))
    if problems:
        return problems
    if not isinstance(payload["digest"], str) or not payload["digest"]:
        problems.append("digest is not a non-empty string")
    fams = payload["families"]
    if not isinstance(fams, dict):
        problems.append("families is not a dict")
    else:
        for name, tokens in fams.items():
            if not isinstance(tokens, list) or \
                    not all(isinstance(t, str) for t in tokens):
                problems.append(
                    "families[{!r}] is not a list of tokens".format(name))
                break
    return problems


# ---------------------------------------------------- control-flow rule
def control_flow_findings(spec_name, walk_result):
    """``collective_in_branch``: a collective primitive nested inside a
    ``cond``/``switch`` branch — the collective executes only on one
    data-dependent path, so value divergence across hosts (a
    host-dependent predicate feeding the branch) reorders the
    collective sequence and hangs the mesh (the GSPMD uniformity
    contract, 2105.04663). Loop bodies (``scan``/``while``) are exempt:
    they execute structurally identically on every device — only
    BRANCHES make a collective conditional."""
    findings = []
    seen = set()
    for info in walk_result.eqns:
        if info.kind != "collective":
            continue
        parts = info.path.split("/")
        if not any(p in ("cond", "switch") for p in parts[:-1]):
            continue
        key = "collective_in_branch:{}:{}".format(spec_name, info.prim)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            rule="concurrency", check="collective_in_branch",
            program=spec_name, severity="warn",
            message="program {!r} runs collective {!r} inside a "
                    "conditional branch ({}) — a host-dependent "
                    "predicate diverges the fleet's collective order "
                    "and hangs the mesh (hoist the collective out of "
                    "the branch, or make the predicate provably "
                    "uniform)".format(spec_name, info.prim, info.path),
            key=key,
            details={"prim": info.prim, "path": info.path}))
    return findings


# ------------------------------------------------------- engine derive
def fingerprint_engine(engine, batch=None):
    """Derive this engine's program fingerprint: walk every resolved
    step program (the same collectors the auditor uses) for its
    collective sequence, plus the lowered segment-plan topology on the
    offload/streamed paths. Heavier than a manifest read (one
    ``make_jaxpr`` per family) — ``engine.audit()`` computes the same
    payload as a side effect of the walk it already does, so prefer
    auditing when both are wanted."""
    import jax

    from .. import programs as collectors
    from ..ir import plan_of, walk
    if hasattr(engine, "prefill_buckets"):
        specs = collectors.collect_inference_programs(engine)
    else:
        specs = collectors.collect_train_programs(engine, batch=batch)
    families = {}
    for spec in specs:
        closed = jax.make_jaxpr(spec.build())(*spec.args)
        families[spec.name] = collective_tokens(walk(closed))
    if getattr(engine, "stream_runner", None) is not None or \
            getattr(engine, "host_state", None) is not None:
        plan = plan_of(engine)
        families["plan/" + plan.name] = plan_tokens(plan)
    return canonical_fingerprint(families)


def publish_fingerprint(engine, fingerprint):
    """Publish a fingerprint into this host's manifest through the
    engine's live telemetry collector (no-op without one — there is no
    manifest to extend then)."""
    tel = getattr(engine, "telemetry", None)
    if tel is None:
        return None
    return tel.publish_fingerprint(fingerprint)


# ----------------------------------------------------------- findings
def _first_divergence(ref_fams, fams):
    """(family, index, ref_token, token) of the first diff between two
    family maps, or a family present on one side only."""
    for name in sorted(set(ref_fams) | set(fams)):
        a, b = ref_fams.get(name), fams.get(name)
        if a is None or b is None:
            return name, None, None if a is None else "present", \
                None if b is None else "present"
        for i in range(max(len(a), len(b))):
            ta = a[i] if i < len(a) else None
            tb = b[i] if i < len(b) else None
            if ta != tb:
                return name, i, ta, tb
    return None, None, None, None


def divergence_findings(comparison):
    """``fleet_divergence`` findings from one comparison payload (the
    ``divergence`` section ``telemetry/fleet/aggregate.py``'s
    ``compare_fingerprints`` builds / ``merge_run`` embeds): one
    finding per divergent host, naming the first differing
    family/token against the reference host."""
    if not isinstance(comparison, dict) or \
            not comparison.get("mismatch"):
        return []
    ref_host = comparison.get("reference")
    fams_by_host = comparison.get("families") or {}
    ref_fams = fams_by_host.get(ref_host) or {}
    findings = []
    for host in comparison.get("divergent_hosts") or []:
        fams = fams_by_host.get(host) or {}
        family, idx, ref_tok, tok = _first_divergence(ref_fams, fams)
        if family is None:
            where = "digests differ (token detail not published)"
        elif idx is None:
            where = "family {!r} exists on only one side".format(family)
        else:
            where = ("family {!r} token {}: reference {!r} vs "
                     "{!r}".format(family, idx, ref_tok, tok))
        findings.append(Finding(
            rule="concurrency", check="fleet_divergence",
            program="fleet", severity="error",
            message="host {!r} lowered a DIFFERENT program than "
                    "reference host {!r}: {} — on a real pod every "
                    "other host hangs in a collective this host never "
                    "enters".format(host, ref_host, where),
            key="fleet_divergence:{}".format(host),
            details={"host": host, "reference": ref_host,
                     "digest": (comparison.get("digests") or {})
                     .get(host),
                     "reference_digest": (comparison.get("digests")
                                          or {}).get(ref_host),
                     "family": family, "index": idx,
                     "reference_token": ref_tok, "token": tok}))
    return findings


def audit_fleet(report_or_comparison, config=None, strict=None):
    """Dispose fleet-divergence findings the PR 10 way: warn each, and
    raise :class:`~..auditor.AuditFindingsError` under
    ``analysis.strict`` (``strict`` argument overrides). Accepts a full
    merged fleet report (``merge_run`` shape) or a bare comparison
    payload; returns the :class:`AnalysisReport`."""
    from ..auditor import dispose
    payload = report_or_comparison or {}
    if "divergence" in payload:
        payload = payload.get("divergence") or {}
    report = AnalysisReport(job="fleet-divergence")
    suppressions = None
    if config is not None and getattr(config, "suppressions", None):
        from ..findings import Suppressions
        suppressions = Suppressions.load(config.suppressions)
    report.extend(divergence_findings(payload), suppressions)
    return dispose(report, config, raise_on_findings=strict)
