"""ProgramSpec collectors: each engine's step programs, described
abstractly for the auditor.

The collectors reach through the engines' OWN builder seams
(``_micro_step_fn`` / ``_fused_train_fn`` / ``_pipe_grads_fn`` / the
streamed runner's segment builders / the inference prefill/decode
factories) so the audited jaxprs are byte-identical to what the
engines jit — there is no parallel re-implementation to drift.

Program families covered (the acceptance matrix):

  * ``micro``      — the micro-step + optimizer-apply pair;
  * ``fused``      — the one-jit scan-over-micros + apply program;
  * ``offload``    — classic ZeRO-Offload's on-device micros scan and
                     the jitted overflow/norm check (host Adam is not a
                     device program);
  * ``streamed``   — the five segment programs of the beyond-HBM
                     runner (embed/group fwd, head grad, group/embed
                     bwd);
  * ``pipeline``   — the 1F1B pipe-loop program (fused or offload
                     split);
  * ``inference``  — bucketed prefill, fused decode, and the
                     speculative verify pass.
"""
import numpy as np

import jax

from .rules import ProgramSpec, _kp_str, _spec_mentions


def _sds(x):
    """array-ish -> ShapeDtypeStruct (mesh sharding preserved); scalars
    and None pass through (make_jaxpr abstracts them itself). Only
    NamedShardings are kept: an uncommitted array reports a
    SingleDeviceSharding that would pin the lowered program to one
    device and clash with the mesh-committed operands."""
    if x is None:
        return None
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        from jax.sharding import NamedSharding
        sharding = getattr(x, "sharding", None)
        if not isinstance(sharding, NamedSharding):
            sharding = None
        try:
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype,
                                        sharding=sharding)
        except TypeError:               # jax without SDS sharding kwarg
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return x


def sds_tree(tree):
    return jax.tree_util.tree_map(_sds, tree)


def _rng_struct():
    key = jax.random.PRNGKey(0)
    return jax.ShapeDtypeStruct(tuple(key.shape), key.dtype)


# --------------------------------------------------------------- train
def _batch_struct(engine, batch):
    """Sample micro-batch -> SDS tree with the shardings _to_device
    would commit (no placement happens)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            shape, dtype = tuple(x.shape), x.dtype
        else:
            arr = np.asarray(x)
            shape, dtype = arr.shape, arr.dtype
        if len(shape) == 0 or shape[0] % engine.dp_world_size != 0:
            sharding = NamedSharding(engine.mesh, P())
        else:
            sharding = engine._batch_sharding(len(shape))
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    return jax.tree_util.tree_map(put, tuple(batch))


def _stacked_struct(engine, micro_struct):
    """Micro-batch SDS tree -> the (gas, ...) stacked struct the fused
    path consumes (mirrors _to_device_stacked's shardings)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    gas = engine.gradient_accumulation_steps()

    def put(s):
        shape = (gas,) + tuple(s.shape)
        if len(shape) <= 2 and (len(shape) < 2 or
                                shape[1] % engine.dp_world_size != 0):
            sharding = NamedSharding(engine.mesh, P())
        elif shape[1] % engine.dp_world_size != 0:
            sharding = NamedSharding(engine.mesh, P())
        else:
            sharding = NamedSharding(
                engine.mesh,
                P(None, engine._batch_axis, *([None] * (len(shape) - 2))))
        return jax.ShapeDtypeStruct(shape, s.dtype, sharding=sharding)

    return jax.tree_util.tree_map(put, micro_struct)


def _resolve_batch(engine, batch):
    if batch is not None:
        return _batch_struct(engine, batch)
    micro = getattr(engine, "_audit_batch_struct", None)
    stacked = getattr(engine, "_audit_batch_struct_stacked", None)
    if micro is None and stacked is not None:
        micro = jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct(
                tuple(s.shape[1:]), s.dtype,
                sharding=getattr(engine, "_batch_sharding")(len(s.shape) - 1)
                if len(s.shape) >= 2 and
                s.shape[1] % engine.dp_world_size == 0 else None),
            stacked)
    if micro is None:
        raise ValueError(
            "audit needs a sample batch: pass engine.audit(batch=...) "
            "(arrays or ShapeDtypeStructs shaped like one micro-batch), "
            "or run one training step first")
    return micro


def _count_sharded(plan, tree, kind, axes):
    if tree is None:
        return 0
    shardings = plan.tree_shardings(tree, kind)
    leaves = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    return sum(1 for s in leaves if _spec_mentions(s, set(axes)))


def _state_out_expect(engine, state_struct, prefix="0"):
    """[(output path, expected axes)] for the state leaves the plan
    data-shards — fed to the compiled output-drift check."""
    plan = engine.zero_plan
    axes = set(plan.data_axes) | set(plan.param_data_axes)
    if not axes:
        return []
    out = []
    for field, kind in (("params", "param"), ("master", "master"),
                        ("acc_grads", "grad")):
        tree = state_struct.get(field) if isinstance(state_struct, dict) \
            else None
        if tree is None:
            continue
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for kp, leaf in flat:
            path = _kp_str(kp)
            sharding = {"param": plan.param_sharding,
                        "master": plan.master_sharding,
                        "grad": plan.grad_sharding}[kind](
                            path, tuple(leaf.shape))
            mentioned = [ax for ax in axes if _spec_mentions(sharding,
                                                             {ax})]
            if mentioned:
                out.append(("{}/{}/{}".format(prefix, field, path),
                            tuple(mentioned)))
    return out


def train_step_sequence(engine):
    """The engine's declared step-order/donation dataflow (state-field
    granularity) for the read-after-donation rule."""
    gas = engine.gradient_accumulation_steps()
    seq = []
    if engine.stream_runner is not None or engine.host_state is not None:
        # host-optimizer paths never donate device state across programs
        return seq
    for _ in range(gas):
        seq.append({"program": "micro", "reads": ("state", "batch"),
                    "donates": ("state",), "produces": ("state",)})
    seq.append({"program": "apply", "reads": ("state",),
                "donates": ("state",), "produces": ("state",)})
    return seq


def collect_train_programs(engine, batch=None):
    plan = engine.zero_plan
    mesh = engine.mesh
    state_struct = sds_tree(engine.state)
    micro_b = _resolve_batch(engine, batch)
    stacked_b = getattr(engine, "_audit_batch_struct_stacked", None)
    if stacked_b is None:
        stacked_b = _stacked_struct(engine, micro_b)
    rng = _rng_struct()
    pld = engine._pld_theta()
    hyper = engine._hyper()
    axes = tuple(sorted(set(plan.data_axes) | set(plan.param_data_axes)))

    if getattr(engine, "stream_runner", None) is not None:
        return _collect_streamed(engine, micro_b, rng)

    if hasattr(engine, "_pipeline_train_fn"):
        return _collect_pipeline(engine, state_struct, stacked_b, rng,
                                 hyper, axes)

    acc = engine.state.get("acc_grads")
    n_grad = _count_sharded(plan, acc, "grad", axes)
    n_master = _count_sharded(plan, acc, "master", axes)
    out_expect = _state_out_expect(engine, state_struct)
    common = dict(plan=plan, mesh=mesh, taint_paths=("0/params",))
    specs = []
    if engine.host_state is not None:
        # classic ZeRO-Offload: on-device micros (single + fused scan),
        # plus the jitted overflow/norm check; Adam runs on host
        specs.append(ProgramSpec(
            name="micro", family="offload", build=engine._micro_step_fn,
            args=(state_struct, micro_b, rng, pld), donate=(0,),
            expected_constraints=n_grad, constraint_axes=axes,
            meta={"out_expect": out_expect}, **common))
        specs.append(ProgramSpec(
            name="fused_micros", family="offload",
            build=engine._fused_micros_fn,
            args=(state_struct, stacked_b, rng, pld), donate=(0,),
            expected_constraints=n_grad, constraint_axes=axes,
            meta={"out_expect": out_expect}, **common))
        specs.append(ProgramSpec(
            name="offload_check", family="offload",
            build=engine._offload_check_fn,
            args=(state_struct["acc_grads"], np.float32(1.0)),
            plan=plan, mesh=mesh))
        return specs

    gas = engine.gradient_accumulation_steps()
    specs.append(ProgramSpec(
        name="micro", family="micro", build=engine._micro_step_fn,
        args=(state_struct, micro_b, rng, pld), donate=(0,),
        expected_constraints=n_grad, constraint_axes=axes,
        meta={"out_expect": out_expect, "wire_multiplier": gas},
        **common))
    specs.append(ProgramSpec(
        name="apply", family="micro", build=engine._apply_step_fn,
        args=(state_struct, hyper), donate=(0,),
        expected_constraints=max(n_master, n_grad), constraint_axes=axes,
        meta={"out_expect": out_expect, "wire_multiplier": 1},
        **common))
    specs.append(ProgramSpec(
        name="fused_train", family="fused", build=engine._fused_train_fn,
        args=(state_struct, stacked_b, rng, hyper, pld),
        donate=(0,),
        expected_constraints=n_grad + max(n_master, n_grad),
        constraint_axes=axes, meta={"out_expect": out_expect}, **common))
    return specs


def _collect_pipeline(engine, state_struct, stacked_b, rng, hyper, axes):
    plan = engine.zero_plan
    acc = engine.state.get("acc_grads")
    n_grad = _count_sharded(plan, acc, "grad", axes)
    n_master = _count_sharded(plan, acc, "master", axes)
    out_expect = _state_out_expect(engine, state_struct)
    common = dict(plan=plan, mesh=engine.mesh, taint_paths=("0/params",))
    if engine.host_state is not None:
        return [ProgramSpec(
            name="pipe_micros", family="pipeline",
            build=engine._pipe_grads_fn,
            args=(state_struct, stacked_b, rng), donate=(0,),
            expected_constraints=n_grad, constraint_axes=axes,
            meta={"out_expect": out_expect}, **common)]
    return [ProgramSpec(
        name="pipe_train", family="pipeline",
        build=engine._fused_train_fn,
        args=(state_struct, stacked_b, rng, hyper), donate=(0,),
        expected_constraints=n_grad + max(n_master, n_grad),
        constraint_axes=axes, meta={"out_expect": out_expect}, **common)]


# ------------------------------------------------------------ streamed
def _collect_streamed(engine, micro_b, rng):
    """The five streamed-offload segment programs, with intermediate
    activation structs derived by chained eval_shape (the auditor never
    uploads or runs anything)."""
    from ..runtime.zero.stream import STREAM_DONATE
    runner = engine.stream_runner
    runner._bind()
    cdtype = np.dtype(engine.compute_dtype)
    repl = runner._replicated

    def seg_sds(leaves):
        return tuple(
            jax.ShapeDtypeStruct(np.shape(p), cdtype, sharding=repl)
            for p in leaves)

    e_sds = seg_sds(runner._e_leaves)
    h_sds = seg_sds(runner._h_leaves)
    g0 = seg_sds(runner._group_leaves(0))
    g0_split = runner._split_group(list(g0), 0)
    start, stop = runner.groups[0]
    b_defs = tuple(runner._b_defs[start:stop])
    has_rng = engine.model.accepts_rng
    key = _rng_struct() if has_rng else None
    n_blocks = stop - start
    gkeys = jax.ShapeDtypeStruct((n_blocks,) + tuple(key.shape),
                                 key.dtype) if has_rng else None
    scale = np.float32(1.0)
    inv_scale = np.float32(1.0)

    e_fwd = runner._embed_fwd_fn(runner._e_def, has_rng)
    x_struct = jax.eval_shape(e_fwd, e_sds, micro_b, key)
    g_fwd = runner._group_fwd_fn(b_defs, has_rng)
    x_out = jax.eval_shape(g_fwd, g0_split, x_struct, gkeys)
    # the head consumes the LAST group's boundary activation; equal-width
    # transformer blocks keep the struct constant across groups, so the
    # first group's output struct stands in for it
    h_grad = runner._head_grad_fn(runner._h_def, has_rng)
    _, dx_struct, _ = jax.eval_shape(h_grad, h_sds, x_out, micro_b, key,
                                     scale, inv_scale)

    common = dict(plan=engine.zero_plan, mesh=engine.mesh, family="streamed")
    return [
        ProgramSpec(
            name="stream/e_fwd",
            build=lambda: runner._embed_fwd_fn(runner._e_def, has_rng),
            args=(e_sds, micro_b, key),
            donate=STREAM_DONATE["e_fwd"], **common),
        ProgramSpec(
            name="stream/g_fwd",
            build=lambda: runner._group_fwd_fn(b_defs, has_rng),
            args=(g0_split, x_struct, gkeys),
            donate=STREAM_DONATE["g_fwd"],
            # the boundary activation input is KEPT for the backward
            # recompute — liveness the donation rule cannot see
            keep_args=("1",), **common),
        ProgramSpec(
            name="stream/h_grad",
            build=lambda: runner._head_grad_fn(runner._h_def, has_rng),
            args=(h_sds, x_out, micro_b, key, scale, inv_scale),
            donate=STREAM_DONATE["h_grad"], **common),
        ProgramSpec(
            name="stream/g_bwd",
            build=lambda: runner._group_bwd_fn(b_defs, has_rng),
            args=(g0_split, x_struct, dx_struct, gkeys, inv_scale),
            donate=STREAM_DONATE["g_bwd"],
            # x_in stays live only because dx claimed the alias; the
            # uploaded weights have no aliasable output (donating them
            # would only buy an XLA warning)
            keep_args=("0", "1"), **common),
        ProgramSpec(
            name="stream/e_bwd",
            build=lambda: runner._embed_bwd_fn(runner._e_def, has_rng),
            args=(e_sds, micro_b, dx_struct, key, inv_scale),
            donate=STREAM_DONATE["e_bwd"],
            keep_args=("0",), **common),
    ]


# ----------------------------------------------------------- inference
def inference_step_sequence(engine):
    seq = [{"program": "prefill", "reads": ("params", "kv"),
            "donates": ("kv",), "produces": ("kv",)},
           {"program": "decode", "reads": ("params", "kv"),
            "donates": ("kv",), "produces": ("kv",)}]
    if engine.spec_k:
        seq.append({"program": "spec_verify", "reads": ("params", "kv"),
                    "donates": ("kv",), "produces": ("kv",)})
    return seq


def collect_inference_programs(engine):
    params = sds_tree(engine.params)
    k_sds, v_sds = _sds(engine.kv.k), _sds(engine.kv.v)
    rng = _rng_struct()
    temp = np.float32(1.0)
    top_p = np.float32(1.0)
    paged = engine.kv_layout == "paged"
    n_buckets = len(engine.prefill_buckets)
    specs = []
    greedy, top_k = True, 0
    for bucket in engine.prefill_buckets:
        ids = jax.ShapeDtypeStruct((1, bucket), np.int32)
        if paged:
            args = (params, k_sds, v_sds, ids,
                    jax.ShapeDtypeStruct((engine.max_pages,), np.int32),
                    np.int32(0), np.int32(1), rng, temp, top_p)
        else:
            args = (params, k_sds, v_sds, ids, np.int32(0), np.int32(0),
                    np.int32(1), rng, temp, top_p)
        specs.append(ProgramSpec(
            name="prefill/b{}".format(bucket), family="inference",
            build=lambda b=bucket: _unjitted_prefill(engine, b, greedy,
                                                     top_k),
            args=args, donate=(1, 2), mesh=engine.mesh,
            # no allow_weak needed: every scalar operand is an explicit
            # np.int32/np.float32 (strong-typed)
            taint_paths=("0",), trace_bound=n_buckets))
    widths = [("decode", 1)]
    if engine.spec_k:
        widths.append(("spec_verify", engine.spec_k + 1))
    for name, width in widths:
        tokens = jax.ShapeDtypeStruct((engine.num_slots, width), np.int32)
        lengths = jax.ShapeDtypeStruct((engine.num_slots,), np.int32)
        if paged:
            tables = jax.ShapeDtypeStruct(
                (engine.num_slots, engine.max_pages), np.int32)
            args = (params, k_sds, v_sds, tokens, lengths, tables, rng,
                    temp, top_p)
        else:
            args = (params, k_sds, v_sds, tokens, lengths, rng, temp,
                    top_p)
        specs.append(ProgramSpec(
            name=name, family="inference",
            build=lambda w=width: _unjitted_decode(engine, greedy, top_k,
                                                   w),
            args=args, donate=(1, 2), mesh=engine.mesh,
            taint_paths=("0",), trace_bound=len(widths)))
    return specs


def _unjitted_prefill(engine, bucket, greedy, top_k):
    """The prefill factory's traced fn WITHOUT entering the engine's
    jit cache (the audit must not inflate compile_stats or the trace
    registry)."""
    fns, stats = engine._prefill_fns, dict(engine.compile_stats)
    tele = engine.telemetry
    engine._prefill_fns, engine.telemetry = {}, None
    try:
        fn = engine._get_prefill_fn(bucket, greedy, top_k)
    finally:
        engine._prefill_fns = fns
        engine.compile_stats = stats
        engine.telemetry = tele
    return fn.__wrapped__


def _unjitted_decode(engine, greedy, top_k, width):
    fns, stats = engine._decode_fns, dict(engine.compile_stats)
    tele = engine.telemetry
    engine._decode_fns, engine.telemetry = {}, None
    try:
        fn = engine._get_decode_fn(greedy, top_k, width=width)
    finally:
        engine._decode_fns = fns
        engine.compile_stats = stats
        engine.telemetry = tele
    return fn.__wrapped__
