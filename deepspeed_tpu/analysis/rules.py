"""Shard-lint rule implementations.

Four rule classes over an abstract :class:`ProgramSpec` (a step program
described by its builder, example arg structs, donation set and the
resolved ``ZeroShardingPlan``):

  * **sharding_drift** — replicated input leaves above the byte
    threshold (shared implementation with the runtime compile
    observatory: :func:`replicated_leaf_finding`), and a
    sharding-constraint census proving the program still carries the
    plan's ``with_sharding_constraint`` calls (strip one and the count
    drops below the plan's expectation);
  * **donation** — dead input buffers that could be donated but are not
    (HBM doubling), donated buffers no output can alias (the donation
    is silently dropped), and donated-state reads after donation
    (:func:`sequence_findings` over the engine's declared step
    sequence);
  * **dtype_promotion** — fp32 GEMMs reachable from bf16 params (an
    upcast leaked into the matmul path; loss/norm/Adam math is
    naturally exempt because it is not GEMM-shaped — extend
    ``analysis.fp32_allowlist`` for intentional fp32 contractions);
  * **host_sync / recompile hazards** — host callbacks under jit
    (``pure_callback``/``debug_*`` force a device->host sync every
    step), weak-typed (Python-scalar) operands that fragment the
    compile cache, and ahead-of-time recompile-storm bounds (a program
    family whose key space exceeds the storm threshold *will* storm —
    shared implementation with the runtime detector:
    :func:`recompile_storm_finding`).

The two shared rule cores carry the SAME default thresholds the
runtime compile observatory uses (``telemetry.programs`` tunes both —
one threshold config, no drift; ``telemetry/programs.py`` imports them
from here).
"""
import dataclasses

import numpy as np

import jax

from .findings import Finding
from .ir import GEMM_PRIMS, HOST_PRIMS, dtype_itemsize, walk

# One home for the thresholds the runtime observatory and the AOT
# auditor share (telemetry/programs.py re-exports for back-compat).
RECOMPILE_STORM_THRESHOLD_DEFAULT = 32
REPLICATED_LEAF_BYTES_DEFAULT = 1 << 30
DONATION_MIN_BYTES_DEFAULT = 1 << 20
CENSUS_MIN_BYTES_DEFAULT = 1 << 10


# ------------------------------------------------------- shared rule core
def replicated_leaf_finding(program, leaf, nbytes, device_count,
                            threshold=REPLICATED_LEAF_BYTES_DEFAULT):
    """The ONE accidental-full-replication rule (used ahead-of-time by
    the auditor on program input structs and at runtime by the compile
    observatory on committed arg shardings). None when under threshold
    or off-mesh."""
    if device_count <= 1 or nbytes < threshold:
        return None
    return Finding(
        rule="sharding_drift", check="replicated_leaf", program=program,
        message="program {!r} takes a fully REPLICATED {:.1f} MB leaf "
                "({}) on a {}-device mesh — likely an accidental "
                "replication (missing partition rule); HBM pays {}x for "
                "it".format(program, nbytes / 2 ** 20, leaf, device_count,
                            device_count),
        key="replicated_leaf:{}:{}".format(program, leaf),
        details={"leaf": leaf, "nbytes": int(nbytes),
                 "device_count": int(device_count),
                 "threshold": int(threshold)})


def recompile_storm_finding(program, count,
                            threshold=RECOMPILE_STORM_THRESHOLD_DEFAULT,
                            hint="its input shapes are not stabilizing"):
    """The ONE recompile-storm rule (runtime: executable-cache growth /
    trace-family growth; ahead-of-time: a program family's static key
    space). None while under threshold."""
    if count <= threshold:
        return None
    return Finding(
        rule="host_sync", check="recompile_storm", program=program,
        message="program {!r} holds {} executables/traces (threshold {}) "
                "— a recompile storm; {}".format(program, count, threshold,
                                                 hint),
        key="recompile_storm:{}".format(program),
        details={"count": int(count), "threshold": int(threshold)})


# ------------------------------------------------------------ ProgramSpec
@dataclasses.dataclass
class ProgramSpec:
    """One step program, described abstractly (nothing executes).

    ``build``            zero-arg callable -> the traced python fn
                         (the engine's ``*_fn`` builder output);
    ``args``             tuple pytree of arrays / ShapeDtypeStructs /
                         scalars — the program's example operands;
    ``donate``           the donation set (argnums) the engine uses on
                         an accelerator (CPU-gated donations still
                         declare the accelerator set here) — the same
                         spelling ``runtime/executor/jit.jit_program``
                         takes, so the audited declaration IS the
                         executed one;
    ``taint_paths``      flat-path prefixes ("0/params") whose low-
                         precision leaves seed the dtype-promotion
                         taint;
    ``keep_args``        flat-path prefixes the engine declares LIVE
                         after the call (excluded from donation_miss —
                         e.g. boundary activations kept for recompute);
    ``allow_weak``       flat-path prefixes exempt from the weak-typed-
                         operand hazard (declared stable scalar blocks,
                         e.g. the optimizer hyperparams);
    ``expected_constraints`` minimum number of sharding-constraint eqns
                         naming a ``constraint_axes`` axis the plan
                         expects in this program (0 = skip the census);
    ``trace_bound``      static key-space size of the program's family
                         (inference bucket lists); checked against the
                         storm threshold ahead-of-time.
    """
    name: str
    family: str
    build: object
    args: tuple
    donate: tuple = ()
    plan: object = None
    mesh: object = None
    taint_paths: tuple = ()
    keep_args: tuple = ()
    allow_weak: tuple = ()
    expected_constraints: int = 0
    constraint_axes: tuple = ()
    trace_bound: object = None
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def donate_argnums(self):
        """Jax spelling of :attr:`donate` (report/readers compat)."""
        return self.donate


def _kp_str(key_path):
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in key_path)


def _abstract(leaf):
    """leaf -> ShapedArray (shape/dtype/weak_type) without touching
    data; handles arrays, ShapeDtypeStructs and Python scalars."""
    from jax.api_util import shaped_abstractify
    return shaped_abstractify(leaf)


def flat_arg_leaves(args):
    """Flatten a program's args exactly the way ``jax.make_jaxpr``
    flattens its invars: [(argnum, "argnum/tree/path", leaf)] in invar
    order."""
    out = []
    for argnum, arg in enumerate(args):
        flat, _ = jax.tree_util.tree_flatten_with_path(arg)
        for kp, leaf in flat:
            path = str(argnum)
            sub = _kp_str(kp)
            if sub:
                path += "/" + sub
            out.append((argnum, path, leaf))
    return out


def _leaf_nbytes(leaf):
    aval = _abstract(leaf)
    shape = tuple(getattr(aval, "shape", ()))
    itemsize = dtype_itemsize(aval.dtype)
    return int(np.prod(shape, dtype=np.int64)) * itemsize if shape \
        else itemsize


def _dtype_key(dtype):
    """Hashable dtype tag tolerating jax extended dtypes."""
    try:
        return np.dtype(dtype).str
    except TypeError:
        return str(dtype)


def _leaf_sharding(leaf):
    return getattr(leaf, "sharding", None)


def _match_prefix(path, prefixes):
    return any(path == p or path.startswith(p.rstrip("/") + "/")
               for p in prefixes)


def donated_flat_indices(spec):
    """Flat-leaf indices covered by the spec's donation set."""
    donated = set()
    for i, (argnum, _, _) in enumerate(flat_arg_leaves(spec.args)):
        if argnum in spec.donate:
            donated.add(i)
    return donated


# -------------------------------------------------------------- donation
def donation_findings(spec, closed_jaxpr,
                      min_bytes=DONATION_MIN_BYTES_DEFAULT):
    """Donation audit over one program's input/output avals."""
    findings = []
    leaves = flat_arg_leaves(spec.args)
    donated = donated_flat_indices(spec)
    out_pool = {}
    for aval in closed_jaxpr.out_avals:
        key = (tuple(aval.shape), _dtype_key(aval.dtype))
        out_pool[key] = out_pool.get(key, 0) + 1

    def take(key):
        if out_pool.get(key, 0) > 0:
            out_pool[key] -= 1
            return True
        return False

    # donated inputs claim their aliases first
    for i, (argnum, path, leaf) in enumerate(leaves):
        if i not in donated:
            continue
        aval = _abstract(leaf)
        key = (tuple(aval.shape), _dtype_key(aval.dtype))
        if not take(key) and _leaf_nbytes(leaf) >= min_bytes:
            findings.append(Finding(
                rule="donation", check="donation_unhonored",
                program=spec.name,
                message="program {!r} donates input {} ({:.1f} MB) but no "
                        "output matches its shape/dtype — XLA drops the "
                        "donation and the buffer is copied".format(
                            spec.name, path,
                            _leaf_nbytes(leaf) / 2 ** 20),
                key="donation_unhonored:{}:{}".format(spec.name, path),
                details={"path": path, "nbytes": _leaf_nbytes(leaf)}))
    # remaining big inputs that still match an unclaimed output could be
    # donated — each one doubles its HBM while the program runs
    for i, (argnum, path, leaf) in enumerate(leaves):
        if i in donated or _match_prefix(path, spec.keep_args):
            continue
        nbytes = _leaf_nbytes(leaf)
        if nbytes < min_bytes:
            continue
        aval = _abstract(leaf)
        key = (tuple(aval.shape), _dtype_key(aval.dtype))
        if take(key):
            findings.append(Finding(
                rule="donation", check="donation_miss", program=spec.name,
                message="program {!r} input {} ({:.1f} MB) matches an "
                        "output it could alias but is not donated — HBM "
                        "holds both copies across the step (add it to "
                        "donate_argnums, or declare it live via the "
                        "spec's keep_args)".format(
                            spec.name, path, nbytes / 2 ** 20),
                key="donation_miss:{}:{}".format(spec.name, path),
                details={"path": path, "nbytes": nbytes,
                         "argnum": argnum}))
    return findings


def sequence_findings(sequence):
    """Read-after-donation over the engine's declared step sequence:
    ``[{"program", "reads", "donates", "produces"}, ...]`` with state-
    field names. A field read after a prior program donated it — without
    an intervening producer rebinding it — is a use-after-free the
    runtime would surface as 'Buffer has been deleted or donated'."""
    findings = []
    dead = {}                      # field -> donor program
    for step in sequence:
        name = step.get("program", "?")
        for field in step.get("reads", ()):
            if field in dead:
                findings.append(Finding(
                    rule="donation", check="read_after_donation",
                    program=name, severity="error",
                    message="program {!r} reads state field {!r} after "
                            "program {!r} donated it without a rebind — "
                            "the buffer is gone at runtime".format(
                                name, field, dead[field]),
                    key="read_after_donation:{}:{}".format(name, field),
                    details={"field": field, "donor": dead[field]}))
        for field in step.get("donates", ()):
            dead.setdefault(field, name)
        for field in step.get("produces", ()):
            dead.pop(field, None)
    return findings


# ------------------------------------------------------- dtype promotion
def taint_vector(spec):
    """Per-flat-leaf taint seeds: low-precision leaves under the spec's
    taint_paths."""
    taint = []
    for _, path, leaf in flat_arg_leaves(spec.args):
        aval = _abstract(leaf)
        low = str(aval.dtype) in ("bfloat16", "float16")
        taint.append(low and _match_prefix(path, spec.taint_paths))
    return taint


def dtype_findings(spec, walk_result, fp32_allowlist=()):
    """fp32 GEMMs whose operand IS a (cast) bf16/fp16 param.

    The param-passthrough taint channel flags values that are still the
    weight itself after casts/layout moves/gathers — so a weight upcast
    into a float32 matmul fires, while intentional fp32 stability
    islands over ACTIVATIONS (attention scores/softmax, the loss, norm
    statistics, the fp32 Adam math) stay naturally exempt."""
    findings = []
    seen = set()
    for info in walk_result.eqns:
        if info.prim not in GEMM_PRIMS:
            continue
        if info.prim in fp32_allowlist:
            continue
        hot = False
        for i, v in enumerate(info.eqn.invars):
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "dtype"):
                continue
            if str(aval.dtype) == "float32" and \
                    i < len(info.in_taint2) and info.in_taint2[i]:
                hot = True
                break
        if not hot:
            continue
        out_shape = tuple(info.eqn.outvars[0].aval.shape) \
            if info.eqn.outvars else ()
        dedup = (info.path, out_shape)
        if dedup in seen:
            continue
        seen.add(dedup)
        findings.append(Finding(
            rule="dtype_promotion", check="fp32_gemm_from_bf16",
            program=spec.name,
            message="program {!r} feeds a bf16/fp16 param UPCAST to "
                    "float32 into a {} (out {}) at {} — the fp32 leak "
                    "drags the whole GEMM off the bf16 MXU path; cast "
                    "the weight back to the compute dtype, or allowlist "
                    "the op via analysis.fp32_allowlist".format(
                        spec.name, info.prim, list(out_shape), info.path),
            key="fp32_gemm_from_bf16:{}:{}".format(spec.name, info.path),
            details={"prim": info.prim, "path": info.path,
                     "out_shape": list(out_shape),
                     "trips": info.trips}))
    return findings


# ------------------------------------------------ host-sync / recompile
def host_sync_findings(spec, walk_result):
    findings = []
    for info in walk_result.by_prim(*HOST_PRIMS):
        findings.append(Finding(
            rule="host_sync", check="host_callback", program=spec.name,
            message="program {!r} traces a {!r} op at {} — a host "
                    "callback under jit forces a device<->host sync "
                    "every call (and pins the step to host latency); "
                    "move it outside the jitted step or behind a "
                    "debug-only gate".format(spec.name, info.prim,
                                             info.path),
            key="host_callback:{}:{}".format(spec.name, info.prim),
            details={"prim": info.prim, "path": info.path,
                     "trips": info.trips}))
    return findings


def hazard_findings(spec,
                    storm_threshold=RECOMPILE_STORM_THRESHOLD_DEFAULT):
    """Ahead-of-time recompile hazards: weak-typed (Python-scalar)
    operands and program families whose static key space exceeds the
    storm threshold."""
    findings = []
    for _, path, leaf in flat_arg_leaves(spec.args):
        if _match_prefix(path, spec.allow_weak):
            continue
        aval = _abstract(leaf)
        if getattr(aval, "weak_type", False):
            findings.append(Finding(
                rule="host_sync", check="weak_typed_operand",
                program=spec.name,
                message="program {!r} operand {} is weak-typed (a bare "
                        "Python scalar reached the jit boundary) — call "
                        "sites that mix scalar kinds fragment the "
                        "compile cache; pass jnp.asarray(x, dtype) "
                        "instead (or declare the block stable via the "
                        "spec's allow_weak)".format(spec.name, path),
                key="weak_typed_operand:{}:{}".format(spec.name, path),
                details={"path": path, "dtype": str(aval.dtype)}))
    if spec.trace_bound is not None:
        f = recompile_storm_finding(
            spec.name, int(spec.trace_bound), storm_threshold,
            hint="its static key space already exceeds the threshold — "
                 "bound it (e.g. inference.prefill_buckets)")
        if f is not None:
            findings.append(f)
    return findings


# ------------------------------------------------------- sharding drift
def _spec_mentions(sharding, axes):
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return False
    for entry in spec:
        cands = entry if isinstance(entry, tuple) else (entry,)
        if any(ax in axes for ax in cands):
            return True
    return False


def sharding_findings(spec, walk_result,
                      replicated_leaf_bytes=REPLICATED_LEAF_BYTES_DEFAULT):
    """Replicated-input audit (shared core) + the sharding-constraint
    census against the plan's expectation."""
    findings = []
    n_dev = 1
    if spec.mesh is not None:
        n_dev = int(np.prod(list(dict(spec.mesh.shape).values()),
                            dtype=np.int64))
    if n_dev > 1:
        for _, path, leaf in flat_arg_leaves(spec.args):
            sharding = _leaf_sharding(leaf)
            if sharding is None or \
                    not getattr(sharding, "is_fully_replicated", False):
                continue
            f = replicated_leaf_finding(
                spec.name, path, _leaf_nbytes(leaf), n_dev,
                replicated_leaf_bytes)
            if f is not None:
                findings.append(f)
    if spec.expected_constraints > 0 and spec.constraint_axes:
        axes = set(spec.constraint_axes)
        count = 0
        for info in walk_result.by_prim("sharding_constraint"):
            if _spec_mentions(info.eqn.params.get("sharding"), axes):
                count += 1
        if count < spec.expected_constraints:
            findings.append(Finding(
                rule="sharding_drift", check="missing_sharding_constraint",
                program=spec.name,
                message="program {!r} carries {} sharding constraints "
                        "naming the plan's data axes {} but the resolved "
                        "ZeroShardingPlan expects at least {} — a "
                        "with_sharding_constraint was dropped and XLA is "
                        "free to place (and all-gather) that state "
                        "behind your back".format(
                            spec.name, count, sorted(axes),
                            spec.expected_constraints),
                key="missing_sharding_constraint:{}".format(spec.name),
                details={"found": count,
                         "expected": spec.expected_constraints,
                         "axes": sorted(axes)}))
    return findings


# ------------------------------------------------------------- auditing
def audit_program(spec, config=None):
    """Run every jaxpr-level rule class on one ProgramSpec.

    Returns (closed_jaxpr, walk_result, [Finding]); tracing errors
    surface as an ``audit_error`` finding rather than killing the whole
    report."""
    cfg = config
    storm = getattr(cfg, "storm_threshold",
                    RECOMPILE_STORM_THRESHOLD_DEFAULT)
    repl = getattr(cfg, "replicated_leaf_bytes",
                   REPLICATED_LEAF_BYTES_DEFAULT)
    don = getattr(cfg, "donation_min_bytes", DONATION_MIN_BYTES_DEFAULT)
    allow = tuple(getattr(cfg, "fp32_allowlist", ()) or ())
    try:
        fn = spec.build()
        closed = jax.make_jaxpr(fn)(*spec.args)
    except Exception as err:  # noqa: BLE001 - report, don't die
        return None, None, [Finding(
            rule="host_sync", check="audit_error", program=spec.name,
            severity="error",
            message="program {!r} could not be abstract-evaluated: "
                    "{}".format(spec.name, err),
            key="audit_error:{}".format(spec.name),
            details={"error": repr(err)})]
    taint = taint_vector(spec)
    walk_result = walk(closed, taint_in=taint, taint2_in=taint)
    findings = []
    findings += sharding_findings(spec, walk_result,
                                  replicated_leaf_bytes=repl)
    findings += donation_findings(spec, closed, min_bytes=don)
    findings += dtype_findings(spec, walk_result, fp32_allowlist=allow)
    findings += host_sync_findings(spec, walk_result)
    findings += hazard_findings(spec, storm_threshold=storm)
    return closed, walk_result, findings
