"""Jaxpr walker: the flat op-record IR every shard-lint rule reads.

``walk(closed_jaxpr, taint_in)`` recursively flattens a (possibly
deeply nested) jaxpr — through pjit/scan/while/cond/custom_vjp/remat/
shard_map bodies — into a list of :class:`EqnInfo` records, each
carrying:

  * the primitive name and the eqn itself (params stay reachable);
  * ``path``: the nesting chain ("scan/custom_vjp_call/…") for
    diagnostics;
  * ``trips``: the static execution multiplier (a ``scan`` body's eqns
    run ``length`` times; ``None`` under a ``while`` whose trip count
    is dynamic) — byte census math multiplies by it;
  * ``tainted``: whether any operand is data-derived from a tainted
    program input (the dtype-promotion rule seeds the taint at the
    bf16 param leaves).

``classify(prim_name)`` buckets a primitive into the small segment
vocabulary (compute / collective / host / transfer / sharding) — the
same vocabulary ROADMAP item 5's schedulable segment graph lowers onto;
this walker is deliberately the first concrete piece of that IR.

``pallas_call`` eqns are recorded as ONE opaque classified segment
(``classify_pallas``: "collective" when the kernel body carries the
remote-copy ring signature — axis_index / manual semaphores — else
"compute") with the surrounding trip count; the kernel jaxpr itself is
a mutable-Ref machine the value-semantics rules cannot read, so it is
censused (``pallas_body_prims``) but never flattened.
"""
import dataclasses

import numpy as np

import jax

# ---------------------------------------------------------------- vocab
COLLECTIVE_PRIMS = frozenset({
    "ppermute", "pshuffle", "psum", "psum_scatter", "pmax", "pmin",
    "all_gather", "all_to_all", "pgather", "reduce_scatter",
})
HOST_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "callback", "outside_call", "host_callback_call", "infeed", "outfeed",
})
TRANSFER_PRIMS = frozenset({"device_put", "copy"})
SHARDING_PRIMS = frozenset({"sharding_constraint"})
GEMM_PRIMS = frozenset({"dot_general", "conv_general_dilated"})
CONVERT_PRIMS = frozenset({"convert_element_type"})

SEGMENT_KINDS = ("compute", "collective", "host", "transfer", "sharding")

# Primitives that carry a PARAM through unchanged-in-substance: casts,
# layout moves, gathers/rings re-materializing a sharded weight. The
# dtype-promotion rule's second taint channel ("this value IS a weight,
# possibly cast") propagates only through these — a dot/add output is a
# new activation, not a weight, which keeps intentional fp32 stability
# islands (attention scores/softmax, loss, norms, Adam) naturally
# exempt while a weight upcast into a GEMM still lights up.
PARAM_PASSTHROUGH_PRIMS = frozenset({
    "convert_element_type", "transpose", "reshape", "broadcast_in_dim",
    "squeeze", "expand_dims", "slice", "dynamic_slice", "concatenate",
    "rev", "copy", "sharding_constraint", "ppermute", "all_gather",
    "gather", "mul", "add_any",
    # qwZ codec ops re-materialize the SAME weight from int8+scales
    "bitcast_convert_type",
})


def classify(prim_name):
    """Primitive -> segment kind (the schedulable-segment vocabulary)."""
    if prim_name in COLLECTIVE_PRIMS:
        return "collective"
    if prim_name in HOST_PRIMS:
        return "host"
    if prim_name in TRANSFER_PRIMS:
        return "transfer"
    if prim_name in SHARDING_PRIMS:
        return "sharding"
    return "compute"


# Kernel-body prims that mark a pallas_call as CROSS-DEVICE: the ring
# GEMMs read their mesh position (axis_index) to address the remote
# copies, and manual semaphore signaling only appears in collective
# kernels. A body without them (flash attention, the paged-attention
# page walk — local HBM->VMEM DMAs only) is a compute segment.
PALLAS_COLLECTIVE_PRIMS = frozenset({
    "axis_index", "semaphore_signal", "semaphore_wait",
})


def pallas_body_prims(eqn):
    """Primitive-name census of a ``pallas_call`` eqn's kernel jaxpr
    (recursive through nested control flow)."""
    prims = set()

    def collect(obj):
        jx = _jaxpr_of(obj)
        for inner_eqn in getattr(jx, "eqns", ()):
            prims.add(inner_eqn.primitive.name)
            for val in inner_eqn.params.values():
                if hasattr(val, "eqns") or hasattr(val, "jaxpr"):
                    collect(val)
                elif isinstance(val, (list, tuple)):
                    for item in val:
                        if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                            collect(item)

    kernel = eqn.params.get("jaxpr")
    if kernel is not None:
        collect(kernel)
    return prims


def classify_pallas(eqn):
    """Segment kind for one ``pallas_call``: "collective" when the
    kernel body carries the remote-copy ring signature, else "compute".
    The body itself is NOT flattened into the op-record IR — kernel
    jaxprs operate on mutable Refs (get/swap/dma), a different register
    machine than the value-semantics rules (donation, dtype taint,
    sharding) are written against — so the call is recorded as ONE
    opaque classified segment with the surrounding trip count
    (docs/analysis.md "Pallas kernels")."""
    if pallas_body_prims(eqn) & PALLAS_COLLECTIVE_PRIMS:
        return "collective"
    return "compute"


def dtype_itemsize(dtype):
    """Itemsize that tolerates jax extended dtypes (key<fry> etc.)."""
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return int(getattr(dtype, "itemsize", 4))


@dataclasses.dataclass
class EqnInfo:
    prim: str
    eqn: object
    path: str
    trips: object          # int multiplier, or None when dynamic
    tainted: bool
    kind: str
    # per-operand flags of the second (param-passthrough) taint
    # channel, positionally aligned with eqn.invars
    in_taint2: tuple = ()

    def out_nbytes(self):
        total = 0
        for var in self.eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None and hasattr(aval, "shape") and \
                    hasattr(aval, "dtype"):
                numel = int(np.prod(aval.shape, dtype=np.int64)) \
                    if aval.shape else 1
                total += numel * dtype_itemsize(aval.dtype)
        return total


class WalkResult:
    def __init__(self):
        self.eqns = []          # [EqnInfo]
        self.out_taint = []     # [bool] aligned with jaxpr.outvars
        self.out_taint2 = []    # [bool] param-passthrough channel

    def by_kind(self, kind):
        return [e for e in self.eqns if e.kind == kind]

    def by_prim(self, *prims):
        prims = frozenset(prims)
        return [e for e in self.eqns if e.prim in prims]


def _inner_jaxprs(eqn):
    """-> [(closed_or_open_jaxpr, invar_offset)] for one eqn's bodies.

    ``invar_offset``: index into ``eqn.invars`` where the body's invars
    start aligning (tail alignment — custom_* calls may carry leading
    consts/tangent args the body does not see)."""
    params = eqn.params
    name = eqn.primitive.name
    out = []
    if name in ("cond", "switch"):
        for br in params.get("branches", ()):
            out.append((br, 1))                       # invars[0] = index
        return out
    if name == "while":
        # cond sees (cond_consts, carry); body sees (body_consts,
        # carry) — walk() handles the split itself (_while_taints);
        # direct callers get the bodies tail-aligned
        out.append((params["cond_jaxpr"], None))
        out.append((params["body_jaxpr"], None))
        return out
    for key in ("jaxpr", "call_jaxpr"):
        if key in params and params[key] is not None:
            out.append((params[key], None))
    return out


def _jaxpr_of(obj):
    """ClosedJaxpr | Jaxpr -> Jaxpr."""
    return getattr(obj, "jaxpr", obj)


def _map_taint_into(eqn, inner, taint_of):
    """Taint flags for ``inner``'s invars, from the eqn's operand taint.

    Tail-aligned: the last ``len(inner.invars)`` eqn operands map 1:1;
    shorter bodies (consts baked into the ClosedJaxpr) still line up
    because jax orders call operands (consts..., args...). When the
    shapes make no sense, degrade conservatively: every inner invar
    inherits "any operand tainted"."""
    jx = _jaxpr_of(inner)
    n_in = len(jx.invars)
    op_taint = [taint_of(v) for v in eqn.invars]
    if n_in <= len(op_taint):
        return op_taint[len(op_taint) - n_in:]
    any_t = any(op_taint)
    return [any_t] * n_in


def _while_taints(eqn, taint_of):
    params = eqn.params
    cn = int(params.get("cond_nconsts", 0))
    bn = int(params.get("body_nconsts", 0))
    op = [taint_of(v) for v in eqn.invars]
    cond_in = op[:cn] + op[cn + bn:]
    body_in = op[cn:cn + bn] + op[cn + bn:]
    return cond_in, body_in


def walk(closed_jaxpr, taint_in=None, taint2_in=None, _path="",
         _trips=1, _result=None):
    """Flatten ``closed_jaxpr`` into a :class:`WalkResult`.

    ``taint_in``: bool per invar (default: none tainted) — the DEEP
    data-derivation channel (any op output of a tainted input is
    tainted). ``taint2_in``: the PARAM-PASSTHROUGH channel — only
    :data:`PARAM_PASSTHROUGH_PRIMS` propagate it, so a flag means "this
    value is still the weight itself (possibly cast/moved/gathered)".
    ``trips`` multiplies through ``scan`` lengths and becomes None
    inside ``while`` bodies (dynamic trip count).
    """
    result = _result if _result is not None else WalkResult()
    jaxpr = _jaxpr_of(closed_jaxpr)
    n_in = len(jaxpr.invars)
    taint_in = list(taint_in) if taint_in is not None else [False] * n_in
    taint2_in = list(taint2_in) if taint2_in is not None \
        else [False] * n_in

    tainted = {}                    # Var -> bool
    tainted2 = {}
    for var, t, t2 in zip(jaxpr.invars, taint_in, taint2_in):
        tainted[var] = bool(t)
        tainted2[var] = bool(t2)

    def _of(table, var):
        try:
            return table.get(var, False)
        except TypeError:           # jax.core.Literal is unhashable
            return False

    def taint_of(var):
        return _of(tainted, var)

    def taint2_of(var):
        return _of(tainted2, var)

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        in_taint = any(taint_of(v) for v in eqn.invars)
        in_taint2 = tuple(taint2_of(v) for v in eqn.invars)
        if name == "pallas_call":
            # ONE opaque classified segment (compute, or collective for
            # the remote-copy ring kernels) at the surrounding trip
            # count. The kernel jaxpr is a Ref machine (get/swap/dma) —
            # flattening it into the value-semantics op records would
            # feed the rules ops they cannot read — so taint flows
            # conservatively input->output and channel 2 stops (a
            # kernel output is a new activation, never the weight).
            for var in eqn.outvars:
                tainted[var] = in_taint or _of(tainted, var)
                tainted2[var] = _of(tainted2, var)
            result.eqns.append(EqnInfo(
                prim=name, eqn=eqn, path=_path + name, trips=_trips,
                tainted=in_taint, kind=classify_pallas(eqn),
                in_taint2=in_taint2))
            continue
        trips = _trips
        if name == "scan":
            length = eqn.params.get("length")
            if _trips is not None and isinstance(length, int):
                trips = _trips * length
            else:
                trips = None
        elif name == "while":
            trips = None

        inner_outs = []             # [(out_taint, out_taint2)]
        if name == "while":
            cond_in, body_in = _while_taints(eqn, taint_of)
            _, body_in2 = _while_taints(eqn, taint2_of)
            params = eqn.params
            bn = int(params.get("body_nconsts", 0))
            # one extra pass feeds carry-out taint back into carry-in
            body_taint = list(body_in)
            for _ in range(2):
                sub = walk(params["body_jaxpr"], body_taint, body_in2,
                           _path=_path + name + "/", _trips=None,
                           _result=None)
                carry_out = sub.out_taint
                new_carry_in = [a or b for a, b in
                                zip(body_taint[bn:], carry_out)]
                if new_carry_in == body_taint[bn:]:
                    break
                body_taint = body_taint[:bn] + new_carry_in
            # record the final body (and the cond) into the result
            sub = walk(params["body_jaxpr"], body_taint, body_in2,
                       _path=_path + name + "/", _trips=None,
                       _result=result)
            walk(params["cond_jaxpr"], cond_in, None,
                 _path=_path + name + "/", _trips=None, _result=result)
            inner_outs.append((sub.out_taint, sub.out_taint2))
        else:
            for inner, offset in _inner_jaxprs(eqn):
                if offset is None:
                    inner_taint = _map_taint_into(eqn, inner, taint_of)
                    inner_taint2 = _map_taint_into(eqn, inner, taint2_of)
                else:
                    jx = _jaxpr_of(inner)
                    ops = [taint_of(v) for v in eqn.invars[offset:]]
                    ops2 = [taint2_of(v) for v in eqn.invars[offset:]]
                    inner_taint = (ops + [False] * len(jx.invars)
                                   )[:len(jx.invars)]
                    inner_taint2 = (ops2 + [False] * len(jx.invars)
                                    )[:len(jx.invars)]
                sub = walk(inner, inner_taint, inner_taint2,
                           _path=_path + name + "/", _trips=trips,
                           _result=result)
                inner_outs.append((sub.out_taint, sub.out_taint2))

        # output taint: prefer positional mapping from an inner body
        out_taint = None
        out_taint2 = None
        for sub_out, sub_out2 in inner_outs:
            if len(sub_out) == len(eqn.outvars):
                out_taint = sub_out if out_taint is None else \
                    [a or b for a, b in zip(out_taint, sub_out)]
                out_taint2 = sub_out2 if out_taint2 is None else \
                    [a or b for a, b in zip(out_taint2, sub_out2)]
        if out_taint is None:
            any_inner = any(any(o) for o, _ in inner_outs)
            out_taint = [in_taint or any_inner] * len(eqn.outvars)
        if out_taint2 is None:
            # channel 2 only flows through passthrough prims
            passthrough = name in PARAM_PASSTHROUGH_PRIMS and \
                any(in_taint2)
            out_taint2 = [passthrough] * len(eqn.outvars)
        for var, t, t2 in zip(eqn.outvars, out_taint, out_taint2):
            tainted[var] = bool(t) or _of(tainted, var)
            tainted2[var] = bool(t2) or _of(tainted2, var)

        result.eqns.append(EqnInfo(
            prim=name, eqn=eqn, path=_path + name, trips=trips,
            tainted=in_taint, kind=classify(name),
            in_taint2=in_taint2))

    result.out_taint = [taint_of(v) for v in jaxpr.outvars]
    result.out_taint2 = [taint2_of(v) for v in jaxpr.outvars]
    return result


def make_walk(fn, args, taint_in=None):
    """``jax.make_jaxpr`` + :func:`walk` in one step. ``args`` may hold
    ``ShapeDtypeStruct`` leaves — nothing executes."""
    closed = jax.make_jaxpr(fn)(*args)
    return closed, walk(closed, taint_in=taint_in)


def plan_of(engine, family=None):
    """The SEGMENT PLAN of an engine's step path (ISSUE 13): the same
    graph construction the executor runs
    (``runtime/executor/plan_for_engine``), abstract — topology,
    kinds, deps and declared prices with no payloads attached. Plan
    construction and audit share one graph: the auditor validates
    exactly the plan the engine executes (``audit_engine`` calls this
    for the offload/streamed families), and the concrete step builders
    attach payloads to the same topology, pinned by
    tests/unit/test_executor.py (executed segment records == plan
    nodes). ``family``: ``"offload_apply"`` / ``"streamed_micro"``, or
    None to resolve from the engine's live path."""
    from ..runtime.executor import plan_for_engine
    return plan_for_engine(engine, family)


def segment_summary(walk_result):
    """Aggregate the walked eqns into the segment vocabulary — the
    embryonic schedulable-segment view (ROADMAP item 5): per-kind op
    counts and output bytes (static trips multiplied in; dynamic-trip
    ops counted once and flagged)."""
    out = {kind: {"ops": 0, "out_bytes": 0} for kind in SEGMENT_KINDS}
    dynamic = 0
    for info in walk_result.eqns:
        slot = out[info.kind]
        trips = info.trips if info.trips is not None else 1
        if info.trips is None:
            dynamic += 1
        slot["ops"] += trips
        slot["out_bytes"] += trips * info.out_nbytes()
    out["dynamic_trip_ops"] = dynamic
    return out
