"""Audit orchestration: ProgramSpecs -> AnalysisReport -> disposition.

``audit_engine(engine, ...)`` is the one entry point both
``DeepSpeedEngine.audit()`` and ``InferenceEngine.audit()`` (and the
dryrun / CLI) call: it collects the engine's program specs
(analysis/programs.py), runs every jaxpr-level rule
(analysis/rules.py), optionally compiles each program for the HLO
collective census + output-sharding drift (analysis/hlo.py), routes
findings through the suppression file, and disposes per the
``analysis`` config section — warn (default), RAISE under
``analysis.strict``, and/or write the JSON report artifact
(``bin/check_bench_schema.py`` validates its shape).
"""
import numpy as np

import jax

from ..utils.logging import logger
from .findings import AnalysisReport, Finding, Suppressions
from .hlo import collective_census, reconcile_wire
from .ir import segment_summary
from .rules import audit_program, sequence_findings


class AuditFindingsError(RuntimeError):
    """Raised under ``analysis.strict`` when unsuppressed findings
    survive an audit."""

    def __init__(self, report):
        self.report = report
        lines = ["shard-lint: {} unsuppressed finding(s) "
                 "(analysis.strict=true):".format(len(report.findings))]
        lines += ["  - [{}] {}".format(f.key, f.message)
                  for f in report.findings]
        super().__init__("\n".join(lines))


def mesh_axis_labels(mesh):
    """{label: [frozenset(device ids)]} for every nontrivial mesh axis,
    plus the combined factored-data label when hpZ split the data axis."""
    from ..parallel.topology import (DATA_REPLICA_AXIS, DATA_SHARD_AXIS,
                                     mesh_axis_groups)
    labels = {}
    if mesh is None:
        return labels
    for ax in mesh.axis_names:
        if int(mesh.shape[ax]) > 1:
            labels[ax] = mesh_axis_groups(mesh, ax)
    factored = tuple(ax for ax in (DATA_REPLICA_AXIS, DATA_SHARD_AXIS)
                     if int(dict(mesh.shape).get(ax, 1)) > 1)
    if len(factored) > 1:
        labels["+".join(factored)] = mesh_axis_groups(mesh, factored)
    return labels


def data_axis_labels(mesh):
    """The label subset that carries ZeRO (data-axis) wire traffic."""
    from ..parallel.topology import (DATA_AXIS, DATA_REPLICA_AXIS,
                                     DATA_SHARD_AXIS)
    if mesh is None:
        return set()
    shape = dict(mesh.shape)
    out = {ax for ax in (DATA_AXIS, DATA_REPLICA_AXIS, DATA_SHARD_AXIS)
           if int(shape.get(ax, 1)) > 1}
    factored = tuple(ax for ax in (DATA_REPLICA_AXIS, DATA_SHARD_AXIS)
                     if int(shape.get(ax, 1)) > 1)
    if len(factored) > 1:
        out.add("+".join(factored))
    return out


def _output_drift_findings(spec, fn, compiled):
    """Compiled output shardings vs. the plan: every output leaf the
    spec expects data-sharded must not come back fully replicated."""
    expects = spec.meta.get("out_expect") or ()
    if not expects:
        return []
    try:
        out_shardings = compiled.output_shardings
        out_struct = jax.eval_shape(fn, *spec.args)
    except Exception as err:  # noqa: BLE001 - census is best-effort
        logger.info("shard-lint: output shardings unavailable for %r "
                    "(%s)", spec.name, err)
        return []
    # join by PATH, never by zip: the two trees flatten differently
    # around None leaves (offload state carries "master": None), and a
    # positional pairing would silently shift every entry after one
    from .rules import _kp_str, _spec_mentions
    flat_sh, _ = jax.tree_util.tree_flatten_with_path(
        out_shardings, is_leaf=lambda x: hasattr(x, "spec") or x is None)
    flat_st, _ = jax.tree_util.tree_flatten_with_path(
        out_struct, is_leaf=lambda x: x is None or hasattr(x, "shape"))
    shardings_by_path = {_kp_str(kp): sh for kp, sh in flat_sh}
    by_path = {}
    for kp, st in flat_st:
        path = _kp_str(kp)
        if st is not None and path in shardings_by_path:
            by_path[path] = (shardings_by_path[path], st)
    findings = []
    for path, axes in expects:
        ent = by_path.get(path)
        if ent is None:
            continue
        sh, st = ent
        nbytes = int(np.prod(st.shape, dtype=np.int64) *
                     np.dtype(st.dtype).itemsize) if st.shape else 0
        if sh is None or _spec_mentions(sh, set(axes)):
            continue
        findings.append(Finding(
            rule="sharding_drift", check="output_sharding_drift",
            program=spec.name,
            message="program {!r} output {} ({:.1f} MB) compiled back "
                    "REPLICATED but the ZeroShardingPlan shards it over "
                    "{} — the step un-shards state the plan paid to "
                    "partition (HBM grows every step)".format(
                        spec.name, path, nbytes / 2 ** 20, list(axes)),
            key="output_sharding_drift:{}:{}".format(spec.name, path),
            details={"path": path, "axes": list(axes),
                     "nbytes": nbytes}))
    return findings


def audit_programs(specs, config, job="audit", suppressions=None,
                   sequence=(), hlo=False, wire_est=None, mesh=None,
                   report_path=None, extra_findings=()):
    """Run the full rule set over ``specs`` and assemble the report.

    ``hlo=True`` additionally compiles each spec whose meta carries a
    ``wire_multiplier`` or ``out_expect`` and runs the collective
    census / output-drift checks; the summed census reconciles against
    ``wire_est`` when given. ``extra_findings``: pre-built findings
    (the lock sanitizer's) routed through the same suppression file.
    The walked collective sequences land in
    ``report.collective_families`` — the program-fingerprint source
    (ISSUE 15; analysis/concurrency/divergence.py).
    """
    from .concurrency.divergence import (collective_tokens,
                                         control_flow_findings)
    report = AnalysisReport(job=job)
    if isinstance(suppressions, str):
        suppressions = Suppressions.load(suppressions)
    axis_labels = mesh_axis_labels(mesh) if hlo else {}
    data_labels = data_axis_labels(mesh)
    census_list = []
    for spec in specs:
        closed, walk_result, findings = audit_program(spec, config)
        report.extend(findings, suppressions)
        meta = {"family": spec.family,
                "donate_argnums": list(spec.donate)}
        if walk_result is not None:
            meta["segments"] = segment_summary(walk_result)
            report.collective_families[spec.name] = \
                collective_tokens(walk_result)
            report.extend(control_flow_findings(spec.name, walk_result),
                          suppressions)
        if hlo and closed is not None and (
                spec.meta.get("wire_multiplier") or
                spec.meta.get("out_expect")):
            try:
                from ..runtime.executor.jit import jit_program
                fn = jit_program(spec.build(), donate=spec.donate)
                compiled = fn.lower(*spec.args).compile()
            except Exception as err:  # noqa: BLE001 - report, don't die
                report.add(Finding(
                    rule="sharding_drift", check="audit_error",
                    program=spec.name, severity="error",
                    message="program {!r} could not be compiled for the "
                            "HLO census: {}".format(spec.name, err),
                    key="audit_error:hlo:{}".format(spec.name)),
                    suppressions)
            else:
                report.extend(_output_drift_findings(spec, fn, compiled),
                              suppressions)
                mult = int(spec.meta.get("wire_multiplier") or 0)
                if mult > 0:
                    census = collective_census(
                        compiled.as_text(), axis_groups=axis_labels,
                        min_bytes=getattr(config, "census_min_bytes",
                                          1024))
                    for op in census["ops"]:
                        op["wire_bytes"] *= mult
                    census["total_bytes"] *= mult
                    for slot in census["by_axis"].values():
                        slot["wire_bytes"] *= mult
                    meta["collective_census"] = {
                        "total_bytes": census["total_bytes"],
                        "by_axis": census["by_axis"],
                    }
                    census_list.append(census)
        report.add_program(spec.name, **meta)
    if sequence:
        report.extend(sequence_findings(sequence), suppressions)
    if extra_findings:
        report.extend(extra_findings, suppressions)
    if hlo and census_list and wire_est is not None:
        sharded_grads = any(
            getattr(s.plan, "stage", 0) >= 2 for s in specs
            if s.plan is not None)
        payload, findings = reconcile_wire(
            census_list, wire_est, data_labels,
            program=job,
            min_bytes=getattr(config, "census_min_bytes", 1024),
            normalize_allreduce=sharded_grads and
            jax.default_backend() != "tpu")
        report.census = payload
        report.extend(findings, suppressions)
    if suppressions is not None:
        # a suppression whose finding no longer exists is a latent mask
        # for a future regression with the same key — surface it loudly
        # (it lands in the report as stale_suppressions, non-failing)
        report.stale_suppressions = suppressions.stale()
        for key in report.stale_suppressions:
            logger.warning(
                "shard-lint: suppression %r matched nothing this audit "
                "— prune it from %s", key,
                suppressions.path or "the suppression list")
    if report_path:
        report.write(report_path)
    return report


def dispose(report, config, raise_on_findings=None):
    """Warn each unsuppressed finding; raise under analysis.strict."""
    for f in report.findings:
        logger.warning("shard-lint: %s", f.message)
    strict = raise_on_findings if raise_on_findings is not None \
        else getattr(config, "strict", False)
    if strict and report.findings:
        raise AuditFindingsError(report)
    return report


def audit_plan(engine, report):
    """Lowered-plan verification (ISSUE 13): build the abstract segment
    plan of the engine's step path through the SAME entry point the
    executor uses (``ir.plan_of``) and run the plan-level rules —
    unique names, IR-vocabulary kinds, resolvable topologically-ordered
    deps. Plan problems are unsuppressable findings (a malformed plan
    is a bug in the lowering, never an accepted quirk); the plan's
    shape lands in the report's program table as ``plan/<name>``."""
    if getattr(engine, "stream_runner", None) is None and \
            getattr(engine, "host_state", None) is None and \
            getattr(engine, "pipe_module", None) is None and \
            not hasattr(engine, "prefill_buckets"):
        return None                 # micro/fused: one-segment plans
    from .ir import plan_of
    try:
        plan = plan_of(engine)
    except Exception as err:  # noqa: BLE001 - report, don't die
        report.add(Finding(
            rule="executor_plan", check="plan_build_error",
            program="plan", severity="error",
            message="segment plan could not be built for the audit: "
                    "{}".format(err),
            key="plan_build_error"))
        return None
    for i, problem in enumerate(plan.validate()):
        report.add(Finding(
            rule="executor_plan", check="plan_invalid",
            program="plan/" + plan.name, severity="error",
            message="segment plan {!r} is invalid: {}".format(
                plan.name, problem),
            key="plan_invalid:{}:{}".format(plan.name, i)))
    summary = plan.summary()
    report.add_program("plan/" + plan.name, family="plan",
                       plan_segments=summary["segments"],
                       per_kind=summary["per_kind"])
    return plan


def audit_engine(engine, batch=None, hlo=None, report_path=None,
                 strict=None):
    """Ahead-of-time shard-lint over one engine's resolved step
    programs. ``engine`` is a DeepSpeedEngine (micro/fused/offload/
    streamed/pipeline paths) or an InferenceEngine
    (prefill/decode/spec-verify). Returns the
    :class:`AnalysisReport`; raises :class:`AuditFindingsError` when
    unsuppressed findings survive and strict is on (argument overrides
    the config).

    ``batch``: a sample micro-batch (arrays or ShapeDtypeStructs) for
    training engines that have not seen a step yet; ``hlo`` overrides
    ``analysis.hlo`` (compile + collective census + output drift).
    """
    from . import programs as collectors
    if hasattr(engine, "prefill_buckets"):           # inference engine
        config = engine.analysis_config
        specs = collectors.collect_inference_programs(engine)
        sequence = collectors.inference_step_sequence(engine)
        mesh = engine.mesh
        wire_est = None
        job = "serve"
    else:
        config = engine._config.analysis_config
        specs = collectors.collect_train_programs(engine, batch=batch)
        sequence = collectors.train_step_sequence(engine)
        mesh = engine.mesh
        wire_est = None
        try:
            from ..runtime.comm.wire import estimate_engine_comm_bytes
            if engine.zero_plan.dp_size > 1 and \
                    engine.state.get("params") is not None:
                # min_component: drop estimator components below the
                # census threshold so the diff compares like-for-like
                # (the 1-bit exchange's scalar-scale gathers are a few
                # dozen bytes — below any census floor)
                wire_est = estimate_engine_comm_bytes(
                    engine, min_component=getattr(
                        config, "census_min_bytes", 1024))
        except Exception as err:  # noqa: BLE001 - estimator optional
            logger.info("shard-lint: wire estimate unavailable (%s)", err)
        job = "train"
    use_hlo = bool(config.hlo if hlo is None else hlo)
    # lock-sanitizer findings (docs/concurrency.md) ride the same
    # report — and the same suppression file — as the program rules
    from .concurrency import locksan
    san = locksan.current()
    report = audit_programs(
        specs, config, job=job,
        suppressions=config.suppressions, sequence=sequence,
        hlo=use_hlo, wire_est=wire_est, mesh=mesh,
        extra_findings=san.report() if san is not None else ())
    # lowered-plan verification rides the same report (and lands in
    # the same artifact) as the program rules — serving included, now
    # that the scheduler's step is a lowered serving_step plan
    plan = audit_plan(engine, report)
    # canonical program fingerprint (ISSUE 15): the collective order of
    # every walked program + the lowered plan topology, published into
    # this host's manifest so bin/ds_fleet.py can verify the whole
    # fleet lowered the SAME program
    if report.collective_families and \
            getattr(config, "concurrency_fingerprint", True):
        from .concurrency.divergence import (canonical_fingerprint,
                                             plan_tokens)
        fams = dict(report.collective_families)
        if plan is not None:
            fams["plan/" + plan.name] = plan_tokens(plan)
        report.fingerprint = canonical_fingerprint(fams)
        tel = getattr(engine, "telemetry", None)
        if tel is not None:
            tel.publish_fingerprint(report.fingerprint)
    out_path = report_path or config.report_path
    if out_path:
        report.write(out_path)
    return dispose(report, config, raise_on_findings=strict)
