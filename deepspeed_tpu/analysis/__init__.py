"""Shard-lint: ahead-of-time SPMD program auditing (docs/analysis.md).

Abstract-evals every engine step program from ``ShapeDtypeStruct``s +
the resolved ``ZeroShardingPlan`` and walks the jaxpr (and optionally
the compiled HLO) for the failure modes that silently destroy MFU:
sharding drift, missed buffer donations, fp32 upcasts in the bf16 GEMM
path, host callbacks under jit, and recompile storms — before a single
step runs. ``bin/ds_lint.py`` adds the repo-wide AST hot-path linter.
"""
from .findings import (AnalysisReport, Finding, Suppressions,
                       validate_analysis_report)
from .rules import (ProgramSpec, RECOMPILE_STORM_THRESHOLD_DEFAULT,
                    REPLICATED_LEAF_BYTES_DEFAULT, audit_program,
                    recompile_storm_finding, replicated_leaf_finding)
from .auditor import (AuditFindingsError, audit_engine, audit_programs,
                      dispose)
from .config import ANALYSIS, DeepSpeedAnalysisConfig, KNOWN_ANALYSIS_KEYS

__all__ = [
    "AnalysisReport", "Finding", "Suppressions",
    "validate_analysis_report", "ProgramSpec", "audit_program",
    "audit_programs", "audit_engine", "dispose", "AuditFindingsError",
    "DeepSpeedAnalysisConfig", "ANALYSIS", "KNOWN_ANALYSIS_KEYS",
    "replicated_leaf_finding", "recompile_storm_finding",
    "RECOMPILE_STORM_THRESHOLD_DEFAULT", "REPLICATED_LEAF_BYTES_DEFAULT",
]
