"""Findings, reports and suppressions for the shard-lint auditor.

A :class:`Finding` is one structured defect the static auditor (or the
repo AST linter) surfaced: which rule fired, on which program (or file),
what the hazard is, and a stable ``key`` the suppression file matches
against. An :class:`AnalysisReport` is the JSON-able artifact one audit
run produces — ``bin/check_bench_schema.py`` validates its shape (a
stdlib re-statement; tests/unit/test_analysis.py pins the key tables
equal so they cannot drift).

Suppression file (committed next to the config that owns the findings)::

    {
      "version": 1,
      "suppressions": [
        {"key": "replicated_leaf:prefill/*", "reason": "persistent ..."},
        {"key": "DSL003:deepspeed_tpu/runtime/engine.py::*", "reason": "."}
      ]
    }

``key`` patterns are ``fnmatch`` globs against ``Finding.key``
(``<check>:<program>[:<detail>]`` for program findings,
``<rule>:<path>::<qualname>`` for repo-lint findings). Every
suppression must carry a non-empty ``reason`` — a silent suppression is
the config smell this subsystem exists to kill.
"""
import dataclasses
import fnmatch
import json
import os

# the report artifact's required keys; check_bench_schema.py keeps a
# stdlib copy (ANALYSIS_REPORT_KEYS there) pinned equal under test
ANALYSIS_REPORT_KEYS = (
    "kind", "version", "job", "programs", "findings", "suppressed",
    "summary",
)
ANALYSIS_REPORT_KIND = "analysis_report"

# required keys of one serialized finding (also mirrored in
# check_bench_schema.py)
FINDING_KEYS = ("rule", "check", "program", "severity", "message", "key")

SEVERITIES = ("error", "warn", "info")


@dataclasses.dataclass
class Finding:
    """One structured defect.

    ``rule``     the rule class ("sharding_drift", "donation",
                 "dtype_promotion", "host_sync", or a DSL### repo-lint
                 code);
    ``check``    the specific check inside the class (e.g.
                 "replicated_leaf", "donation_miss");
    ``program``  the audited program's name (or the repo-relative file
                 path for repo-lint findings);
    ``key``      the stable suppression key;
    ``details``  machine-readable extras (byte counts, leaf paths, line
                 numbers) for the JSON report.
    """
    rule: str
    check: str
    program: str
    message: str
    severity: str = "warn"
    key: str = ""
    details: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if not self.key:
            self.key = "{}:{}".format(self.check, self.program)
        assert self.severity in SEVERITIES, self.severity

    def to_dict(self):
        out = {
            "rule": self.rule,
            "check": self.check,
            "program": self.program,
            "severity": self.severity,
            "message": self.message,
            "key": self.key,
        }
        if self.details:
            out["details"] = _jsonable(self.details)
        return out


def _jsonable(val):
    """Degrade arbitrary detail values to JSON-safe types (the flight
    recorder's discipline: a report must never fail to serialize)."""
    if isinstance(val, dict):
        return {str(k): _jsonable(v) for k, v in val.items()}
    if isinstance(val, (list, tuple, set, frozenset)):
        return [_jsonable(v) for v in val]
    if isinstance(val, (str, bool)) or val is None:
        return val
    if isinstance(val, (int, float)):
        return val
    try:
        return int(val)
    except (TypeError, ValueError):
        pass
    try:
        return float(val)
    except (TypeError, ValueError):
        return repr(val)


class Suppressions:
    """Parsed suppression file. ``match(finding)`` returns the matching
    entry (and counts the hit) or None."""

    def __init__(self, entries=(), path=None):
        self.path = path
        self.entries = []
        for ent in entries:
            if not isinstance(ent, dict) or not ent.get("key") or \
                    not str(ent.get("reason", "")).strip():
                raise ValueError(
                    "suppression entries need a 'key' glob and a non-empty "
                    "'reason': {!r}".format(ent))
            self.entries.append({"key": str(ent["key"]),
                                 "reason": str(ent["reason"]), "hits": 0})

    @classmethod
    def load(cls, path):
        with open(path) as fh:
            payload = json.load(fh)
        if not isinstance(payload, dict) or \
                not isinstance(payload.get("suppressions"), list):
            raise ValueError(
                "{}: suppression file must be an object with a "
                "'suppressions' list".format(path))
        return cls(payload["suppressions"], path=path)

    def match(self, finding):
        for ent in self.entries:
            if fnmatch.fnmatchcase(finding.key, ent["key"]):
                ent["hits"] += 1
                return ent
        return None

    def stale(self):
        """Entries that matched nothing this run (candidates to delete)."""
        return [ent["key"] for ent in self.entries if not ent["hits"]]


class AnalysisReport:
    """One audit run's result: the programs audited, the findings that
    survived suppression, and what was suppressed (with reasons)."""

    def __init__(self, job="audit"):
        self.job = job
        self.programs = {}          # name -> {family, ...meta}
        self.findings = []          # [Finding]
        self.suppressed = []        # [(Finding, reason)]
        self.census = None          # optional wire-reconciliation payload
        self.stale_suppressions = []  # suppression keys that matched 0
        # canonical program fingerprint (ISSUE 15): set by the auditor
        # from the walked collective sequences + lowered plan topology,
        # published into the host manifest for the fleet divergence
        # check (analysis/concurrency/divergence.py)
        self.fingerprint = None
        self.collective_families = {}   # {program: [collective tokens]}

    def add_program(self, name, **meta):
        self.programs[name] = _jsonable(meta)

    def add(self, finding, suppressions=None):
        """Route one finding through the suppression file."""
        if finding is None:
            return None
        ent = suppressions.match(finding) if suppressions is not None \
            else None
        if ent is not None:
            self.suppressed.append((finding, ent["reason"]))
        else:
            self.findings.append(finding)
        return finding

    def extend(self, findings, suppressions=None):
        for f in findings:
            self.add(f, suppressions)

    def errors(self):
        return [f for f in self.findings if f.severity == "error"]

    def by_check(self, check):
        return [f for f in self.findings if f.check == check]

    def summary(self):
        counts = {}
        for f in self.findings:
            counts[f.check] = counts.get(f.check, 0) + 1
        return {
            "programs_audited": len(self.programs),
            "findings": len(self.findings),
            "suppressed": len(self.suppressed),
            "by_check": counts,
        }

    def to_dict(self):
        out = {
            "kind": ANALYSIS_REPORT_KIND,
            "version": 1,
            "job": self.job,
            "programs": dict(self.programs),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [dict(f.to_dict(), suppressed_reason=reason)
                           for f, reason in self.suppressed],
            "summary": self.summary(),
        }
        if self.census is not None:
            out["census"] = _jsonable(self.census)
        if self.stale_suppressions:
            out["stale_suppressions"] = list(self.stale_suppressions)
        if self.fingerprint is not None:
            out["fingerprint"] = _jsonable(self.fingerprint)
        return out

    def write(self, path):
        """Atomic JSON dump (tmp + rename, the checkpoint discipline)."""
        payload = self.to_dict()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path


def validate_analysis_report(payload):
    """-> list of problems with one serialized analysis report (the
    writer-side source of truth; bin/check_bench_schema.py carries the
    stdlib twin for CI artifact checking)."""
    problems = []
    if not isinstance(payload, dict):
        return ["report is not a dict"]
    for key in ANALYSIS_REPORT_KEYS:
        if key not in payload:
            problems.append("missing key {!r}".format(key))
    if problems:
        return problems
    if payload.get("kind") != ANALYSIS_REPORT_KIND:
        problems.append("kind is not {!r}".format(ANALYSIS_REPORT_KIND))
    if not isinstance(payload.get("programs"), dict):
        problems.append("programs is not a dict")
    for section in ("findings", "suppressed"):
        entries = payload.get(section)
        if not isinstance(entries, list):
            problems.append("{} is not a list".format(section))
            continue
        for i, ent in enumerate(entries):
            if not isinstance(ent, dict):
                problems.append("{}[{}] is not an object".format(section, i))
                break
            for key in FINDING_KEYS:
                if not isinstance(ent.get(key), str):
                    problems.append(
                        "{}[{}].{} is not a string".format(section, i, key))
            if ent.get("severity") not in SEVERITIES:
                problems.append("{}[{}] has unknown severity {!r}".format(
                    section, i, ent.get("severity")))
            if section == "suppressed" and \
                    not ent.get("suppressed_reason"):
                problems.append(
                    "suppressed[{}] lacks a suppressed_reason".format(i))
            if problems:
                break
    summary = payload.get("summary")
    if not isinstance(summary, dict):
        problems.append("summary is not a dict")
    else:
        for key in ("programs_audited", "findings", "suppressed"):
            val = summary.get(key)
            if not isinstance(val, int) or isinstance(val, bool) or val < 0:
                problems.append(
                    "summary.{} is not an int >= 0".format(key))
    return problems
