"""Runtime math/utility helpers.

Reference parity: deepspeed/runtime/utils.py (partition_uniform/
partition_balanced :312-394, get_grad_norm :171, get_weight_norm :229,
see_memory_usage :548). Norms are computed functionally inside jit with mesh
collectives instead of iterating ``param.grad`` tensors.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger


def ensure_directory_exists(filename):
    import os
    dirname = os.path.dirname(filename)
    if dirname:
        os.makedirs(dirname, exist_ok=True)


def partition_uniform(num_items, num_parts):
    """Boundaries of ``num_parts`` near-equal contiguous chunks of ``num_items``.

    Returns a list of length ``num_parts + 1``; part p owns
    ``[parts[p], parts[p+1])``. Matches reference semantics: uniform chunking
    with the remainder spread one-per-part from the front.
    """
    parts = [0] * (num_parts + 1)
    if num_items <= num_parts:
        for p in range(num_parts + 1):
            parts[p] = min(p, num_items)
        return parts
    chunksize = num_items // num_parts
    residual = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunksize + (1 if p < residual else 0)
    return parts


def prefix_sum_inc(weights):
    """Inclusive prefix sum."""
    out = list(weights)
    for i in range(1, len(out)):
        out[i] += out[i - 1]
    return out


def _is_valid_partition(prefix, num_parts, bottleneck):
    """Greedy check: can weights (given by inclusive prefix sums) split into
    num_parts contiguous chunks each weighing <= bottleneck?"""
    parts_used = 0
    chunk_start = 0.0
    idx = 0
    n = len(prefix)
    while idx < n:
        if prefix[idx] - chunk_start > bottleneck:
            # weight idx starts a new chunk; a single item heavier than the
            # bottleneck makes the bottleneck infeasible
            prev = prefix[idx - 1] if idx > 0 else 0.0
            if prefix[idx] - prev > bottleneck:
                return False
            parts_used += 1
            chunk_start = prev
            if parts_used >= num_parts:
                return False
        else:
            idx += 1
    return parts_used + 1 <= num_parts


def partition_balanced(weights, num_parts, eps=1e-3):
    """Contiguous partition of ``weights`` into ``num_parts`` chunks minimizing
    the heaviest chunk (binary search on the bottleneck, reference :378)."""
    num_items = len(weights)
    if num_items <= num_parts:
        return partition_uniform(num_items, num_parts)

    prefix = prefix_sum_inc([float(w) for w in weights])
    total = prefix[-1]
    lower = max(total / num_parts, max(float(w) for w in weights) * (1 - eps))
    upper = total

    while upper - lower > eps * max(total, 1.0):
        mid = (lower + upper) / 2
        if _is_valid_partition(prefix, num_parts, mid):
            upper = mid
        else:
            lower = mid

    # Greedily materialize boundaries for the found bottleneck.
    bottleneck = upper * (1 + eps)
    parts = [0]
    chunk_start = 0.0
    for idx in range(num_items):
        if prefix[idx] - chunk_start > bottleneck and len(parts) < num_parts:
            parts.append(idx)
            chunk_start = prefix[idx - 1] if idx > 0 else 0.0
    while len(parts) < num_parts:
        parts.append(num_items)
    parts.append(num_items)
    # Ensure monotone boundaries covering all items.
    for i in range(1, len(parts)):
        parts[i] = max(parts[i], parts[i - 1])
    parts[-1] = num_items
    return parts


def global_norm_from_pytree(tree, ord=2.0):
    """L-norm over all leaves of a pytree (traced; safe inside jit)."""
    leaves = [jnp.asarray(x) for x in jax.tree_util.tree_leaves(tree)]
    if not leaves:
        return jnp.asarray(0.0, dtype=jnp.float32)
    if math.isinf(ord):
        return jnp.max(jnp.stack(
            [jnp.max(jnp.abs(x.astype(jnp.float32))) for x in leaves]))
    total = sum(jnp.sum(jnp.abs(x.astype(jnp.float32)) ** ord) for x in leaves)
    return total ** (1.0 / ord)


def get_grad_norm(grads, norm_type=2.0):
    """Gradient norm over a grad pytree (reference get_grad_norm :171).

    Under GSPMD the grads are global arrays, so no explicit cross-rank
    reduction is needed — XLA inserts it from the shardings.
    """
    return global_norm_from_pytree(grads, ord=float(norm_type))


def get_weight_norm(params, norm_type=2.0):
    return global_norm_from_pytree(params, ord=float(norm_type))


def clip_grad_norm_(grads, max_norm, norm_type=2.0, total_norm=None):
    """Return grads scaled so their global norm is <= max_norm (functional
    version of reference clip_grad_norm_)."""
    if total_norm is None:
        total_norm = get_grad_norm(grads, norm_type)
    clip_coef = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
    return jax.tree_util.tree_map(lambda g: g * clip_coef, grads), total_norm


class CheckOverflow:
    """Functional inf/nan detection over a grad pytree
    (reference CheckOverflow :64). Returns a traced boolean."""

    @staticmethod
    def has_overflow(grads):
        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return jnp.asarray(False)
        finite = jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves])
        return jnp.logical_not(jnp.all(finite))


def see_memory_usage(message, force=False):
    if not force:
        return
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        ma = stats.get("bytes_in_use", 0) / (1024 ** 3)
        peak = stats.get("peak_bytes_in_use", 0) / (1024 ** 3)
        limit = stats.get("bytes_limit", 0) / (1024 ** 3)
        logger.info("{}: MA {:.2f} GB, peak {:.2f} GB, limit {:.2f} GB".format(
            message, ma, peak, limit))
    except Exception:
        logger.info("{}: device memory stats unavailable".format(message))


def call_to_str(base, *args, **kwargs):
    """``name(arg1, arg2, kw=val)`` string builder (reference :24)."""
    name = "{}(".format(base)
    if args:
        name += ", ".join(str(arg) for arg in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join("{}={}".format(key, kwargs[key]) for key in kwargs)
    name += ")"
    return name


def count_parameters(params):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))


class PartitionedTensor:
    """Shard one tensor over a mesh axis; reassemble on demand.

    Reference parity: runtime/utils.py PartitionedTensor (:396-503) — the
    pipeline engine uses it to send tensor-parallel-partitioned activations
    between stages. Here the partitioned form IS a sharded jax.Array
    (flattened, padded to the axis size, NamedSharding over ``axis``);
    ``full()`` restores the original shape (XLA inserts the all-gather),
    and ``to_meta``/``from_meta`` round-trip the (shape, padded size) info
    the reference ships alongside the data.
    """

    def __init__(self, tensor, mesh, axis="model", _meta=None):
        from jax.sharding import NamedSharding, PartitionSpec
        self.mesh = mesh
        self.axis = axis
        if _meta is not None:
            # ``tensor`` is the GLOBAL padded flat (sharded) array, not a
            # single rank's slice — under SPMD the sharded jax.Array IS the
            # per-rank-partitioned form the reference ships piecewise
            self.orig_shape, self.orig_size = _meta
            self.local_data = tensor
            return
        self.orig_shape = tuple(tensor.shape)
        self.orig_size = int(np.prod(self.orig_shape))
        parts = int(mesh.shape.get(axis, 1))
        flat = jnp.ravel(tensor)
        pad = (-self.orig_size) % parts
        if pad:
            flat = jnp.pad(flat, (0, pad))
        # replicate when the axis is absent/size-1 (naming a missing mesh
        # axis in a PartitionSpec is an error)
        spec = PartitionSpec(axis) if parts > 1 else PartitionSpec()
        self.local_data = jax.device_put(flat, NamedSharding(mesh, spec))

    def to_meta(self):
        return (self.orig_shape, self.orig_size)

    @classmethod
    def from_meta(cls, meta, part_data, mesh, axis="model"):
        """Rebuild from ``to_meta()`` info + the sharded flat array
        (``PartitionedTensor.local_data``)."""
        return cls(part_data, mesh, axis=axis, _meta=tuple(meta))

    def full(self):
        """Reassembled tensor in the original shape (all-gather by XLA)."""
        return self.local_data[:self.orig_size].reshape(self.orig_shape)
