"""``ds_config.json`` parser.

Reference parity: deepspeed/runtime/config.py (DeepSpeedConfig at :519,
batch-triple inference :679-725, sanity checks :750-787). The JSON surface is
identical; ``world_size`` is the number of data-parallel shards of the device
mesh rather than a torch process-group size.

TPU-native additions (non-breaking): a ``bf16`` block (preferred on TPU —
no loss scaler needed), accepted alongside the reference's ``fp16`` block.
"""
import json
import logging

from .constants import *
from .config_utils import (get_scalar_param, dict_raise_error_on_duplicate_keys)
from .comm.config import COMM, KNOWN_COMM_KEYS, DeepSpeedCommConfig
from .zero.config import DeepSpeedZeroConfig
from .zero.constants import (ZERO_OPTIMIZATION, ZERO_OPTIMIZATION_DISABLED,
                             MAX_STAGE_ZERO_OPTIMIZATION)
from .activation_checkpointing.config import DeepSpeedActivationCheckpointingConfig
from ..profiling.config import DeepSpeedFlopsProfilerConfig
from ..inference.config import DeepSpeedInferenceConfig, INFERENCE
from ..telemetry.config import (DeepSpeedTelemetryConfig, TELEMETRY,
                                KNOWN_TELEMETRY_KEYS)
from ..analysis.config import (DeepSpeedAnalysisConfig, ANALYSIS,
                               KNOWN_ANALYSIS_KEYS)
from ..utils.logging import logger

TENSOR_CORE_ALIGN_SIZE = 8


class DeepSpeedConfigError(Exception):
    pass


class ValidationMode:
    WARN = "WARN"
    IGNORE = "IGNORE"
    FAIL = "FAIL"


def get_amp_enabled(param_dict):
    if AMP in param_dict:
        return get_scalar_param(param_dict[AMP], AMP_ENABLED, AMP_ENABLED_DEFAULT)
    return False


def get_amp_params(param_dict):
    if AMP in param_dict:
        amp_params = dict(param_dict[AMP])
        amp_params.pop(AMP_ENABLED, None)
        return amp_params
    return False


def get_fp16_enabled(param_dict):
    if FP16 in param_dict:
        return get_scalar_param(param_dict[FP16], FP16_ENABLED, FP16_ENABLED_DEFAULT)
    return False


def get_bf16_enabled(param_dict):
    if BF16 in param_dict:
        return get_scalar_param(param_dict[BF16], BF16_ENABLED, BF16_ENABLED_DEFAULT)
    return False


def get_loss_scale(param_dict):
    if get_fp16_enabled(param_dict):
        return get_scalar_param(param_dict[FP16], FP16_LOSS_SCALE,
                                FP16_LOSS_SCALE_DEFAULT)
    return FP16_LOSS_SCALE_DEFAULT


def get_initial_dynamic_scale(param_dict):
    if get_fp16_enabled(param_dict):
        initial_scale_power = get_scalar_param(param_dict[FP16],
                                               FP16_INITIAL_SCALE_POWER,
                                               FP16_INITIAL_SCALE_POWER_DEFAULT)
    else:
        initial_scale_power = FP16_INITIAL_SCALE_POWER_DEFAULT
    return 2 ** initial_scale_power


def get_dynamic_loss_scale_args(param_dict):
    loss_scale_args = None
    if get_fp16_enabled(param_dict):
        fp16_dict = param_dict[FP16]
        dynamic_keys = (FP16_INITIAL_SCALE_POWER, FP16_LOSS_SCALE_WINDOW,
                        FP16_MIN_LOSS_SCALE, FP16_HYSTERESIS)
        if any(key in fp16_dict for key in dynamic_keys):
            init_scale = get_scalar_param(fp16_dict, FP16_INITIAL_SCALE_POWER,
                                          FP16_INITIAL_SCALE_POWER_DEFAULT)
            scale_window = get_scalar_param(fp16_dict, FP16_LOSS_SCALE_WINDOW,
                                            FP16_LOSS_SCALE_WINDOW_DEFAULT)
            delayed_shift = get_scalar_param(fp16_dict, FP16_HYSTERESIS,
                                             FP16_HYSTERESIS_DEFAULT)
            min_loss_scale = get_scalar_param(fp16_dict, FP16_MIN_LOSS_SCALE,
                                              FP16_MIN_LOSS_SCALE_DEFAULT)
            loss_scale_args = {
                "init_scale": 2 ** init_scale,
                "scale_window": scale_window,
                "delayed_shift": delayed_shift,
                "min_scale": min_loss_scale,
            }
    return loss_scale_args


def get_gradient_accumulation_steps(param_dict):
    return get_scalar_param(param_dict, GRADIENT_ACCUMULATION_STEPS,
                            GRADIENT_ACCUMULATION_STEPS_DEFAULT)


def get_sparse_gradients_enabled(param_dict):
    return get_scalar_param(param_dict, SPARSE_GRADIENTS, SPARSE_GRADIENTS_DEFAULT)


def get_allreduce_always_fp32(param_dict):
    return get_scalar_param(param_dict, FP32_ALLREDUCE, FP32_ALLREDUCE_DEFAULT)


def get_prescale_gradients(param_dict):
    return get_scalar_param(param_dict, PRESCALE_GRADIENTS,
                            PRESCALE_GRADIENTS_DEFAULT)


def get_gradient_predivide_factor(param_dict):
    return get_scalar_param(param_dict, GRADIENT_PREDIVIDE_FACTOR,
                            GRADIENT_PREDIVIDE_FACTOR_DEFAULT)


def get_steps_per_print(param_dict):
    return get_scalar_param(param_dict, STEPS_PER_PRINT, STEPS_PER_PRINT_DEFAULT)


def get_disable_allgather(param_dict):
    return get_scalar_param(param_dict, DISABLE_ALLGATHER, DISABLE_ALLGATHER_DEFAULT)


def get_dump_state(param_dict):
    return get_scalar_param(param_dict, DUMP_STATE, DUMP_STATE_DEFAULT)


def get_gradient_clipping(param_dict):
    return get_scalar_param(param_dict, GRADIENT_CLIPPING,
                            GRADIENT_CLIPPING_DEFAULT)


def get_grad_accum_dtype(param_dict):
    """data_types.grad_accum_dtype: storage dtype of the gradient
    accumulation buffer. "bf16" halves its HBM (2N vs 4N bytes) and is
    LOSSLESS at gradient_accumulation_steps=1 (micro grads arrive bf16
    from the compute dtype; storing them wider adds no information);
    with real accumulation (gas>1) bf16 summation is lossy — the engine
    warns. None (default) keeps fp32."""
    sub = param_dict.get("data_types") or {}
    if not isinstance(sub, dict):
        raise DeepSpeedConfigError(
            f"data_types must be a dict, got {type(sub).__name__}")
    val = sub.get("grad_accum_dtype")
    if val is None:
        return None
    norm = str(val).lower()
    if norm not in ("fp32", "float32", "bf16", "bfloat16"):
        raise DeepSpeedConfigError(
            f"data_types.grad_accum_dtype={val!r}: want fp32 or bf16")
    return "bf16" if norm in ("bf16", "bfloat16") else "fp32"


def get_sparse_attention(param_dict):
    if SPARSE_ATTENTION not in param_dict:
        return None
    sparsity = param_dict[SPARSE_ATTENTION]
    mode = get_scalar_param(sparsity, SPARSE_MODE, SPARSE_MODE_DEFAULT)
    if mode == SPARSE_DENSE_MODE:
        return get_sparse_dense_config(sparsity)
    elif mode == SPARSE_FIXED_MODE:
        return get_sparse_fixed_config(sparsity)
    elif mode == SPARSE_VARIABLE_MODE:
        return get_sparse_variable_config(sparsity)
    elif mode == SPARSE_BIGBIRD_MODE:
        return get_sparse_bigbird_config(sparsity)
    elif mode == SPARSE_BSLONGFORMER_MODE:
        return get_sparse_bslongformer_config(sparsity)
    elif mode == SPARSE_SLIDING_WINDOW_MODE:
        return get_sparse_sliding_window_config(sparsity)
    else:
        raise NotImplementedError(
            "Given sparsity mode, {}, has not been implemented yet!".format(mode))


def get_sparse_dense_config(sparsity):
    block = get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT)
    return {SPARSE_MODE: SPARSE_DENSE_MODE, SPARSE_BLOCK: block}


def get_sparse_fixed_config(sparsity):
    return {
        SPARSE_MODE: SPARSE_FIXED_MODE,
        SPARSE_BLOCK:
            get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT),
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD:
            get_scalar_param(sparsity, SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
                             SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT),
        SPARSE_NUM_LOCAL_BLOCKS:
            get_scalar_param(sparsity, SPARSE_NUM_LOCAL_BLOCKS,
                             SPARSE_NUM_LOCAL_BLOCKS_DEFAULT),
        SPARSE_NUM_GLOBAL_BLOCKS:
            get_scalar_param(sparsity, SPARSE_NUM_GLOBAL_BLOCKS,
                             SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
        SPARSE_ATTENTION_TYPE:
            get_scalar_param(sparsity, SPARSE_ATTENTION_TYPE,
                             SPARSE_ATTENTION_TYPE_DEFAULT),
        SPARSE_HORIZONTAL_GLOBAL_ATTENTION:
            get_scalar_param(sparsity, SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                             SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
        SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS:
            get_scalar_param(sparsity, SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
                             SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT),
    }


def get_sparse_variable_config(sparsity):
    return {
        SPARSE_MODE: SPARSE_VARIABLE_MODE,
        SPARSE_BLOCK:
            get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT),
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD:
            get_scalar_param(sparsity, SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
                             SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT),
        SPARSE_NUM_RANDOM_BLOCKS:
            get_scalar_param(sparsity, SPARSE_NUM_RANDOM_BLOCKS,
                             SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
        SPARSE_LOCAL_WINDOW_BLOCKS:
            get_scalar_param(sparsity, SPARSE_LOCAL_WINDOW_BLOCKS,
                             SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT),
        SPARSE_GLOBAL_BLOCK_INDICES:
            get_scalar_param(sparsity, SPARSE_GLOBAL_BLOCK_INDICES,
                             SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
        SPARSE_GLOBAL_BLOCK_END_INDICES:
            get_scalar_param(sparsity, SPARSE_GLOBAL_BLOCK_END_INDICES,
                             SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
        SPARSE_ATTENTION_TYPE:
            get_scalar_param(sparsity, SPARSE_ATTENTION_TYPE,
                             SPARSE_ATTENTION_TYPE_DEFAULT),
        SPARSE_HORIZONTAL_GLOBAL_ATTENTION:
            get_scalar_param(sparsity, SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                             SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
    }


def get_sparse_bigbird_config(sparsity):
    return {
        SPARSE_MODE: SPARSE_BIGBIRD_MODE,
        SPARSE_BLOCK:
            get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT),
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD:
            get_scalar_param(sparsity, SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
                             SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT),
        SPARSE_NUM_RANDOM_BLOCKS:
            get_scalar_param(sparsity, SPARSE_NUM_RANDOM_BLOCKS,
                             SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS:
            get_scalar_param(sparsity, SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                             SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
        SPARSE_NUM_GLOBAL_BLOCKS:
            get_scalar_param(sparsity, SPARSE_NUM_GLOBAL_BLOCKS,
                             SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
    }


def get_sparse_sliding_window_config(sparsity):
    return {
        SPARSE_MODE: SPARSE_SLIDING_WINDOW_MODE,
        SPARSE_BLOCK:
            get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT),
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS:
            get_scalar_param(sparsity, SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                             SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
    }


def get_sparse_bslongformer_config(sparsity):
    return {
        SPARSE_MODE: SPARSE_BSLONGFORMER_MODE,
        SPARSE_BLOCK:
            get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT),
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD:
            get_scalar_param(sparsity, SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
                             SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT),
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS:
            get_scalar_param(sparsity, SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                             SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
        SPARSE_GLOBAL_BLOCK_INDICES:
            get_scalar_param(sparsity, SPARSE_GLOBAL_BLOCK_INDICES,
                             SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
        SPARSE_GLOBAL_BLOCK_END_INDICES:
            get_scalar_param(sparsity, SPARSE_GLOBAL_BLOCK_END_INDICES,
                             SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
    }


def get_optimizer_name(param_dict):
    if OPTIMIZER in param_dict and TYPE in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][TYPE]
    return OPTIMIZER_TYPE_DEFAULT


def get_optimizer_params(param_dict):
    if get_optimizer_name(param_dict) is not None and \
            OPTIMIZER_PARAMS in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][OPTIMIZER_PARAMS]
    return None


def get_optimizer_gradient_clipping(param_dict):
    optimizer_params = get_optimizer_params(param_dict)
    if optimizer_params is not None and MAX_GRAD_NORM in optimizer_params:
        return optimizer_params[MAX_GRAD_NORM]
    return None


def get_optimizer_legacy_fusion(param_dict):
    if OPTIMIZER in param_dict and LEGACY_FUSION in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][LEGACY_FUSION]
    return LEGACY_FUSION_DEFAULT


def get_zero_allow_untested_optimizer(param_dict):
    return get_scalar_param(param_dict, ZERO_ALLOW_UNTESTED_OPTIMIZER,
                            ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)


def get_scheduler_name(param_dict):
    if SCHEDULER in param_dict and TYPE in param_dict[SCHEDULER]:
        return param_dict[SCHEDULER][TYPE]
    return SCHEDULER_TYPE_DEFAULT


def get_scheduler_params(param_dict):
    if get_scheduler_name(param_dict) is not None and \
            SCHEDULER_PARAMS in param_dict[SCHEDULER]:
        return param_dict[SCHEDULER][SCHEDULER_PARAMS]
    return None


def get_train_batch_size(param_dict):
    return get_scalar_param(param_dict, TRAIN_BATCH_SIZE, TRAIN_BATCH_SIZE_DEFAULT)


def get_train_micro_batch_size_per_gpu(param_dict):
    return get_scalar_param(param_dict, TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                            TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)


def get_wall_clock_breakdown(param_dict):
    return get_scalar_param(param_dict, WALL_CLOCK_BREAKDOWN,
                            WALL_CLOCK_BREAKDOWN_DEFAULT)


def get_memory_breakdown(param_dict):
    return get_scalar_param(param_dict, MEMORY_BREAKDOWN, MEMORY_BREAKDOWN_DEFAULT)


def get_tensorboard_enabled(param_dict):
    if TENSORBOARD in param_dict:
        return get_scalar_param(param_dict[TENSORBOARD], TENSORBOARD_ENABLED,
                                TENSORBOARD_ENABLED_DEFAULT)
    return False


def get_tensorboard_output_path(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[TENSORBOARD], TENSORBOARD_OUTPUT_PATH,
                                TENSORBOARD_OUTPUT_PATH_DEFAULT)
    return TENSORBOARD_OUTPUT_PATH_DEFAULT


def get_tensorboard_job_name(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[TENSORBOARD], TENSORBOARD_JOB_NAME,
                                TENSORBOARD_JOB_NAME_DEFAULT)
    return TENSORBOARD_JOB_NAME_DEFAULT


def get_checkpoint_params(param_dict):
    return param_dict.get(CHECKPOINT, {})


def get_checkpoint_tag_validation_mode(checkpoint_params):
    tag_validation_mode = checkpoint_params.get(CHECKPOINT_TAG_VALIDATION,
                                                CHECKPOINT_TAG_VALIDATION_DEFAULT)
    tag_validation_mode = tag_validation_mode.upper()
    if tag_validation_mode in (ValidationMode.WARN, ValidationMode.IGNORE,
                               ValidationMode.FAIL):
        return tag_validation_mode
    raise DeepSpeedConfigError(
        "Checkpoint config contains invalid tag_validation "
        "value of {}, expecting one of {}".format(
            tag_validation_mode,
            [ValidationMode.WARN, ValidationMode.IGNORE, ValidationMode.FAIL]))


def get_checkpoint_io_retries(checkpoint_params):
    val = checkpoint_params.get(CHECKPOINT_IO_RETRIES,
                                CHECKPOINT_IO_RETRIES_DEFAULT)
    if isinstance(val, bool) or not isinstance(val, int) or val < 0:
        raise DeepSpeedConfigError(
            "checkpoint.{} must be an int >= 0, got {!r}".format(
                CHECKPOINT_IO_RETRIES, val))
    return val


def get_checkpoint_io_backoff(checkpoint_params):
    val = checkpoint_params.get(CHECKPOINT_IO_RETRY_BACKOFF,
                                CHECKPOINT_IO_RETRY_BACKOFF_DEFAULT)
    if isinstance(val, bool) or not isinstance(val, (int, float)) or val < 0:
        raise DeepSpeedConfigError(
            "checkpoint.{} must be a number >= 0, got {!r}".format(
                CHECKPOINT_IO_RETRY_BACKOFF, val))
    return float(val)


def get_checkpoint_keep_last_n(checkpoint_params):
    val = checkpoint_params.get(CHECKPOINT_KEEP_LAST_N,
                                CHECKPOINT_KEEP_LAST_N_DEFAULT)
    if val is None:
        return None
    if isinstance(val, bool) or not isinstance(val, int) or val < 1:
        raise DeepSpeedConfigError(
            "checkpoint.{} must be an int >= 1 (or null to disable "
            "pruning), got {!r}".format(CHECKPOINT_KEEP_LAST_N, val))
    return val


TRANSFORMER = "transformer"
TRANSFORMER_FLASH_ATTENTION = "flash_attention"

#############################################
# Runtime executor (docs/executor.md)
#############################################
RUNTIME = "runtime"
RUNTIME_EXECUTOR = "executor"
RUNTIME_EXECUTOR_DEFAULT = "auto"
RUNTIME_EXECUTOR_MODES = ("auto", "on", "off")


def get_runtime_executor(param_dict):
    """``runtime.executor``: tri-state gate for the segment-plan
    executor's constructed overlap (``runtime/executor/``). ``auto``
    (default) and ``on`` run plans with async transfer/compute overlap;
    ``off`` runs every plan serially in plan order — the bit-exact
    oracle mode for A/B debugging. Strict-validated: any other value
    raises (an enum typo silently falling back would un-A/B the
    comparison it exists for)."""
    sub = param_dict.get(RUNTIME) or {}
    if not isinstance(sub, dict):
        raise DeepSpeedConfigError(
            "runtime must be a dict, got {}".format(type(sub).__name__))
    val = sub.get(RUNTIME_EXECUTOR, RUNTIME_EXECUTOR_DEFAULT)
    if not isinstance(val, str) or \
            val.lower() not in RUNTIME_EXECUTOR_MODES:
        raise DeepSpeedConfigError(
            "runtime.{} must be one of {}, got {!r}".format(
                RUNTIME_EXECUTOR, "|".join(RUNTIME_EXECUTOR_MODES), val))
    return val.lower()


RUNTIME_EXECUTOR_REWRITES = "executor_rewrites"
RUNTIME_EXECUTOR_REWRITE_PASSES = ("hoist", "widen", "fuse")
RUNTIME_EXECUTOR_REWRITES_KEYS = (
    "enabled", "passes", "max_window", "hoist_max_live_bytes")
RUNTIME_EXECUTOR_REWRITES_MAX_WINDOW_DEFAULT = 8
RUNTIME_EXECUTOR_REWRITES_LIVE_BYTES_DEFAULT = 1 << 28


def get_runtime_executor_rewrites(param_dict):
    """``runtime.executor_rewrites``: the plan rewrite passes
    (``runtime/executor/rewrite.py``, docs/executor.md) applied at
    plan-build time in overlap mode — collective/transfer hoisting,
    prefetch-window widening, small-segment fusion. Default OFF (the
    lowered plans execute exactly as declared). ``true`` enables every
    pass; a dict selects passes and bounds (``max_window``: widening
    ceiling per pool; ``hoist_max_live_bytes``: the live-bytes window a
    hoist may extend a result's lifetime across). Strict-validated like
    ``runtime.executor``: unknown keys or pass names raise — a typo'd
    pass silently not running would fake an A/B result."""
    sub = param_dict.get(RUNTIME) or {}
    if not isinstance(sub, dict):
        raise DeepSpeedConfigError(
            "runtime must be a dict, got {}".format(type(sub).__name__))
    val = sub.get(RUNTIME_EXECUTOR_REWRITES, False)
    if isinstance(val, bool):
        val = {"enabled": val}
    if not isinstance(val, dict):
        raise DeepSpeedConfigError(
            "runtime.{} must be a bool or a dict, got {!r}".format(
                RUNTIME_EXECUTOR_REWRITES, val))
    for key in val:
        if key not in RUNTIME_EXECUTOR_REWRITES_KEYS:
            raise DeepSpeedConfigError(
                "unknown key {!r} in runtime.{} (accepted: {})".format(
                    key, RUNTIME_EXECUTOR_REWRITES,
                    ", ".join(RUNTIME_EXECUTOR_REWRITES_KEYS)))
    enabled = val.get("enabled", True)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            "runtime.{}.enabled must be a bool, got {!r}".format(
                RUNTIME_EXECUTOR_REWRITES, enabled))
    passes = val.get("passes", list(RUNTIME_EXECUTOR_REWRITE_PASSES))
    if not isinstance(passes, (list, tuple)) or not all(
            isinstance(p, str) for p in passes):
        raise DeepSpeedConfigError(
            "runtime.{}.passes must be a list of pass names, got "
            "{!r}".format(RUNTIME_EXECUTOR_REWRITES, passes))
    for p in passes:
        if p not in RUNTIME_EXECUTOR_REWRITE_PASSES:
            raise DeepSpeedConfigError(
                "unknown rewrite pass {!r} in runtime.{}.passes "
                "(accepted: {})".format(
                    p, RUNTIME_EXECUTOR_REWRITES,
                    "|".join(RUNTIME_EXECUTOR_REWRITE_PASSES)))
    max_window = val.get("max_window",
                         RUNTIME_EXECUTOR_REWRITES_MAX_WINDOW_DEFAULT)
    if isinstance(max_window, bool) or not isinstance(max_window, int) \
            or max_window < 1:
        raise DeepSpeedConfigError(
            "runtime.{}.max_window must be an int >= 1, got {!r}".format(
                RUNTIME_EXECUTOR_REWRITES, max_window))
    live_bytes = val.get("hoist_max_live_bytes",
                         RUNTIME_EXECUTOR_REWRITES_LIVE_BYTES_DEFAULT)
    if isinstance(live_bytes, bool) or not isinstance(live_bytes, int) \
            or live_bytes < 1:
        raise DeepSpeedConfigError(
            "runtime.{}.hoist_max_live_bytes must be an int >= 1, got "
            "{!r}".format(RUNTIME_EXECUTOR_REWRITES, live_bytes))
    return {"enabled": enabled, "passes": tuple(passes),
            "max_window": max_window,
            "hoist_max_live_bytes": live_bytes}


CONTROLLER = "controller"
CONTROLLER_KEYS = ("enabled", "interval_steps", "eval_steps",
                   "cooldown_steps", "guardrail_pct",
                   "max_moves_per_tick", "policies")
CONTROLLER_INTERVAL_STEPS_DEFAULT = 20
CONTROLLER_EVAL_STEPS_DEFAULT = 20
CONTROLLER_COOLDOWN_STEPS_DEFAULT = 40
CONTROLLER_GUARDRAIL_PCT_DEFAULT = 0.2
CONTROLLER_MAX_MOVES_DEFAULT = 1


def get_controller(param_dict):
    """Top-level ``controller`` section: the closed-loop runtime
    controller (``runtime/controller/``, docs/controller.md) that
    retunes launch-ahead windows, transfer chunks, speculative k,
    chunked-prefill size, quantized collectives and prefill buckets
    from live telemetry. Default OFF and structurally absent — the
    parser returns ``None`` so engines never construct a controller,
    ledger file or policy object. ``true`` enables every policy with
    defaults; a dict selects policies and bounds. Strict-validated
    like ``runtime.executor``: unknown keys or policy names raise — a
    typo'd policy silently not steering would fake a recovery."""
    from .controller.policies import CONTROLLER_POLICIES
    val = param_dict.get(CONTROLLER, False)
    if isinstance(val, bool):
        val = {"enabled": val}
    if not isinstance(val, dict):
        raise DeepSpeedConfigError(
            "{} must be a bool or a dict, got {!r}".format(
                CONTROLLER, val))
    for key in val:
        if key not in CONTROLLER_KEYS:
            raise DeepSpeedConfigError(
                "unknown key {!r} in {} (accepted: {})".format(
                    key, CONTROLLER, ", ".join(CONTROLLER_KEYS)))
    enabled = val.get("enabled", True)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            "{}.enabled must be a bool, got {!r}".format(
                CONTROLLER, enabled))
    if not enabled:
        return None
    out = {}
    for key, default in (
            ("interval_steps", CONTROLLER_INTERVAL_STEPS_DEFAULT),
            ("eval_steps", CONTROLLER_EVAL_STEPS_DEFAULT),
            ("cooldown_steps", CONTROLLER_COOLDOWN_STEPS_DEFAULT),
            ("max_moves_per_tick", CONTROLLER_MAX_MOVES_DEFAULT)):
        n = val.get(key, default)
        if isinstance(n, bool) or not isinstance(n, int) or n < 1:
            raise DeepSpeedConfigError(
                "{}.{} must be an int >= 1, got {!r}".format(
                    CONTROLLER, key, n))
        out[key] = n
    pct = val.get("guardrail_pct", CONTROLLER_GUARDRAIL_PCT_DEFAULT)
    if isinstance(pct, bool) or not isinstance(pct, (int, float)) \
            or pct <= 0:
        raise DeepSpeedConfigError(
            "{}.guardrail_pct must be a positive number, got "
            "{!r}".format(CONTROLLER, pct))
    out["guardrail_pct"] = float(pct)
    policies = val.get("policies", list(CONTROLLER_POLICIES))
    if not isinstance(policies, (list, tuple)) or not policies or \
            not all(isinstance(p, str) for p in policies):
        raise DeepSpeedConfigError(
            "{}.policies must be a non-empty list of policy names, "
            "got {!r}".format(CONTROLLER, policies))
    for p in policies:
        if p not in CONTROLLER_POLICIES:
            raise DeepSpeedConfigError(
                "unknown policy {!r} in {}.policies (accepted: "
                "{})".format(p, CONTROLLER,
                             "|".join(CONTROLLER_POLICIES)))
    out["policies"] = list(policies)
    out["enabled"] = True
    return out


TRANSFORMER_FLASH_ATTENTION_MODES = ("auto", "pallas", "xla")


def get_transformer_flash_attention(param_dict):
    """``transformer.flash_attention``: tri-state gate for the Pallas
    flash-attention kernel on the dense training path, mirroring
    ``inference.paged_attention_kernel``. ``None`` (key or section
    absent) leaves the model config's own default. ``"auto"`` takes the
    kernel exactly on TPU and the XLA reference elsewhere; ``"pallas"``
    forces the kernel — off-TPU it runs under the Pallas interpreter
    with a LOUD one-time warning (parity/debug) instead of silently
    going dense; ``"xla"`` pins the reference oracle. The legacy bools
    still parse: true -> "auto", false -> "xla". Strict-validated like
    runtime.executor — an enum typo raises instead of silently changing
    the kernel under a benchmark."""
    sub = param_dict.get(TRANSFORMER) or {}
    if not isinstance(sub, dict):
        raise DeepSpeedConfigError(
            "transformer must be a dict, got {}".format(type(sub).__name__))
    val = sub.get(TRANSFORMER_FLASH_ATTENTION)
    if val is None:
        return None
    if isinstance(val, bool):
        return "auto" if val else "xla"
    if not isinstance(val, str) or \
            val.lower() not in TRANSFORMER_FLASH_ATTENTION_MODES:
        raise DeepSpeedConfigError(
            "transformer.{} must be a bool, null or one of {}, got {!r}"
            .format(TRANSFORMER_FLASH_ATTENTION,
                    "|".join(TRANSFORMER_FLASH_ATTENTION_MODES), val))
    return val.lower()


def get_pld_enabled(param_dict):
    if PROGRESSIVE_LAYER_DROP in param_dict:
        return get_scalar_param(param_dict[PROGRESSIVE_LAYER_DROP], PLD_ENABLED,
                                PLD_ENABLED_DEFAULT)
    return False


def get_pld_params(param_dict):
    if PROGRESSIVE_LAYER_DROP in param_dict:
        pld_params = dict(param_dict[PROGRESSIVE_LAYER_DROP])
        pld_params.pop(PLD_ENABLED, None)
        return pld_params
    return False


class DeepSpeedConfig(object):
    """Typed view of a full ``ds_config`` dict (or json file path).

    ``world_size`` is the data-parallel world size: for a mesh
    (data, model, pipe) it is the size of the ``data`` axis — matching the
    reference where world_size = total ranks / model-parallel size
    (reference config.py:529-539).
    """

    def __init__(self, json_file, mpu=None, param_dict=None, mesh=None,
                 inference_only=False):
        super(DeepSpeedConfig, self).__init__()
        # init_inference sets this: an inference-only parse needs no
        # training batch triple. Keyed on the CALLER, not on the presence
        # of an "inference" section — one config may drive both
        # initialize() and init_inference(), and the training path must
        # keep validating its triple.
        self._inference_only = inference_only

        if param_dict is None:
            with open(json_file, "r") as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        else:
            self._param_dict = param_dict

        try:
            import jax
            self.global_rank = jax.process_index()
            total_devices = jax.device_count()
        except Exception:
            self.global_rank = 0
            total_devices = 1

        if mesh is not None:
            self.world_size = int(mesh.shape.get("data", 1))
        elif mpu is not None:
            self.world_size = total_devices // mpu.get_model_parallel_world_size()
        else:
            self.world_size = total_devices

        # If elasticity is enabled, it overrides the batch config for the
        # current world size and pins an immutable fingerprint.
        self.elasticity_enabled = False
        if self._param_dict.get("elasticity", {}).get("enabled", False):
            self._configure_elasticity()

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._validate_known_keys()
        self._do_sanity_check()

    def _configure_elasticity(self):
        from ..elasticity import (compute_elastic_config, elasticity_enabled,
                                  ensure_immutable_elastic_config,
                                  IGNORE_NON_ELASTIC_BATCH_INFO,
                                  IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT,
                                  ELASTICITY)
        from ..version import __version__
        self.elasticity_enabled = elasticity_enabled(self._param_dict)

        elastic_dict = self._param_dict[ELASTICITY]
        ignore_non_elastic_batch_info = elastic_dict.get(
            IGNORE_NON_ELASTIC_BATCH_INFO, IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
        if not ignore_non_elastic_batch_info:
            batch_params = [TRAIN_BATCH_SIZE, TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                            GRADIENT_ACCUMULATION_STEPS]
            if any(p in self._param_dict for p in batch_params):
                raise DeepSpeedConfigError(
                    "One or more batch related parameters were found in your "
                    "ds_config ({}). These parameters *will not be used* since "
                    "elastic training is enabled, which takes control of these "
                    "parameters. If you want to suppress this error set '{}': "
                    "true in your elasticity config.".format(
                        ", ".join(batch_params), IGNORE_NON_ELASTIC_BATCH_INFO))

        ensure_immutable_elastic_config(elastic_dict)
        final_batch_size, valid_gpus, micro_batch_size = compute_elastic_config(
            ds_config=self._param_dict,
            target_deepspeed_version=__version__,
            world_size=self.world_size)
        self.elastic_valid_world_sizes = valid_gpus
        gradient_accu_steps = final_batch_size // (micro_batch_size *
                                                   self.world_size)
        self._param_dict[TRAIN_BATCH_SIZE] = final_batch_size
        self._param_dict[TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro_batch_size
        self._param_dict[GRADIENT_ACCUMULATION_STEPS] = gradient_accu_steps

    def validate_elastic_world_size(self, world_size):
        """Preflight a PROPOSED world size for an elastic rescale
        (runtime/elastic/): the same candidate-batch math that ran at
        init, re-run for the target topology BEFORE any teardown.
        Raises ``ElasticityIncompatibleWorldSize`` (with the valid
        counts, or the divisibility that failed) when the target cannot
        preserve the global batch; returns the
        ``(final_batch, micro_batch, grad_accum)`` triple the rescaled
        engine will train with."""
        from ..elasticity import (ElasticityIncompatibleWorldSize,
                                  compute_elastic_config)
        from ..version import __version__
        world_size = int(world_size)
        if world_size < 1:
            raise ElasticityIncompatibleWorldSize(
                "world size {} is not positive".format(world_size))
        if self.elasticity_enabled:
            final_batch, _valid, micro = compute_elastic_config(
                ds_config=self._param_dict,
                target_deepspeed_version=__version__,
                world_size=world_size)
            return (final_batch, micro,
                    final_batch // (micro * world_size))
        # non-elastic config: the rescale must keep the SAME global
        # batch by re-deriving the batch triple for the TARGET world
        # from the EXPLICIT keys only — the values this config derived
        # for ITS world (e.g. micro = batch/world) do not transfer
        batch = get_train_batch_size(self._param_dict)
        micro = get_train_micro_batch_size_per_gpu(self._param_dict)
        grad_acc = get_gradient_accumulation_steps(self._param_dict)
        if batch is None:
            # no pinned global batch — any world works (micro * accum
            # scales the global batch with the world, like init does)
            return (None, micro, grad_acc or 1)
        fixed = (micro if micro is not None else grad_acc) or 1
        if batch % (fixed * world_size) != 0:
            raise ElasticityIncompatibleWorldSize(
                "world size {} cannot preserve train_batch_size={} "
                "({} {} x world {} does not divide it; add an "
                "elasticity section for candidate world sizes)".format(
                    world_size, batch,
                    "micro batch" if micro is not None
                    else "grad-accum", fixed, world_size))
        if micro is not None:
            return (batch, micro, batch // (micro * world_size))
        if grad_acc is not None:
            return (batch, batch // (grad_acc * world_size), grad_acc)
        return (batch, batch // world_size, 1)

    def _initialize_params(self, param_dict):
        self.train_batch_size = get_train_batch_size(param_dict)
        self.train_micro_batch_size_per_gpu = \
            get_train_micro_batch_size_per_gpu(param_dict)
        self.gradient_accumulation_steps = get_gradient_accumulation_steps(param_dict)
        self.steps_per_print = get_steps_per_print(param_dict)
        self.dump_state = get_dump_state(param_dict)

        self.disable_allgather = get_disable_allgather(param_dict)
        self.allreduce_always_fp32 = get_allreduce_always_fp32(param_dict)
        self.prescale_gradients = get_prescale_gradients(param_dict)
        self.gradient_predivide_factor = get_gradient_predivide_factor(param_dict)
        self.sparse_gradients_enabled = get_sparse_gradients_enabled(param_dict)

        self.zero_config = DeepSpeedZeroConfig(param_dict)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = \
            DeepSpeedActivationCheckpointingConfig(param_dict)
        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig(param_dict)
        self.inference_config = DeepSpeedInferenceConfig(param_dict)
        self.telemetry_config = DeepSpeedTelemetryConfig(param_dict)
        # the auditor shares the observatory's thresholds (one config)
        self.analysis_config = DeepSpeedAnalysisConfig(
            param_dict, telemetry_config=self.telemetry_config)
        self.comm_config = DeepSpeedCommConfig(param_dict)
        self.transformer_flash_attention = \
            get_transformer_flash_attention(param_dict)
        self.runtime_executor = get_runtime_executor(param_dict)
        self.runtime_executor_rewrites = \
            get_runtime_executor_rewrites(param_dict)
        # closed-loop controller (runtime/controller/): None = off =
        # structurally absent on both engines
        self.controller_config = get_controller(param_dict)

        self.gradient_clipping = get_gradient_clipping(param_dict)
        self.grad_accum_dtype = get_grad_accum_dtype(param_dict)
        self.fp16_enabled = get_fp16_enabled(param_dict)
        self.bf16_enabled = get_bf16_enabled(param_dict)
        self.amp_enabled = get_amp_enabled(param_dict)
        self.amp_params = get_amp_params(param_dict)
        self.loss_scale = get_loss_scale(param_dict)
        self.initial_dynamic_scale = get_initial_dynamic_scale(param_dict)
        self.dynamic_loss_scale_args = get_dynamic_loss_scale_args(param_dict)

        self.optimizer_name = get_optimizer_name(param_dict)
        if self.optimizer_name is not None and \
                self.optimizer_name.lower() in DEEPSPEED_OPTIMIZERS:
            self.optimizer_name = self.optimizer_name.lower()
        self.optimizer_params = get_optimizer_params(param_dict)
        self.optimizer_legacy_fusion = get_optimizer_legacy_fusion(param_dict)

        self.zero_allow_untested_optimizer = \
            get_zero_allow_untested_optimizer(param_dict)

        self.scheduler_name = get_scheduler_name(param_dict)
        self.scheduler_params = get_scheduler_params(param_dict)

        self.wall_clock_breakdown = get_wall_clock_breakdown(param_dict)
        self.memory_breakdown = get_memory_breakdown(param_dict)
        self.tensorboard_enabled = get_tensorboard_enabled(param_dict)
        self.tensorboard_output_path = get_tensorboard_output_path(param_dict)
        self.tensorboard_job_name = get_tensorboard_job_name(param_dict)

        self.sparse_attention = get_sparse_attention(param_dict)

        self.pld_enabled = get_pld_enabled(param_dict)
        self.pld_params = get_pld_params(param_dict)

        checkpoint_params = get_checkpoint_params(param_dict)
        validation_mode = get_checkpoint_tag_validation_mode(checkpoint_params)
        self.checkpoint_tag_validation_enabled = \
            validation_mode != ValidationMode.IGNORE
        self.checkpoint_tag_validation_fail = validation_mode == ValidationMode.FAIL
        self.checkpoint_io_retries = get_checkpoint_io_retries(checkpoint_params)
        self.checkpoint_io_backoff_seconds = \
            get_checkpoint_io_backoff(checkpoint_params)
        self.checkpoint_keep_last_n = \
            get_checkpoint_keep_last_n(checkpoint_params)

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        assert train_batch > 0, \
            "Train batch size: {} has to be greater than 0".format(train_batch)
        assert micro_batch > 0, \
            "Micro batch size per device: {} has to be greater than 0".format(
                micro_batch)
        assert grad_acc > 0, \
            "Gradient accumulation steps: {} has to be greater than 0".format(
                grad_acc)
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            "Check batch related parameters. train_batch_size is not equal to "
            "micro_batch_per_gpu * gradient_acc_step * world_size: "
            "{} != {} * {} * {}".format(train_batch, micro_batch, grad_acc,
                                        self.world_size))

    def _set_batch_related_parameters(self):
        """Infer the missing member(s) of the batch triple
        (train_batch, micro_batch, grad_accum); any two determine the third."""
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        if all(v is not None for v in (train_batch, micro_batch, grad_acc)):
            return
        elif train_batch is not None and micro_batch is not None:
            self.gradient_accumulation_steps = \
                train_batch // micro_batch // self.world_size
        elif train_batch is not None and grad_acc is not None:
            self.train_micro_batch_size_per_gpu = \
                train_batch // self.world_size // grad_acc
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * self.world_size
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        elif self._inference_only:
            # init_inference parse: no training batch triple required
            self.train_micro_batch_size_per_gpu = 1
            self.gradient_accumulation_steps = 1
            self.train_batch_size = self.world_size
        else:
            raise AssertionError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

    def _configure_train_batch_size(self):
        self._set_batch_related_parameters()
        self._batch_assertion()

    # The accepted config surface. docs/_pages/config-json.md documents
    # exactly these keys; _validate_known_keys keeps doc and parser from
    # drifting (unknown keys warn by default, raise under
    # "config_validation": "strict", silent under "ignore").
    KNOWN_TOP_LEVEL_KEYS = {
        "train_batch_size", "train_micro_batch_size_per_gpu",
        "gradient_accumulation_steps", "optimizer", "scheduler",
        "fp16", "bf16", "amp", "gradient_clipping",
        "zero_optimization", "zero_allow_untested_optimizer",
        "steps_per_print", "wall_clock_breakdown", "dump_state",
        "memory_breakdown", "tensorboard", "flops_profiler",
        "activation_checkpointing", "sparse_attention",
        "progressive_layer_drop", "elasticity", "checkpoint",
        "sparse_gradients", "prescale_gradients",
        "gradient_predivide_factor", "disable_allgather", "fp32_allreduce",
        "vocabulary_size", "config_validation", "data_types",
        INFERENCE, TELEMETRY, COMM, TRANSFORMER, ANALYSIS, RUNTIME,
        CONTROLLER,
        # deprecated boolean form + its companion (read_zero_config_deprecated)
        "allgather_size",
    }
    KNOWN_SUBDICT_KEYS = {
        "fp16": {"enabled", "loss_scale", "initial_scale_power",
                 "loss_scale_window", "hysteresis", "min_loss_scale"},
        "bf16": {"enabled"},
        "zero_optimization": {
            "stage", "allgather_partitions", "allgather_bucket_size",
            "overlap_comm", "reduce_scatter",
            "reduce_bucket_size", "contiguous_gradients", "cpu_offload",
            "cpu_offload_params", "cpu_offload_use_pin_memory",
            "sub_group_size", "stage3_prefetch_bucket_size",
            "stage3_max_live_parameters", "stage3_max_reuse_distance",
            "stage3_param_persistence_threshold", "elastic_checkpoint",
            "load_from_fp32_weights",
            "stage3_gather_fp16_weights_on_model_save",
            # ZeRO++ comm-efficiency modes (docs/zeropp.md)
            "zero_quantized_weights", "zero_hierarchical_partition",
            "zero_quantized_gradients",
            # no-silent-no-ops enforcement (docs/zero3_offload.md)
            "strict",
            # short alias of stage3_param_persistence_threshold (the
            # zero.Init config-dict spelling)
            "param_persistence_threshold"},
        "flops_profiler": {"enabled", "profile_step", "module_depth",
                           "top_modules", "detailed"},
        "activation_checkpointing": {
            "partition_activations", "contiguous_memory_optimization",
            "cpu_checkpointing", "number_checkpoints",
            "synchronize_checkpoint_boundary", "profile"},
        "progressive_layer_drop": {"enabled", "theta", "gamma"},
        "tensorboard": {"enabled", "output_path", "job_name"},
        "checkpoint": {"tag_validation", "io_retries",
                       "io_retry_backoff_seconds", "keep_last_n"},
        "data_types": {"grad_accum_dtype"},
        INFERENCE: DeepSpeedInferenceConfig.KNOWN_KEYS,
        TELEMETRY: KNOWN_TELEMETRY_KEYS,
        ANALYSIS: KNOWN_ANALYSIS_KEYS,
        # nested collective_matmul keys are validated (strict-aware) by
        # CollectiveMatmulConfig itself (runtime/comm/config.py)
        COMM: KNOWN_COMM_KEYS,
        TRANSFORMER: {TRANSFORMER_FLASH_ATTENTION},
        RUNTIME: {RUNTIME_EXECUTOR, RUNTIME_EXECUTOR_REWRITES},
        "elasticity": {"enabled", "max_train_batch_size",
                       "micro_batch_sizes", "min_gpus", "max_gpus",
                       "min_time", "prefer_larger_batch",
                       "ignore_non_elastic_batch_info", "version",
                       # runtime rescale policy (ISSUE 16,
                       # runtime/elastic/, docs/elasticity.md)
                       "rescale_retries", "rescale_backoff_seconds",
                       "eviction_severity", "eviction_windows",
                       "preemption_notice_file", "fingerprint_gate"},
        # optimizer/scheduler "params" and "amp" bodies are free-form
        # passthrough (per-type / apex-parity); sparse_attention keys vary
        # by mode and are validated by the layout builders themselves
    }

    def _validate_known_keys(self):
        mode = str(self._param_dict.get("config_validation", "warn")).lower()
        if mode not in ("warn", "strict", "ignore"):
            raise DeepSpeedConfigError(
                "config_validation must be one of warn|strict|ignore, got "
                "{!r}".format(mode))
        if mode == "ignore":
            return
        problems = []
        for key in self._param_dict:
            if key not in self.KNOWN_TOP_LEVEL_KEYS:
                problems.append("unknown top-level key {!r}".format(key))
        for section, known in self.KNOWN_SUBDICT_KEYS.items():
            sub = self._param_dict.get(section)
            if not isinstance(sub, dict):
                continue
            for key in sub:
                if key not in known:
                    problems.append("unknown key {!r} in {!r}".format(
                        key, section))
        if not problems:
            return
        msg = ("DeepSpeedConfig: {} (the accepted surface is documented in "
               "docs/_pages/config-json.md; set \"config_validation\": "
               "\"ignore\" to bypass)").format("; ".join(problems))
        if mode == "strict":
            raise DeepSpeedConfigError(msg)
        logger.warning(msg)

    def _do_sanity_check(self):
        self._do_error_check()
        self._do_warning_check()

    def print(self, name):
        logger.info("{}:".format(name))
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                dots = "." * (29 - len(arg))
                logger.info("  {} {} {}".format(arg, dots, getattr(self, arg)))
        logger.info("  json = {}".format(
            json.dumps(self._param_dict, sort_keys=True, indent=4,
                       separators=(",", ":"))))

    def _do_error_check(self):
        assert self.train_micro_batch_size_per_gpu, \
            "DeepSpeedConfig: {} is not defined".format(
                TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        assert self.gradient_accumulation_steps, \
            "DeepSpeedConfig: {} is not defined".format(GRADIENT_ACCUMULATION_STEPS)
        if self.zero_enabled:
            # Reference requires fp16 for ZeRO; bf16 is the TPU-native
            # equivalent and is accepted as well.
            assert self.fp16_enabled or self.bf16_enabled, \
                "DeepSpeedConfig: ZeRO is only supported if fp16/bf16 is enabled"
            assert self.zero_optimization_stage <= MAX_STAGE_ZERO_OPTIMIZATION, \
                "DeepSpeedConfig: Maximum supported ZeRO stage is {}".format(
                    MAX_STAGE_ZERO_OPTIMIZATION)

    def _do_warning_check(self):
        fp16_enabled = self.fp16_enabled or self.zero_enabled
        vocabulary_size = self._param_dict.get(VOCABULARY_SIZE,
                                               VOCABULARY_SIZE_DEFAULT)
        if vocabulary_size and vocabulary_size % TENSOR_CORE_ALIGN_SIZE != 0:
            logger.warning(
                "DeepSpeedConfig: vocabulary size {} is not aligned to {}, may "
                "impact MXU utilization.".format(vocabulary_size,
                                                TENSOR_CORE_ALIGN_SIZE))
        if self.optimizer_params is not None and \
                MAX_GRAD_NORM in self.optimizer_params.keys() and \
                self.optimizer_params[MAX_GRAD_NORM] > 0:
            if fp16_enabled:
                if self.global_rank == 0:
                    logger.warning(
                        "DeepSpeedConfig: In FP16 mode, DeepSpeed will pass "
                        "{}:{} to FP16 wrapper".format(
                            MAX_GRAD_NORM, self.optimizer_params[MAX_GRAD_NORM]))
            else:
                if self.global_rank == 0:
                    logger.warning(
                        "DeepSpeedConfig: In FP32 mode, DeepSpeed does not "
                        "permit MAX_GRAD_NORM ({}) > 0, setting to zero".format(
                            self.optimizer_params[MAX_GRAD_NORM]))
                self.optimizer_params[MAX_GRAD_NORM] = 0.0
