"""Continuous-batching scheduler step, lowered onto the segment
executor.

Replaces the bespoke phase sequence that lived in
``ContinuousBatchingScheduler._step_impl``: one scheduler step is now
a :class:`~.plan.SegmentPlan` —

  ``admit -> prefill -> decode -> retire``

where ``admit`` fills free slots from the queue (paged admission,
prefix-cache mapping), ``prefill`` runs at most one prefill chunk per
admitted-but-not-ready slot, ``decode`` runs one fused decode/verify
step for every decoding slot, and ``retire`` closes the step (step
counters, occupancy accounting, the serving_step telemetry record)
and carries the retired uids out as the plan's kept result.

Serving-phase state rides the scheduler object (slots, queue, the
``retired`` list) rather than the value environment — the deps encode
the ORDER contract (a decode may never observe a half-admitted slot),
which is what the executor enforces and the auditor fingerprints.
Every segment is main-thread synchronous: the serving step is a strict
phase chain (each phase reads slot state the previous one wrote), so
serial and overlap modes execute identically by construction — the
lowering buys the plan REPRESENTATION (pricing, auditing, rewrite
passes over multi-plan programs), not intra-step overlap.

``_serving_step_topology`` is the ONE place the plan shape is written
down: ``build_serving_plan(engine_or_scheduler)`` with no payloads is
the ABSTRACT twin for ``analysis.ir.plan_of`` / the auditor.
"""
from .plan import Segment, SegmentPlan


def _serving_step_topology():
    """Ordered (name, kind, deps, pool, phase) descriptors of one
    continuous-batching scheduler step."""
    return [
        ("admit", "host", (), None, None),
        ("prefill", "compute", ("admit",), None, "prefill_s"),
        ("decode", "compute", ("prefill",), None, "decode_s"),
        ("retire", "host", ("decode",), None, None),
    ]


def build_serving_plan(engine_or_scheduler=None, payloads=None):
    """Segment plan of one scheduler step. ``payloads`` maps names to
    run callables; absent -> abstract plan (``ir.plan_of``). The plan
    shape is state-independent, so the engine/scheduler argument is
    accepted only for signature symmetry with the other builders."""
    payloads = payloads or {}
    plan = SegmentPlan("serving_step")
    for name, kind, deps, pool, phase in _serving_step_topology():
        plan.add(Segment(
            name=name, kind=kind, deps=deps,
            run=payloads.get(name),
            async_ok=pool is not None, pool=pool or "d2h", phase=phase,
            keep_result=(name == "retire")))
    return plan


def run_serving_step(sched, record_step):
    """One scheduler step on the executor. Returns the retired uids —
    bit-exact with the bespoke phase sequence (same phase callables in
    the same order; the plan adds ordering enforcement, per-segment
    accounting and the audit/rewrite surface)."""
    retired = []
    state = {}

    def admit(env):
        sched._admit()

    def prefill(env):
        sched._prefill_chunks(retired)
        # occupancy counts slots that did work THIS step — retire-at-
        # prefill already freed some, so measure before the decode
        # retire pass too
        state["busy"] = sched.num_active + len(retired)

    def decode(env):
        sched._decode(retired)

    def retire(env):
        engine = sched.engine
        sched.steps += 1
        engine.serving_record_steps = record_step + 1
        occupancy = min(state["busy"], engine.num_slots) \
            / engine.num_slots
        sched._account("record_schedule",
                       occupancy=occupancy,
                       queue_depth=len(sched.queue), step=sched.steps)
        tel = getattr(engine, "telemetry", None)
        if tel is not None:
            # one serving_step record per scheduler step through the
            # same sink layer the training engine writes
            tel.emit_serving_step(
                step=record_step, metrics=sched._record_metrics,
                active_slots=sched.num_active,
                queue_depth=len(sched.queue), occupancy=occupancy,
                page_pool=engine.page_pool_stats(),
                prefix=engine.prefix_stats(),
                role=getattr(engine, "serving_role", None))
        return retired

    payloads = {"admit": admit, "prefill": prefill, "decode": decode,
                "retire": retire}
    plan = build_serving_plan(sched.engine, payloads=payloads)
    env = sched.engine.plan_executor().execute(plan)
    return env["retire"]
