"""PlanExecutor: runs a :class:`~.plan.SegmentPlan` as an ordered,
overlappable schedule.

One scheduler under every step path (ISSUE 13 / ROADMAP item 1): the
engines describe WHAT a step does (segments + deps + prices); this
module owns WHEN — phase timing, async dispatch, bounded transfer
windows, result lifetime — implemented exactly once instead of once per
engine path.

Two modes, selected by the strict-validated ``runtime.executor``
ds_config key (``auto|on|off``; docs/executor.md):

  * ``serial`` (``off``) — every segment runs inline on the calling
    thread in plan (insertion) order. This is the bit-exact ORACLE: the
    same payloads in the same order with zero constructed overlap.
  * ``overlap`` (``on``/``auto``) — async-eligible segments (host and
    transfer work marked ``async_ok``) are launched the moment their
    deps resolve, bounded by a per-pool in-flight window (each pool is
    ONE serial worker, so launch order is execution order and values
    never reorder): their ``start`` hook fires on the main thread
    (issue the DMA / enqueue the coalesced upload) and ``run`` rides
    the worker while the main thread streams the next compute segment.
    Overlap is CONSTRUCTED from the dependency graph, not recovered by
    a lucky scheduler (T3 2401.16677, 2305.06942).

Numerics contract: both modes invoke identical payloads with identical
inputs in an identical consumption order — mode changes WALL CLOCK
placement only, never values (pinned bit-exactly by
tests/unit/test_executor.py and the dryrun executor leg).

Accounting: per-segment wall/wait records (the flight-recorder span
tree of an executed step is derived 1:1 from them — spans.py), phase
clocks billed to the SAME disjoint keys the bespoke paths used
(``host_adam_s`` / ``d2h_wait_s`` / ...), and a per-step
``step_snapshot()`` in the ``SEGMENT_KEYS`` schema
(telemetry/record.py) with per-kind run/wait walls and the constructed
``overlap_efficiency`` = main-thread-busy / (busy + exposed waits).
"""
import time
from concurrent.futures import ThreadPoolExecutor

# blocking-call tripwire (docs/concurrency.md): a worker-future wait
# with any sanitized lock held stalls every thread behind that lock —
# one is-None check when the sanitizer is off
from ...analysis.concurrency.locksan import note_blocking
from ...utils.lifecycle import AtexitCloseMixin
from .plan import PlanError, Segment, SegmentPlan

# bounded in-flight launches per worker class: each launched-but-not-
# yet-consumed async segment may pin buffers (a D2H staging copy, an
# uploaded layer group), so the window bounds the extra memory overlap
# may use — the executor twin of engine._D2H_WINDOW and the streamed
# runner's "2 live groups" budget.
DEFAULT_WINDOWS = {"d2h": 4, "h2d": 2, "host": 4}

# launch-ahead scan horizon: async segments sit within a few plan
# positions of their consumers in every lowering, and the windows are
# single digits — bounding the per-iteration scan keeps the scheduler
# O(n·H) instead of O(n²) on thousand-segment offload plans
LOOKAHEAD_SEGMENTS = 64


class SegmentRecord:
    """One executed segment's measured walls (consumed by telemetry
    spans and the per-step snapshot)."""

    __slots__ = ("name", "kind", "phase", "start_s", "end_s", "run_s",
                 "wait_s", "async_run", "nbytes")

    def __init__(self, name, kind, phase=None, nbytes=0):
        self.name = name
        self.kind = kind
        self.phase = phase
        self.start_s = None
        self.end_s = None
        self.run_s = 0.0
        self.wait_s = 0.0
        self.async_run = False
        self.nbytes = int(nbytes or 0)

    def to_dict(self):
        return {"name": self.name, "kind": self.kind,
                "start_s": self.start_s, "end_s": self.end_s,
                "run_s": self.run_s, "wait_s": self.wait_s,
                "async": self.async_run, "nbytes": self.nbytes}


def _timed_run(fn, snap):
    t0 = time.time()
    value = fn(snap) if fn is not None else None
    return value, t0, time.time()


class PlanExecutor(AtexitCloseMixin):
    """Executes segment plans; owns the worker pools and the per-step
    accounting. One instance per engine (``engine.plan_executor()``)."""

    def __init__(self, mode="overlap", windows=None, rewrites=None):
        if mode not in ("overlap", "serial"):
            raise ValueError(
                "executor mode must be 'overlap' or 'serial', got "
                "{!r}".format(mode))
        self.mode = mode
        self.windows = dict(DEFAULT_WINDOWS)
        if windows:
            self.windows.update({k: int(v) for k, v in windows.items()})
        # plan rewrite passes (runtime/executor/rewrite.py), applied at
        # execute time in overlap mode only — the strict-validated
        # ``runtime.executor_rewrites`` dict, or None/disabled
        self.rewrites = rewrites
        self._pools = {}
        # per-step accounting (drained by the telemetry emit path)
        self._step_records = []
        # engine-lifetime counters (bench extra.executor); per-kind
        # walls accumulate at drain time so the lifetime view survives
        # the per-step record drains
        self.plans_total = 0
        self.segments_total = 0
        self.last_plan_segments = 0
        self._life_per_kind = {}
        self._life_busy = 0.0
        self._life_waits = 0.0
        # rewrite accounting: calibrate-then-rewrite — the FIRST
        # execution of each plan name runs unrewritten and records its
        # exposed wait as the baseline the rewritten executions are
        # measured against (values are mode-invariant, so the
        # calibration step costs nothing but its un-overlapped wall)
        self._rewrite_base = {}       # plan name -> baseline waits
        self._rewrite_meas = {}       # plan name -> [rewritten waits]
        self._rewrite_pass_totals = {}   # pass name -> aggregated entry

    # ------------------------------------------------------------- pools
    def _pool(self, key):
        pool = self._pools.get(key)
        if pool is None:
            if not self._pools:
                self._register_atexit_close()
            pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="executor-" + key)
            self._pools[key] = pool
        return pool

    def close(self):
        """Shut down the worker pools. Registered at interpreter exit
        when the first pool spins up (long multi-engine processes never
        accumulate idle workers past close); idempotent, and a later
        execute() lazily rebuilds what it needs."""
        if self._finish_close():
            return
        for pool in self._pools.values():
            pool.shutdown(wait=False)
        self._pools = {}

    # ----------------------------------------------------------- execute
    def execute(self, plan, env=None, phases=None):
        """Run ``plan``; returns the value environment (results of
        segments nobody consumed stay available to the caller). Phase
        walls accumulate into ``phases`` when given (the engine's
        ``offload_phase_times`` dict)."""
        problems = plan.validate()
        if problems:
            raise PlanError("plan {!r} invalid: {}".format(
                plan.name, "; ".join(problems)))
        env = {} if env is None else env
        phases = {} if phases is None else phases
        overlap = self.mode == "overlap"
        rewritten = False
        if overlap and self.rewrites and self.rewrites.get("enabled"):
            if plan.name not in self._rewrite_base:
                # calibration execution: run the canonical plan and
                # record its exposed wait as this plan name's baseline
                self._rewrite_base[plan.name] = None
            else:
                from .rewrite import apply_rewrites
                plan, pass_stats = apply_rewrites(plan, self.rewrites,
                                                  executor=self)
                rewritten = bool(pass_stats)
                for entry in pass_stats:
                    slot = self._rewrite_pass_totals.setdefault(
                        entry["name"],
                        {"name": entry["name"], "segments_moved": 0,
                         "predicted_exposed_wait_delta_s": 0.0})
                    slot["segments_moved"] += entry["segments_moved"]
                    slot["predicted_exposed_wait_delta_s"] += \
                        entry["predicted_exposed_wait_delta_s"]
                problems = plan.validate()
                if problems:
                    raise PlanError(
                        "rewritten plan {!r} invalid: {}".format(
                            plan.name, "; ".join(problems)))
        windows = dict(self.windows)
        windows.update(plan.windows)
        segs = plan.segments
        remaining = plan.consumer_counts()
        launched = {}               # name -> (future, record)
        completed = set()
        inflight = {}               # pool -> launched-not-yet-consumed
        records = []

        def bill(phase, dt):
            if phase and dt > 0:
                phases[phase] = phases.get(phase, 0.0) + dt

        def dep_done(name):
            if name in completed:
                return True
            ent = launched.get(name)
            return ent is not None and ent[0].done()

        def materialize(name, waiter=None, wait_phase=None):
            """Ensure ``env[name]`` holds an async segment's result;
            bills the blocking residual (the EXPOSED wait overlap could
            not hide) to the waiter."""
            ent = launched.get(name)
            if ent is None or name in completed:
                return
            fut, rec = ent
            if not fut.done():
                note_blocking("executor.wait:{}".format(name))
            t0 = time.time()
            value, r0, r1 = fut.result()
            wait = time.time() - t0
            rec.start_s, rec.end_s, rec.run_s = r0, r1, r1 - r0
            env[name] = value
            completed.add(name)
            if wait > 0:
                bill(wait_phase, wait)
                if waiter is not None:
                    waiter.wait_s += wait

        def consume(seg):
            """Decrement the refcount of each dep; release exhausted
            results (frees device buffers at the same point the bespoke
            paths dropped their references)."""
            for dep in seg.deps:
                left = remaining.get(dep)
                if left is None:
                    continue
                left -= 1
                remaining[dep] = left
                if left == 0:
                    dep_seg = plan[dep]
                    if not dep_seg.keep_result:
                        env.pop(dep, None)
                    if dep in launched:
                        inflight[dep_seg.pool] = max(
                            inflight.get(dep_seg.pool, 0) - 1, 0)

        def launch_ahead(idx):
            """Launch every async-eligible segment from ``idx`` on whose
            deps resolved, within its pool window — in plan order per
            pool (one blocked segment blocks the segments behind it on
            the same pool, so a serial worker never reorders)."""
            if not overlap:
                return
            blocked = set()
            for seg in segs[idx:idx + LOOKAHEAD_SEGMENTS]:
                if not seg.async_ok or seg.name in launched or \
                        seg.name in completed:
                    continue
                if seg.pool in blocked:
                    continue
                if inflight.get(seg.pool, 0) >= \
                        windows.get(seg.pool, 1) or \
                        not all(dep_done(d) for d in seg.deps):
                    blocked.add(seg.pool)
                    continue
                for dep in seg.deps:
                    materialize(dep)        # futures done: no wait
                snap = {d: env[d] for d in set(seg.deps)}
                rec = SegmentRecord(seg.name, seg.kind, phase=seg.phase,
                                    nbytes=seg.nbytes)
                rec.async_run = True
                if seg.start is not None:
                    seg.start(snap)
                fut = self._pool(seg.pool).submit(_timed_run, seg.run,
                                                  snap)
                launched[seg.name] = (fut, rec)
                records.append(rec)
                inflight[seg.pool] = inflight.get(seg.pool, 0) + 1
                consume(seg)    # snapshot holds the dep refs now

        try:
            for idx, seg in enumerate(segs):
                launch_ahead(idx)
                if seg.name in launched:
                    continue                # riding a worker
                rec = SegmentRecord(seg.name, seg.kind, phase=seg.phase,
                                    nbytes=seg.nbytes)
                for dep in seg.deps:
                    materialize(dep, waiter=rec,
                                wait_phase=seg.wait_phase)
                snap = {d: env[d] for d in set(seg.deps)}
                t0 = time.time()
                if seg.start is not None:
                    seg.start(snap)
                value = seg.run(snap) if seg.run is not None else None
                t1 = time.time()
                rec.start_s, rec.end_s, rec.run_s = t0, t1, t1 - t0
                bill(seg.phase, rec.run_s)
                env[seg.name] = value
                completed.add(seg.name)
                records.append(rec)
                consume(seg)
        finally:
            # drain stragglers (none on the happy path: every async
            # segment has a consumer) so a raised step never leaves a
            # worker mutating freed state
            for name, (fut, _rec) in list(launched.items()):
                if name not in completed:
                    try:
                        if not fut.done():
                            note_blocking(
                                "executor.drain:{}".format(name))
                        value, r0, r1 = fut.result()
                        _rec.start_s, _rec.end_s = r0, r1
                        _rec.run_s = r1 - r0
                        env[name] = value
                        completed.add(name)
                    except Exception:  # noqa: BLE001 - secondary failure
                        pass
            self._step_records.extend(records)
            self.plans_total += 1
            self.segments_total += len(segs)
            self.last_plan_segments = len(segs)
            if self.rewrites and self.rewrites.get("enabled") and \
                    self.mode == "overlap":
                _, _, plan_waits = self._aggregate(records)
                if self._rewrite_base.get(plan.name) is None and \
                        not rewritten:
                    self._rewrite_base[plan.name] = plan_waits
                elif rewritten:
                    self._rewrite_meas.setdefault(
                        plan.name, []).append(plan_waits)
        return env

    def run_program(self, name, kind, fn, phase=None):
        """One-segment convenience plan: the micro/fused/apply jit
        programs ride the same executor (and the same accounting) as
        the multi-segment offload lowerings."""
        plan = SegmentPlan(name)
        plan.add(Segment(name=name, kind=kind, phase=phase,
                         run=lambda env: fn()))
        return self.execute(plan)[name]

    # -------------------------------------------------------- accounting
    def drain_step_records(self):
        """This step's executed-segment records (for the span tree);
        clears the per-step buffer, folding the walls into the
        lifetime per-kind totals."""
        per_kind, busy, waits = self._aggregate(self._step_records)
        for kind, slot in per_kind.items():
            life = self._life_per_kind.setdefault(
                kind, {"segments": 0, "run_s": 0.0, "wait_s": 0.0})
            for key in ("segments", "run_s", "wait_s"):
                life[key] += slot[key]
        self._life_busy += busy
        self._life_waits += waits
        records = self._step_records
        self._step_records = []
        return records

    @staticmethod
    def _aggregate(records):
        per_kind = {}
        busy = waits = 0.0
        for rec in records:
            slot = per_kind.setdefault(
                rec.kind, {"segments": 0, "run_s": 0.0, "wait_s": 0.0})
            slot["segments"] += 1
            slot["run_s"] += rec.run_s
            slot["wait_s"] += rec.wait_s
            waits += rec.wait_s
            if rec.async_run:
                continue            # hidden behind main-thread work
            if rec.kind == "transfer":
                waits += rec.run_s  # serial mode: exposed transfer wall
            else:
                busy += rec.run_s
        return per_kind, busy, waits

    def measured_totals(self):
        """Lifetime (busy, waits) including the live step window — the
        measured accounting the widen rewrite pass reads."""
        per_kind, busy, waits = self._aggregate(self._step_records)
        for kind, life in self._life_per_kind.items():
            slot = per_kind.setdefault(
                kind, {"segments": 0, "run_s": 0.0, "wait_s": 0.0})
            for key in ("segments", "run_s", "wait_s"):
                slot[key] += life[key]
        return per_kind, busy + self._life_busy, \
            waits + self._life_waits

    def rewrite_snapshot(self):
        """The ``extra.executor.rewrites`` section (REWRITE_KEYS
        schema, telemetry/record.py): which passes fired, how many
        segments they moved, and the predicted vs MEASURED exposed-
        wait delta (calibration baseline minus the rewritten
        executions' mean, summed over plan names with both). None when
        rewrites are not configured."""
        if not self.rewrites:
            return None
        passes = [dict(self._rewrite_pass_totals[name],
                       predicted_exposed_wait_delta_s=round(
                           self._rewrite_pass_totals[name]
                           ["predicted_exposed_wait_delta_s"], 9))
                  for name in sorted(self._rewrite_pass_totals)]
        predicted = round(sum(p["predicted_exposed_wait_delta_s"]
                              for p in passes), 9)
        measured = None
        deltas = []
        for name, meas in self._rewrite_meas.items():
            base = self._rewrite_base.get(name)
            if base is None or not meas:
                continue
            deltas.append(base - sum(meas) / len(meas))
        if deltas:
            measured = round(sum(deltas), 9)
        return {
            "enabled": bool(self.rewrites.get("enabled")),
            "passes": passes,
            "segments_moved": sum(p["segments_moved"] for p in passes),
            "predicted_exposed_wait_delta_s": predicted,
            "measured_exposed_wait_delta_s": measured,
        }

    @staticmethod
    def _rounded(per_kind):
        return {kind: {"segments": slot["segments"],
                       "run_s": round(slot["run_s"], 6),
                       "wait_s": round(slot["wait_s"], 6)}
                for kind, slot in per_kind.items()}

    def step_snapshot(self):
        """Per-kind walls + constructed overlap for the live step window
        (SEGMENT_KEYS core; the caller merges path-specific upload
        counters). ``plan_segments`` counts every segment executed in
        the window — ALL the step's plans (gas micro-plans + the apply
        on the streamed path); one plan's own size lives in the audit
        report's ``plan/<name>`` entry. Does NOT clear —
        ``drain_step_records`` does."""
        per_kind, busy, waits = self._aggregate(self._step_records)
        eff = None
        if busy + waits > 0:
            eff = round(busy / (busy + waits), 4)
        return {
            "plan_segments": len(self._step_records),
            "per_kind": self._rounded(per_kind),
            "overlap_efficiency": eff,
        }

    def lifetime_snapshot(self):
        """Engine-lifetime counters (bench ``extra.executor``):
        cumulative per-kind walls over every executed plan (drained
        steps included) + the live window."""
        per_kind, busy, waits = self.measured_totals()
        eff = None
        if busy + waits > 0:
            eff = round(busy / (busy + waits), 4)
        out = {
            "plan_segments": len(self._step_records),
            "per_kind": self._rounded(per_kind),
            "overlap_efficiency": eff,
            "mode": self.mode,
            "plans_executed": self.plans_total,
            "segments_executed": self.segments_total,
            "last_plan_segments": self.last_plan_segments,
        }
        rewrites = self.rewrite_snapshot()
        if rewrites is not None:
            out["rewrites"] = rewrites
        return out
