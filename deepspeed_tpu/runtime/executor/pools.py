"""Serial worker pools: the one place background step workers spin up.

Every ordered background worker in the runtime — the executor's own
per-class pools (``scheduler.py``), the coalesced H2D upload worker
(``runtime/zero/transfer.py``), the checkpoint shard writer
(``runtime/checkpointing.py``) — is a single-thread pool so submission
order IS execution order. Constructing them here (DSL006: worker pools
live in ``runtime/executor/`` only) keeps that invariant reviewable in
one file instead of once per subsystem.
"""
from concurrent.futures import ThreadPoolExecutor


def serial_pool(name):
    """One ordered background worker (``max_workers=1``): submissions
    execute in submission order, so a caller can sequence work by
    submit order alone."""
    return ThreadPoolExecutor(max_workers=1, thread_name_prefix=name)


def upload_pool(name="offload-upload"):
    """The serial pack+device_put worker of the coalesced H2D batcher
    (jax dispatch is thread-safe; one worker keeps uploads ordered)."""
    return serial_pool(name)


def write_pool(name="ckpt-write"):
    """The serial checkpoint shard writer: an async ``save_latest``
    queued after the shard writes cannot run until they all landed."""
    return serial_pool(name)
