"""Classic ZeRO-Offload optimizer step, lowered onto the segment
executor.

This replaces the bespoke hand-scheduled shard pipeline that lived in
``engine._host_apply_step`` / ``engine._offload_update_loop``: the
same payloads (jitted overflow check, per-chunk D2H fetch, in-place
host Adam, coalesced H2D upload, jitted reshard) now run as a
:class:`~.plan.SegmentPlan` whose overlap — async D2H fetches streaming
ahead of the host Adam inside a bounded window, leaf uploads riding the
coalescing batcher behind the remaining chunks — is CONSTRUCTED by the
scheduler from declared deps instead of hand-interleaved loops.

Numerics are bit-exact with the bespoke implementation (and between
``serial`` and ``overlap`` modes): every chunk's Adam is elementwise on
disjoint views, the overflow/norm reductions are the same jitted
program, and the upload packing is value-preserving (pinned by
tests/unit/test_executor.py and the dryrun executor leg).

``build_update_plan(engine)`` with no payloads is the ABSTRACT twin
(``analysis.ir.plan_of``): the same topology from the host shard
registry's shapes alone, for the auditor.
"""
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..checkpointing import shard_key as _shard_key
from ..fp16 import loss_scaler as ls
from ..zero.transfer import H2DBatcher, chunk_rows, host_adam_chunk
from .plan import Segment, SegmentPlan


def _work_chunks(engine, flat_acc=None):
    """The flat (leaf, shard, row-chunk) work list of one offload step,
    derived from the HOST shard registry (replicated leaves dedupe to
    one entry — the same order the Adam consumes). With ``flat_acc``
    each item carries its live device grad buffer; without (the
    abstract/audit path) buffers stay None and only the topology is
    real."""
    hs = engine.host_state
    work = []           # (leaf_idx, shard_tup, buf, rows|None, buf_idx)
    shard_bufs = []
    for i, shards in enumerate(hs["shard_leaves"]):
        local = None
        if flat_acc is not None:
            local = {_shard_key(sh.index): sh.data
                     for sh in flat_acc[i].addressable_shards}
        for tup in shards:
            buf = local[_shard_key(tup[0])] if local is not None else None
            buf_idx = len(shard_bufs)
            shard_bufs.append(buf)
            chunks = chunk_rows(np.shape(tup[1]), engine._sub_group_size)
            whole = len(chunks) == 1
            for r0, r1 in chunks:
                work.append((i, tup, buf,
                             None if whole else (r0, r1), buf_idx))
    return work, shard_bufs


def resolve_adam_step(engine, sumsq, inv_scale, clip):
    """The host-Adam step preamble both lowered apply paths share
    (classic offload here, streamed in ``executor/stream.py``): grad
    norm + clip coefficient, the host step-counter bump, bias
    correction, and adam_w/kernel-lib resolution — one implementation
    so the two paths can never diverge. Returns
    ``(grad_norm, coef, hyper, bc1, bc2, adam_w, lib)``."""
    hs = engine.host_state
    hyper = engine._hyper()
    grad_norm = float(np.sqrt(float(sumsq)))
    coef = inv_scale
    if clip > 0 and grad_norm > clip:
        coef *= clip / (grad_norm + 1e-6)
    hs["step"] += 1
    step = hs["step"]
    beta1, beta2 = hyper["beta1"], hyper["beta2"]
    bias_correction = getattr(engine.optimizer, "bias_correction", True)
    bc1 = 1.0 - beta1 ** step if bias_correction else 1.0
    bc2 = 1.0 - beta2 ** step if bias_correction else 1.0
    adam_w = 1 if getattr(engine.optimizer, "adam_w_mode", True) else 0
    lib = engine._offload_lib()
    return grad_norm, coef, hyper, bc1, bc2, adam_w, lib


def build_update_plan(engine, work=None, payloads=None):
    """The offload update pipeline's segment plan: per-chunk
    ``d2h/<j> -> adam/<j>``, per-leaf ``upload/<i>`` after the leaf's
    last chunk, then ``upload_finish -> reshard``. ``payloads`` maps
    segment names to (run, start) callables; absent -> abstract plan
    (topology only, for ``ir.plan_of`` / the auditor)."""
    if work is None:
        work, _ = _work_chunks(engine)
    payloads = payloads or {}
    plan = SegmentPlan("offload_apply")
    plan.windows = {"d2h": engine._D2H_WINDOW}
    by_leaf = {}
    for j, item in enumerate(work):
        by_leaf.setdefault(item[0], []).append(j)
    upload_names = []
    leaf_bytes = {}
    for j, item in enumerate(work):
        i = item[0]
        rows = item[3]
        shape = np.shape(item[1][1])
        n = int(np.prod(shape)) if shape else 1
        if rows is not None and shape:
            n = (rows[1] - rows[0]) * \
                (int(np.prod(shape[1:])) if len(shape) > 1 else 1)
        leaf_bytes[i] = leaf_bytes.get(i, 0) + n * 4
        run, start = payloads.get("d2h/%d" % j, (None, None))
        plan.add(Segment(
            name="d2h/%d" % j, kind="transfer", async_ok=True,
            pool="d2h", phase="d2h_wait_s", run=run, start=start,
            nbytes=n * 4))
        run, _ = payloads.get("adam/%d" % j, (None, None))
        plan.add(Segment(
            name="adam/%d" % j, kind="host", deps=("d2h/%d" % j,),
            phase="host_adam_s", wait_phase="d2h_wait_s", run=run))
        if j == by_leaf[i][-1]:
            run, _ = payloads.get("upload/%d" % i, (None, None))
            plan.add(Segment(
                name="upload/%d" % i, kind="transfer",
                deps=tuple("adam/%d" % jj for jj in by_leaf[i]),
                phase="h2d_dispatch_s", run=run,
                nbytes=leaf_bytes[i]))
            upload_names.append("upload/%d" % i)
    run, _ = payloads.get("upload_finish", (None, None))
    plan.add(Segment(
        name="upload_finish", kind="transfer", deps=tuple(upload_names),
        phase="h2d_dispatch_s", run=run))
    run, _ = payloads.get("reshard", (None, None))
    plan.add(Segment(
        name="reshard", kind="compute", deps=("upload_finish",),
        phase="h2d_reshard_s", run=run))
    # reshard re-places the uploaded masters across the mesh — its
    # traffic price is the wire.py census-ground-truthed per-step bytes
    from .costs import price_plan, wire_collective_bytes
    wire = wire_collective_bytes(engine)
    price_plan(plan, engine=engine,
               nbytes={"reshard": wire} if wire else None)
    return plan


def run_offload_apply(engine):
    """The classic ZeRO-Offload optimizer step (engine
    ``_host_apply_step``): jitted overflow/norm check, then the lowered
    update plan; overflow skips the plan and resets the accumulators.
    Returns the metrics dict (and updates the loss scaler), exactly as
    the bespoke implementation did."""
    scaler = engine.state["scaler"]
    cur_scale = float(scaler.cur_scale)
    inv_scale = 1.0 / cur_scale
    clip = engine.gradient_clipping()

    # the same disjoint phase clocks the bespoke path reported;
    # "micros_and_check" includes waiting for the jitted micro steps to
    # finish — the check's value fetch is the first sync point
    phases = {"micros_and_check_s": 0.0, "d2h_wait_s": 0.0,
              "host_adam_s": 0.0, "h2d_dispatch_s": 0.0,
              "h2d_reshard_s": 0.0}
    engine.offload_phase_times = phases
    t_phase = time.time()
    check = engine._get_jit("offload_check", engine._offload_check_fn)
    finite, sumsq = check(engine.state["acc_grads"],
                          np.float32(inv_scale))
    hs = engine.host_state
    flat_acc = hs["treedef"].flatten_up_to(engine.state["acc_grads"])
    work, shard_bufs = _work_chunks(engine, flat_acc)
    engine.offload_work_chunks = len(work)

    # bounded async D2H warm-up: the first window of shard copies
    # streams behind the (round-trip) overflow fetch below; each d2h
    # segment's launch hook tops the window up from there. An unbounded
    # warm-up pins a device staging buffer per shard and OOMs at 1.5B.
    issued = [0]

    def _issue_upto(limit):
        while getattr(engine, "_async_d2h", True) and \
                issued[0] < min(limit, len(shard_bufs)):
            try:
                shard_bufs[issued[0]].copy_to_host_async()
            except Exception:  # noqa: BLE001 - plugin without async copy
                engine._async_d2h = False
                return
            issued[0] += 1

    _issue_upto(engine._D2H_WINDOW)
    # a sumsq that overflowed despite finite elements is an overflow
    # too: clipping against an inf norm would silently zero the update
    overflow = (not bool(finite)) or not np.isfinite(float(sumsq))
    phases["micros_and_check_s"] = time.time() - t_phase

    grad_norm = 0.0
    if not overflow:
        grad_norm, coef, hyper, bc1, bc2, adam_w, lib = \
            resolve_adam_step(engine, sumsq, inv_scale, clip)

        left_in_leaf = [0] * len(flat_acc)
        for i, *_ in work:
            left_in_leaf[i] += 1
        flat_params = [None] * len(flat_acc)

        # release the engine's references so device memory frees as the
        # plan consumes it: params' updated values come from the host
        # master; each acc leaf is dead once its last chunk fetched
        acc_specs = [(a.shape, a.dtype) for a in flat_acc]
        acc_shardings = [a.sharding for a in flat_acc]
        engine.state["params"] = None
        engine.state["acc_grads"] = None

        batcher = H2DBatcher(
            engine._h2d_bucket_elems, engine.compute_dtype,
            pool=engine._upload_pool(),
            jit_cache=engine._h2d_split_cache())

        payloads = {}
        for j, item in enumerate(work):
            payloads["d2h/%d" % j] = _d2h_payload(item, _issue_upto)
            payloads["adam/%d" % j] = _adam_payload(
                j, item, work, left_in_leaf, coef, hyper, bc1, bc2,
                adam_w, lib)
        for i in set(it[0] for it in work):
            payloads["upload/%d" % i] = (_upload_payload(
                engine, batcher, i, acc_specs, acc_shardings, hs,
                flat_acc), None)
        payloads["upload_finish"] = (_finish_payload(
            engine, batcher, flat_params, acc_specs, acc_shardings),
            None)
        payloads["reshard"] = (_reshard_payload(
            engine, flat_params, acc_specs, acc_shardings, hs), None)
        plan = build_update_plan(engine, work=work, payloads=payloads)

        try:
            engine.plan_executor().execute(plan, phases=phases)
        except BaseException:
            # a mid-step failure must not strand the engine with None
            # pytrees: the host masters hold the authoritative values —
            # rebuild params from them (best effort) and record the torn
            # step so a checkpoint taken after the re-raise carries the
            # fact instead of silently looking whole
            hs["torn_step"] = hs["step"]
            try:
                engine._restore_params_from_host(acc_specs,
                                                 acc_shardings, hs)
            except Exception:  # noqa: BLE001
                pass
            raise
        hs.pop("torn_step", None)
        if os.environ.get("DS_OFFLOAD_PROFILE"):
            # force the uploads/reshard to COMPLETE so the phase clock
            # captures the H2D wait (serializes the tail — profiling
            # only; only a value fetch syncs through the axon tunnel)
            t0 = time.time()
            leaf = jax.tree_util.tree_leaves(engine.state["params"])[0]
            float(jnp.asarray(leaf).ravel()[0])
            phases["h2d_reshard_s"] += time.time() - t0
    else:
        engine.state["acc_grads"] = jax.tree_util.tree_map(
            jnp.zeros_like, engine.state["acc_grads"])
        if "qg_error" in engine.state:
            # poisoned by the inf/nan grads this window quantized —
            # reset with the skip (mirrors _apply_step_fn)
            engine.state["qg_error"] = jax.tree_util.tree_map(
                jnp.zeros_like, engine.state["qg_error"])
    engine.state["scaler"] = ls.update_scale(scaler, overflow)
    return {"overflow": overflow, "grad_norm": grad_norm,
            "loss_scale": cur_scale}


# ----------------------------------------------------------- payloads
def _d2h_payload(item, issue_upto):
    def start(env):
        # ensure this chunk's buffer has an async copy in flight; the
        # scheduler's launch window bounds how far ahead this reaches
        issue_upto(item[4] + 1)

    def run(env):
        # writable fp32 copy for the in-place host Adam; a sub_group
        # row-chunk fetches only its slice
        rows = item[3]
        if rows is None:
            return np.array(item[2], dtype=np.float32)
        return np.array(item[2][rows[0]:rows[1]], dtype=np.float32)

    return run, start


def _adam_payload(j, item, work, left_in_leaf, coef, hyper, bc1, bc2,
                  adam_w, lib):
    def run(env):
        g = env["d2h/%d" % j]
        g *= coef              # unscale (+clip) in place on the host copy
        i, (idx, p, m, v), _, rows, _ = item
        if rows is not None:
            # sub_group chunk: in-place Adam on contiguous row-range
            # views of the host shard
            p = p[rows[0]:rows[1]]
            m = m[rows[0]:rows[1]]
            v = v[rows[0]:rows[1]]
        host_adam_chunk(lib, p, g, m, v, hyper, bc1, bc2, adam_w)
        # drop the consumed work reference so its buffers free
        work[j] = None
        left_in_leaf[i] -= 1

    return run, None


def _upload_payload(engine, batcher, i, acc_specs, acc_shardings, hs,
                    flat_acc):
    def run(env):
        # the leaf's last chunk stepped: queue its master shards on the
        # coalescing upload batcher (packing + device_put ride the
        # upload worker behind the remaining chunks' Adam)
        engine._enqueue_leaf_upload(
            batcher, i, acc_specs[i][0], acc_shardings[i],
            hs["shard_leaves"][i])
        flat_acc[i] = None

    return run


def _finish_payload(engine, batcher, flat_params, acc_specs,
                    acc_shardings):
    def run(env):
        uploaded = batcher.finish()
        engine.h2d_batches = batcher.batches
        engine.h2d_elems = batcher.elems
        engine.h2d_bucket_occupancy = batcher.occupancy()
        for i, sharding in enumerate(acc_shardings):
            flat_params[i] = engine._assemble_uploaded_leaf(
                uploaded, i, acc_specs[i][0], sharding)

    return run


def _reshard_payload(engine, flat_params, acc_specs, acc_shardings, hs):
    def run(env):
        engine._finish_offload_step(flat_params, acc_specs,
                                    acc_shardings, hs)

    return run
