"""Segment plans: the one step-scheduling representation every engine
path lowers onto.

A :class:`SegmentPlan` is an ordered DAG of :class:`Segment` nodes in
the shard-lint IR's segment vocabulary (``analysis/ir.py``
``SEGMENT_KINDS``: compute / collective / host / transfer / sharding).
Each node declares

  * ``deps`` — the segment names whose results it consumes (the plan's
    insertion order must be a valid topological order: a dep always
    precedes its consumer, so the serial "oracle" execution is simply
    insertion order);
  * ``run(env)`` — the payload: reads its inputs from the value
    environment (keyed by producer segment name), returns the value
    stored under its own name. ``None`` in ABSTRACT plans (built by
    ``analysis.ir.plan_of`` for the auditor — topology only, nothing
    executable);
  * ``start(env)`` — optional nonblocking launch hook for async-eligible
    segments (issue a ``copy_to_host_async``, enqueue an upload on the
    coalescing batcher); the scheduler calls it on the main thread the
    moment the segment is dispatched, then runs ``run`` on the segment
    class's worker — this is where transfer/compute overlap is
    CONSTRUCTED rather than hoped for (T3, 2401.16677);
  * ``async_ok`` / ``pool`` — whether the segment may run off the main
    thread, and on which serial worker class (``"d2h"`` / ``"h2d"``);
  * ``phase`` / ``wait_phase`` — the engine phase-clock names its run
    wall and its dep-wait wall bill to (the SAME disjoint keys the
    StepRecord ``phases`` dict always carried, so telemetry consumers
    see no schema change);
  * ``donate`` — the donation declaration of the jitted program the
    segment invokes (informational mirror of the one declaration the
    jit path reads, e.g. ``stream.STREAM_DONATE``), plus ``flops`` /
    ``nbytes`` prices when the lowering knows them.

``validate()`` is the plan-level contract the auditor enforces on
lowered plans (``analysis/auditor.py`` via ``ir.plan_of``): unique
names, declared kinds in the IR vocabulary, every dep resolvable, and
deps-precede-consumers (acyclic by construction).
"""
import dataclasses

# The schedulable-segment vocabulary. Canonically defined by the
# shard-lint IR (analysis/ir.py SEGMENT_KINDS); duplicated here so the
# runtime executor never imports the analysis package at module scope
# (tests/unit/test_executor.py pins the two tuples equal).
SEGMENT_KINDS = ("compute", "collective", "host", "transfer", "sharding")

# serial worker classes async segments may run on
POOL_KEYS = ("d2h", "h2d", "host")


@dataclasses.dataclass
class Segment:
    name: str
    kind: str
    deps: tuple = ()
    run: object = None            # callable(env) -> value, or None (abstract)
    start: object = None          # optional nonblocking launch hook(env)
    async_ok: bool = False
    pool: str = "d2h"             # worker class when async_ok
    phase: str = None             # phase clock the run wall bills to
    wait_phase: str = None        # phase clock dep-wait walls bill to
    donate: tuple = ()            # declared donation of the jitted payload
    flops: float = 0.0            # price, when the lowering knows it
    nbytes: int = 0               # payload bytes (transfers), when known
    keep_result: bool = False     # exempt from refcount release

    def __post_init__(self):
        self.deps = tuple(self.deps)


class PlanError(ValueError):
    """A malformed segment plan (duplicate name, unknown kind, dangling
    or out-of-order dep)."""


class SegmentPlan:
    """Ordered segment DAG. Insertion order IS the serial schedule."""

    def __init__(self, name, segments=None):
        self.name = str(name)
        self.segments = []
        self._by_name = {}
        # per-plan overrides of the executor's in-flight windows (e.g.
        # the streamed micro plan's grad fetches all ride behind compute
        # like the bespoke path's deferred resolve — unbounded window)
        self.windows = {}
        for seg in segments or ():
            self.add(seg)

    def add(self, segment):
        if segment.name in self._by_name:
            raise PlanError("plan {!r}: duplicate segment {!r}".format(
                self.name, segment.name))
        self.segments.append(segment)
        self._by_name[segment.name] = segment
        return segment

    def __len__(self):
        return len(self.segments)

    def __getitem__(self, name):
        return self._by_name[name]

    def __contains__(self, name):
        return name in self._by_name

    def validate(self):
        """-> list of problem strings; empty = valid. The executor
        refuses to run an invalid plan; the auditor turns each problem
        into a finding."""
        problems = []
        seen = set()
        for seg in self.segments:
            if seg.kind not in SEGMENT_KINDS:
                problems.append(
                    "segment {!r} has unknown kind {!r} (vocabulary: "
                    "{})".format(seg.name, seg.kind,
                                 "/".join(SEGMENT_KINDS)))
            if seg.async_ok and seg.pool not in POOL_KEYS:
                problems.append(
                    "segment {!r} names unknown worker pool {!r}".format(
                        seg.name, seg.pool))
            for dep in seg.deps:
                if dep not in self._by_name:
                    problems.append(
                        "segment {!r} depends on unknown segment "
                        "{!r}".format(seg.name, dep))
                elif dep not in seen:
                    problems.append(
                        "segment {!r} depends on {!r} which is inserted "
                        "AFTER it — insertion order must be a "
                        "topological order".format(seg.name, dep))
            seen.add(seg.name)
        return problems

    def consumer_counts(self):
        """{segment name: number of dependents} — the refcount table the
        scheduler uses to release a segment's result (free its device
        buffers) the moment the last consumer finished."""
        counts = {seg.name: 0 for seg in self.segments}
        for seg in self.segments:
            for dep in seg.deps:
                if dep in counts:
                    counts[dep] += 1
        return counts

    def summary(self):
        """Per-kind node counts + declared prices — the plan-shape view
        telemetry and ``extra.executor`` report."""
        per_kind = {}
        for seg in self.segments:
            slot = per_kind.setdefault(seg.kind,
                                       {"segments": 0, "nbytes": 0})
            slot["segments"] += 1
            slot["nbytes"] += int(seg.nbytes or 0)
        return {"name": self.name, "segments": len(self.segments),
                "per_kind": per_kind}
