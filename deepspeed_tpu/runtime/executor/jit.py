"""The one place a step program declares donation.

Every jitted step program in the repo obtains its wrapper through
:func:`jit_program` (directly, or via ``DeepSpeedEngine._get_jit`` /
``StreamedOffloadRunner._jit`` which route here): the executor owns the
donation policy exactly like it owns async dispatch and phase timing
(DSL006 — step scheduling lives in ``runtime/executor/`` only; since
ISSUE 19 the baseline for that rule is EMPTY).

``donate`` is the same declaration :class:`~.plan.Segment.donate`
mirrors and ``analysis/rules.py``'s donation audit reads — one spelling
per program, checked end to end: the engine passes it here, the plan
records it, the auditor verifies the jitted program honors it.
"""
import jax


def jit_program(fn, donate=(), **jit_kwargs):
    """``jax.jit`` with the executor-owned donation declaration.

    ``donate``: positional argnums the program consumes (its
    ``donate_argnums``). Extra ``jit_kwargs`` (``out_shardings``,
    ``static_argnums``, ...) pass through untouched.
    """
    if donate:
        jit_kwargs["donate_argnums"] = tuple(donate)
    return jax.jit(fn, **jit_kwargs)
