"""Segment-plan pricing: every lowered plan is a priced DAG.

The rewrite passes (``rewrite.py``) reason over declared prices — a
hoist trades live bytes for exposed-wait reduction, a widened window
pins more in-flight transfer bytes — so the prices must come from
seams the repo already trusts rather than fresh guesswork:

  * ``nbytes`` on transfer segments: the actual payload sizes the
    lowering knows (host-buffer shapes, batch leaves), or — for
    collective segments — ``runtime/comm/wire.py``'s census-ground-
    truthed per-step byte estimate split across the plan's collective
    nodes;
  * ``flops`` on compute segments: the XLA ``cost_analysis`` prices
    the engine's ``_tele_flops`` telemetry seam caches per jit key
    (Pallas kernels surface theirs through the same seam via
    ``pl.CostEstimate``-backed cost_analysis).

Pricing mutates the plan in place and is idempotent; abstract plans
(``ir.plan_of``) price the same way, so the audited DAG carries the
same numbers the executed one does.
"""
import numpy as np


def batch_nbytes(batch):
    """Total bytes of a host batch pytree (the ``h2d/batch`` price)."""
    total = 0
    import jax
    for leaf in jax.tree_util.tree_leaves(batch):
        nb = getattr(leaf, "nbytes", None)   # no copy for array leaves
        if nb is None:
            arr = np.asarray(leaf)
            nb = int(arr.size) * int(arr.dtype.itemsize)
        total += int(nb)
    return total


def wire_collective_bytes(engine):
    """Per-step collective bytes from the wire.py estimator; 0 when the
    engine cannot be priced (no zero_plan yet, serving engine)."""
    try:
        est = engine._telemetry_wire()
    except Exception:  # noqa: BLE001 - pricing must never break a step
        est = None
    if not est:
        return 0
    return int(est.get("total_bytes_per_step", 0) or 0)


def price_plan(plan, engine=None, nbytes=None, flops=None):
    """Attach prices to ``plan``'s segments in place and return it.

    ``nbytes``/``flops`` map segment names to explicit prices (the
    lowering's own knowledge — these win). Without an explicit price,
    collective segments split the engine's wire.py per-step bytes
    evenly, and compute segments read the ``_tele_flops_cache`` entry
    for the jit key named by ``flops`` (so a price appears once the
    program has been priced by its first ``_jit_priced`` call).
    """
    nbytes = nbytes or {}
    flops = flops or {}
    collectives = [s for s in plan.segments if s.kind == "collective"
                   and s.name not in nbytes]
    share = 0
    if engine is not None and collectives:
        share = wire_collective_bytes(engine) // len(collectives)
    for seg in plan.segments:
        if seg.name in nbytes:
            seg.nbytes = int(nbytes[seg.name])
        elif seg.kind == "collective" and share and not seg.nbytes:
            seg.nbytes = share
        price = flops.get(seg.name)
        if price is None:
            continue
        if isinstance(price, str):
            # a jit-key reference into the telemetry pricing seam
            cache = getattr(engine, "_tele_flops_cache", None) or {}
            price = cache.get(price)
        if price:
            seg.flops = float(price)
    return plan
