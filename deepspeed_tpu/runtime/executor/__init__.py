"""Segment-graph executor: one overlap-constructing scheduler under
every step path (docs/executor.md).

``plan.py`` defines the :class:`SegmentPlan` / :class:`Segment`
vocabulary (the shard-lint IR's segment kinds), ``scheduler.py`` the
:class:`PlanExecutor` that runs plans serially (the bit-exact oracle,
``runtime.executor: "off"``) or with constructed transfer/compute
overlap (``on``/``auto``), and ``offload.py`` / ``stream.py`` the
lowerings of the classic ZeRO-Offload and streamed beyond-HBM step
paths onto it.

``plan_for_engine`` is the abstract entry point the auditor uses via
``analysis.ir.plan_of``: the same plan topology that executes, with no
payloads attached.
"""
from .plan import PlanError, Segment, SegmentPlan, SEGMENT_KINDS
from .scheduler import PlanExecutor, SegmentRecord


def plan_for_engine(engine, family=None):
    """The abstract segment plan of ``engine``'s step path (topology
    only — run payloads are None). ``family``: ``"offload_apply"`` /
    ``"streamed_micro"`` / ``"streamed_apply"`` / ``"pipe_step"`` /
    ``"pipe_eval_step"`` / ``"serving_step"``; default resolves from
    the engine's live path. Raises ValueError for paths that have no
    multi-segment lowering (micro/fused run as one-segment plans built
    inline at step time)."""
    if family is None:
        if hasattr(engine, "prefill_buckets"):       # inference engine
            family = "serving_step"
        elif getattr(engine, "pipe_module", None) is not None:
            family = "pipe_step"
        elif getattr(engine, "stream_runner", None) is not None:
            family = "streamed_micro"
        elif getattr(engine, "host_state", None) is not None:
            family = "offload_apply"
        else:
            raise ValueError(
                "plan_for_engine: engine runs the {} path, which lowers "
                "to one-segment plans built at step time — only the "
                "pipe/offload/streamed paths expose a multi-segment "
                "plan ahead of time".format(
                    getattr(engine, "_step_path", "micro")))
    if family in ("pipe_step", "pipe_eval_step"):
        from .pipe import build_pipe_plan
        return build_pipe_plan(engine,
                               eval_mode=(family == "pipe_eval_step"))
    if family == "serving_step":
        from .serving import build_serving_plan
        return build_serving_plan(engine)
    if family == "offload_apply":
        from .offload import build_update_plan
        return build_update_plan(engine)
    if family == "streamed_micro":
        from .stream import build_micro_plan
        runner = engine.stream_runner
        runner._bind()
        return build_micro_plan(runner)
    if family == "streamed_apply":
        raise ValueError(
            "streamed_apply's plan shape depends on which slots carry "
            "grads this step — audit the streamed_micro plan instead")
    raise ValueError("unknown plan family {!r}".format(family))


__all__ = ["Segment", "SegmentPlan", "SegmentRecord", "PlanExecutor",
           "PlanError", "SEGMENT_KINDS", "plan_for_engine"]
