"""Pipeline 1F1B train/eval step, lowered onto the segment executor.

Replaces the bespoke step body that lived in
``PipelineEngine._pipe_train_batch_impl``: one optimizer step is now a
:class:`~.plan.SegmentPlan` —

  ``h2d/batch -> cycles [-> apply] -> loss``

where ``h2d/batch`` stages the stacked microbatches onto the mesh (an
async ``h2d``-pool transfer the overlap mode launches ahead of the
main thread), ``cycles`` invokes the ONE jitted 1F1B shard_map program
(warmup/steady/drain fori_loops — the loop itself stays a single XLA
program; the plan schedules AROUND it, never inside it), ``apply`` is
the ZeRO-Offload host optimizer step when the engine runs host_state
(itself a nested ``offload_apply`` plan), and ``loss`` closes the step
with the (mean_loss, metrics) pair the engine consumes.

``_pipe_step_topology`` is the ONE place the plan shape is written
down: ``build_pipe_plan(engine)`` with no payloads is the ABSTRACT
twin for ``analysis.ir.plan_of`` / the auditor, so the audited
topology can never drift from what executes.
"""
from .plan import Segment, SegmentPlan


def _pipe_step_topology(offload, eval_mode=False):
    """Ordered (name, kind, deps, pool, phase) descriptors of one
    pipeline step. ``offload``: the ZeRO-Offload split (grads jit +
    host apply); ``eval_mode``: the forward-only InferenceSchedule
    twin."""
    nodes = []

    def add(name, kind, deps=(), pool=None, phase=None):
        nodes.append((name, kind, tuple(deps), pool, phase))

    add("h2d/batch", "transfer", (), "h2d", "h2d_dispatch_s")
    if eval_mode:
        add("cycles_eval", "compute", ("h2d/batch",))
        add("loss", "host", ("cycles_eval",))
        return nodes
    add("cycles", "compute", ("h2d/batch",))
    if offload:
        # the host optimizer step (itself a nested offload_apply plan
        # billing its own phase clocks) gates the step's metrics
        add("apply", "host", ("cycles",))
        add("loss", "host", ("cycles", "apply"))
    else:
        add("loss", "host", ("cycles",))
    return nodes


def build_pipe_plan(engine, payloads=None, eval_mode=False, batch=None):
    """Segment plan of one pipeline step. ``payloads`` maps names to
    run callables; absent -> abstract plan (``ir.plan_of``). ``batch``
    (the host microbatch stack, when the caller has one) prices the
    ``h2d/batch`` transfer; the cycles segment is priced from the
    telemetry flops cache once ``_jit_priced`` has seen the program."""
    offload = getattr(engine, "host_state", None) is not None
    nodes = _pipe_step_topology(offload, eval_mode=eval_mode)
    payloads = payloads or {}
    plan = SegmentPlan("pipe_eval_step" if eval_mode else "pipe_step")
    for name, kind, deps, pool, phase in nodes:
        plan.add(Segment(
            name=name, kind=kind, deps=deps,
            run=payloads.get(name),
            async_ok=pool is not None, pool=pool or "d2h", phase=phase,
            wait_phase="h2d_wait_s" if kind == "compute" else None,
            # the fused/micro pipe programs donate their state arg —
            # the same declaration analysis/programs.py publishes
            donate=(0,) if name == "cycles" else (),
            keep_result=(name == "loss")))
    from .costs import batch_nbytes, price_plan
    nbytes = {"h2d/batch": batch_nbytes(batch)} if batch is not None \
        else None
    price_plan(plan, engine=engine, nbytes=nbytes, flops={
        "cycles_eval": "pipe_eval",
        "cycles": "pipe_micros" if offload else "pipe_train"})
    return plan


def run_pipe_step(engine, batch, step_rng):
    """One pipeline optimizer step on the executor. Returns
    ``(mean_loss, metrics)`` — bit-exact with the bespoke body (same
    programs, same values, same order; the executor changes wall-clock
    placement only)."""
    offload = engine.host_state is not None

    payloads = {
        "h2d/batch": lambda env: engine._to_device_stacked(batch),
    }

    if offload:
        # ZeRO-Offload under pipelines: jit only the pipe loop's grad
        # accumulation; the optimizer step runs on host
        def cycles(env):
            dev_batch = env["h2d/batch"]
            micros = engine._jit_priced(
                "pipe_micros", engine._pipe_grads_fn,
                engine.state, dev_batch, step_rng)
            engine.state, mean_loss = micros(engine.state, dev_batch,
                                             step_rng)
            return mean_loss

        payloads["cycles"] = cycles
        payloads["apply"] = lambda env: engine._host_apply_step()
        payloads["loss"] = lambda env: (env["cycles"], env["apply"])
    else:
        def cycles(env):
            dev_batch = env["h2d/batch"]
            fused = engine._jit_priced(
                "pipe_train", engine._fused_train_fn,
                engine.state, dev_batch, step_rng, engine._hyper())
            engine.state, out = fused(engine.state, dev_batch,
                                      step_rng, engine._hyper())
            return out

        payloads["cycles"] = cycles
        payloads["loss"] = lambda env: env["cycles"]

    plan = build_pipe_plan(engine, payloads=payloads, batch=batch)
    env = engine.plan_executor().execute(plan)
    return env["loss"]


def run_pipe_eval(engine, batch):
    """Forward-only evaluation through the pipe loop on the executor
    (the InferenceSchedule twin). Returns the loss value."""
    def cycles_eval(env):
        inputs_stack, labels_stack = env["h2d/batch"]
        fn = engine._get_jit("pipe_eval", engine._pipeline_eval_fn)
        return fn(engine.state["params"], inputs_stack, labels_stack)

    payloads = {
        "h2d/batch": lambda env: engine._to_device_stacked(batch),
        "cycles_eval": cycles_eval,
        "loss": lambda env: env["cycles_eval"],
    }
    plan = build_pipe_plan(engine, payloads=payloads, eval_mode=True,
                           batch=batch)
    env = engine.plan_executor().execute(plan)
    return env["loss"]
