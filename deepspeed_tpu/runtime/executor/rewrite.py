"""Plan rewrite passes: optimize a lowered step plan as a graph.

Once every step path is a :class:`~.plan.SegmentPlan` (pipe, serving,
offload, streamed), step-scheduling optimizations become graph
rewrites applied in ONE place instead of per-engine hand surgery.
Three passes, gated by the strict-validated ``runtime.executor_rewrites``
ds_config section (docs/executor.md):

  * ``hoist`` — move an async-eligible segment to the earliest
    position its deps allow, bounded by a live-bytes window (hoisting
    extends the result's lifetime, pinning its buffer longer) and
    never reordering collective segments against each other (their
    rendezvous order must match on every rank). A hoisted transfer
    enters the scheduler's bounded launch-ahead scan sooner, so its
    wall rides behind more main-thread compute.
  * ``widen`` — raise a pool's in-flight window when the executor's
    MEASURED exposed wait dominates: window-blocked async segments run
    inline and bill their wall as exposed wait, so a too-narrow window
    shows up directly in the accounting this pass reads.
  * ``fuse`` — merge a small transfer/collective segment into its
    adjacent sole consumer (the PR 12 quantized-collective pattern:
    a tiny packed-collective node feeding exactly one compute node).
    Adjacency means no main-thread work could have overlapped the
    producer anyway, so fusion removes a scheduling hop for free.

Every pass preserves the execution contract: identical payloads,
identical values, identical per-segment consumption order — a rewrite
changes WHEN work launches, never WHAT it computes, so rewritten plans
stay bitwise equal to the unrewritten serial oracle (pinned by
tests/unit/test_executor.py). Rewrites run at plan-build time inside
``PlanExecutor.execute`` in overlap mode only; the ABSTRACT plans the
auditor fingerprints (``analysis.ir.plan_of``) are never rewritten,
so plan fingerprints are stable by construction.
"""
from .plan import Segment, SegmentPlan

# rewritten-plan stats schema (telemetry/record.py pins the canonical
# copy; bin/check_bench_schema.py carries the stdlib twin)
REWRITE_KEYS = ("enabled", "passes", "segments_moved",
                "predicted_exposed_wait_delta_s",
                "measured_exposed_wait_delta_s")
REWRITE_PASS_KEYS = ("name", "segments_moved",
                     "predicted_exposed_wait_delta_s")

# nominal host-link bandwidth for the hoist pass's predicted-delta
# price (bytes/s); deliberately conservative — predictions are
# compared against the measured delta in extra.executor.rewrites, so a
# bad nominal shows up as a visible predicted-vs-measured gap
NOMINAL_XFER_BYTES_PER_S = 10e9


def _clone(plan, segments=None):
    out = SegmentPlan(plan.name)
    out.windows = dict(plan.windows)
    for seg in (plan.segments if segments is None else segments):
        out.add(seg)
    return out


def hoist_pass(plan, max_live_bytes):
    """Move async segments to the earliest position their deps allow.
    Returns ``(plan, moved, predicted_s)``. A hoist is REFUSED when it
    would cross a dependency (earliest position is derived from the
    deps, so this holds by construction), reorder two collectives, or
    push the hoisted results' extra live bytes past the budget."""
    order = list(plan.segments)
    # extra live bytes pinned at each schedule position by prior hoists
    extra = [0] * (len(order) + 1)
    moved = 0
    hoisted_bytes = 0
    for seg in [s for s in plan.segments if s.async_ok]:
        old = order.index(seg)
        earliest = 0
        for dep in seg.deps:
            earliest = max(earliest, order.index(plan[dep]) + 1)
        if seg.kind == "collective":
            for j in range(earliest, old):
                if order[j].kind == "collective":
                    earliest = j + 1
        new = earliest
        nbytes = int(seg.nbytes or 0)
        while new < old and any(
                extra[j] + nbytes > max_live_bytes
                for j in range(new, old)):
            new += 1
        if new >= old:
            continue
        for j in range(new, old):
            extra[j] += nbytes
        order.pop(old)
        order.insert(new, seg)
        moved += 1
        hoisted_bytes += nbytes
    if not moved:
        return plan, 0, 0.0
    predicted = hoisted_bytes / NOMINAL_XFER_BYTES_PER_S
    return _clone(plan, order), moved, predicted


def fuse_pass(plan):
    """Merge adjacent producer -> sole-consumer pairs where the
    producer is a transfer/collective node. Returns
    ``(plan, fused_count)``. A fused node keeps the consumer's name
    and identity (deps union minus the producer), bridging the
    producer's value through a private env so the consumer payload
    still reads ``env[producer.name]``. Producers with other
    consumers, ``keep_result`` producers, and non-adjacent pairs are
    refused — fusing those would change lifetimes or lose overlap."""
    counts = plan.consumer_counts()
    out = []
    fused = 0
    for seg in plan.segments:
        prev = out[-1] if out else None
        if prev is not None and \
                prev.kind in ("transfer", "collective") and \
                not prev.keep_result and \
                counts.get(prev.name, 0) == 1 and \
                prev.name in seg.deps:
            out[-1] = _fused_segment(prev, seg)
            fused += 1
            continue
        out.append(seg)
    if not fused:
        return plan, 0
    return _clone(plan, out), fused


def _fused_segment(producer, consumer):
    deps = tuple(dict.fromkeys(
        tuple(producer.deps) +
        tuple(d for d in consumer.deps if d != producer.name)))
    run = None
    if producer.run is not None or consumer.run is not None:
        def run(env, _p=producer, _c=consumer):
            penv = {d: env[d] for d in _p.deps if d in env}
            if _p.start is not None:
                _p.start(penv)
            value = _p.run(penv) if _p.run is not None else None
            cenv = dict(env)
            cenv[_p.name] = value
            return _c.run(cenv) if _c.run is not None else None
    return Segment(
        name=consumer.name, kind=consumer.kind, deps=deps, run=run,
        start=None, async_ok=consumer.async_ok, pool=consumer.pool,
        phase=consumer.phase, wait_phase=consumer.wait_phase,
        donate=consumer.donate,
        flops=(producer.flops or 0.0) + (consumer.flops or 0.0),
        nbytes=int(producer.nbytes or 0) + int(consumer.nbytes or 0),
        keep_result=consumer.keep_result)


def widen_pass(plan, executor, max_window):
    """Raise per-pool in-flight windows on ``plan`` when the
    executor's measured exposed wait dominates (> 10% of main-thread
    busy). Returns ``(plan, widened_pools, predicted_s)``. Until the
    executor has measurements (first plan of a run) nothing widens —
    calibrate-then-rewrite."""
    per_kind, busy, waits = executor.measured_totals()
    if waits <= 0.10 * max(busy, 1e-12):
        return plan, 0, 0.0
    pools = {}
    for seg in plan.segments:
        if seg.async_ok:
            pools[seg.pool] = pools.get(seg.pool, 0) + 1
    widened = 0
    predicted = 0.0
    new_windows = dict(plan.windows)
    for pool, count in pools.items():
        cur = new_windows.get(pool, executor.windows.get(pool, 1))
        target = min(max_window, count)
        if target > cur:
            new_windows[pool] = target
            widened += 1
            # the waits a wider window could hide, pro-rated by how
            # much deeper the in-flight pipeline gets
            predicted += waits * (1.0 - cur / float(target)) \
                / max(executor.plans_total, 1)
    if not widened:
        return plan, 0, 0.0
    out = _clone(plan)
    out.windows = new_windows
    return out, widened, predicted


def apply_rewrites(plan, rewrites, executor=None):
    """Run the configured passes over ``plan``; returns
    ``(plan, pass_stats)`` where ``pass_stats`` is a list of
    ``{name, segments_moved, predicted_exposed_wait_delta_s}`` entries
    for the passes that FIRED (empty when nothing changed). The input
    plan is never mutated — callers keep the canonical plan for
    auditing/fingerprinting."""
    if not rewrites or not rewrites.get("enabled"):
        return plan, []
    passes = rewrites.get("passes", ())
    stats = []
    if "hoist" in passes:
        plan, moved, predicted = hoist_pass(
            plan, int(rewrites.get("hoist_max_live_bytes", 1 << 28)))
        if moved:
            stats.append({"name": "hoist", "segments_moved": moved,
                          "predicted_exposed_wait_delta_s":
                          round(predicted, 9)})
    if "fuse" in passes:
        plan, fused = fuse_pass(plan)
        if fused:
            stats.append({"name": "fuse", "segments_moved": fused,
                          "predicted_exposed_wait_delta_s": 0.0})
    if "widen" in passes and executor is not None:
        plan, widened, predicted = widen_pass(
            plan, executor, int(rewrites.get("max_window", 8)))
        if widened:
            stats.append({"name": "widen", "segments_moved": widened,
                          "predicted_exposed_wait_delta_s":
                          round(predicted, 9)})
    return plan, stats
