"""Streamed (beyond-HBM) parameter offload, lowered onto the segment
executor.

Replaces the bespoke hand-interleaved upload/compute/fetch loop that
lived in ``StreamedOffloadRunner.micro_step``: one micro step is now a
:class:`~.plan.SegmentPlan` —

  ``up/e_f -> e_fwd -> [up/g_f<g> -> g_fwd/<g>]* -> up/h_f -> h_grad
  -> [up/g_b<g> -> g_bwd/<g>]* (reverse) -> up/e_b -> e_bwd``

with an async ``d2h/*`` grad-fetch segment per packed gradient vector
and one ``resolve`` segment accumulating them into the host buffers in
plan order (bit-for-bit the bespoke fetch order). The double-buffered
"current + prefetched layer group" discipline is the ``h2d`` pool's
in-flight window of 2 — constructed by the scheduler from the declared
deps, not by hand-threaded ``pending`` variables.

The optimizer apply (host Adam over the accumulated grads) lowers to a
plan of per-slot host segments (``run_streamed_apply``).

``build_micro_plan(runner)`` with no payloads is the ABSTRACT twin for
``analysis.ir.plan_of`` / the auditor: same topology, nothing
executable.
"""
import numpy as np

import jax

from ..zero.transfer import chunk_rows, host_adam_chunk
from .offload import resolve_adam_step
from .plan import Segment, SegmentPlan


def _micro_topology(G):
    """Ordered (name, kind, deps, pool, phase) descriptors of one
    streamed micro step over ``G`` layer groups — the ONE place the
    plan shape is written down (concrete and abstract builders share
    it, so ``plan_of`` can never drift from what executes)."""
    nodes = []

    def add(name, kind, deps=(), pool=None, phase=None):
        nodes.append((name, kind, tuple(deps), pool, phase))

    add("up/e_f", "transfer", (), "h2d", "h2d_wait_s")
    add("e_fwd", "compute", ("up/e_f",), None, "compute_fwd_s")
    prev = "e_fwd"
    for g in range(G):
        add("up/g_f%d" % g, "transfer", (), "h2d", "h2d_wait_s")
        add("g_fwd/%d" % g, "compute", ("up/g_f%d" % g, prev), None,
            "compute_fwd_s")
        prev = "g_fwd/%d" % g
    add("up/h_f", "transfer", (), "h2d", "h2d_wait_s")
    add("h_grad", "compute", ("up/h_f", prev), None, "compute_bwd_s")
    add("loss", "host", ("h_grad",), None, None)
    add("d2h/h", "transfer", ("h_grad",), "d2h", "d2h_grads_s")
    prev_dx = "h_grad"
    for g in reversed(range(G)):
        if g == G - 1:
            dev = "up/g_f%d" % g        # the last fwd group's upload is
            # KEPT for the first backward group (no re-stream)
        else:
            dev = "up/g_b%d" % g
            add(dev, "transfer", (), "h2d", "h2d_wait_s")
        x_in = "e_fwd" if g == 0 else "g_fwd/%d" % (g - 1)
        add("g_bwd/%d" % g, "compute", (dev, x_in, prev_dx), None,
            "compute_bwd_s")
        add("d2h/g%d" % g, "transfer", ("g_bwd/%d" % g,), "d2h",
            "d2h_grads_s")
        prev_dx = "g_bwd/%d" % g
    add("up/e_b", "transfer", (), "h2d", "h2d_wait_s")
    add("e_bwd", "compute", ("up/e_b", prev_dx), None, "compute_bwd_s")
    add("d2h/e", "transfer", ("e_bwd",), "d2h", "d2h_grads_s")
    fetches = ["d2h/h"] + ["d2h/g%d" % g for g in reversed(range(G))] \
        + ["d2h/e"]
    add("resolve", "host", tuple(fetches), None, "d2h_grads_s")
    return nodes, fetches


def build_micro_plan(runner, payloads=None):
    """Segment plan of one streamed micro step. ``payloads`` maps
    names to (run, start); absent -> abstract plan (``ir.plan_of``)."""
    G = len(runner.groups)
    nodes, fetches = _micro_topology(G)
    payloads = payloads or {}
    plan = SegmentPlan("streamed_micro")
    # grad fetches all ride behind compute and resolve at the end (the
    # bespoke deferred-resolve semantics): unbounded d2h window; the
    # h2d window of 2 IS the "current + prefetched group" HBM budget
    plan.windows = {"d2h": len(fetches), "h2d": 2}
    from ..zero.stream import STREAM_DONATE

    def _leaves_nbytes(leaves):
        return sum(int(getattr(p, "nbytes", 0)) for p in leaves)

    # transfer prices from the host master leaves the segments move
    # (uploads stream the params up; d2h fetches bring the grads back,
    # same shapes) — the rewrite passes budget live bytes against these
    nbytes = {"up/e_f": _leaves_nbytes(runner._e_leaves),
              "up/e_b": _leaves_nbytes(runner._e_leaves),
              "d2h/e": _leaves_nbytes(runner._e_leaves),
              "up/h_f": _leaves_nbytes(runner._h_leaves),
              "d2h/h": _leaves_nbytes(runner._h_leaves)}
    for g in range(G):
        group = _leaves_nbytes(runner._group_leaves(g))
        nbytes["up/g_f%d" % g] = group
        nbytes["up/g_b%d" % g] = group
        nbytes["d2h/g%d" % g] = group
    for name, kind, deps, pool, phase in nodes:
        run, start = payloads.get(name, (None, None))
        plan.add(Segment(
            name=name, kind=kind, deps=deps, run=run, start=start,
            async_ok=pool is not None, pool=pool or "d2h", phase=phase,
            wait_phase="h2d_wait_s" if kind == "compute"
            else ("d2h_grads_s" if name == "resolve" else None),
            keep_result=(name == "loss"),
            nbytes=nbytes.get(name, 0),
            # the plan mirrors the ONE donation declaration the jit
            # path and the shard-lint auditor read (stream.py)
            donate=STREAM_DONATE.get(name.split("/")[0], ())))
    return plan


def run_streamed_micro(runner, batch, rng):
    """One streamed micro step on the executor: forward + backward with
    grads accumulated into the host buffers. Returns the (unscaled)
    loss as a device scalar — bit-exact with the bespoke loop (same
    programs, same values, same accumulation order)."""
    eng = runner.engine
    runner._bind()
    gas = eng.gradient_accumulation_steps()
    scaler = eng.state["scaler"]
    scale = np.float32(float(scaler.cur_scale) / gas)
    inv_scale = np.float32(1.0 / float(scaler.cur_scale))
    has_rng = eng.model.accepts_rng and rng is not None
    keys_all = (jax.random.split(rng, runner.n_layers)
                if has_rng else None)
    G = len(runner.groups)
    e_def, b_defs, h_def = runner._e_def, runner._b_defs, runner._h_def
    key0 = keys_all[0] if has_rng else None

    payloads = {}

    def upload(name, leaves):
        pending = {}

        def start(env):
            pending["p"] = runner._start_upload(leaves)

        def run(env):
            return runner._finish_upload(pending["p"], bill_wait=False)

        payloads[name] = (run, start)

    def compute(name, key, builder, make_args):
        def run(env):
            return runner._run(key, builder, *make_args(env))

        payloads[name] = (run, None)

    def d2h(name, producer, pick):
        def start(env):
            try:
                pick(env[producer]).copy_to_host_async()
            except Exception:  # noqa: BLE001 - plugin without async copy
                pass

        def run(env):
            return np.asarray(pick(env[producer]))

        payloads[name] = (run, start)

    upload("up/e_f", runner._e_leaves)
    compute("e_fwd", ("e_fwd", has_rng),
            lambda: runner._embed_fwd_fn(e_def, has_rng),
            lambda env: (env["up/e_f"], batch, key0))
    for g in range(G):
        start_i, stop_i = runner.groups[g]
        defs = tuple(b_defs[start_i:stop_i])
        gkeys = keys_all[start_i:stop_i] if has_rng else None
        upload("up/g_f%d" % g, runner._group_leaves(g))
        x_src = "e_fwd" if g == 0 else "g_fwd/%d" % (g - 1)
        compute("g_fwd/%d" % g, ("g_fwd", defs, has_rng),
                lambda d=defs: runner._group_fwd_fn(d, has_rng),
                lambda env, g=g, x=x_src, k=gkeys:
                (runner._split_group(env["up/g_f%d" % g], g),
                 env[x], k))
    upload("up/h_f", runner._h_leaves)
    x_last = "g_fwd/%d" % (G - 1) if G else "e_fwd"
    compute("h_grad", ("h_grad", has_rng),
            lambda: runner._head_grad_fn(h_def, has_rng),
            lambda env: (env["up/h_f"], env[x_last], batch, key0, scale,
                         inv_scale))
    payloads["loss"] = (lambda env: env["h_grad"][0], None)
    d2h("d2h/h", "h_grad", lambda out: out[2])
    for g in reversed(range(G)):
        start_i, stop_i = runner.groups[g]
        defs = tuple(b_defs[start_i:stop_i])
        gkeys = keys_all[start_i:stop_i] if has_rng else None
        dev = "up/g_f%d" % g if g == G - 1 else "up/g_b%d" % g
        if g != G - 1:
            upload(dev, runner._group_leaves(g))
        x_in = "e_fwd" if g == 0 else "g_fwd/%d" % (g - 1)
        dx_src = "h_grad" if g == G - 1 else "g_bwd/%d" % (g + 1)
        dx_pos = 1 if g == G - 1 else 0
        compute("g_bwd/%d" % g, ("g_bwd", defs, has_rng),
                lambda d=defs: runner._group_bwd_fn(d, has_rng),
                lambda env, g=g, dev=dev, x=x_in, dxs=dx_src, dxp=dx_pos,
                k=gkeys:
                (runner._split_group(env[dev], g), env[x],
                 env[dxs][dxp], k, inv_scale))
        d2h("d2h/g%d" % g, "g_bwd/%d" % g, lambda out: out[1])
    upload("up/e_b", runner._e_leaves)
    dx_src = "g_bwd/0" if G else "h_grad"
    dx_pos = 0 if G else 1
    compute("e_bwd", ("e_bwd", has_rng),
            lambda: runner._embed_bwd_fn(e_def, has_rng),
            lambda env: (env["up/e_b"], batch, env[dx_src][dx_pos], key0,
                         inv_scale))
    d2h("d2h/e", "e_bwd", lambda out: out)

    _, fetches = _micro_topology(G)
    fetch_slots = {
        "d2h/h": (runner._h_slots,
                  [np.shape(p) for p in runner._h_leaves]),
        "d2h/e": (runner._e_slots,
                  [np.shape(p) for p in runner._e_leaves]),
    }
    for g in range(G):
        start_i, stop_i = runner.groups[g]
        fetch_slots["d2h/g%d" % g] = (
            [s for i in range(start_i, stop_i)
             for s in runner._b_slots[i]],
            [np.shape(p) for p in runner._group_leaves(g)])

    def resolve(env):
        finite_all, sumsq_all = True, 0.0
        for name in fetches:
            slot_idxs, shapes = fetch_slots[name]
            finite, sumsq = runner._accumulate_fetched(
                env[name], slot_idxs, shapes)
            finite_all = finite_all and finite
            sumsq_all += sumsq
        runner._micro_finites.append(finite_all)
        runner._micro_sumsqs.append(sumsq_all)
        runner._micros_in_step += 1

    payloads["resolve"] = (resolve, None)

    plan = build_micro_plan(runner, payloads=payloads)
    env = eng.plan_executor().execute(plan, phases=runner.phase_times)
    return env["loss"]


def run_streamed_apply(runner):
    """Host Adam over the accumulated grads, as a plan of per-slot host
    segments (chunked by ``sub_group_size``), with classic offload's
    overflow-skip semantics. Returns the metrics dict; the caller
    updates the scaler — bit-exact with the bespoke loop."""
    eng = runner.engine
    scaler = eng.state["scaler"]
    cur_scale = float(scaler.cur_scale)
    inv_scale = 1.0 / cur_scale
    clip = eng.gradient_clipping()

    finite = all(runner._micro_finites) if runner._micro_finites \
        else False
    if runner._micros_in_step == 1 and \
            not getattr(runner, "_has_shared_slots", True):
        # single micro, no tied leaves: the per-segment device
        # reductions sum to the true norm
        sumsq = sum(runner._micro_sumsqs)
    else:
        # multi-micro windows price PARTIAL per-micro grads, and tied
        # leaves (wte in embed+head) need the square of the SUM, not
        # the sum of squares — recompute over the accumulated host
        # buffers (one bandwidth pass)
        sumsq = 0.0
        if finite:
            for buf in runner._grad_bufs:
                if buf is None:
                    continue
                flat = buf.ravel()
                if not np.all(np.isfinite(flat)):
                    finite = False
                    break
                scaled = flat.astype(np.float64) * inv_scale
                sumsq += float(np.dot(scaled, scaled))
    overflow = (not finite) or not np.isfinite(sumsq)

    grad_norm = 0.0
    if not overflow:
        grad_norm, coef, hyper, bc1, bc2, adam_w, lib = \
            resolve_adam_step(eng, sumsq, inv_scale, clip)

        plan = SegmentPlan("streamed_apply")
        for slot, (p, m, v) in enumerate(runner._slots):
            if runner._grad_bufs[slot] is None:
                continue
            plan.add(Segment(
                name="adam/%d" % slot, kind="host",
                phase="host_adam_s",
                run=_slot_adam(runner, slot, p, m, v, eng, coef, hyper,
                               bc1, bc2, adam_w, lib)))
        eng.plan_executor().execute(plan, phases=runner.phase_times)
    runner.zero_grads()
    return {"overflow": overflow, "grad_norm": grad_norm,
            "loss_scale": cur_scale}


def _slot_adam(runner, slot, p, m, v, eng, coef, hyper, bc1, bc2,
               adam_w, lib):
    def run(env):
        g = runner._grad_bufs[slot]
        for r0, r1 in chunk_rows(np.shape(p), eng._sub_group_size):
            if np.shape(p):
                pc, gc = p[r0:r1], g[r0:r1]
                mc, vc = m[r0:r1], v[r0:r1]
            else:
                pc, gc, mc, vc = p, g, m, v
            # fresh scratch: host_adam_chunk consumes g in place
            gc = gc * np.float32(coef)
            host_adam_chunk(lib, pc, gc, mc, vc, hyper, bc1, bc2,
                            adam_w)

    return run
