"""Activation checkpointing (rematerialization) for TPU.

Reference parity: deepspeed/runtime/activation_checkpointing/checkpointing.py
(CheckpointFunction :379-705, configure :788-867, CudaRNGStatesTracker
:150-266). The torch version re-runs the forward inside backward with manually
saved/restored CUDA RNG states; under JAX, ``jax.checkpoint`` gives
recompute-in-backward natively and PRNG keys are explicit values, so recompute
sees bit-identical dropout by construction — the RNG tracker survives only as
an API-compatible key-derivation helper.

Option mapping (reference module globals :52-56):
  PARTITION_ACTIVATIONS  -> saved residuals sharded over the 'model' mesh axis
                            via a sharding constraint inside the remat'd fn
                            (reference shards checkpointed activations across
                            MP ranks, :268-316).
  PA_TO_CPU              -> remat policy that offloads saved residuals to
                            pinned host memory when the backend supports it
                            (reference copies checkpoint tensors to host).
  CONTIGUOUS_CHECKPOINTING -> accepted for parity; XLA owns layout, no ring
                            buffers needed.
  SYNCHRONIZE            -> block_until_ready around the call (profiling aid).
  PROFILE_TIME           -> wall-clock timing of fwd via utils/timer.
"""
import contextlib
import functools

import jax
import jax.numpy as jnp

from ...utils.logging import logger
from ...utils.timer import SynchronizedWallClockTimer

# --------------------------------------------------------------------------
# module-level option state (reference :43-56)
# --------------------------------------------------------------------------
PARTITION_ACTIVATIONS = False
CPU_CHECKPOINT = False
CONTIGUOUS_CHECKPOINTING = False
SYNCHRONIZE = False
PROFILE_TIME = False

num_layers = None
mp_size = 1
mpu = None

deepspeed_checkpointing_enabled = False

timers = None

_MODEL_AXIS = "model"


# --------------------------------------------------------------------------
# RNG state tracking (reference CudaRNGStatesTracker :150-266)
# --------------------------------------------------------------------------
_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


class RNGStatesTracker:
    """Named PRNG-key tracker.

    The reference forks/restores CUDA RNG states so that recompute inside
    backward sees the same dropout mask. JAX PRNG keys are pure values —
    recompute is identical automatically — so this tracker only maintains
    named keys for model-parallel-aware dropout (each named stream advances
    deterministically via ``jax.random.fold_in``).
    """

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if seed in self.seeds_:
            raise Exception("seed {} already exists".format(seed))
        self.seeds_.add(seed)
        if name in self.states_:
            raise Exception("state {} already exists".format(name))
        self.states_[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def fork(self, name=_MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Yield the named key and advance the stream on exit."""
        if name not in self.states_:
            raise Exception("state {} does not exist".format(name))
        key = self.states_[name]
        try:
            yield key
        finally:
            self.states_[name] = jax.random.fold_in(key, 1)


_CUDA_RNG_STATE_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker():
    """Reference API name kept (checkpointing.py:240); returns the tracker."""
    return _CUDA_RNG_STATE_TRACKER


def model_parallel_cuda_manual_seed(seed, tp_rank=0):
    """Seed the default + model-parallel RNG streams (reference :243-266).

    Data-parallel stream = ``seed``; model-parallel stream offset by
    2718 + tp_rank so TP ranks draw different dropout on sliced activations.
    """
    model_parallel_seed = seed + 2718 + tp_rank
    _CUDA_RNG_STATE_TRACKER.reset()
    _CUDA_RNG_STATE_TRACKER.add("default", seed)
    _CUDA_RNG_STATE_TRACKER.add(_MODEL_PARALLEL_RNG_TRACKER_NAME,
                                model_parallel_seed)


# --------------------------------------------------------------------------
# remat policies
# --------------------------------------------------------------------------
def _offload_policy():
    """Best-effort host-offload remat policy for PA_TO_CPU."""
    try:
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["checkpointed"],
            offload_src="device", offload_dst="pinned_host")
    except Exception:  # pragma: no cover - older jax
        return jax.checkpoint_policies.nothing_saveable


def _shard_over_model_axis(tree):
    """Apply a sharding constraint splitting each leaf's last dim over the
    model axis when divisible (reference partitions checkpointed activations
    across MP ranks, :268-316). Outside jit / without a mesh this is an
    identity."""
    from jax.sharding import PartitionSpec as P

    def constrain(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x
        spec = [None] * x.ndim
        spec[-1] = _MODEL_AXIS
        try:
            return jax.lax.with_sharding_constraint(x, P(*spec))
        except Exception:
            return x

    return jax.tree_util.tree_map(constrain, tree)


def checkpoint(function, *args):
    """Recompute-in-backward wrapper (reference ``checkpoint()`` :706).

    Returns ``function(*args)`` with residuals dropped and recomputed during
    the backward pass. Differentiable; composes with jit/pjit/scan.
    """
    policy = _offload_policy() if CPU_CHECKPOINT else \
        jax.checkpoint_policies.nothing_saveable

    if PARTITION_ACTIVATIONS or CPU_CHECKPOINT:
        def fn(*a):
            if CPU_CHECKPOINT:
                # Tag the residuals so the offload policy can match them
                # (save_and_offload_only_these_names keys on checkpoint_name).
                from jax.ad_checkpoint import checkpoint_name
                a = jax.tree_util.tree_map(
                    lambda x: checkpoint_name(x, "checkpointed")
                    if hasattr(x, "ndim") else x, a)
            if PARTITION_ACTIVATIONS:
                a = _shard_over_model_axis(a)
            return function(*a)
    else:
        fn = function

    wrapped = jax.checkpoint(fn, policy=policy)

    if PROFILE_TIME and timers is not None:
        timers("forward").start()
    out = wrapped(*args)
    if SYNCHRONIZE:
        try:
            jax.block_until_ready(out)
        except Exception:
            pass
    if PROFILE_TIME and timers is not None:
        timers("forward").stop()
    return out


def checkpoint_wrapper(function):
    """Decorator form: ``fn = checkpoint_wrapper(fn)``."""
    @functools.wraps(function)
    def wrapped(*args):
        return checkpoint(function, *args)
    return wrapped


# --------------------------------------------------------------------------
# configuration surface (reference :706-877)
# --------------------------------------------------------------------------
def set_num_layers(nlayers):
    global num_layers
    num_layers = nlayers


def reset():
    """Reference ``reset()``: clears contiguous buffers; here a no-op that
    keeps API parity (XLA owns activation memory)."""


def partition_activations_in_checkpoint(partition_activation):
    global PARTITION_ACTIVATIONS
    PARTITION_ACTIVATIONS = partition_activation
    if PARTITION_ACTIVATIONS:
        logger.info("**************Partition Activations {}************".
                    format(PARTITION_ACTIVATIONS))


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations=None,
              contiguous_checkpointing=None,
              num_checkpoints=None,
              checkpoint_in_cpu=None,
              synchronize=None,
              profile=None):
    """Configure module options (reference ``configure()`` :788-867).

    Explicit kwargs override values from ``deepspeed_config`` (a parsed
    DeepSpeedConfig or a path/dict accepted by DeepSpeedConfig).
    """
    global mpu, num_layers, deepspeed_checkpointing_enabled, timers
    global PARTITION_ACTIVATIONS, CONTIGUOUS_CHECKPOINTING, \
        CPU_CHECKPOINT, SYNCHRONIZE, PROFILE_TIME

    deepspeed_checkpointing_enabled = True
    mpu = mpu_

    if deepspeed_config is not None:
        from ..config import DeepSpeedConfig
        if not isinstance(deepspeed_config, DeepSpeedConfig):
            deepspeed_config = DeepSpeedConfig(deepspeed_config)
        cfg = deepspeed_config.activation_checkpointing_config
        PARTITION_ACTIVATIONS = cfg.partition_activations
        CONTIGUOUS_CHECKPOINTING = cfg.contiguous_memory_optimization
        num_layers = cfg.number_checkpoints
        CPU_CHECKPOINT = cfg.cpu_checkpointing
        SYNCHRONIZE = cfg.synchronize_checkpoint_boundary
        PROFILE_TIME = cfg.profile

    if partition_activations is not None:
        PARTITION_ACTIVATIONS = partition_activations
    if contiguous_checkpointing is not None:
        CONTIGUOUS_CHECKPOINTING = contiguous_checkpointing
    if num_checkpoints is not None:
        num_layers = num_checkpoints
    if checkpoint_in_cpu is not None:
        CPU_CHECKPOINT = checkpoint_in_cpu
    if synchronize is not None:
        SYNCHRONIZE = synchronize
    if profile is not None:
        PROFILE_TIME = profile

    if PROFILE_TIME and timers is None:
        timers = SynchronizedWallClockTimer()

    if CONTIGUOUS_CHECKPOINTING:
        assert num_layers is not None, \
            "Must specify the number of checkpoints with contiguous memory " \
            "optimization"
    if CONTIGUOUS_CHECKPOINTING and not PARTITION_ACTIVATIONS:
        raise ValueError("Contiguous memory optimization requires partitioned "
                         "activations")


def is_configured():
    """True once ``configure()`` has been called (reference :870)."""
    return deepspeed_checkpointing_enabled
