"""Checkpoint serialization helpers.

Reference parity: engine.py:1343-1685 file-layout semantics — ``latest``
pointer, ``<dir>/<tag>/mp_rank_XX_model_states.pt`` model file, separate
``zero_pp_rank_N_mp_rank_XX_optim_states.pt`` optimizer shards, client-state
round trip. Tensors are stored as numpy inside a pickled dict; sharded
``jax.Array``s are gathered to host first (orbax-style async sharded
checkpointing can replace the transport without changing this layout).

Integrity layer (docs/checkpoint_recovery.md): every file write is atomic
(tmp + fsync + rename) and returns a ``{"path", "crc32", "bytes"}`` record;
a tag's LAST content file is ``manifest.json`` listing every file with its
CRC32 and byte size, so *a tag without a valid manifest is by definition
incomplete*. ``verify_tag`` re-checks existence/size/CRC before a load,
``newest_complete_tag`` scans backward to the last good tag when the
pointed-to one is torn or bit-rotted, and ``prune_checkpoints`` retains the
newest N tags without ever deleting the tag ``latest`` names (or anything
newer). All reads/writes retry transient ``OSError`` with exponential
backoff + jitter (utils/retry.py) — on TPU pods preemption and flaky
GCS-fuse-style storage are the normal case, not the exception.
"""
import atexit
import json
import os
import pickle
import shutil
import zlib

import numpy as np

import jax

from ..utils.logging import logger
from ..utils.retry import RetryPolicy, retry_call

MANIFEST_NAME = "manifest.json"
CHECKPOINT_FORMAT_VERSION = 1
# verify_tag reason for a tag dir predating the manifest format; callers
# may choose to load such tags unverified (legacy) instead of rejecting
NO_MANIFEST = "no manifest"


class CheckpointCorruptionError(Exception):
    """A checkpoint file exists but its contents are torn or bit-rotted
    (truncated pickle, checksum mismatch). NOT retried: corruption does
    not heal — the caller should fall back to the last complete tag."""


# ----------------------------------------------------------------- IO policy
_RETRY_POLICY = RetryPolicy()

# installed by utils/fault_injection.inject_faults for crash/bit-rot tests
_FAULT_INJECTOR = None


def set_retry_policy(policy=None, **kwargs):
    """Configure transient-IO retry behavior for every checkpoint
    read/write in this process (ds_config ``"checkpoint"`` block; kwargs
    are RetryPolicy fields, e.g. ``retries=``, ``backoff_seconds=``)."""
    global _RETRY_POLICY
    _RETRY_POLICY = policy if policy is not None \
        else _RETRY_POLICY._replace(**kwargs)
    return _RETRY_POLICY


def _log_io_retry(path):
    def _on_retry(attempt, exc, delay):
        logger.warning(
            "transient checkpoint IO failure on %s (attempt %d: %s) — "
            "retrying in %.3fs", path, attempt + 1, exc, delay)
    return _on_retry


def tree_to_numpy(tree):
    def to_np(x):
        if isinstance(x, jax.Array):
            if not x.is_fully_addressable:
                if getattr(x.sharding, "is_fully_replicated", False):
                    # every device shard IS the global value
                    return np.asarray(x.addressable_data(0))
                from jax.experimental import multihost_utils
                # tiled: the shards tile the global shape (the non-tiled
                # mode stacks a leading processes dim, which is not what a
                # checkpoint of a sharded leaf means)
                return np.asarray(
                    multihost_utils.process_allgather(x, tiled=True))
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(to_np, tree)


def shard_key(index):
    """Serializable key for a shard's tuple-of-slices index."""
    return tuple((s.start, s.stop, s.step) for s in index)


def key_to_index(key):
    return tuple(slice(a, b, c) for a, b, c in key)


def _is_full_cover(key, shape):
    return all((a in (None, 0)) and (b is None or b == dim) and
               c in (None, 1)
               for (a, b, c), dim in zip(key, shape)) or len(key) == 0


def shard_lists_of_tree(tree, write_replicated):
    """Per-leaf ``(global_shape, [(key, np.array), ...])`` entries of this
    process's unique addressable shards, in tree_flatten order — the
    device-state analogue of the offload path's host shard files
    (reference per-rank zero_pp_rank files, engine.py:1350-1377). Shapes
    ride along so reassembly needs no template (the saved layout may
    differ from the loading engine's, e.g. pipeline re-partitioning).
    Fully-replicated leaves are written only when ``write_replicated``
    (process 0), so N processes don't store N copies."""
    import jax.numpy as jnp
    flat, _ = jax.tree_util.tree_flatten(tree)
    out = []
    for leaf in flat:
        entries, seen = [], set()
        arr = leaf if isinstance(leaf, jax.Array) else jnp.asarray(leaf)
        for sh in arr.addressable_shards:
            key = shard_key(sh.index)
            if key in seen:
                continue
            seen.add(key)
            if _is_full_cover(key, arr.shape) and not write_replicated:
                continue
            entries.append((key, np.asarray(sh.data)))
        out.append((tuple(arr.shape), entries))
    return out


def assemble_shard_lists(per_file_lists, what="leaf"):
    """Reassemble full numpy leaves from every process's shard lists
    (each: the output of ``shard_lists_of_tree`` loaded from one zero
    file). Raises if the union of shards does not cover a leaf
    (checkpoint written with an incomplete process set)."""
    n_leaves = len(per_file_lists[0])
    out = []
    for i in range(n_leaves):
        shape = tuple(per_file_lists[0][i][0])
        buf = np.zeros(shape, np.float32)
        seen, covered = set(), 0
        for lists in per_file_lists:
            for key, data in lists[i][1]:
                key = tuple(map(tuple, key))
                if key in seen:
                    continue
                seen.add(key)
                buf[key_to_index(key)] = data
                covered += int(np.prod(np.shape(data)))
        if covered != int(np.prod(shape)):
            raise RuntimeError(
                "zero shard files cover {}/{} elements of {} {} — "
                "checkpoint is missing per-rank files; resume with the "
                "layout it was saved under".format(
                    covered, int(np.prod(shape)), what, i))
        out.append(buf)
    return out


_WRITE_POOL = None


def _write_pool():
    """One serial background writer: submissions execute in order, so an
    async ``save_latest`` queued after the shard writes cannot run until
    they have all landed. An atexit drain guarantees queued shard writes
    and the ``latest`` update complete on clean interpreter exit instead
    of being dropped mid-queue."""
    global _WRITE_POOL
    if _WRITE_POOL is None:
        from .executor.pools import write_pool
        _WRITE_POOL = write_pool()
        atexit.register(_drain_write_pool_at_exit)
    return _WRITE_POOL


def _drain_write_pool_at_exit():
    pool = _WRITE_POOL
    if pool is not None:
        pool.shutdown(wait=True)


def wait_pending_writes():
    """Block until every checkpoint write queued on the background pool so
    far has executed (success or failure — failures stay recorded on
    their futures). Engines call this before re-saving a tag so a
    still-queued write of the same path cannot interleave."""
    if _WRITE_POOL is None:
        return
    _WRITE_POOL.submit(lambda: None).result()


def _fsync_dir(dirname):
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _CRC32Writer:
    """File-object shim that CRCs and counts everything written through
    it, so the integrity record costs no second pass over the bytes."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.size = 0

    def write(self, data):
        n = self._f.write(data)
        self.crc = zlib.crc32(data, self.crc)
        self.size += len(data)
        return n

    def flush(self):
        self._f.flush()

    def fileno(self):
        return self._f.fileno()


def _atomic_write_bytes(path, write_fn):
    """tmp + fsync + rename: a crash at ANY point leaves either the old
    complete file or no file — never a truncated one (reference parity
    gap, round-3 VERDICT weak #6: the 2021 reference pickles in place).
    Transient OSErrors restart the whole attempt (the tmp file is
    rewritten from scratch). Returns the ``{"path", "crc32", "bytes"}``
    record the tag manifest is built from."""
    def _attempt():
        if _FAULT_INJECTOR is not None:
            _FAULT_INJECTOR.before_write(path)
        tmp = path + ".tmp"
        with open(tmp, "wb") as raw:
            shim = _CRC32Writer(raw)
            write_fn(shim)
            raw.flush()
            os.fsync(raw.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path) or ".")
        return {"path": path, "crc32": shim.crc, "bytes": shim.size}
    record = retry_call(_attempt, policy=_RETRY_POLICY,
                        retry_on=(OSError,), on_retry=_log_io_retry(path))
    if _FAULT_INJECTOR is not None:
        _FAULT_INJECTOR.after_write(path)
    return record


def save_state_dict(path, state_dict, async_save=False):
    """Atomically persist ``state_dict`` (device leaves gathered to host
    SYNCHRONOUSLY — callers may mutate or donate them right after this
    returns). Returns the write's integrity record; with ``async_save``
    the pickle+write runs on the serial background writer and a future of
    that record is returned instead — at 1.5B a per-rank shard file is
    GB-scale and the write otherwise blocks the train loop.
    Async COPIES host numpy leaves first: the ZeRO-Offload payload holds
    the live master/moment arrays that the next step's in-place host
    Adam mutates, and pickling them concurrently would tear the file."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = tree_to_numpy(state_dict)
    if async_save:
        payload = jax.tree_util.tree_map(
            lambda x: np.array(x) if isinstance(x, np.ndarray) else x,
            payload)
    writer = lambda f: pickle.dump(payload, f, protocol=4)
    if async_save:
        return _write_pool().submit(_atomic_write_bytes, path, writer)
    return _atomic_write_bytes(path, writer)


def save_latest_after(save_dir, tag, shard_futures):
    """Queue an async ``latest`` update that runs ONLY if every earlier
    queued shard write succeeded. The writer pool is serial, so by the
    time this task runs the shard futures are resolved; a failed one
    means ``latest`` must keep naming the previous complete checkpoint."""
    shard_futures = tuple(f for f in shard_futures if f is not None)

    def _update():
        for fut in shard_futures:
            err = fut.exception()
            if err is not None:
                raise RuntimeError(
                    "latest pointer NOT updated: an earlier checkpoint "
                    "shard write failed") from err
        save_latest(save_dir, tag)

    return _write_pool().submit(_update)


# truncated/garbled pickle payloads surface as any of these from
# pickle.load; none of them heal on retry
_UNPICKLE_ERRORS = (EOFError, pickle.UnpicklingError, ValueError,
                    IndexError, KeyError, AttributeError, ImportError,
                    UnicodeDecodeError)


def load_state_dict(path):
    """Unpickle one checkpoint file (transient OSErrors retried). A
    truncated or bit-rotted payload raises CheckpointCorruptionError
    naming the file — callers (engine.load_checkpoint) fall back to the
    newest complete tag instead of crashing on torn state."""
    def _read():
        if _FAULT_INJECTOR is not None:
            _FAULT_INJECTOR.before_read(path)
        with open(path, "rb") as f:
            return pickle.load(f)
    try:
        return retry_call(_read, policy=_RETRY_POLICY, retry_on=(OSError,),
                          on_retry=_log_io_retry(path))
    except _UNPICKLE_ERRORS as err:
        raise CheckpointCorruptionError(
            "checkpoint file {} is corrupt ({}: {}) — it was likely "
            "truncated by a crash or bit-rotted in storage; "
            "load_checkpoint falls back to the newest complete tag".format(
                path, type(err).__name__, err)) from err


def model_ckpt_name(checkpoints_path, tag, mp_rank=0):
    return os.path.join(checkpoints_path, str(tag),
                        "mp_rank_{:02d}_model_states.pt".format(mp_rank))


def zero_ckpt_name(checkpoints_path, tag, dp_rank=0, mp_rank=0):
    return os.path.join(
        checkpoints_path, str(tag),
        "zero_pp_rank_{}_mp_rank_{:02d}_optim_states.pt".format(dp_rank, mp_rank))


def layer_ckpt_name(checkpoints_path, tag, layer_id, model_rank=0):
    return os.path.join(
        checkpoints_path, str(tag),
        "layer_{:02d}-model_{:02d}-model_states.pt".format(layer_id, model_rank))


def manifest_path(checkpoints_path, tag):
    return os.path.join(checkpoints_path, str(tag), MANIFEST_NAME)


def save_latest(save_dir, tag, async_save=False):
    """Atomically update the ``latest`` pointer. Callers must only invoke
    this AFTER every checkpoint file of ``tag`` has landed (the engine
    barriers first); with ``async_save`` the update is queued on the same
    serial writer as the shard files, which preserves that ordering."""
    os.makedirs(save_dir, exist_ok=True)
    path = os.path.join(save_dir, "latest")
    writer = lambda f: f.write(str(tag).encode())
    if async_save:
        return _write_pool().submit(_atomic_write_bytes, path, writer)
    return _atomic_write_bytes(path, writer)


def read_latest(load_dir):
    """The tag named by the ``latest`` pointer, or None when the pointer
    is absent, empty/whitespace, or names a tag directory that no longer
    exists — all three mean "no trustworthy pointer" and callers fall
    back (scan for the newest complete tag) instead of failing later
    with a confusing missing-file error."""
    latest_path = os.path.join(load_dir, "latest")
    if not os.path.isfile(latest_path):
        return None

    def _read():
        with open(latest_path, "r") as f:
            return f.read()
    tag = retry_call(_read, policy=_RETRY_POLICY, retry_on=(OSError,),
                     on_retry=_log_io_retry(latest_path)).strip()
    if not tag:
        logger.warning("latest pointer %s is empty — ignoring it",
                       latest_path)
        return None
    if not os.path.isdir(os.path.join(load_dir, tag)):
        logger.warning(
            "latest pointer %s names tag %r but %s does not exist — "
            "ignoring it", latest_path, tag, os.path.join(load_dir, tag))
        return None
    return tag


# ----------------------------------------------------------- tag manifests
def _file_crc32(path, chunk_bytes=1 << 20):
    def _read():
        if _FAULT_INJECTOR is not None:
            _FAULT_INJECTOR.before_read(path)
        crc = 0
        with open(path, "rb") as f:
            while True:
                block = f.read(chunk_bytes)
                if not block:
                    break
                crc = zlib.crc32(block, crc)
        return crc
    return retry_call(_read, policy=_RETRY_POLICY, retry_on=(OSError,),
                      on_retry=_log_io_retry(path))


def write_manifest(save_dir, tag, records, meta=None):
    """Write ``<tag>/manifest.json`` as the LAST content file of the tag:
    file list with per-file CRC32/byte-size plus ``meta`` (global_step,
    dp/mp world sizes). ``records`` are this process's own write records;
    files written by OTHER processes (multi-host zero shards — the save
    barrier already ran, so they are complete) are picked up by scanning
    the tag dir and checksummed by reading them back."""
    tag_dir = os.path.join(save_dir, str(tag))
    files = {}
    for rec in records or ():
        if not isinstance(rec, dict) or "path" not in rec:
            continue
        if os.path.dirname(os.path.abspath(rec["path"])) != \
                os.path.abspath(tag_dir):
            continue  # e.g. the `latest` pointer — lives above the tag
        files[os.path.basename(rec["path"])] = {
            "crc32": rec["crc32"], "bytes": rec["bytes"]}
    if os.path.isdir(tag_dir):
        for name in sorted(os.listdir(tag_dir)):
            if name == MANIFEST_NAME or name.endswith(".tmp") or \
                    name in files:
                continue
            path = os.path.join(tag_dir, name)
            if not os.path.isfile(path):
                continue
            files[name] = {"crc32": _file_crc32(path),
                           "bytes": os.path.getsize(path)}
    manifest = {"format_version": CHECKPOINT_FORMAT_VERSION,
                "tag": str(tag), "files": files}
    manifest.update(meta or {})
    payload = json.dumps(manifest, indent=2, sort_keys=True).encode()
    return _atomic_write_bytes(manifest_path(save_dir, tag),
                               lambda f: f.write(payload))


def write_manifest_after(save_dir, tag, shard_futures, meta=None):
    """Queue the manifest write behind the tag's async shard writes on the
    serial pool. Refuses to write if ANY shard failed — the tag must then
    read as incomplete, so ``latest`` (queued after this, gated on this
    future too) keeps naming the previous complete checkpoint."""
    shard_futures = tuple(f for f in shard_futures if f is not None)

    def _write():
        records = []
        for fut in shard_futures:
            err = fut.exception()
            if err is not None:
                raise RuntimeError(
                    "manifest NOT written: an earlier checkpoint shard "
                    "write failed — tag {} stays incomplete".format(
                        tag)) from err
            res = fut.result()
            if isinstance(res, dict) and "path" in res:
                records.append(res)
        return write_manifest(save_dir, tag, records, meta)

    return _write_pool().submit(_write)


def read_manifest(load_dir, tag):
    """The parsed manifest dict, or None when absent/unreadable."""
    path = manifest_path(load_dir, tag)
    if not os.path.isfile(path):
        return None

    def _read():
        if _FAULT_INJECTOR is not None:
            _FAULT_INJECTOR.before_read(path)
        with open(path, "r") as f:
            return json.load(f)
    try:
        manifest = retry_call(_read, policy=_RETRY_POLICY,
                              retry_on=(OSError,),
                              on_retry=_log_io_retry(path))
    except (ValueError, OSError):
        return None
    return manifest if isinstance(manifest, dict) else None


def verify_tag(load_dir, tag):
    """Is ``<load_dir>/<tag>`` a complete, uncorrupted checkpoint?
    Returns ``(True, None)`` or ``(False, reason)``. The completeness
    invariant: the manifest is written last, so its presence proves every
    listed file was fully written — and each file must still exist with
    the recorded byte size and CRC32 (bit-rot detection)."""
    tag_dir = os.path.join(load_dir, str(tag))
    if not os.path.isdir(tag_dir):
        return False, "tag directory {} does not exist".format(tag_dir)
    path = manifest_path(load_dir, tag)
    if not os.path.isfile(path):
        return False, NO_MANIFEST
    manifest = read_manifest(load_dir, tag)
    if manifest is None:
        return False, "manifest {} is unreadable".format(path)
    version = manifest.get("format_version")
    if not isinstance(version, int) or version > CHECKPOINT_FORMAT_VERSION:
        return False, "manifest {} has unsupported format_version {!r}".format(
            path, version)
    entries = manifest.get("files")
    if not isinstance(entries, dict) or not entries:
        return False, "manifest {} lists no files".format(path)
    for name, rec in entries.items():
        fpath = os.path.join(tag_dir, name)
        if not os.path.isfile(fpath):
            return False, "missing checkpoint file {}".format(fpath)
        size = os.path.getsize(fpath)
        if size != rec.get("bytes"):
            return False, "size mismatch on {}: {} bytes on disk, " \
                "{} in manifest (truncated write?)".format(
                    fpath, size, rec.get("bytes"))
        crc = _file_crc32(fpath)
        if crc != rec.get("crc32"):
            return False, "checksum mismatch on {}: crc32 {} on disk, " \
                "{} in manifest (storage bit-rot?)".format(
                    fpath, crc, rec.get("crc32"))
    return True, None


def list_tags(load_dir):
    """Tag directory names under ``load_dir`` (no completeness check)."""
    if not os.path.isdir(load_dir):
        return []
    return [name for name in os.listdir(load_dir)
            if os.path.isdir(os.path.join(load_dir, name))]


def _tag_recency_key(load_dir, tag):
    """Sort key ordering tags newest-first when reverse-sorted: manifest
    global_step when available (authoritative), directory mtime as the
    tie-break / manifest-less fallback."""
    manifest = read_manifest(load_dir, tag)
    step = manifest.get("global_step", -1) if manifest else -1
    if not isinstance(step, (int, float)):
        step = -1
    try:
        mtime = os.path.getmtime(os.path.join(load_dir, tag))
    except OSError:
        mtime = 0.0
    return (step, mtime)


def newest_complete_tag(load_dir, exclude=(), on_reject=None):
    """Scan backward (newest first) through the tags under ``load_dir``
    and return the first one whose manifest and checksums verify — the
    last-good-checkpoint fallback. Tags in ``exclude`` (already tried and
    rejected by the caller) are skipped; ``on_reject(tag, reason)``
    observes every rejection so operators can see exactly what was
    skipped and why."""
    exclude = set(str(t) for t in exclude)
    tags = [t for t in list_tags(load_dir) if t not in exclude]
    tags.sort(key=lambda t: _tag_recency_key(load_dir, t), reverse=True)
    for tag in tags:
        ok, reason = verify_tag(load_dir, tag)
        if ok:
            return tag
        if on_reject is not None:
            on_reject(tag, reason)
    return None


# ------------------------------------------------------------- retention GC
def prune_checkpoints(save_dir, keep_last_n):
    """Delete all but the newest ``keep_last_n`` tags. NEVER deletes the
    tag named by ``latest`` or any tag newer than it — a crash between a
    tag's manifest and the ``latest`` update leaves a complete tag the
    pointer hasn't reached yet, and GC must not eat it. Returns the list
    of deleted tags."""
    if not keep_last_n or keep_last_n < 1:
        return []
    tags = list_tags(save_dir)
    # one manifest read per tag — the keys are reused for the sort, the
    # latest lookup, and the newer-than-latest protection below
    keys = {t: _tag_recency_key(save_dir, t) for t in tags}
    order = sorted(tags, key=keys.__getitem__, reverse=True)
    keep = set(order[:keep_last_n])
    latest = read_latest(save_dir)
    if latest in keys:
        keep.update(t for t in tags if keys[t] >= keys[latest])
    deleted = []
    for tag in order:
        if tag in keep:
            continue
        try:
            shutil.rmtree(os.path.join(save_dir, tag))
            deleted.append(tag)
        except OSError as err:
            logger.warning("could not prune checkpoint tag %s: %s", tag, err)
    if deleted:
        logger.info("pruned old checkpoint tags under %s: %s", save_dir,
                    ", ".join(deleted))
    return deleted


def prune_after(save_dir, keep_last_n, shard_futures):
    """Queue retention GC behind an async save's writes. Runs only if
    every earlier write (shards, manifest, latest) succeeded — after a
    failed save ``latest`` still names an OLD tag, and GC keyed off a
    stale pointer must not run."""
    shard_futures = tuple(f for f in shard_futures if f is not None)

    def _prune():
        for fut in shard_futures:
            if fut.exception() is not None:
                return []
        return prune_checkpoints(save_dir, keep_last_n)

    return _write_pool().submit(_prune)
