"""Checkpoint serialization helpers.

Reference parity: engine.py:1343-1685 file-layout semantics — ``latest``
pointer, ``<dir>/<tag>/mp_rank_XX_model_states.pt`` model file, separate
``zero_pp_rank_N_mp_rank_XX_optim_states.pt`` optimizer shards, client-state
round trip. Tensors are stored as numpy inside a pickled dict; sharded
``jax.Array``s are gathered to host first (orbax-style async sharded
checkpointing can replace the transport without changing this layout).
"""
import os
import pickle

import numpy as np

import jax


def tree_to_numpy(tree):
    def to_np(x):
        if isinstance(x, jax.Array):
            if not x.is_fully_addressable:
                if getattr(x.sharding, "is_fully_replicated", False):
                    # every device shard IS the global value
                    return np.asarray(x.addressable_data(0))
                from jax.experimental import multihost_utils
                # tiled: the shards tile the global shape (the non-tiled
                # mode stacks a leading processes dim, which is not what a
                # checkpoint of a sharded leaf means)
                return np.asarray(
                    multihost_utils.process_allgather(x, tiled=True))
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(to_np, tree)


def shard_key(index):
    """Serializable key for a shard's tuple-of-slices index."""
    return tuple((s.start, s.stop, s.step) for s in index)


def key_to_index(key):
    return tuple(slice(a, b, c) for a, b, c in key)


def _is_full_cover(key, shape):
    return all((a in (None, 0)) and (b is None or b == dim) and
               c in (None, 1)
               for (a, b, c), dim in zip(key, shape)) or len(key) == 0


def shard_lists_of_tree(tree, write_replicated):
    """Per-leaf ``(global_shape, [(key, np.array), ...])`` entries of this
    process's unique addressable shards, in tree_flatten order — the
    device-state analogue of the offload path's host shard files
    (reference per-rank zero_pp_rank files, engine.py:1350-1377). Shapes
    ride along so reassembly needs no template (the saved layout may
    differ from the loading engine's, e.g. pipeline re-partitioning).
    Fully-replicated leaves are written only when ``write_replicated``
    (process 0), so N processes don't store N copies."""
    import jax.numpy as jnp
    flat, _ = jax.tree_util.tree_flatten(tree)
    out = []
    for leaf in flat:
        entries, seen = [], set()
        arr = leaf if isinstance(leaf, jax.Array) else jnp.asarray(leaf)
        for sh in arr.addressable_shards:
            key = shard_key(sh.index)
            if key in seen:
                continue
            seen.add(key)
            if _is_full_cover(key, arr.shape) and not write_replicated:
                continue
            entries.append((key, np.asarray(sh.data)))
        out.append((tuple(arr.shape), entries))
    return out


def assemble_shard_lists(per_file_lists, what="leaf"):
    """Reassemble full numpy leaves from every process's shard lists
    (each: the output of ``shard_lists_of_tree`` loaded from one zero
    file). Raises if the union of shards does not cover a leaf
    (checkpoint written with an incomplete process set)."""
    n_leaves = len(per_file_lists[0])
    out = []
    for i in range(n_leaves):
        shape = tuple(per_file_lists[0][i][0])
        buf = np.zeros(shape, np.float32)
        seen, covered = set(), 0
        for lists in per_file_lists:
            for key, data in lists[i][1]:
                key = tuple(map(tuple, key))
                if key in seen:
                    continue
                seen.add(key)
                buf[key_to_index(key)] = data
                covered += int(np.prod(np.shape(data)))
        if covered != int(np.prod(shape)):
            raise RuntimeError(
                "zero shard files cover {}/{} elements of {} {} — "
                "checkpoint is missing per-rank files; resume with the "
                "layout it was saved under".format(
                    covered, int(np.prod(shape)), what, i))
        out.append(buf)
    return out


def save_state_dict(path, state_dict):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(tree_to_numpy(state_dict), f, protocol=4)


def load_state_dict(path):
    with open(path, "rb") as f:
        return pickle.load(f)


def model_ckpt_name(checkpoints_path, tag, mp_rank=0):
    return os.path.join(checkpoints_path, str(tag),
                        "mp_rank_{:02d}_model_states.pt".format(mp_rank))


def zero_ckpt_name(checkpoints_path, tag, dp_rank=0, mp_rank=0):
    return os.path.join(
        checkpoints_path, str(tag),
        "zero_pp_rank_{}_mp_rank_{:02d}_optim_states.pt".format(dp_rank, mp_rank))


def layer_ckpt_name(checkpoints_path, tag, layer_id, model_rank=0):
    return os.path.join(
        checkpoints_path, str(tag),
        "layer_{:02d}-model_{:02d}-model_states.pt".format(layer_id, model_rank))


def save_latest(save_dir, tag):
    os.makedirs(save_dir, exist_ok=True)
    with open(os.path.join(save_dir, "latest"), "w") as f:
        f.write(str(tag))


def read_latest(load_dir):
    latest_path = os.path.join(load_dir, "latest")
    if os.path.isfile(latest_path):
        with open(latest_path, "r") as f:
            return f.read().strip()
    return None
