"""Checkpoint serialization helpers.

Reference parity: engine.py:1343-1685 file-layout semantics — ``latest``
pointer, ``<dir>/<tag>/mp_rank_XX_model_states.pt`` model file, separate
``zero_pp_rank_N_mp_rank_XX_optim_states.pt`` optimizer shards, client-state
round trip. Tensors are stored as numpy inside a pickled dict; sharded
``jax.Array``s are gathered to host first (orbax-style async sharded
checkpointing can replace the transport without changing this layout).
"""
import os
import pickle

import numpy as np

import jax


def tree_to_numpy(tree):
    def to_np(x):
        if isinstance(x, jax.Array):
            if not x.is_fully_addressable:
                if getattr(x.sharding, "is_fully_replicated", False):
                    # every device shard IS the global value
                    return np.asarray(x.addressable_data(0))
                from jax.experimental import multihost_utils
                # tiled: the shards tile the global shape (the non-tiled
                # mode stacks a leading processes dim, which is not what a
                # checkpoint of a sharded leaf means)
                return np.asarray(
                    multihost_utils.process_allgather(x, tiled=True))
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(to_np, tree)


def shard_key(index):
    """Serializable key for a shard's tuple-of-slices index."""
    return tuple((s.start, s.stop, s.step) for s in index)


def key_to_index(key):
    return tuple(slice(a, b, c) for a, b, c in key)


def _is_full_cover(key, shape):
    return all((a in (None, 0)) and (b is None or b == dim) and
               c in (None, 1)
               for (a, b, c), dim in zip(key, shape)) or len(key) == 0


def shard_lists_of_tree(tree, write_replicated):
    """Per-leaf ``(global_shape, [(key, np.array), ...])`` entries of this
    process's unique addressable shards, in tree_flatten order — the
    device-state analogue of the offload path's host shard files
    (reference per-rank zero_pp_rank files, engine.py:1350-1377). Shapes
    ride along so reassembly needs no template (the saved layout may
    differ from the loading engine's, e.g. pipeline re-partitioning).
    Fully-replicated leaves are written only when ``write_replicated``
    (process 0), so N processes don't store N copies."""
    import jax.numpy as jnp
    flat, _ = jax.tree_util.tree_flatten(tree)
    out = []
    for leaf in flat:
        entries, seen = [], set()
        arr = leaf if isinstance(leaf, jax.Array) else jnp.asarray(leaf)
        for sh in arr.addressable_shards:
            key = shard_key(sh.index)
            if key in seen:
                continue
            seen.add(key)
            if _is_full_cover(key, arr.shape) and not write_replicated:
                continue
            entries.append((key, np.asarray(sh.data)))
        out.append((tuple(arr.shape), entries))
    return out


def assemble_shard_lists(per_file_lists, what="leaf"):
    """Reassemble full numpy leaves from every process's shard lists
    (each: the output of ``shard_lists_of_tree`` loaded from one zero
    file). Raises if the union of shards does not cover a leaf
    (checkpoint written with an incomplete process set)."""
    n_leaves = len(per_file_lists[0])
    out = []
    for i in range(n_leaves):
        shape = tuple(per_file_lists[0][i][0])
        buf = np.zeros(shape, np.float32)
        seen, covered = set(), 0
        for lists in per_file_lists:
            for key, data in lists[i][1]:
                key = tuple(map(tuple, key))
                if key in seen:
                    continue
                seen.add(key)
                buf[key_to_index(key)] = data
                covered += int(np.prod(np.shape(data)))
        if covered != int(np.prod(shape)):
            raise RuntimeError(
                "zero shard files cover {}/{} elements of {} {} — "
                "checkpoint is missing per-rank files; resume with the "
                "layout it was saved under".format(
                    covered, int(np.prod(shape)), what, i))
        out.append(buf)
    return out


_WRITE_POOL = None


def _write_pool():
    """One serial background writer: submissions execute in order, so an
    async ``save_latest`` queued after the shard writes cannot run until
    they have all landed."""
    global _WRITE_POOL
    if _WRITE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor
        _WRITE_POOL = ThreadPoolExecutor(max_workers=1,
                                         thread_name_prefix="ckpt-write")
    return _WRITE_POOL


def _fsync_dir(dirname):
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write_bytes(path, write_fn):
    """tmp + fsync + rename: a crash at ANY point leaves either the old
    complete file or no file — never a truncated one (reference parity
    gap, round-3 VERDICT weak #6: the 2021 reference pickles in place)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        write_fn(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def save_state_dict(path, state_dict, async_save=False):
    """Atomically persist ``state_dict`` (device leaves gathered to host
    SYNCHRONOUSLY — callers may mutate or donate them right after this
    returns). With ``async_save`` the pickle+write runs on the serial
    background writer and a future is returned; at 1.5B a per-rank shard
    file is GB-scale and the write otherwise blocks the train loop.
    Async COPIES host numpy leaves first: the ZeRO-Offload payload holds
    the live master/moment arrays that the next step's in-place host
    Adam mutates, and pickling them concurrently would tear the file."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = tree_to_numpy(state_dict)
    if async_save:
        payload = jax.tree_util.tree_map(
            lambda x: np.array(x) if isinstance(x, np.ndarray) else x,
            payload)
    writer = lambda f: pickle.dump(payload, f, protocol=4)
    if async_save:
        return _write_pool().submit(_atomic_write_bytes, path, writer)
    _atomic_write_bytes(path, writer)
    return None


def save_latest_after(save_dir, tag, shard_futures):
    """Queue an async ``latest`` update that runs ONLY if every earlier
    queued shard write succeeded. The writer pool is serial, so by the
    time this task runs the shard futures are resolved; a failed one
    means ``latest`` must keep naming the previous complete checkpoint."""
    shard_futures = tuple(f for f in shard_futures if f is not None)

    def _update():
        for fut in shard_futures:
            err = fut.exception()
            if err is not None:
                raise RuntimeError(
                    "latest pointer NOT updated: an earlier checkpoint "
                    "shard write failed") from err
        save_latest(save_dir, tag)

    return _write_pool().submit(_update)


def load_state_dict(path):
    with open(path, "rb") as f:
        return pickle.load(f)


def model_ckpt_name(checkpoints_path, tag, mp_rank=0):
    return os.path.join(checkpoints_path, str(tag),
                        "mp_rank_{:02d}_model_states.pt".format(mp_rank))


def zero_ckpt_name(checkpoints_path, tag, dp_rank=0, mp_rank=0):
    return os.path.join(
        checkpoints_path, str(tag),
        "zero_pp_rank_{}_mp_rank_{:02d}_optim_states.pt".format(dp_rank, mp_rank))


def layer_ckpt_name(checkpoints_path, tag, layer_id, model_rank=0):
    return os.path.join(
        checkpoints_path, str(tag),
        "layer_{:02d}-model_{:02d}-model_states.pt".format(layer_id, model_rank))


def save_latest(save_dir, tag, async_save=False):
    """Atomically update the ``latest`` pointer. Callers must only invoke
    this AFTER every checkpoint file of ``tag`` has landed (the engine
    barriers first); with ``async_save`` the update is queued on the same
    serial writer as the shard files, which preserves that ordering."""
    os.makedirs(save_dir, exist_ok=True)
    path = os.path.join(save_dir, "latest")
    writer = lambda f: f.write(str(tag).encode())
    if async_save:
        return _write_pool().submit(_atomic_write_bytes, path, writer)
    _atomic_write_bytes(path, writer)
    return None


def read_latest(load_dir):
    latest_path = os.path.join(load_dir, "latest")
    if os.path.isfile(latest_path):
        with open(latest_path, "r") as f:
            return f.read().strip()
    return None
