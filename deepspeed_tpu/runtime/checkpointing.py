"""Checkpoint serialization helpers.

Reference parity: engine.py:1343-1685 file-layout semantics — ``latest``
pointer, ``<dir>/<tag>/mp_rank_XX_model_states.pt`` model file, separate
``zero_pp_rank_N_mp_rank_XX_optim_states.pt`` optimizer shards, client-state
round trip. Tensors are stored as numpy inside a pickled dict; sharded
``jax.Array``s are gathered to host first (orbax-style async sharded
checkpointing can replace the transport without changing this layout).
"""
import os
import pickle

import numpy as np

import jax


def tree_to_numpy(tree):
    def to_np(x):
        if isinstance(x, jax.Array):
            if not x.is_fully_addressable:
                if getattr(x.sharding, "is_fully_replicated", False):
                    # every device shard IS the global value
                    return np.asarray(x.addressable_data(0))
                from jax.experimental import multihost_utils
                # tiled: the shards tile the global shape (the non-tiled
                # mode stacks a leading processes dim, which is not what a
                # checkpoint of a sharded leaf means)
                return np.asarray(
                    multihost_utils.process_allgather(x, tiled=True))
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(to_np, tree)


def save_state_dict(path, state_dict):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(tree_to_numpy(state_dict), f, protocol=4)


def load_state_dict(path):
    with open(path, "rb") as f:
        return pickle.load(f)


def model_ckpt_name(checkpoints_path, tag, mp_rank=0):
    return os.path.join(checkpoints_path, str(tag),
                        "mp_rank_{:02d}_model_states.pt".format(mp_rank))


def zero_ckpt_name(checkpoints_path, tag, dp_rank=0, mp_rank=0):
    return os.path.join(
        checkpoints_path, str(tag),
        "zero_pp_rank_{}_mp_rank_{:02d}_optim_states.pt".format(dp_rank, mp_rank))


def layer_ckpt_name(checkpoints_path, tag, layer_id, model_rank=0):
    return os.path.join(
        checkpoints_path, str(tag),
        "layer_{:02d}-model_{:02d}-model_states.pt".format(layer_id, model_rank))


def save_latest(save_dir, tag):
    os.makedirs(save_dir, exist_ok=True)
    with open(os.path.join(save_dir, "latest"), "w") as f:
        f.write(str(tag))


def read_latest(load_dir):
    latest_path = os.path.join(load_dir, "latest")
    if os.path.isfile(latest_path):
        with open(latest_path, "r") as f:
            return f.read().strip()
    return None
