from .indexed_dataset import (IndexedDataset, IndexedDatasetBuilder,
                              NativePrefetchLoader)

__all__ = ["IndexedDataset", "IndexedDatasetBuilder", "NativePrefetchLoader"]
