"""Indexed token datasets with a native (C++) reader + prefetch loader.

TPU-native counterpart of the reference era's mmap'd .bin/.idx token
datasets (the Megatron-GPT2 workloads the reference drives; DeepSpeed's
own loader is deepspeed/runtime/dataloader.py). The input pipeline is a
host-side concern on TPU — the chip computes while a producer thread
gathers the next batch from the mmap'd file through csrc/ds_dataio.cpp
(OpenMP gather, double-buffered ring). A pure-numpy fallback keeps every
feature working when the native op can't build.

Format:
  <prefix>.bin  raw little-endian tokens (int32 or uint16)
  <prefix>.idx  "DSTPUIDX" magic, u32 version, u32 dtype code (4=int32,
                2=uint16), u64 n_docs, (n_docs+1) u64 token offsets
"""
import os
import struct
import threading

import numpy as np

from ...utils.logging import logger

# per-epoch shuffle multipliers; all prime and >= 2654435761 (the enforced
# n_samples bound) so each is coprime with n_samples. Mirrors kMult[] in
# csrc/ds_dataio.cpp — keep both tables identical.
_SHUFFLE_MULTS = np.array(
    [2654435761, 2754435769, 2854435811, 2954435791,
     3054435863, 3154435859, 3254435857, 3354435823,
     3454435837, 3554435839, 3654435857, 3754435859,
     3854435863, 3954435869, 4054435873, 4154435867], dtype=np.uint64)

_MAGIC = b"DSTPUIDX"
_VERSION = 1
_DTYPE_CODES = {np.dtype(np.int32): 4, np.dtype(np.uint16): 2}
_CODE_DTYPES = {4: np.int32, 2: np.uint16}


class IndexedDatasetBuilder:
    """Stream documents (1-D token arrays) into a .bin/.idx pair."""

    def __init__(self, prefix, dtype=np.int32):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        assert self.dtype in _DTYPE_CODES, self.dtype
        self._bin = open(prefix + ".bin", "wb")
        self._offsets = [0]

    def add_doc(self, tokens):
        arr = np.ascontiguousarray(tokens, dtype=self.dtype)
        assert arr.ndim == 1
        self._bin.write(arr.tobytes())
        self._offsets.append(self._offsets[-1] + arr.size)

    def finalize(self):
        self._bin.close()
        with open(self.prefix + ".idx", "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<II", _VERSION, _DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(self._offsets) - 1))
            f.write(np.asarray(self._offsets, dtype=np.uint64).tobytes())
        return self.prefix


def _load_native():
    try:
        from ...ops.op_builder.dataio import DataIOBuilder
        return DataIOBuilder().load()
    except Exception as err:  # noqa: BLE001
        logger.warning("native data-IO unavailable (%s); numpy fallback",
                       err)
        return None


class IndexedDataset:
    """Read side. Documents by index, or fixed seq-length windows over the
    concatenated token stream (GPT-2 pretraining convention)."""

    def __init__(self, prefix, use_native=True):
        self.prefix = prefix
        self._lib = _load_native() if use_native else None
        self._handle = None
        # close() handshake: native calls register in-flight so close()
        # can quiesce them (via ds_dataio_stop) before freeing the handle
        self._io_cond = threading.Condition()
        self._inflight = 0
        self._closing = False
        idx_path = (prefix + ".idx").encode()
        bin_path = (prefix + ".bin").encode()
        if self._lib is not None:
            self._handle = self._lib.ds_dataio_open(idx_path, bin_path)
            if not self._handle:
                logger.warning("native open failed for %s; numpy fallback",
                               prefix)
                self._lib = None
        self._was_native = self._lib is not None
        if self._lib is None:
            self._np_open()
        else:
            self.num_docs = int(self._lib.ds_dataio_num_docs(self._handle))
            self.num_tokens = int(
                self._lib.ds_dataio_num_tokens(self._handle))

    def _np_open(self):
        with open(self.prefix + ".idx", "rb") as f:
            assert f.read(8) == _MAGIC, "bad idx magic"
            version, code = struct.unpack("<II", f.read(8))
            assert version == _VERSION, \
                "idx version {} != supported {}".format(version, _VERSION)
            (n_docs,) = struct.unpack("<Q", f.read(8))
            self._offsets = np.frombuffer(f.read(8 * (n_docs + 1)),
                                          dtype=np.uint64)
        self._tokens = np.memmap(self.prefix + ".bin", mode="r",
                                 dtype=_CODE_DTYPES[code])
        self.num_docs = int(n_docs)
        self.num_tokens = int(self._offsets[-1])

    # -- close()-safe native-call guard ------------------------------------
    def _enter_io(self):
        """Register a native call in flight; returns (lib, handle), or
        None for numpy-backed readers. Raises once close() has begun so a
        racing reader can never touch a freed handle. Callers MUST pair a
        non-None return with _exit_io() in a finally block."""
        with self._io_cond:
            if self._closing or (self._was_native and self._lib is None):
                raise RuntimeError("IndexedDataset is closed")
            if self._lib is None:
                return None
            self._inflight += 1
            return self._lib, self._handle

    def _exit_io(self):
        with self._io_cond:
            self._inflight -= 1
            self._io_cond.notify_all()

    # -- documents ---------------------------------------------------------
    def doc(self, i):
        io = self._enter_io()
        if io is not None:
            lib, handle = io
            try:
                n = int(lib.ds_dataio_doc_len(handle, i))
                out = np.empty(n, dtype=np.int32)
                got = lib.ds_dataio_get_doc(handle, i, out.ctypes.data, n)
                return out[:got]
            finally:
                self._exit_io()
        s, e = int(self._offsets[i]), int(self._offsets[i + 1])
        return np.asarray(self._tokens[s:e], dtype=np.int32)

    def __len__(self):
        return self.num_docs

    def __getitem__(self, i):
        return self.doc(i)

    # -- fixed-window samples ---------------------------------------------
    def num_samples(self, seq_len):
        return self.num_tokens // seq_len

    def batch(self, sample_idx, seq_len):
        """Gather (len(sample_idx), seq_len) int32 windows."""
        idx = np.ascontiguousarray(sample_idx, dtype=np.int64)
        out = np.empty((idx.size, seq_len), dtype=np.int32)
        io = self._enter_io()
        if io is not None:
            lib, handle = io
            try:
                lib.ds_dataio_batch(handle, idx.ctypes.data,
                                    idx.size, seq_len, out.ctypes.data)
                return out
            finally:
                self._exit_io()
        for r, s in enumerate(idx):
            start = int(s) * seq_len
            chunk = np.asarray(self._tokens[start:start + seq_len],
                               dtype=np.int32)
            out[r, :chunk.size] = chunk
            out[r, chunk.size:] = 0
        return out

    def close(self):
        """Two-phase close: ds_dataio_stop wakes any reader blocked inside
        a native call (prefetch next returns -1), then we wait for the
        in-flight count to drain before ds_dataio_close frees the C++
        Dataset — no reader can touch a freed handle."""
        with self._io_cond:
            if self._closing:
                return
            self._closing = True
            lib, handle = self._lib, self._handle
        if lib is not None and handle:
            lib.ds_dataio_stop(handle)
            with self._io_cond:
                while self._inflight > 0:
                    self._io_cond.wait(timeout=10)
            lib.ds_dataio_close(handle)
            with self._io_cond:
                self._handle = None
                self._lib = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


class NativePrefetchLoader:
    """Infinite (batch, seq) int32 batches, produced ahead of consumption.

    Native path: the C++ producer thread fills a double-buffered ring
    (csrc/ds_dataio.cpp) while the previous batch feeds the device —
    the role DataLoader worker processes play in the reference
    (runtime/dataloader.py), without pickling/IPC. Numpy fallback uses a
    Python thread with the same epoch-mixed affine shuffled order
    (see _indices)."""

    def __init__(self, dataset, batch_size, seq_len):
        self.ds = dataset
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.n_samples = dataset.num_samples(seq_len)
        assert self.n_samples > 0, "dataset smaller than one sample"
        # bijection precondition of the affine shuffle (multiplier coprime
        # with n_samples, no 2^64 wrap); the native side enforces the same
        if self.n_samples >= 2654435761:
            raise ValueError(
                "dataset has {} seq-{} samples; the shuffle supports fewer "
                "than 2654435761 — use a longer seq_len or shard the "
                "corpus".format(self.n_samples, seq_len))
        self._native = dataset._lib is not None
        self._closed = False
        if self._native:
            lib, handle = dataset._enter_io()
            try:
                rc = lib.ds_dataio_start_prefetch(
                    handle, self.batch_size, self.seq_len)
            finally:
                dataset._exit_io()
            assert rc == 0, "prefetch start failed: {}".format(rc)
        else:
            self._cursor = 0
            self._buf = None
            self._cond = threading.Condition()
            self._thread = threading.Thread(target=self._produce,
                                            daemon=True)
            self._thread.start()

    def _indices(self, cursor):
        # uint64 throughout: the C++ producer uses uint64, and int64 would
        # silently overflow (and diverge from it) past ~3.5e9 samples.
        # Epoch-varying affine shuffle: every multiplier is a prime >= the
        # enforced n_samples bound (2654435761), hence coprime with
        # n_samples -> each epoch's map is a bijection, and j*mult stays
        # below 2^64; the additive term is reduced mod n BEFORE the sum (a
        # wrap of the sum would break the bijection). Varying the
        # MULTIPLIER per epoch changes the successor structure — a
        # constant-only mix would merely rotate one fixed cyclic order.
        # MUST stay in lockstep with fill_slot() in csrc/ds_dataio.cpp.
        n = np.uint64(self.n_samples)
        pos = (np.uint64(cursor)
               + np.arange(self.batch_size, dtype=np.uint64))
        j = pos % n
        epoch = pos // n
        c = (np.uint64(12345)
             + epoch * np.uint64(0x9E3779B97F4A7C15)) % n
        mult = _SHUFFLE_MULTS[(epoch % np.uint64(16)).astype(np.int64)]
        return ((j * mult % n + c) % n).astype(np.int64)

    def _produce(self):
        try:
            while not self._closed:
                batch = self.ds.batch(self._indices(self._cursor),
                                      self.seq_len)
                self._cursor += self.batch_size
                with self._cond:
                    while self._buf is not None and not self._closed:
                        self._cond.wait()
                    if self._closed:
                        return
                    self._buf = batch
                    self._cond.notify_all()
        except RuntimeError:
            # dataset closed underneath us (ds.batch raises once
            # IndexedDataset.close() begins): mark the loader closed and
            # wake consumers so a blocked __next__ raises instead of
            # waiting forever on a producer that no longer exists
            with self._cond:
                self._closed = True
                self._cond.notify_all()

    def close(self):
        """Stop producing. The native producer thread is owned by the
        dataset and stops in IndexedDataset.close(); the fallback thread
        stops here. next() after close raises."""
        if self._closed:
            return
        self._closed = True
        if not self._native:
            with self._cond:
                self._cond.notify_all()
            self._thread.join(timeout=5)

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed or (self._native and self.ds._lib is None):
            raise RuntimeError("NativePrefetchLoader is closed (or its "
                               "dataset was closed underneath it)")
        if self._native:
            out = np.empty((self.batch_size, self.seq_len), dtype=np.int32)
            lib, handle = self.ds._enter_io()   # raises once close() began
            try:
                rc = lib.ds_dataio_next(handle, out.ctypes.data)
            finally:
                self.ds._exit_io()
            if rc != 0:
                # producer stopped (dataset closed underneath us): out was
                # never written — surfacing it would feed garbage token ids
                raise RuntimeError(
                    "NativePrefetchLoader: dataset closed while waiting "
                    "for the next batch (rc={})".format(rc))
            return out
        with self._cond:
            while self._buf is None:
                if self._closed:
                    # mirror the native path: close() while blocked here
                    # must raise, not hang (the producer thread is gone)
                    raise RuntimeError(
                        "NativePrefetchLoader: dataset closed while "
                        "waiting for the next batch")
                self._cond.wait()
            out, self._buf = self._buf, None
            self._cond.notify_all()
        return out
