"""CSR (compressed sparse row) gradient representation.

Reference parity: deepspeed/runtime/csr_tensor.py (CSRTensor) + the sparse
embedding-gradient allreduce in engine.py:1285-1341, which all_gathers CSR
values/indices (with per-rank size equalization) instead of all-reducing a
mostly-zero dense [vocab, hidden] gradient.

TPU context: under GSPMD the embedding gradient's reduction is inserted by
XLA, and the idiomatic bandwidth fix is vocab-sharding the embedding on the
``model`` axis (models/gpt2.py partition_spec_fn) so no rank ever owns the
dense [vocab, hidden] grad. The CSR form remains useful at the *host*
boundary — sparse checkpoint deltas, grad inspection, CPU-offloaded
embedding updates — and this class keeps the reference's exact API:
``from_dense / to_dense / sparse_size / add``, plus ``all_gather_concat``
reproducing the size-equalized gather semantics for host-side use.
"""
import numpy as np

import jax.numpy as jnp


class CSRTensor:
    """Row-sparse matrix: only rows with any nonzero are stored."""

    def __init__(self, indices, values, dense_size):
        self.indices = jnp.asarray(indices, dtype=jnp.int32)
        self.values = jnp.asarray(values)
        self.dense_size = tuple(dense_size)

    @staticmethod
    def from_dense(dense):
        """Keep rows with any nonzero entry (reference from_dense)."""
        d = np.asarray(dense)
        row_nnz = np.abs(d).sum(axis=tuple(range(1, d.ndim))) != 0
        indices = np.nonzero(row_nnz)[0].astype(np.int32)
        return CSRTensor(indices, d[indices], d.shape)

    def to_dense(self):
        dense = jnp.zeros(self.dense_size, dtype=self.values.dtype)
        if self.indices.size == 0:
            return dense
        return dense.at[self.indices].set(self.values)

    def sparse_size(self):
        """(stored elements, total elements) — reference returns the ratio's
        ingredients for logging."""
        total = int(np.prod(self.dense_size))
        stored = int(self.values.size)
        return stored, total

    def add(self, other):
        """Elementwise add of two CSR tensors over the same dense shape."""
        assert self.dense_size == other.dense_size
        dense = self.to_dense() + other.to_dense()
        return CSRTensor.from_dense(dense)

    def __repr__(self):
        stored, total = self.sparse_size()
        return "CSRTensor(dense_size={}, stored={}/{})".format(
            self.dense_size, stored, total)


def all_gather_concat(csr_list):
    """Combine per-rank CSR shards into the summed dense gradient —
    the semantic result of the reference's sparse_allreduce_bucket
    (engine.py:1309-1336: all_gather values+indices padded to the max
    per-rank size, then scatter-add). Host-side equivalent for offloaded
    embedding updates."""
    assert csr_list
    dense = csr_list[0].to_dense()
    for csr in csr_list[1:]:
        if csr.indices.size:
            dense = dense.at[csr.indices].add(csr.values)
    return dense
