"""Data loading with data-parallel sharding.

Reference parity: deepspeed/runtime/dataloader.py (DeepSpeedDataLoader :33,
RepeatingLoader :10). The torch DataLoader + DistributedSampler pair becomes
a numpy batcher that yields this process's shard of each global batch; the
engine turns shards into globally-sharded ``jax.Array``s via the mesh.
"""
import numpy as np


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference :10)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


def _default_collate(samples):
    """Stack a list of per-sample tuples/dicts/arrays into batched numpy."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _default_collate([s[k] for s in samples]) for k in first}
    arrs = [np.asarray(s) for s in samples]
    return np.stack(arrs)


class DeepSpeedDataLoader:
    """DP-sharded batch loader (reference :33).

    Yields numpy batches of ``batch_size = micro_batch * local_dp_ranks`` for
    this process, drawn from the process's contiguous shard of the dataset
    (the DistributedSampler equivalent). Works with any dataset exposing
    ``__len__``/``__getitem__`` (incl. torch datasets).
    """

    def __init__(self, dataset, batch_size, local_rank=0, collate_fn=None,
                 data_parallel_world_size=1, data_parallel_rank=0,
                 shuffle=False, seed=0, drop_last=True, num_local_io_workers=None,
                 pin_memory=False, dataloader_drop_last=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.dp_world_size = data_parallel_world_size
        self.dp_rank = data_parallel_rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last if dataloader_drop_last is None \
            else dataloader_drop_last
        self.epoch = 0
        self.len = self._shard_len() // batch_size if self.drop_last else \
            -(-self._shard_len() // batch_size)

    def _shard_len(self):
        return len(self.dataset) // self.dp_world_size

    def set_epoch(self, epoch):
        self.epoch = epoch

    def _shard_indices(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(indices)
        per_rank = n // self.dp_world_size
        start = self.dp_rank * per_rank
        return indices[start:start + per_rank]

    def __len__(self):
        return self.len

    def __iter__(self):
        indices = self._shard_indices()
        n_full = len(indices) // self.batch_size * self.batch_size
        if not self.drop_last:
            n_full = len(indices)
        for i in range(0, n_full, self.batch_size):
            chunk = indices[i:i + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                break
            samples = [self.dataset[int(j)] for j in chunk]
            yield self.collate_fn(samples)
        self.epoch += 1
