from .partition import ZeroShardingPlan
from .init_ctx import (Init, GatheredParameters,
                       register_external_parameter)
