"""zero.Init / GatheredParameters: construct-time parameter partitioning.

Reference parity: deepspeed/runtime/zero/partition_parameters.py — ``Init``
(:226) monkey-patches ``nn.Module.__init__`` so every parameter is
partitioned the moment it is created (1/N slice per rank, optionally on
CPU), and ``GatheredParameters`` (:852) temporarily all-gathers full values
for user access.

TPU re-founding: a parameter is a ``jax.Array`` whose NamedSharding IS the
partitioning, so "convert at construction" means device_put-ing each leaf
with the stage-3 plan's sharding as the model object is built — host RAM
briefly holds each full leaf (as the reference's CPU-side init does) but
device HBM only ever holds the 1/N shard. The patch point is
:class:`runtime.model.Model` (our nn.Module equivalent): inside ``with
zero.Init(mesh=...)``, every Model constructed gets ``params`` sharded and
tagged ``ds_sharded=True``. No ds_id/ds_status state machine survives —
AVAILABLE/NOT_AVAILABLE/INFLIGHT (:110) was eager-mode bookkeeping; under
jit, gather/release is XLA's schedule.

``remote_device="cpu"`` keeps the shard on host memory (ZeRO-Offload
params, reference :341-346) via jax.device_put to the host platform;
``pin_memory`` is accepted for parity (host arrays are already DMA-able).
"""
import numpy as np

import jax
import jax.numpy as jnp

from ...parallel.topology import DATA_AXIS, build_mesh
from ...utils.logging import logger
from .partition import ZeroShardingPlan


def _threshold_from_config(ds_config):
    if ds_config is None:
        return 100000
    if isinstance(ds_config, dict):
        zero_cfg = ds_config.get("zero_optimization", {})
        # canonical stage3_-prefixed spelling wins; short alias accepted
        return zero_cfg.get(
            "stage3_param_persistence_threshold",
            zero_cfg.get("param_persistence_threshold", 100000))
    # DeepSpeedConfig object: the parsed value lives on its zero_config
    zc = getattr(ds_config, "zero_config", None)
    if zc is not None and getattr(zc, "param_persistence_threshold",
                                  None) is not None:
        return zc.param_persistence_threshold
    return 100000


class Init:
    """Context manager: Models constructed inside get stage-3-sharded params.

    ``with zero.Init(mesh=mesh): model = make_gpt2_model(...)`` — every
    parameter leaf is placed with the ZeRO-3 plan's NamedSharding at
    construction (reference partition_parameters.py:226's post-init hook).
    """

    _active = None

    def __init__(self, module=None, data_parallel_group=None, mesh=None,
                 mem_efficient_linear=True, remote_device=None,
                 pin_memory=False, config=None, enabled=True, dtype=None,
                 param_persistence_threshold=None):
        self.enabled = enabled
        self.mesh = mesh if mesh is not None else build_mesh()
        self.remote_device = remote_device
        self.pin_memory = pin_memory
        self.dtype = dtype
        threshold = (param_persistence_threshold
                     if param_persistence_threshold is not None
                     else _threshold_from_config(config))
        self.plan = ZeroShardingPlan(self.mesh, stage=3,
                                     param_persistence_threshold=threshold)
        self._saved_init = None

    # -- tree sharding -------------------------------------------------------
    def shard_tree(self, tree, spec_fn=None):
        """device_put every leaf with its stage-3 sharding. ``spec_fn``
        optionally provides TP PartitionSpecs (Model.partition_spec_fn)."""
        plan = self.plan
        if spec_fn is not None:
            plan = ZeroShardingPlan(self.mesh, stage=3,
                                    param_persistence_threshold=plan.persist_threshold,
                                    model_spec_fn=spec_fn)

        host_mesh = self._host_mesh() if self.remote_device == "cpu" else None

        def place(path, leaf):
            arr = leaf
            if self.dtype is not None and hasattr(arr, "astype"):
                arr = arr.astype(self.dtype)
            sharding = plan.param_sharding(path, np.shape(arr))
            if self.remote_device == "cpu":
                # ZeRO-Offload params: the SAME 1/N shard layout, kept in
                # host memory (engine streams to HBM per use). Rebind the
                # plan's spec onto a CPU-device mesh when one of matching
                # shape exists; otherwise fall back to one host device.
                if host_mesh is not None:
                    from jax.sharding import NamedSharding
                    return jax.device_put(
                        arr, NamedSharding(host_mesh, sharding.spec))
                return jax.device_put(arr, self._host_fallback_device())
            return jax.device_put(arr, sharding)

        from .partition import _path_str
        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: place(_path_str(kp), leaf), tree)

    def _host_mesh(self):
        """A CPU-device mesh mirroring the accelerator mesh's axis shape,
        so offloaded shards keep the 1/N layout in host RAM. None when the
        host doesn't expose enough CPU devices."""
        if getattr(self, "_host_mesh_cache", False) is not False:
            return self._host_mesh_cache
        import jax as _jax
        from jax.sharding import Mesh
        try:
            cpus = _jax.devices("cpu")
        except RuntimeError:
            cpus = []
        need = int(np.prod(list(self.mesh.shape.values())))
        if len(cpus) >= need:
            arr = np.array(cpus[:need]).reshape(
                tuple(self.mesh.shape.values()))
            self._host_mesh_cache = Mesh(arr, tuple(self.mesh.shape.keys()))
        else:
            logger.warning(
                "zero.Init(remote_device='cpu'): only %d CPU device(s) for "
                "a %d-way mesh; offloaded params stay unsharded on host "
                "(set --xla_force_host_platform_device_count to shard)",
                len(cpus), need)
            self._host_mesh_cache = None
        return self._host_mesh_cache

    def _host_fallback_device(self):
        import jax as _jax
        return _jax.devices("cpu")[0]

    # -- Model construction hook ---------------------------------------------
    def __enter__(self):
        if not self.enabled:
            return self
        from ..model import Model
        Init._active = self
        self._saved_init = Model.__init__
        ctx = self

        def patched_init(model_self, apply_fn, params, partition_spec_fn=None,
                         name=None):
            ctx._saved_init(model_self, apply_fn, params,
                            partition_spec_fn=partition_spec_fn, name=name)
            model_self.params = ctx.shard_tree(model_self.params,
                                               spec_fn=partition_spec_fn)
            model_self.ds_sharded = True

        Model.__init__ = patched_init
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if not self.enabled:
            return False
        from ..model import Model
        Model.__init__ = self._saved_init
        Init._active = None
        return False


class GatheredParameters:
    """Temporarily materialize full (replicated) parameter values.

    Reference partition_parameters.py:852: inside the context every listed
    param is all-gathered; if ``modifier_rank`` is set, rank's modifications
    are re-partitioned + broadcast on exit. Here: ``with
    GatheredParameters(model) as full:`` yields a mutable dict of full
    numpy arrays; on exit (when ``modifier_rank`` is not None) the —
    possibly modified — values are re-sharded back into ``model.params``.
    Under SPMD every process runs the same modification, which subsumes the
    reference's broadcast-from-modifier semantics.
    """

    def __init__(self, target, modifier_rank=None, fwd_module=None,
                 enabled=True):
        self.enabled = enabled
        self.modifier_rank = modifier_rank
        self._model = None
        if hasattr(target, "params") and hasattr(target, "apply_fn"):
            self._model = target
            self.params = target.params
        else:
            self.params = target
        self._full = None

    def __enter__(self):
        if not self.enabled:
            return self.params
        self._full = jax.tree_util.tree_map(
            lambda leaf: np.array(leaf), self.params)  # writable copies
        return self._full

    def __exit__(self, exc_type, exc_val, exc_tb):
        if not self.enabled or exc_type is not None:
            return False
        if self.modifier_rank is None:
            return False
        # map over (new, old) pairs: None shardings can't ride a pytree
        # (None is an empty container for tree_map)
        resharded = jax.tree_util.tree_map(
            lambda new, old: (jax.device_put(jnp.asarray(new), old.sharding)
                              if hasattr(old, "sharding")
                              else jnp.asarray(new)),
            self._full, self.params)
        if self._model is not None:
            self._model.params = resharded
        else:
            # in-place dict update so callers holding the tree see it
            if isinstance(self.params, dict):
                self.params.clear()
                self.params.update(resharded)
        return False


def register_external_parameter(module, parameter):
    """API parity no-op (reference partition_parameters.py:45). The
    reference needs explicit registration when a module uses another
    module's weights so the coordinator knows to gather them; under XLA's
    dataflow any leaf referenced by the traced apply_fn is gathered where
    used — there is no hook machinery to inform."""
    logger.debug("register_external_parameter: no-op under SPMD/XLA")
