"""ZeRO config key names/defaults (reference: deepspeed/runtime/zero/constants.py)."""

ZERO_OPTIMIZATION = "zero_optimization"

ZERO_FORMAT = """
ZeRO optimization should be enabled as:
"zero_optimization": {
  "stage": [0|1|2|3],
  "allgather_partitions": [true|false],
  "allgather_bucket_size": 500000000,
  "overlap_comm": [true|false],
  "reduce_scatter": [true|false],
  "reduce_bucket_size": 500000000,
  "contiguous_gradients": [true|false],
  "cpu_offload": [true|false],
  "cpu_offload_params": [true|false],
  "cpu_offload_use_pin_memory": [true|false],
  "strict": [true|false],
  "sub_group_size": 1000000000000,
  "stage3_max_live_parameters": 1000000000,
  "stage3_max_reuse_distance": 1000000000,
  "stage3_prefetch_bucket_size": 500000000,
  "stage3_param_persistence_threshold": 100000,
  "elastic_checkpoint": [true|false],
  "zero_quantized_weights": [true|false],
  "zero_hierarchical_partition": 0,
  "zero_quantized_gradients": [true|false]
}
"""

ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

ZERO_OPTIMIZATION_STAGE = "stage"
ZERO_OPTIMIZATION_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED

ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT = True

ZERO_OPTIMIZATION_REDUCE_SCATTER = "reduce_scatter"
ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT = True

ZERO_OPTIMIZATION_OVERLAP_COMM = "overlap_comm"
ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT = False

ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT = False

ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT = 500000000

ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT = 500000000
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED = "allgather_size"

ZERO_OPTIMIZATION_CPU_OFFLOAD = "cpu_offload"
ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT = False

ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS = "cpu_offload_params"
ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT = False

# Strict mode: a zero_optimization key this runtime cannot give real
# semantics to (see runtime/engine.py _validate_zero_keys) RAISES instead
# of warning — no silent config no-ops.
ZERO_OPTIMIZATION_STRICT = "strict"
ZERO_OPTIMIZATION_STRICT_DEFAULT = False

ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY = "cpu_offload_use_pin_memory"
ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT = False

ZERO_OPTIMIZATION_SUB_GROUP_SIZE = "sub_group_size"
ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT = 1000000000000

ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS = "stage3_max_live_parameters"
ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT = 1000000000

ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE = "stage3_max_reuse_distance"
ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT = 1000000000

ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE = "stage3_prefetch_bucket_size"
ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT = 50000000

ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD = "stage3_param_persistence_threshold"
ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT = 100000

ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE = "stage3_gather_fp16_weights_on_model_save"
ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT = False

ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT = True

ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS = "load_from_fp32_weights"
ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT = True

# --- ZeRO++ communication-efficiency modes (arXiv:2306.10209), all
# independently toggleable and default-off ---

# qwZ: stage-3 weight all-gathers move blockwise-int8 data + per-block
# scales instead of the compute dtype (runtime/comm/quantize.py).
ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS = "zero_quantized_weights"
ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS_DEFAULT = False

# hpZ: secondary partition size — the ``data`` mesh axis is factored into
# (replica, shard) sub-axes of shard size N; stage-3 params shard only
# within the N-device shard group so per-step gathers ride the short hop.
# 0/1 disables; N must divide the data-parallel degree.
ZERO_OPTIMIZATION_HIERARCHICAL_PARTITION = "zero_hierarchical_partition"
ZERO_OPTIMIZATION_HIERARCHICAL_PARTITION_DEFAULT = 0

# qgZ: each micro-step's gradient contribution passes through the
# error-compensated int8 codec before accumulation (ZeRO-2/3).
ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS = "zero_quantized_gradients"
ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS_DEFAULT = False
