"""ZeRO sub-config parser (reference: deepspeed/runtime/zero/config.py).

On TPU, ZeRO stages are realized as GSPMD sharding of the train-state pytree
over the mesh's ``data`` axis rather than via gradient hooks:
  stage 1 -> optimizer state (+fp32 master) sharded,
  stage 2 -> stage 1 + gradients reduce-scattered (psum_scatter),
  stage 3 -> stage 2 + parameters sharded with per-use all-gather.
Bucket-size/overlap knobs are accepted for surface parity; XLA's latency
hiding scheduler replaces the manual stream machinery.
"""
from ..config_utils import get_scalar_param
from .constants import *
from ...utils.logging import logger


class DeepSpeedZeroConfig(object):
    def __init__(self, param_dict):
        self.stage = None
        self.contiguous_gradients = None
        self.reduce_scatter = None
        self.reduce_bucket_size = None
        self.allgather_partitions = None
        self.allgather_bucket_size = None
        self.overlap_comm = None
        self.cpu_offload = None
        self.cpu_offload_params = None
        self.cpu_offload_use_pin_memory = None
        self.sub_group_size = None
        self.max_live_parameters = None
        self.max_reuse_distance = None
        self.prefetch_bucket_size = None
        self.param_persistence_threshold = None
        self.gather_fp16_weights_on_model_save = None
        self.elastic_checkpoint = None
        self.load_from_fp32_weights = None
        self.quantized_weights = None
        self.hierarchical_partition = None
        self.quantized_gradients = None
        self.strict = None

        if ZERO_OPTIMIZATION in param_dict:
            zero_config_dict = param_dict[ZERO_OPTIMIZATION]
            if isinstance(zero_config_dict, bool):
                zero_config_dict = self.read_zero_config_deprecated(param_dict)
        else:
            zero_config_dict = {}
        self._initialize(zero_config_dict)

    def read_zero_config_deprecated(self, param_dict):
        zero_config_dict = {
            ZERO_OPTIMIZATION_STAGE:
                1 if param_dict[ZERO_OPTIMIZATION] else 0
        }
        if zero_config_dict[ZERO_OPTIMIZATION_STAGE] > 0:
            zero_config_dict[ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE] = \
                get_scalar_param(param_dict,
                                 ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED,
                                 ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)
        logger.warning(
            "DeepSpeedConfig: this format of ZeRO optimization setup is deprecated."
            " Please use the following format: {}".format(ZERO_FORMAT))
        return zero_config_dict

    def _initialize(self, zero_config_dict):
        g = lambda key, default: get_scalar_param(zero_config_dict, key, default)
        self.stage = g(ZERO_OPTIMIZATION_STAGE, ZERO_OPTIMIZATION_STAGE_DEFAULT)
        self.contiguous_gradients = g(ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
                                      ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT)
        self.reduce_bucket_size = g(ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                                    ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT)
        self.reduce_scatter = g(ZERO_OPTIMIZATION_REDUCE_SCATTER,
                                ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)
        self.overlap_comm = g(ZERO_OPTIMIZATION_OVERLAP_COMM,
                              ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT)
        self.allgather_partitions = g(ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
                                      ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)
        self.allgather_bucket_size = g(ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
                                       ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)
        self.cpu_offload = g(ZERO_OPTIMIZATION_CPU_OFFLOAD,
                             ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT)
        self.cpu_offload_params = g(ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS,
                                    ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT)
        self.cpu_offload_use_pin_memory = g(
            ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY,
            ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT)
        self.sub_group_size = g(ZERO_OPTIMIZATION_SUB_GROUP_SIZE,
                                ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT)
        self.max_live_parameters = g(ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS,
                                     ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT)
        self.max_reuse_distance = g(ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE,
                                    ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT)
        self.prefetch_bucket_size = g(ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE,
                                      ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT)
        # the stage3_-prefixed reference spelling wins; the short alias is
        # also accepted (zero.Init's config-dict path uses it)
        self.param_persistence_threshold = g(
            ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD,
            zero_config_dict.get(
                "param_persistence_threshold",
                ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT))
        self.gather_fp16_weights_on_model_save = g(
            ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE,
            ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT)
        self.elastic_checkpoint = g(ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
                                    ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)
        self.load_from_fp32_weights = g(ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS,
                                        ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT)
        # ZeRO++ comm-efficiency modes (independently toggleable, off by
        # default; see runtime/comm/quantize.py + docs/zeropp.md)
        self.quantized_weights = bool(g(
            ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS,
            ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS_DEFAULT))
        hpz = g(ZERO_OPTIMIZATION_HIERARCHICAL_PARTITION,
                ZERO_OPTIMIZATION_HIERARCHICAL_PARTITION_DEFAULT)
        if isinstance(hpz, bool) or not isinstance(hpz, int) or hpz < 0:
            raise ValueError(
                "zero_optimization.{} must be an int >= 0 (the secondary "
                "partition size; 0/1 disables), got {!r}".format(
                    ZERO_OPTIMIZATION_HIERARCHICAL_PARTITION, hpz))
        self.hierarchical_partition = hpz
        self.quantized_gradients = bool(g(
            ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS,
            ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS_DEFAULT))
        # strict: unimplementable keys raise instead of warning (the
        # engine's _validate_zero_keys enforces it)
        self.strict = bool(g(ZERO_OPTIMIZATION_STRICT,
                             ZERO_OPTIMIZATION_STRICT_DEFAULT))

    def repr(self):
        return self.__dict__

    def __repr__(self):
        import json
        return json.dumps(self.__dict__, indent=4, sort_keys=True)
