"""ZeRO stages as GSPMD sharding plans.

Reference parity: deepspeed/runtime/zero/stage{1,2,3}.py +
partition_parameters.py, re-founded on sharding annotations (SURVEY §2.4):

  stage 0: params/master/optimizer replicated; grads all-reduced (psum via
           GSPMD from the batch sharding).
  stage 1: fp32 master + Adam moments sharded over the ``data`` axis; the
           updated compute-dtype params are re-replicated each step (XLA emits
           the all-gather the reference does manually, stage1.py:624-708).
  stage 2: stage 1 + gradient accumulation buffers sharded like the master —
           constraining grads to that sharding makes XLA lower the grad psum
           to reduce-scatter (the IPG bucket reduce-scatter, stage2.py:947).
  stage 3: stage 2 + compute params sharded; XLA inserts per-use all-gathers
           (the PartitionedParameterCoordinator's fetch/release,
           stage3.py:274-493, becomes compiler scheduling). Parameters
           smaller than ``param_persistence_threshold`` stay replicated
           (ds_persist, partition_parameters.py:341).

The flat-buffer/padding machinery of the reference (stage2.py:222-278) is
unnecessary: per-tensor dimension sharding with replicate-fallback gives the
same memory scaling without reshaping, and uneven dims are handled by GSPMD
padding.
"""
import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.topology import (DATA_AXIS, DATA_REPLICA_AXIS,
                                  DATA_SHARD_AXIS)


class ZeroShardingPlan:
    """Computed shardings for every piece of the train state.

    Secondary partitioning (ZeRO++ hpZ): on a mesh whose ``data`` axis was
    factored into (``data_replica``, ``data_shard``) sub-axes
    (topology.factor_data_axis), master/optimizer/gradient state shards
    over BOTH sub-axes (the primary partition — identical placement to the
    flat plan) while stage-3 compute params shard only over ``data_shard``
    (the secondary partition): forward/backward all-gathers then cross
    only the short intra-replica hop, at the cost of params being
    replicated ``data_replica``-ways.
    """

    def __init__(self, mesh, stage=0, param_persistence_threshold=100000,
                 model_spec_fn=None, max_live_parameters=None):
        self.mesh = mesh
        self.stage = stage
        self.persist_threshold = param_persistence_threshold
        # stage3_max_live_parameters: an element budget on the stage-3
        # leaves that stay PERSISTENTLY gathered (replicated) in HBM.
        # configure_live_budget() demotes persistent leaves to data-sharded
        # until the persistent set fits; per-use gather liveness inside a
        # step is XLA's memory-aware schedule (the reference's
        # fetch/release coordinator is compiler scheduling here), and the
        # streamed-offload runner sizes its layer groups by the same
        # budget (runtime/zero/stream.py).
        self.max_live_parameters = max_live_parameters
        self._demoted = set()
        if DATA_AXIS in mesh.shape:
            self.data_axes = (DATA_AXIS,)
            self.param_data_axes = (DATA_AXIS,)
        elif DATA_SHARD_AXIS in mesh.shape:
            self.data_axes = tuple(a for a in (DATA_REPLICA_AXIS,
                                               DATA_SHARD_AXIS)
                                   if a in mesh.shape)
            self.param_data_axes = (DATA_SHARD_AXIS,)
        else:
            self.data_axes = ()
            self.param_data_axes = ()
        self.dp_size = int(np.prod([mesh.shape[a] for a in self.data_axes],
                                   dtype=np.int64)) if self.data_axes else 1
        self.param_shard_size = int(np.prod(
            [mesh.shape[a] for a in self.param_data_axes],
            dtype=np.int64)) if self.param_data_axes else 1
        self.hierarchical = self.param_data_axes != self.data_axes
        # Optional per-param tensor-parallel PartitionSpec provider
        # (path, shape) -> PartitionSpec, used by TP-aware models.
        self.model_spec_fn = model_spec_fn

    def _named(self, spec):
        return NamedSharding(self.mesh, spec)

    def replicated(self):
        return self._named(P())

    def topology(self):
        """JSON-able summary of the topology this plan shards for —
        the rescale events' ``old_mesh``/``new_mesh`` payload and the
        crash bundle's topology section share this shape, so a
        post-mortem can diff two plans without reconstructing them."""
        return {
            "mesh": {str(k): int(v) for k, v in self.mesh.shape.items()},
            "stage": int(self.stage),
            "dp_size": int(self.dp_size),
            "param_shard_size": int(self.param_shard_size),
            "data_axes": [str(a) for a in self.data_axes],
            "hierarchical": bool(self.hierarchical),
        }

    def _tp_spec(self, path, shape):
        if self.model_spec_fn is None:
            return None
        spec = self.model_spec_fn(path, shape)
        if spec is None:
            return None
        # Drop axes the mesh doesn't carry (e.g. TP layouts on a DP-only
        # mesh): the param is simply replicated along those dims.
        cleaned = []
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            if entry is None or all(ax in self.mesh.shape for ax in axes):
                cleaned.append(entry)
            else:
                cleaned.append(None)
        if all(c is None for c in cleaned):
            return None
        return P(*cleaned)

    def _zero_spec(self, path, shape, threshold, data_axes=None):
        """Combine any TP spec with data-axis sharding of a free dimension.

        ``data_axes``: the mesh axes (tuple) the free dimension shards
        over — the full factored set for master/grad state, the shard
        sub-axis only for secondary-partitioned stage-3 params."""
        if data_axes is None:
            data_axes = self.data_axes
        shard_ways = int(np.prod([self.mesh.shape[a] for a in data_axes],
                                 dtype=np.int64)) if data_axes else 1
        tp_spec = self._tp_spec(path, shape)
        base = list(tp_spec) if tp_spec is not None else [None] * len(shape)
        while len(base) < len(shape):
            base.append(None)
        numel = int(np.prod(shape)) if shape else 1
        # shard_ways <= 1 also covers meshes that dropped the size-1 data
        # axis entirely (e.g. a pure-sequence mesh): annotating 'data'
        # there would name an axis the mesh doesn't carry
        if shard_ways <= 1 or numel < max(threshold, shard_ways) \
                or not shape:
            return P(*base) if tp_spec is not None else P()
        # Shard the first unclaimed axis divisible by the shard degree
        for dim, size in enumerate(shape):
            if base[dim] is None and size % shard_ways == 0:
                base[dim] = data_axes[0] if len(data_axes) == 1 \
                    else tuple(data_axes)
                return P(*base)
        return P(*base)

    def _effective_threshold(self, path):
        """Persistence threshold for a leaf, honoring live-budget
        demotions (a demoted leaf shards regardless of its size)."""
        return 0 if path in self._demoted else self.persist_threshold

    def _can_data_shard(self, path, shape):
        """Whether any free dim divides the param shard degree (the
        only leaves the budget can demote)."""
        ways = self.param_shard_size
        if ways <= 1 or not shape:
            return False
        spec = self._zero_spec(path, shape, threshold=0,
                               data_axes=self.param_data_axes)
        wanted = set(self.param_data_axes)
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            if any(ax in wanted for ax in axes):
                return True
        return False

    def configure_live_budget(self, tree):
        """Honor ``stage3_max_live_parameters``: demote persistent
        (below-threshold) stage-3 leaves to data-sharded, largest first,
        until the persistently-gathered element count fits the budget.

        Returns (persistent_elements, demoted_paths). Leaves with no
        shardable dim cannot be demoted; if they alone exceed the budget
        the caller warns (or raises under strict) — the budget is then
        unsatisfiable rather than silently ignored."""
        self._demoted = set()
        budget = self.max_live_parameters
        if budget is None or self.stage < 3 or not self.param_data_axes:
            return None, ()
        persistent = []   # (numel, path, demotable)
        def visit(kp, leaf):
            path = _path_str(kp)
            shape = np.shape(leaf)
            if not self.param_is_data_sharded(path, shape):
                persistent.append(
                    (int(np.prod(shape)) if shape else 1, path,
                     self._can_data_shard(path, shape)))
            return leaf
        jax.tree_util.tree_map_with_path(visit, tree)
        total = sum(n for n, _, _ in persistent)
        for numel, path, demotable in sorted(persistent, reverse=True):
            if total <= budget:
                break
            if not demotable:
                continue
            self._demoted.add(path)
            total -= numel
        return total, tuple(sorted(self._demoted))

    # --- public sharding queries -------------------------------------------
    def param_sharding(self, path, shape):
        """Compute-dtype parameters: sharded only at stage 3 (over the
        secondary-partition sub-axis when the plan is hierarchical)."""
        if self.stage >= 3:
            return self._named(self._zero_spec(
                path, shape, self._effective_threshold(path),
                data_axes=self.param_data_axes))
        tp_spec = self._tp_spec(path, shape)
        return self._named(tp_spec if tp_spec is not None else P())

    def gather_sharding(self, path, shape):
        """The qwZ all-gather target: the param's spec with every data
        (sub-)axis dropped — TP placement intact, data axes replicated."""
        tp_spec = self._tp_spec(path, shape)
        return self._named(tp_spec if tp_spec is not None else P())

    def param_is_data_sharded(self, path, shape, flat=False):
        """Whether the stage-3 compute param actually shards over a data
        (sub-)axis — the leaves qwZ gathers explicitly. ``flat=True``
        answers for the UN-factored plan (full data axis) instead: what
        flat ZeRO-3 would shard — the wire estimator's baseline."""
        data_axes = self.data_axes if flat else self.param_data_axes
        if self.stage < 3 or not data_axes:
            return False
        spec = self._zero_spec(path, shape, self._effective_threshold(path),
                               data_axes=data_axes)
        wanted = set(data_axes)
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            if any(ax in wanted for ax in axes):
                return True
        return False

    def tp_ways(self, path, shape):
        """How many ways the leaf's TENSOR-PARALLEL spec splits it (1 =
        no TP). A TP-sharded leaf's per-device wire share for data-axis
        collectives is ``numel / tp_ways`` — the wire estimator divides
        by this (shard-lint census ground truth, PR 10)."""
        spec = self._tp_spec(path, shape)
        if spec is None:
            return 1
        data_axes = set(self.data_axes) | set(self.param_data_axes)
        ways = 1
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is not None and ax not in data_axes and \
                        ax in self.mesh.shape:
                    ways *= int(self.mesh.shape[ax])
        return ways

    def master_sharding(self, path, shape):
        """fp32 master + optimizer moments: sharded from stage 1 up."""
        if self.stage >= 1:
            return self._named(self._zero_spec(path, shape, 0))
        tp_spec = self._tp_spec(path, shape)
        return self._named(tp_spec if tp_spec is not None else P())

    def grad_sharding(self, path, shape):
        """Accumulated gradients: sharded like master from stage 2 up."""
        if self.stage >= 2:
            return self.master_sharding(path, shape)
        tp_spec = self._tp_spec(path, shape)
        return self._named(tp_spec if tp_spec is not None else P())

    # --- tree helpers -------------------------------------------------------
    def tree_shardings(self, tree, kind):
        """Sharding pytree for params/master/grads over an example tree."""
        fn = {"param": self.param_sharding, "master": self.master_sharding,
              "grad": self.grad_sharding}[kind]

        def per_leaf(path, leaf):
            return fn(path, np.shape(leaf))

        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: per_leaf(_path_str(kp), leaf), tree)

    def constrain(self, tree, kind):
        """with_sharding_constraint a whole tree inside jit."""
        shardings = self.tree_shardings(tree, kind)
        return jax.tree_util.tree_map(
            lambda x, s: jax.lax.with_sharding_constraint(x, s), tree,
            shardings)


def _path_str(key_path):
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in key_path)
