"""ZeRO stages as GSPMD sharding plans.

Reference parity: deepspeed/runtime/zero/stage{1,2,3}.py +
partition_parameters.py, re-founded on sharding annotations (SURVEY §2.4):

  stage 0: params/master/optimizer replicated; grads all-reduced (psum via
           GSPMD from the batch sharding).
  stage 1: fp32 master + Adam moments sharded over the ``data`` axis; the
           updated compute-dtype params are re-replicated each step (XLA emits
           the all-gather the reference does manually, stage1.py:624-708).
  stage 2: stage 1 + gradient accumulation buffers sharded like the master —
           constraining grads to that sharding makes XLA lower the grad psum
           to reduce-scatter (the IPG bucket reduce-scatter, stage2.py:947).
  stage 3: stage 2 + compute params sharded; XLA inserts per-use all-gathers
           (the PartitionedParameterCoordinator's fetch/release,
           stage3.py:274-493, becomes compiler scheduling). Parameters
           smaller than ``param_persistence_threshold`` stay replicated
           (ds_persist, partition_parameters.py:341).

The flat-buffer/padding machinery of the reference (stage2.py:222-278) is
unnecessary: per-tensor dimension sharding with replicate-fallback gives the
same memory scaling without reshaping, and uneven dims are handled by GSPMD
padding.
"""
import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.topology import DATA_AXIS


class ZeroShardingPlan:
    """Computed shardings for every piece of the train state."""

    def __init__(self, mesh, stage=0, param_persistence_threshold=100000,
                 model_spec_fn=None):
        self.mesh = mesh
        self.stage = stage
        self.persist_threshold = param_persistence_threshold
        self.dp_size = int(mesh.shape.get(DATA_AXIS, 1))
        # Optional per-param tensor-parallel PartitionSpec provider
        # (path, shape) -> PartitionSpec, used by TP-aware models.
        self.model_spec_fn = model_spec_fn

    def _named(self, spec):
        return NamedSharding(self.mesh, spec)

    def replicated(self):
        return self._named(P())

    def _tp_spec(self, path, shape):
        if self.model_spec_fn is None:
            return None
        spec = self.model_spec_fn(path, shape)
        if spec is None:
            return None
        # Drop axes the mesh doesn't carry (e.g. TP layouts on a DP-only
        # mesh): the param is simply replicated along those dims.
        cleaned = []
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            if entry is None or all(ax in self.mesh.shape for ax in axes):
                cleaned.append(entry)
            else:
                cleaned.append(None)
        if all(c is None for c in cleaned):
            return None
        return P(*cleaned)

    def _zero_spec(self, path, shape, threshold):
        """Combine any TP spec with data-axis sharding of a free dimension."""
        tp_spec = self._tp_spec(path, shape)
        base = list(tp_spec) if tp_spec is not None else [None] * len(shape)
        while len(base) < len(shape):
            base.append(None)
        numel = int(np.prod(shape)) if shape else 1
        # dp_size <= 1 also covers meshes that dropped the size-1 data
        # axis entirely (e.g. a pure-sequence mesh): annotating 'data'
        # there would name an axis the mesh doesn't carry
        if self.dp_size <= 1 or numel < max(threshold, self.dp_size) \
                or not shape:
            return P(*base) if tp_spec is not None else P()
        # Shard the first unclaimed axis divisible by dp
        for dim, size in enumerate(shape):
            if base[dim] is None and size % self.dp_size == 0:
                base[dim] = DATA_AXIS
                return P(*base)
        return P(*base)

    # --- public sharding queries -------------------------------------------
    def param_sharding(self, path, shape):
        """Compute-dtype parameters: sharded only at stage 3."""
        if self.stage >= 3:
            return self._named(self._zero_spec(path, shape,
                                               self.persist_threshold))
        tp_spec = self._tp_spec(path, shape)
        return self._named(tp_spec if tp_spec is not None else P())

    def master_sharding(self, path, shape):
        """fp32 master + optimizer moments: sharded from stage 1 up."""
        if self.stage >= 1:
            return self._named(self._zero_spec(path, shape, 0))
        tp_spec = self._tp_spec(path, shape)
        return self._named(tp_spec if tp_spec is not None else P())

    def grad_sharding(self, path, shape):
        """Accumulated gradients: sharded like master from stage 2 up."""
        if self.stage >= 2:
            return self.master_sharding(path, shape)
        tp_spec = self._tp_spec(path, shape)
        return self._named(tp_spec if tp_spec is not None else P())

    # --- tree helpers -------------------------------------------------------
    def tree_shardings(self, tree, kind):
        """Sharding pytree for params/master/grads over an example tree."""
        fn = {"param": self.param_sharding, "master": self.master_sharding,
              "grad": self.grad_sharding}[kind]

        def per_leaf(path, leaf):
            return fn(path, np.shape(leaf))

        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: per_leaf(_path_str(kp), leaf), tree)

    def constrain(self, tree, kind):
        """with_sharding_constraint a whole tree inside jit."""
        shardings = self.tree_shardings(tree, kind)
        return jax.tree_util.tree_map(
            lambda x, s: jax.lax.with_sharding_constraint(x, s), tree,
            shardings)


def _path_str(key_path):
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in key_path)
