"""Coalesced host<->HBM transfer machinery for the offload paths.

The round-5 1.5B offload profile (`BENCH_XL_r05.json`) spent 116 s of a
462 s step in `h2d_dispatch`: one `jax.device_put` per parameter leaf,
each serializing its host buffer before returning — dispatch overhead,
not transfer bandwidth (the T3 finding, arXiv:2401.16677, applied to the
host<->HBM hop). The fix is the same discipline the reference implements
with pinned buffers and dedicated streams (stage2.py:780-908): coalesce
many small uploads into few large transfers and overlap them with host
compute.

:class:`H2DBatcher` packs queued host arrays into per-device flat
buckets of at most ``bucket_elems`` elements (the now-live
``stage3_prefetch_bucket_size``), uploads each bucket with ONE
``device_put``, and splits it back into the original shapes with one
jitted (donated, on-device) reshape program per bucket layout. Packing +
upload run on a single background worker so the serialization cost rides
behind the caller's host Adam.
"""
import numpy as np

import jax
import jax.numpy as jnp

# blocking-call tripwire (docs/concurrency.md): finish() waits on the
# upload worker — a sanitized lock held across it stalls its owners
from ...analysis.concurrency.locksan import note_blocking


def _split_fn_for(layout):
    """Jitted flat-buffer -> tuple-of-reshaped-views program for one
    bucket layout ((numel, shape) pairs).

    NOT donated: jax matches donated inputs to outputs by exact aval,
    and no reshaped slice matches the flat staging buffer — the
    donation was silently dropped with a "donated buffers were not
    usable" warning on every backend (the PR 10 shard-lint donation
    audit surfaced this; ``donation_unhonored`` in docs/analysis.md).
    The staging copy frees when the caller's reference drops after the
    split returns, which is the same point the unusable donation freed
    it."""
    offsets = []
    off = 0
    for numel, shape in layout:
        offsets.append((off, numel, shape))
        off += numel

    def split(flat):
        return tuple(flat[o:o + n].reshape(s) for o, n, s in offsets)

    return jax.jit(split)


class H2DBatcher:
    """Batch host->device uploads into few large transfers.

    ``add(key, host_array, device)`` queues one compute-dtype host array
    for one device; buckets flush automatically at ``bucket_elems``
    queued elements per device (and on ``finish()``). Each flush is ONE
    ``device_put`` of a packed flat buffer plus one jitted on-device
    split. ``finish()`` blocks until every queued upload landed and
    returns ``{key: {device: single-device array}}``.

    When ``pool`` is given, packing+upload run on it (a serial worker),
    overlapping the caller's host compute; otherwise flushes are
    synchronous in the caller.
    """

    def __init__(self, bucket_elems, dtype, pool=None, jit_cache=None):
        self.bucket_elems = max(int(bucket_elems), 1)
        self.dtype = np.dtype(dtype)
        self.pool = pool
        # jitted splitters keyed by bucket layout; pass a shared dict so
        # per-step batchers reuse compiles across steps
        self._split_cache = jit_cache if jit_cache is not None else {}
        self._pending = {}      # device -> [(key, np_array), ...]
        self._pending_elems = {}
        self._futures = []
        self._results = {}      # key -> {device: array}
        self.batches = 0        # device_put count (observable under test)
        self.elems = 0          # total elements queued (bucket occupancy
                                # = elems / (batches * bucket_elems))

    def add(self, key, host_array, device):
        self._pending.setdefault(device, []).append((key, host_array))
        self.elems += int(host_array.size)
        n = self._pending_elems.get(device, 0) + int(host_array.size)
        self._pending_elems[device] = n
        if n >= self.bucket_elems:
            self._flush_device(device)

    def occupancy(self):
        """Mean fill fraction of the flushed buckets (telemetry: how
        well ``stage3_prefetch_bucket_size`` matches the workload; can
        exceed 1.0 when one queued array alone overflows a bucket)."""
        if not self.batches or not self.bucket_elems:
            return None
        return self.elems / (self.batches * self.bucket_elems)

    def _flush_device(self, device):
        items = self._pending.pop(device, [])
        self._pending_elems.pop(device, None)
        if not items:
            return
        self.batches += 1
        if self.pool is not None:
            self._futures.append(
                self.pool.submit(self._upload, device, items))
        else:
            self._store(self._upload(device, items))

    def _upload(self, device, items):
        """Pack -> one device_put -> one jitted split (runs on the
        worker when a pool is set)."""
        cast = [np.ascontiguousarray(a, dtype=self.dtype).ravel()
                for _, a in items]
        layout = tuple((int(c.size), tuple(np.shape(a)))
                       for c, (_, a) in zip(cast, items))
        flat = cast[0] if len(cast) == 1 else np.concatenate(cast)
        dev_flat = jax.device_put(flat, device)
        if layout not in self._split_cache:
            self._split_cache[layout] = _split_fn_for(layout)
        parts = self._split_cache[layout](dev_flat)
        return [(key, device, part)
                for (key, _), part in zip(items, parts)]

    def _store(self, uploaded):
        for key, device, part in uploaded:
            self._results.setdefault(key, {})[device] = part

    def flush(self):
        """Kick every pending bucket onto the worker WITHOUT waiting —
        callers prefetching the next segment start the packing now and
        ``finish()`` later."""
        for device in list(self._pending):
            self._flush_device(device)

    def finish(self):
        """Flush everything, wait for in-flight uploads, return the
        ``{key: {device: array}}`` map."""
        for device in list(self._pending):
            self._flush_device(device)
        for fut in self._futures:
            if not fut.done():
                note_blocking("h2d_batcher.finish")
            self._store(fut.result())
        self._futures = []
        return self._results


def make_upload_pool(name="offload-upload"):
    """One serial background worker for pack+device_put (jax dispatch is
    thread-safe; a single worker keeps uploads ordered). Pool
    construction lives with the executor (DSL006) — this is the
    batcher-local spelling of ``runtime/executor/pools.upload_pool``."""
    from ..executor.pools import upload_pool
    return upload_pool(name)


def host_adam_chunk(lib, p, g, m, v, hyper, bc1, bc2, adam_w):
    """One in-place host Adam chunk on fp32 numpy arrays (native SIMD
    kernel when built, numpy fallback otherwise) — shared by the
    executor-lowered classic offload plan (runtime/executor/offload.py)
    and the streamed-offload apply plan (runtime/executor/stream.py).
    ``g`` is consumed (the classic-L2 mode folds decay into it in
    place)."""
    beta1, beta2 = hyper["beta1"], hyper["beta2"]
    if lib is not None:
        lib.ds_cpu_adam_step(
            p.ctypes.data, g.ctypes.data, m.ctypes.data, v.ctypes.data,
            p.size, hyper["lr"], beta1, beta2, hyper["eps"],
            hyper["weight_decay"], bc1, bc2, adam_w)
        return
    if not adam_w and hyper["weight_decay"]:
        # classic-L2 mode folds decay into the gradient
        # (matches csrc/cpu_adam.cpp adam_w_mode=0)
        g += hyper["weight_decay"] * p
    np.multiply(m, beta1, out=m)
    m += (1.0 - beta1) * g
    np.multiply(v, beta2, out=v)
    v += (1.0 - beta2) * np.square(g)
    update = (m / bc1) / (np.sqrt(v / bc2) + hyper["eps"])
    if adam_w:
        update += hyper["weight_decay"] * p
    p -= hyper["lr"] * update


def chunk_rows(shape, sub_group_size):
    """Row-range chunks of a shard covering at most ``sub_group_size``
    elements each — the now-live ``sub_group_size``: the element chunk
    size of the offload shard pipeline's D2H -> host-Adam work items
    (reference stage3.py sub-group-partitioned optimizer step). Returns
    ``[(row_start, row_stop), ...]``; ``[(0, rows)]`` when one chunk
    suffices. Scalars and tiny shards are a single chunk."""
    if not shape:
        return [(0, 1)]
    rows = int(shape[0])
    row_elems = int(np.prod(shape[1:], dtype=np.int64)) if len(shape) > 1 \
        else 1
    total = rows * row_elems
    if total <= sub_group_size or rows <= 1:
        return [(0, rows)]
    rows_per = max(1, int(sub_group_size // max(row_elems, 1)))
    return [(r, min(r + rows_per, rows)) for r in range(0, rows, rows_per)]
