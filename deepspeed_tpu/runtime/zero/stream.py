"""Streamed ZeRO-3 parameter offload: train beyond-HBM models on one chip.

Reference parity: ZeRO-3 Offload's parameter offload
(`deepspeed/runtime/zero/stage3.py:2281`, `partition_parameters.py:341`)
— the machinery behind the reference's 13B/40B-params-on-one-32GB-V100
story. There, parameters live in CPU memory and are fetched into device
memory per-submodule by the PartitionedParameterCoordinator. Here the
same discipline is re-founded for the jit world:

  * the fp32 master (and Adam moments) live in HOST memory
    (``engine.host_state``), exactly like classic ZeRO-Offload;
  * compute parameters have NO resident device copy at all. Each step
    streams them into HBM one LAYER GROUP at a time through the
    coalesced-transfer batcher (transfer.py), double-buffered: group
    k+1's H2D rides the upload worker while group k's jitted segment
    computes (async dispatch);
  * the forward runs segment-by-segment (embed -> block groups -> head)
    keeping only the group-boundary activations; the backward re-streams
    each group in reverse and computes its VJP (recomputing the group
    forward — the streaming analogue of activation checkpointing, ~1
    extra forward of compute for O(boundary) activation memory);
  * gradients leave the device as ONE packed fp32 buffer per segment
    (async D2H), are split into per-leaf host views, and accumulated —
    tied leaves (GPT-2's wte in embed AND head) sum their contributions;
  * the optimizer step is the host Adam, chunked by ``sub_group_size``.

HBM high-water mark: ~2 layer groups of parameters (current + prefetch)
+ the largest of the embed/head segments + boundary activations + one
segment's gradients — governed by ``stage3_max_live_parameters`` (the
live-parameter budget sizes the groups), NOT by total model size. That
raises the trainable ceiling past params+grads <= HBM
(docs/zero3_offload.md; demonstrated by tests/perf/bench_beyond_hbm.py).
"""
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...utils.logging import logger, log_dist
from .transfer import H2DBatcher


def _full_index(shape):
    """The whole-leaf shard index (streamed masters are unsharded)."""
    return tuple(slice(0, d, None) for d in shape)


# Donation sets of the streamed segment programs, by program key — the
# ONE declaration the jit path and the shard-lint auditor
# (analysis/programs.py) both read, so the audited donation list cannot
# drift from the executed one. Only inputs with an aliasable output are
# donated (XLA donation IS input->output aliasing; donating the dead
# uploaded weights would only buy a "donated buffer unusable" warning):
#
#   * ``h_grad`` donates the final boundary activation (arg 1) into its
#     own cotangent d_x — the (B, S, d) head-input buffer stops
#     double-residing during the loss/backward segment;
#   * ``g_bwd`` donates the incoming cotangent dx (arg 2) into d_xi —
#     the backward sweep updates its gradient wave in place instead of
#     holding two (B, S, d) buffers per group hop.
#
# Forward segments donate nothing: their activation inputs are KEPT as
# boundary activations for the backward recompute. Donation frees one
# (B, S, d) compute-dtype buffer per backward hop plus one at the head
# — at the PR 4 bench shapes (batch 8 x seq 1024 x d_model 1600, bf16)
# that is ~26 MB less live HBM through the entire backward sweep.
STREAM_DONATE = {
    "e_fwd": (), "g_fwd": (), "h_grad": (1,), "g_bwd": (2,), "e_bwd": (),
}


def _numel(tree):
    return sum(int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
               for leaf in jax.tree_util.tree_leaves(tree))


class StreamedOffloadRunner:
    """Drives the streamed train/eval step for one engine.

    The engine owns the host master/moment registry
    (``host_state["shard_leaves"]``, one full-leaf entry per master
    leaf); the runner re-derives its segment views from it each step, so
    a checkpoint load (which replaces the arrays) needs no rebinding
    hook.
    """

    def __init__(self, engine):
        self.engine = engine
        self.spec = engine.model.stream_spec
        if self.spec is None:
            raise ValueError(
                "zero_optimization.cpu_offload_params needs a model with "
                "a stream_spec (runtime/model.py StreamSpec); {} does "
                "not expose one".format(engine.model.name))
        if jax.process_count() > 1:
            raise NotImplementedError(
                "streamed parameter offload is single-process (multi-"
                "process runs keep classic cpu_offload)")
        self.mesh = engine.mesh
        self.cdtype = np.dtype(engine.compute_dtype)
        self._devices = list(self.mesh.devices.flat)
        self._replicated = NamedSharding(self.mesh, P())
        self._jit_cache = {}
        self._grad_bufs = None
        self._micro_finites = []
        self._micro_sumsqs = []
        self._micros_in_step = 0
        self.phase_times = {}
        # per-step upload accounting for telemetry (transfer_snapshot):
        # bucket occupancy + live-param upload volume, T3-style
        self._step_upload_batches = 0
        self._step_upload_elems = 0
        self._segment_upload_bytes_peak = 0
        # comm.collective_matmul composes with streaming through the
        # MODEL config, not the params: uploads land replicated, so the
        # ZeRO-3 ring gather has nothing to do here (the engine resolves
        # _cm_zero3 False under cpu_offload_params), but a TP model axis
        # still routes the segments' qkv/fc/proj GEMMs through the fused
        # ring ops — the segment programs built by _run pick the binding
        # up from the config at trace time.
        self.collective_matmul = getattr(
            getattr(engine.model, "config", None), "collective_matmul",
            None) is not None
        if self.collective_matmul:
            log_dist(
                "streamed offload: collective_matmul binding live — "
                "segment TP GEMMs run ring-fused", ranks=[0])
        self._plan_groups()

    # ------------------------------------------------------------ planning
    def _host_trees(self):
        """(master, exp_avg, exp_avg_sq) fp32 numpy trees, views of the
        engine's host_state registry."""
        hs = self.engine.host_state
        td = hs["treedef"]
        return (td.unflatten([s[0][1] for s in hs["shard_leaves"]]),
                td.unflatten([s[0][2] for s in hs["shard_leaves"]]),
                td.unflatten([s[0][3] for s in hs["shard_leaves"]]))

    def _plan_groups(self):
        """Size layer groups so ~2 groups (live + prefetched) plus the
        larger terminal segment fit ``stage3_max_live_parameters``."""
        masters, _, _ = self._host_trees()
        embed_t, blocks, head_t = self.spec.split(masters)
        self.n_layers = len(blocks)
        block_elems = [_numel(b) for b in blocks]
        terminal = max(_numel(embed_t), _numel(head_t))
        budget = self.engine.zero_plan.max_live_parameters
        if budget is None:
            budget = 10 ** 9
        per_group = max((budget - terminal) // 2, 1)
        groups, start, acc = [], 0, 0
        for i, n in enumerate(block_elems):
            if i > start and acc + n > per_group:
                groups.append((start, i))
                start, acc = i, 0
            acc += n
        groups.append((start, len(blocks)))
        self.groups = groups
        min_live = 2 * max(block_elems) + terminal
        if budget < min_live:
            logger.warning(
                "stage3_max_live_parameters=%d is below the streamed "
                "minimum for this model (~%d: two 1-layer groups + the "
                "largest terminal segment); streaming proceeds at that "
                "minimum", budget, min_live)
        log_dist(
            "streamed offload: {} layers in {} groups (budget {:,} "
            "elements, terminal {:,})".format(
                self.n_layers, len(groups), budget, terminal), ranks=[0])

    def release(self):
        """Drop this runner's compiled programs and live device buffers.
        ``engine.close()`` calls it on elastic teardown so the outgoing
        topology's HBM is free before the replacement engine compiles;
        the runner stays structurally valid (a later step would simply
        re-trace)."""
        self._jit_cache.clear()
        self._grad_bufs = None
        self._micro_finites = []
        self._micro_sumsqs = []

    # ------------------------------------------------------------- uploads
    def _start_upload(self, leaves):
        """Queue a segment's host leaves for coalesced upload to every
        mesh device (replicated); packing+device_put ride the background
        upload worker so they overlap the current segment's compute."""
        eng = self.engine
        batcher = H2DBatcher(eng._h2d_bucket_elems, self.cdtype,
                             pool=eng._upload_pool(),
                             jit_cache=eng._h2d_split_cache())
        for li, arr in enumerate(leaves):
            for dev in self._devices:
                batcher.add(li, arr, dev)
        batcher.flush()
        return batcher, [np.shape(a) for a in leaves]

    def _finish_upload(self, pending, bill_wait=True):
        """Block on a queued upload; return replicated global arrays.
        ``bill_wait=False`` when the executor runs this on its h2d
        worker — there the EXPOSED wait is billed by the scheduler at
        the consuming compute segment, so billing the worker's own wall
        here would double-count it."""
        t0 = time.time()
        batcher, shapes = pending
        res = batcher.finish()
        out = []
        for li, shape in enumerate(shapes):
            singles = list(res[li].values())
            out.append(jax.make_array_from_single_device_arrays(
                shape, self._replicated, singles))
        if bill_wait:
            self.phase_times["h2d_wait_s"] = \
                self.phase_times.get("h2d_wait_s", 0.0) + \
                (time.time() - t0)
        # upload accounting (per device replica; telemetry snapshot)
        elems = sum(int(np.prod(s)) if s else 1 for s in shapes)
        self._step_upload_batches += batcher.batches
        self._step_upload_elems += elems * len(self._devices)
        self._segment_upload_bytes_peak = max(
            self._segment_upload_bytes_peak,
            elems * self.cdtype.itemsize)
        return tuple(out)

    # ------------------------------------------------------------ jit fns
    def _jit(self, key, builder):
        if key not in self._jit_cache:
            # donation is gated off the CPU rung like transfer.py's
            # split program: CPU cannot alias the buffers and warns on
            # every call; the declared (accelerator) set is what the
            # shard-lint auditor verifies
            from ..executor.jit import jit_program
            donate = STREAM_DONATE.get(key[0], ()) \
                if jax.default_backend() != "cpu" else ()
            self._jit_cache[key] = jit_program(builder(), donate=donate)
        return self._jit_cache[key]

    def _run(self, key, builder, *args):
        """Invoke one streamed-segment program, accumulating its
        cost_analysis flops into the engine's step window when telemetry
        is live (cached per key — one lowering, then a dict lookup)."""
        fn = self._jit(key, builder)
        self.engine._tele_add_flops(("stream",) + tuple(key), fn, *args)
        return fn(*args)

    def transfer_snapshot(self, exec_stats=None):
        """Per-step upload/overlap stats for the telemetry record in
        the unified ``SEGMENT_KEYS`` schema (telemetry/record.py — the
        same shape the classic-offload executor stats use, validated by
        bin/check_bench_schema.py): T3-style overlap efficiency, bucket
        occupancy of the coalesced H2D batcher, and the executed plan's
        per-kind walls when the engine's PlanExecutor ran this step.
        Read-only — safe as a debugging probe; the telemetry emit path
        resets the per-step counters afterwards via
        reset_step_counters()."""
        eng = self.engine
        phases = getattr(eng, "offload_phase_times", None) or {}
        compute = sum(phases.get(k, 0.0) for k in
                      ("compute_fwd_s", "compute_bwd_s", "host_adam_s"))
        waits = sum(phases.get(k, 0.0) for k in
                    ("h2d_wait_s", "d2h_grads_s"))
        bucket_elems = eng._h2d_bucket_elems
        batches = self._step_upload_batches
        exec_stats = exec_stats or {}
        snap = {
            "plan_segments": int(exec_stats.get("plan_segments", 0)),
            "per_kind": exec_stats.get("per_kind", {}),
            "upload_batches": batches,
            "upload_elems": self._step_upload_elems,
            "upload_bytes": self._step_upload_elems *
            self.cdtype.itemsize,
            "segment_upload_bytes_peak": self._segment_upload_bytes_peak,
            "bucket_elems": bucket_elems,
            "bucket_occupancy": round(
                self._step_upload_elems / (batches * bucket_elems), 4)
            if batches and bucket_elems else None,
            "overlap_efficiency": round(compute / (compute + waits), 4)
            if (compute + waits) > 0 else None,
            "groups": len(self.groups),
            "collective_matmul": self.collective_matmul,
        }
        return snap

    def reset_step_counters(self):
        """Open the next step's upload-accounting window (called by the
        telemetry emit path after it embeds transfer_snapshot())."""
        self._step_upload_batches = 0
        self._step_upload_elems = 0
        self._segment_upload_bytes_peak = 0

    @staticmethod
    def _pack_grads(grad_leaves, finite, sumsq):
        """Segment gradients -> ONE fp32 vector [grads..., finite,
        sumsq]: a single D2H fetch carries the grads and the overflow/
        norm reductions."""
        flats = [g.astype(jnp.float32).ravel() for g in grad_leaves]
        return jnp.concatenate(
            flats + [finite.astype(jnp.float32)[None], sumsq[None]])

    @staticmethod
    def _finite_sumsq(grad_leaves, inv_scale):
        finite = jnp.bool_(True)
        sumsq = jnp.float32(0)
        for g in grad_leaves:
            finite = jnp.logical_and(finite, jnp.isfinite(g).all())
            g32 = g.astype(jnp.float32) * inv_scale
            sumsq = sumsq + jnp.sum(g32 * g32)
        return finite, sumsq

    def _embed_fwd_fn(self, e_def, has_rng):
        spec = self.spec

        def fn(e_leaves, batch, key):
            et = jax.tree_util.tree_unflatten(e_def, list(e_leaves))
            return spec.embed_apply(et, batch,
                                    key if has_rng else None, True)

        return fn

    def _group_fwd_fn(self, b_defs, has_rng):
        spec = self.spec

        def fn(b_leaves_tuple, x, keys):
            for i, (bdef, bl) in enumerate(zip(b_defs, b_leaves_tuple)):
                bt = jax.tree_util.tree_unflatten(bdef, list(bl))
                x = spec.block_apply(bt, x,
                                     keys[i] if has_rng else None, True)
            return x

        return fn

    def _group_bwd_fn(self, b_defs, has_rng):
        fwd = self._group_fwd_fn(b_defs, has_rng)
        pack = self._pack_grads
        fs = self._finite_sumsq

        def fn(b_leaves_tuple, x_in, dx, keys, inv_scale):
            _, vjp = jax.vjp(lambda bl, xi: fwd(bl, xi, keys),
                             b_leaves_tuple, x_in)
            d_bl, d_xi = vjp(dx)
            leaves = [g for bl in d_bl for g in bl]
            finite, sumsq = fs(leaves, inv_scale)
            return d_xi, pack(leaves, finite, sumsq)

        return fn

    def _head_grad_fn(self, h_def, has_rng):
        spec = self.spec
        pack = self._pack_grads
        fs = self._finite_sumsq

        def fn(h_leaves, x, batch, key, scale, inv_scale):
            def loss_fn(hl, xx):
                ht = jax.tree_util.tree_unflatten(h_def, list(hl))
                loss = spec.head_apply(ht, xx, batch,
                                       key if has_rng else None, True)
                return loss.astype(jnp.float32) * scale, loss

            (_, loss), (d_h, d_x) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(h_leaves, x)
            finite, sumsq = fs(list(d_h), inv_scale)
            return loss, d_x, pack(list(d_h), finite, sumsq)

        return fn

    def _embed_bwd_fn(self, e_def, has_rng):
        spec = self.spec
        pack = self._pack_grads
        fs = self._finite_sumsq

        def fn(e_leaves, batch, dx, key, inv_scale):
            _, vjp = jax.vjp(
                lambda el: spec.embed_apply(
                    jax.tree_util.tree_unflatten(e_def, list(el)), batch,
                    key if has_rng else None, True), e_leaves)
            (d_el,) = vjp(dx)
            finite, sumsq = fs(list(d_el), inv_scale)
            return pack(list(d_el), finite, sumsq)

        return fn

    def _eval_fn(self, e_def, b_defs_by_k, h_def):
        """Segment-streamed eval loss (dropout off, no grads)."""
        spec = self.spec

        def embed(e_leaves, batch):
            et = jax.tree_util.tree_unflatten(e_def, list(e_leaves))
            return spec.embed_apply(et, batch, None, False)

        def group(b_defs):
            def fn(b_leaves_tuple, x):
                for bdef, bl in zip(b_defs, b_leaves_tuple):
                    bt = jax.tree_util.tree_unflatten(bdef, list(bl))
                    x = spec.block_apply(bt, x, None, False)
                return x
            return fn

        def head(h_leaves, x, batch):
            ht = jax.tree_util.tree_unflatten(h_def, list(h_leaves))
            return spec.head_apply(ht, x, batch, None, False)

        return embed, group, head

    # ------------------------------------------------------------ binding
    def _bind(self):
        """Per-step registry: segment views of the host master/moments
        plus the slot map that dedupes shared (tied) leaves."""
        masters, ms, vs = self._host_trees()
        e_m, b_m, h_m = self.spec.split(masters)
        e_mm, b_mm, h_mm = self.spec.split(ms)
        e_mv, b_mv, h_mv = self.spec.split(vs)

        self._slots = []            # (param, exp_avg, exp_avg_sq)
        slot_of = {}
        def register(tree, m_tree, v_tree):
            leaves, tdef = jax.tree_util.tree_flatten(tree)
            m_leaves = tdef.flatten_up_to(m_tree)
            v_leaves = tdef.flatten_up_to(v_tree)
            idxs = []
            for p, m, v in zip(leaves, m_leaves, v_leaves):
                if id(p) not in slot_of:
                    slot_of[id(p)] = len(self._slots)
                    self._slots.append((p, m, v))
                idxs.append(slot_of[id(p)])
            return leaves, tdef, idxs

        self._e_leaves, self._e_def, self._e_slots = register(
            e_m, e_mm, e_mv)
        self._b_leaves, self._b_defs, self._b_slots = [], [], []
        for bt, bmt, bvt in zip(b_m, b_mm, b_mv):
            lv, td, ix = register(bt, bmt, bvt)
            self._b_leaves.append(lv)
            self._b_defs.append(td)
            self._b_slots.append(ix)
        self._h_leaves, self._h_def, self._h_slots = register(
            h_m, h_mm, h_mv)
        # tied leaves (one slot referenced from 2+ segments): their
        # per-segment sumsq shortcut is invalid (||a||^2+||b||^2 !=
        # ||a+b||^2), so apply_step must price the accumulated buffers
        n_refs = (len(self._e_slots) + len(self._h_slots)
                  + sum(len(ix) for ix in self._b_slots))
        self._has_shared_slots = n_refs > len(self._slots)
        if self._grad_bufs is None or \
                len(self._grad_bufs) != len(self._slots):
            self._grad_bufs = [None] * len(self._slots)

    def _group_leaves(self, g):
        start, stop = self.groups[g]
        return [leaf for i in range(start, stop)
                for leaf in self._b_leaves[i]]

    # ------------------------------------------------------------- fetch
    def _accumulate_fetched(self, host, slot_idxs, shapes):
        """Split one fetched packed grad vector into per-leaf host views
        and accumulate per slot; returns the packed (finite, sumsq)
        tail. Called by the executor's ``resolve`` segment in the
        bespoke fetch order (runtime/executor/stream.py)."""
        off = 0
        for slot, shape in zip(slot_idxs, shapes):
            n = int(np.prod(shape)) if shape else 1
            view = host[off:off + n].reshape(shape)
            off += n
            if self._grad_bufs[slot] is None:
                # adopt the fetched view without copying — jax host
                # buffers are read-only, so a later accumulation
                # into this slot (tied leaf / gas>1) copies lazily
                self._grad_bufs[slot] = view
            elif self._grad_bufs[slot].flags.writeable:
                self._grad_bufs[slot] += view
            else:
                self._grad_bufs[slot] = self._grad_bufs[slot] + view
        return bool(host[off] > 0.5), float(host[off + 1])

    # ------------------------------------------------------------- steps
    def micro_step(self, batch, rng):
        """One streamed micro-step: forward + backward with grads
        accumulated into the host buffers. Returns the (unscaled) loss
        as a device scalar. Lowered onto the segment executor
        (runtime/executor/stream.py): the double-buffered upload /
        compute / grad-fetch interleaving that used to be hand-threaded
        here is now a SegmentPlan the scheduler overlaps."""
        from ..executor.stream import run_streamed_micro
        return run_streamed_micro(self, batch, rng)

    def apply_step(self):
        """Host Adam over the accumulated grads (chunked by
        sub_group_size), with classic offload's overflow-skip
        semantics, lowered onto the segment executor. Returns the
        metrics dict; the caller updates the scaler."""
        from ..executor.stream import run_streamed_apply
        return run_streamed_apply(self)

    def zero_grads(self):
        self._grad_bufs = [None] * len(self._grad_bufs or [])
        self._micro_finites = []
        self._micro_sumsqs = []
        self._micros_in_step = 0

    # -------------------------------------------------------------- eval
    def eval_loss(self, batch):
        """Streamed forward-only loss (dropout off)."""
        # _finish_upload bills h2d waits and the per-step upload
        # counters; an eval between optimizer steps must not leak them
        # into the NEXT train record's phases/transfer stats
        saved = (dict(self.phase_times), self._step_upload_batches,
                 self._step_upload_elems, self._segment_upload_bytes_peak)
        try:
            return self._eval_loss(batch)
        finally:
            (self.phase_times, self._step_upload_batches,
             self._step_upload_elems,
             self._segment_upload_bytes_peak) = saved

    def _eval_loss(self, batch):
        self._bind()
        e_def, b_defs, h_def = self._e_def, self._b_defs, self._h_def
        embed, group, head = self._eval_fn(e_def, b_defs, h_def)
        G = len(self.groups)
        pending = self._start_upload(self._e_leaves)
        e_dev = self._finish_upload(pending)
        pending = self._start_upload(self._group_leaves(0)) if G else None
        x = self._jit(("e_eval",), lambda: embed)(tuple(e_dev), batch)
        del e_dev
        for g in range(G):
            bl = self._finish_upload(pending)
            pending = (self._start_upload(self._group_leaves(g + 1))
                       if g + 1 < G
                       else self._start_upload(self._h_leaves))
            start, stop = self.groups[g]
            fn = self._jit(("g_eval", tuple(b_defs[start:stop])),
                           lambda: group(tuple(b_defs[start:stop])))
            x = fn(self._split_group(bl, g), x)
            del bl
        h_dev = self._finish_upload(pending)
        return self._jit(("h_eval",), lambda: head)(tuple(h_dev), x,
                                                    batch)

    def _split_group(self, flat_leaves, g):
        """Flat uploaded leaf tuple -> tuple of per-block leaf tuples."""
        start, stop = self.groups[g]
        out, off = [], 0
        for i in range(start, stop):
            n = len(self._b_leaves[i])
            out.append(tuple(flat_leaves[off:off + n]))
            off += n
        return tuple(out)
