"""Model container: the nn.Module-equivalent handed to ``initialize()``.

The reference wraps a ``torch.nn.Module`` whose ``forward(*inputs)`` returns
the loss (engine.py:886-929). Here a model is a pure apply function plus a
params pytree. Flax modules are adapted automatically.
"""
import inspect


class StreamSpec:
    """Layer-group decomposition contract for streamed parameter offload
    (``zero_optimization.cpu_offload_params``; runtime/zero/stream.py).

    A model that can be trained beyond-HBM exposes its forward as three
    jittable segments the runner streams parameters into one layer group
    at a time:

      ``split(params) -> (embed_tree, [block_tree, ...], head_tree)``
        Restructure the params tree into an embedding segment, per-layer
        block segments, and a head segment. Leaf VALUES must be the
        original tree's objects — a tied weight appearing in two segments
        (e.g. GPT-2's ``wte`` in embed and head) must be the SAME object,
        so the runner can sum both gradient contributions and step the
        master once.
      ``embed_apply(embed_tree, batch, rng, train) -> x``
      ``block_apply(block_tree, x, rng, train) -> x``      (one layer)
      ``head_apply(head_tree, x, batch, rng, train) -> loss``  (fp32 scalar)

    ``batch`` is the full input tuple the engine received (the spec picks
    what each segment needs, e.g. ids for embed, labels for head). The
    composition ``head(blocks(embed(batch)))`` must equal the model's
    ``apply_fn`` loss so the streamed step matches the monolithic one.
    """

    def __init__(self, split, embed_apply, block_apply, head_apply):
        self.split = split
        self.embed_apply = embed_apply
        self.block_apply = block_apply
        self.head_apply = head_apply


class Model:
    """(apply_fn, params) pair.

    ``apply_fn(params, *inputs)`` must return the scalar loss (training
    convention, as the reference's ``module(*inputs)``), or a tuple whose
    first element is the loss. If the function accepts an ``rng`` keyword the
    engine threads a fresh PRNG key per micro-step (dropout etc.); if it
    accepts ``train`` the engine passes the current mode.

    ``partition_spec_fn(path, shape) -> PartitionSpec|None`` may be provided
    for tensor-parallel parameter layouts.
    """

    def __init__(self, apply_fn, params, partition_spec_fn=None, name=None):
        self.apply_fn = apply_fn
        self.params = params
        self.partition_spec_fn = partition_spec_fn
        # optional StreamSpec for streamed parameter offload
        # (cpu_offload_params); models attach it post-construction
        self.stream_spec = None
        self.name = name or getattr(apply_fn, "__name__", "model")
        sig_params = _signature_params(apply_fn)
        self.accepts_rng = "rng" in sig_params or "rngs" in sig_params
        self.rng_kwarg = "rngs" if "rngs" in sig_params else "rng"
        # Mode kwarg: either train=bool or the flax-common deterministic=bool.
        if "train" in sig_params:
            self.mode_kwarg = "train"
        elif "deterministic" in sig_params:
            self.mode_kwarg = "deterministic"
        else:
            self.mode_kwarg = None
        self.accepts_kwargs = any(
            p.kind == inspect.Parameter.VAR_KEYWORD for p in sig_params.values())
        self.param_names = set(sig_params)

    def accepts_kwarg(self, name):
        return self.accepts_kwargs or name in self.param_names

    def mode_kwargs(self, train):
        if self.mode_kwarg == "train":
            return {"train": train}
        if self.mode_kwarg == "deterministic":
            return {"deterministic": not train}
        return {}

    def rng_kwargs(self, rng):
        if not self.accepts_rng:
            return {}
        if self.rng_kwarg == "rngs":
            return {"rngs": {"dropout": rng}}
        return {"rng": rng}


def _signature_params(fn):
    try:
        return inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return {}


def as_model(model, model_parameters=None):
    """Coerce user input to a :class:`Model`.

    Accepts: a Model; a flax linen Module (+ params/variables in
    ``model_parameters``); or a bare callable (+ params).
    """
    if isinstance(model, Model):
        return model

    try:
        from flax import linen as nn
        is_flax = isinstance(model, nn.Module)
    except ImportError:
        is_flax = False

    if is_flax:
        assert model_parameters is not None, \
            "flax modules require model_parameters (params or variables dict)"
        variables = model_parameters
        if not (isinstance(variables, dict) and "params" in variables):
            variables = {"params": model_parameters}

        def apply_fn(params, *inputs, **kwargs):
            vs = dict(variables)
            vs["params"] = params
            return model.apply(vs, *inputs, **kwargs)

        sig = _signature_params(model.__call__)
        m = Model(apply_fn, variables["params"],
                  name=type(model).__name__)
        m.accepts_rng = True  # flax apply always takes rngs
        m.rng_kwarg = "rngs"
        if "train" in sig:
            m.mode_kwarg = "train"
        elif "deterministic" in sig:
            m.mode_kwarg = "deterministic"
        else:
            m.mode_kwarg = None
        return m

    if callable(model):
        params = model_parameters
        if params is None:
            params = getattr(model, "params", None)
        assert params is not None, \
            "callable models require model_parameters (a params pytree)"
        return Model(model, params)

    raise TypeError("Cannot interpret model of type {}".format(type(model)))
