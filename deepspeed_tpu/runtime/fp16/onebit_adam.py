"""1-bit Adam: communication-compressed Adam with error feedback.

Reference parity: deepspeed/runtime/fp16/onebit/adam.py. Two phases:

  * warmup (< ``freeze_step``): exact Adam — the per-worker local
    gradients are averaged at full precision (or through the
    in-collective int8 ring when ``comm.quantized_collectives`` is on);
  * compression (>= ``freeze_step``): the variance (``exp_avg_sq``) is
    FROZEN and the *momentum* is what crosses the wire: each worker
    updates its local momentum from its LOCAL gradient, sign-compresses
    it with persistent fp32 worker error feedback, and the exchange runs
    as a real ``shard_map`` reduce-scatter / all-gather pair
    (runtime/comm/onebit.py) — ``all_to_all`` of sign-bit chunks, server
    averaging, server-error-compensated re-compression, ``all_gather``
    back — so GSPMD sees 1-bit collectives and the wire moves ``n/8``
    bytes where fp32 moved ``4n``.

The momentum lives as ONE flat fused buffer (``exp_avg["_flat"]``, the
reference fuses its buckets the same way) replicated across the data
axis; worker/server error state is per-worker (leading ``world`` dim,
sharded one row per device) and rides checkpoints inside the optimizer
state like any other moment — save/resume is bit-exact (the engine
resets both error tensors on overflow, like qg_error). The engine feeds
this optimizer STACKED local gradients (leaves ``(world, *shape)``) from
its local-grad ``shard_map`` micro step; ``frozen`` is compiled in
host-side by the engine (one program per regime — a warmup run never
executes compression code, and the transition is a plain re-jit over
identical state).

Reference-key surface (mirrored, docs/onebit_adam.md): ``freeze_step``
is honored; ``cuda_aware=True`` is REJECTED loudly (there is no CUDA
transport — the exchange rides ICI through shard_map); NCCL/MPI
``comm_backend_name`` values are reinterpreted to the XLA transport with
a loud warning.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ...ops.adam.fused_adam import FusedAdam
from ...utils.logging import logger
from ..comm.onebit import (onebit_all_gather_local, onebit_padded_size,
                           onebit_reduce_scatter_local)
from ..comm.quantize import FusedFlatLayout


class OnebitAdam(FusedAdam):
    name = "onebitadam"
    # ZeRO stages 1-2 are supported (the engine keeps exp_avg replicated
    # and the error state per-worker; master/exp_avg_sq shard normally);
    # stage 3 is rejected by the engine — data-sharded compute params
    # cannot feed the local-grad shard_map body.
    supports_zero = True
    # the engine zeroes these opt-state subtrees on an overflowed step
    # (an overflow window compressed inf/nan — the residuals are
    # poisoned), mirroring the qgZ error reset
    error_state_keys = ("worker_error", "server_error")

    def __init__(self, lr=1e-3, freeze_step=100000, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 max_coeff=None, min_coeff=None, amsgrad=False,
                 cuda_aware=False, mesh=None, comm_backend_name="xla",
                 **kwargs):
        kwargs.pop("use_pallas", None)
        super().__init__(lr=lr, bias_correction=bias_correction, betas=betas,
                         eps=eps, adam_w_mode=False, weight_decay=weight_decay,
                         amsgrad=amsgrad, use_pallas=False)
        if cuda_aware:
            raise ValueError(
                "OneBitAdam cuda_aware=true is a CUDA/NCCL transport key "
                "the TPU runtime cannot honor — the compressed exchange "
                "rides ICI through shard_map collectives; remove the key "
                "(docs/onebit_adam.md)")
        if comm_backend_name not in (None, "xla", "shard_map"):
            logger.warning(
                "OneBitAdam comm_backend_name=%r reinterpreted: the "
                "compressed allreduce runs as shard_map collectives over "
                "the mesh's data axis (there is no %s backend here)",
                comm_backend_name, comm_backend_name)
        if max_coeff is not None or min_coeff is not None:
            logger.warning(
                "OneBitAdam max_coeff/min_coeff are 1-bit LAMB "
                "coefficient bounds; OneBitAdam ignores them (reference "
                "parity)")
        self.freeze_step = int(freeze_step)
        self.comm_backend_name = comm_backend_name
        self.mesh = None
        self.axes = None
        self.world_size = 1
        if mesh is not None:
            self.configure_comm(mesh)
        # fused flat-buffer layout (comm.quantize.FusedFlatLayout — the
        # same helper the engine's quantized exchange uses), filled by
        # init_state
        self._layout = None

    # ------------------------------------------------------------ comm setup
    def configure_comm(self, mesh):
        """Bind the exchange to a mesh's data axis (or its hpZ-factored
        sub-axes). Called by the engine after the mesh is final."""
        from ...parallel.topology import (DATA_AXIS, DATA_REPLICA_AXIS,
                                          DATA_SHARD_AXIS)
        self.mesh = mesh
        if DATA_AXIS in mesh.shape:
            self.axes = DATA_AXIS
        elif DATA_SHARD_AXIS in mesh.shape:
            self.axes = tuple(a for a in (DATA_REPLICA_AXIS,
                                          DATA_SHARD_AXIS)
                              if a in mesh.shape)
        else:
            raise ValueError(
                "OneBitAdam needs a data axis to exchange over; mesh has "
                "{}".format(dict(mesh.shape)))
        names = self.axes if isinstance(self.axes, tuple) else (self.axes,)
        self.world_size = int(np.prod([mesh.shape[a] for a in names],
                                      dtype=np.int64))

    def frozen_at(self, step):
        """Whether optimizer step ``step`` (0-based attempted steps — the
        engine's global_steps counter) runs the compressed regime."""
        return int(step) >= self.freeze_step

    # ---------------------------------------------------------------- state
    def init_state(self, params):
        w = self.world_size
        self._layout = FusedFlatLayout(
            params, lambda n: onebit_padded_size(n, w))
        padded = self._layout.padded
        return {
            "step": jnp.zeros((), dtype=jnp.int32),
            "exp_avg": {"_flat": jnp.zeros(padded, jnp.float32)},
            "exp_avg_sq": jax.tree_util.tree_map(
                lambda p: jnp.zeros(np.shape(p), jnp.float32), params),
            "worker_error": {"_flat": jnp.zeros((w, padded),
                                                jnp.float32)},
            "server_error": {"_flat": jnp.zeros((w, padded // w),
                                                jnp.float32)},
        }

    def state_placements(self):
        """Engine placement hints: the fused momentum is replicated
        (every worker compresses the full buffer); the error tensors are
        per-worker — one row per device on the data axis."""
        return {"exp_avg": "replicated", "worker_error": "stacked",
                "server_error": "stacked"}

    def state_dict_names(self):
        return ["exp_avg", "exp_avg_sq", "worker_error", "server_error",
                "step"]

    def reshard_state(self, opt, saved_world, pristine=None):
        """Canonicalise a gathered checkpoint state dict saved at
        ``saved_world`` workers to THIS optimizer's world (the engine's
        elastic-restore hook; called with numpy trees before placement).

        The fused buffers are world-size dependent only through their
        PADDING (``onebit_padded_size(numel, w)``) and, for the error
        tensors, the per-worker row layout — the exchange masks every
        lane >= numel to zero each step (comm/onebit.py), so truncating
        to ``numel`` and re-padding is bitwise lossless:

        * ``exp_avg``: truncate/re-pad the flat momentum — bitwise;
        * ``server_error``: rows concatenate to one flat residual whose
          chunk boundaries move with the world; truncate/re-pad/re-chunk
          keeps every lane's value — bitwise per position;
        * ``worker_error``: per-worker residuals are consumed
          NONLINEARLY (each worker compresses its own ``m_i + we_i``),
          so no M-row layout can stand in for a different N-row one
          once a step runs. Two cases:

          - ``pristine`` (the checkpoint's ``onebit_pristine`` sidecar:
            the original per-worker rows, carried while NO step has
            consumed them) matches this world → the exact decomposition
            is reconstructed bit for bit: an 8→4→8 rescale with no
            steps at 4 restores the 8-way rows exactly;
          - otherwise the rows are summed in fixed index order and
            folded into row 0 (rows 1..M-1 zero): the total residual —
            the conserved quantity of error feedback — is preserved
            bitwise, and the sidecar it stashes on ``self``
            (``_reshard_pristine``) lets the engine re-emit the
            original rows if this host saves before stepping.

        World-agnostic subtrees (``step``, ``exp_avg_sq``) pass through
        untouched. A same-world call (or a state dict without the fused
        buffers — saved under a different optimizer) returns ``opt``
        unchanged."""
        import functools
        if self._layout is None:
            raise RuntimeError(
                "OnebitAdam.reshard_state before init_state (the "
                "flat-buffer layout supplies numel/padding)")
        w_new = self.world_size
        self._reshard_pristine = pristine
        if int(saved_world) == w_new:
            return opt
        fused = ("exp_avg", "worker_error", "server_error")
        if not all(isinstance(opt.get(k), dict) and "_flat" in opt[k]
                   for k in fused):
            return opt
        numel, padded_new = self._layout.numel, self._layout.padded

        def repad(flat):
            flat = np.asarray(flat, np.float32).reshape(-1)[:numel]
            out = np.zeros(padded_new, np.float32)
            out[:numel] = flat
            return out

        out = dict(opt)
        out["exp_avg"] = {"_flat": repad(opt["exp_avg"]["_flat"])}
        out["server_error"] = {"_flat": repad(
            opt["server_error"]["_flat"]).reshape(w_new,
                                                  padded_new // w_new)}
        if pristine is not None and \
                int(pristine.get("world", -1)) == w_new:
            # exact reconstruction: the original w_new-way rows rode
            # the sidecar through the intermediate world untouched
            rows = np.asarray(pristine["rows"], np.float32)
            we = np.zeros((w_new, padded_new), np.float32)
            we[:, :numel] = rows[:, :numel]
            out["worker_error"] = {"_flat": we}
            logger.info(
                "OneBitAdam: resharded error-feedback state %d -> %d "
                "workers (pristine %d-way worker residuals restored "
                "bit-exactly)", int(saved_world), w_new, w_new)
        else:
            rows = [np.asarray(r, np.float32)
                    for r in opt["worker_error"]["_flat"]]
            total = functools.reduce(np.add, rows)  # fixed index order
            we = np.zeros((w_new, padded_new), np.float32)
            we[0] = repad(total)
            out["worker_error"] = {"_flat": we}
            if pristine is None:
                self._reshard_pristine = {
                    "world": int(saved_world),
                    "rows": np.stack([r[:numel] for r in rows]),
                }
            logger.info(
                "OneBitAdam: resharded error-feedback state %d -> %d "
                "workers (momentum/server residual bitwise; worker "
                "residuals folded to their sum, original rows kept as "
                "the pristine sidecar)", int(saved_world), w_new)
        return out

    # ------------------------------------------------------------- update
    def _exchange(self, gflat, m, we, se, beta1, wd_flat):
        """The frozen-phase compressed momentum exchange: per-worker
        momentum update + the shard_map reduce-scatter/all-gather pair.
        Returns (m_new (padded,) replicated, new worker/server error)."""
        from jax.sharding import PartitionSpec as P
        from ...parallel.topology import shard_map_compat
        axes, w = self.axes, self.world_size
        numel, padded = self._layout.numel, self._layout.padded
        with_wd = wd_flat is not None

        def body(g_row, m_in, we_row, se_row, *wd_term):
            g = g_row[0]
            if with_wd:
                g = g + wd_term[0]
            m_w = beta1 * m_in + (jnp.float32(1.0) - beta1) * g
            chunk_mean, cmask, ccount, nwe = onebit_reduce_scatter_local(
                m_w, we_row[0], axes, w, real_size=numel)
            full, nse = onebit_all_gather_local(
                chunk_mean, se_row[0], axes, cmask, ccount)
            mask = (jnp.arange(padded) < numel).astype(jnp.float32)
            return full * mask, nwe[None], nse[None]

        in_specs = (P(axes), P(), P(axes), P(axes)) + \
            ((P(),) if with_wd else ())
        operands = (gflat, m, we, se) + ((wd_flat,) if with_wd else ())
        sharded = shard_map_compat(
            body, mesh=self.mesh, in_specs=in_specs,
            out_specs=(P(), P(axes), P(axes)))
        return sharded(*operands)

    def update(self, grads, state, params, lr, beta1, beta2, eps,
               weight_decay, frozen=False, averaged=False):
        """One 1-bit Adam step.

        ``grads``: STACKED local grads (leaves ``(world, *shape)``) —
        the engine's local-grad micro step — or, with ``averaged=True``
        (warmup only), a plain tree of already-averaged gradients (the
        engine pre-averaged them through quantized collectives).
        ``frozen`` is compiled in host-side by the engine, one program
        per regime; a direct (engine-less) caller gets warmup semantics
        with plain averaging."""
        if self._layout is None:
            raise RuntimeError(
                "OnebitAdam.update before init_state (the flat-buffer "
                "layout is derived from the param tree)")
        if averaged and frozen:
            raise ValueError("averaged grads only apply to the warmup "
                             "regime (frozen exchanges locals)")
        step = state["step"] + 1
        beta1 = jnp.asarray(beta1, jnp.float32)
        beta2 = jnp.asarray(beta2, jnp.float32)
        m = state["exp_avg"]["_flat"]
        we = state["worker_error"]["_flat"]
        se = state["server_error"]["_flat"]
        # weight decay needs the full flat params on every worker for
        # the fused momentum buffer; the engine restricts wd>0 to
        # replicated-param configs (ZeRO stage 0, docs/onebit_adam.md)
        wd = float(self.weight_decay or 0.0)

        if frozen:
            gflat = self._layout.flatten_rows(grads)      # (w, padded)
            wd_flat = jnp.asarray(wd, jnp.float32) * \
                self._layout.flatten(params) if wd else None
            m_new, we_new, se_new = self._exchange(gflat, m, we, se,
                                                   beta1, wd_flat)
            v_new = state["exp_avg_sq"]            # frozen variance
        else:
            # warmup: exact Adam on the worker-averaged gradient — the
            # mean over the stacked rows IS the uncompressed allreduce
            # (GSPMD lowers it on the data axis) unless the engine
            # already averaged through quantized collectives.
            g_mean = self._layout.flatten(grads) if averaged \
                else self._layout.flatten_rows(grads).mean(axis=0)
            if wd:
                g_mean = g_mean + jnp.asarray(wd, jnp.float32) * \
                    self._layout.flatten(params)
            m_new = beta1 * m + (jnp.float32(1.0) - beta1) * g_mean
            g_tree = self._layout.slices(g_mean)
            v_new = jax.tree_util.tree_map(
                lambda v, g: beta2 * v + (jnp.float32(1.0) - beta2) *
                (g * g), state["exp_avg_sq"], g_tree)
            we_new, se_new = we, se

        if self.bias_correction:
            bc1 = 1.0 - jnp.power(beta1, step.astype(jnp.float32))
            bc2 = 1.0 - jnp.power(beta2, step.astype(jnp.float32))
        else:
            bc1 = bc2 = jnp.float32(1.0)

        m_tree = self._layout.slices(m_new)
        new_params = jax.tree_util.tree_map(
            lambda p, mm, vv: (p.astype(jnp.float32) - lr *
                               ((mm / bc1) / (jnp.sqrt(vv / bc2) + eps))
                               ).astype(p.dtype),
            params, m_tree, v_new)
        return new_params, {
            "step": step,
            "exp_avg": {"_flat": m_new},
            "exp_avg_sq": v_new,
            "worker_error": {"_flat": we_new},
            "server_error": {"_flat": se_new},
        }
