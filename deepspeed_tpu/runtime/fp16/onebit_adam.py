"""1-bit Adam: communication-compressed Adam.

Reference parity: deepspeed/runtime/fp16/onebit/adam.py. Two phases:
  * warmup (< freeze_step): exact Adam — full-precision gradient averaging;
  * compression (>= freeze_step): the variance (exp_avg_sq) is frozen and the
    *momentum* is what crosses the wire, sign-compressed with error feedback
    (reference :201-219 via NcclBackend.compressed_allreduce).

Under GSPMD the gradient mean is normally inserted by XLA. To express the
compressed exchange explicitly, the update uses a ``shard_map`` over the
``data`` axis when per-shard gradients are provided; the sign-pack +
all_to_all + allgather pipeline lives in runtime/comm/compressed.py. When
the engine hands us already-averaged global gradients (the default GSPMD
path), compression is mathematically inactive but the variance-freeze
schedule still applies — matching the reference's convergence behavior, with
comm compression engaged once the engine runs in shard_map mode.
"""
import jax
import jax.numpy as jnp

from ...ops.adam.fused_adam import FusedAdam


class OnebitAdam(FusedAdam):
    name = "onebitadam"
    supports_zero = False  # reference restricts to stage < 2

    def __init__(self, lr=1e-3, freeze_step=100000, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 max_coeff=None, min_coeff=None, amsgrad=False,
                 cuda_aware=False, mesh=None, comm_backend_name="xla",
                 **kwargs):
        kwargs.pop("use_pallas", None)
        super().__init__(lr=lr, bias_correction=bias_correction, betas=betas,
                         eps=eps, adam_w_mode=False, weight_decay=weight_decay,
                         amsgrad=amsgrad, use_pallas=False)
        self.freeze_step = int(freeze_step)
        self.mesh = mesh
        self.comm_backend_name = comm_backend_name

    def init_state(self, params):
        state = super().init_state(params)
        # error-feedback accumulator for the compression phase
        state["worker_error"] = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, dtype=jnp.float32), params)
        return state

    def update(self, grads, state, params, lr, beta1, beta2, eps, weight_decay):
        step = state["step"] + 1
        frozen = step > self.freeze_step

        def leaf(p, g, m, v, err):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            g = g + weight_decay * p32
            # Momentum always updates; in the frozen phase the reference
            # exchanges it sign-compressed with error feedback. With global
            # grads the compression is exact (error=0), so the error buffer
            # tracks the compression residual only in shard_map mode.
            m_new = beta1 * m + (1.0 - beta1) * g
            v_new = jnp.where(frozen, v, beta2 * v + (1.0 - beta2) * (g * g))
            if self.bias_correction:
                bc1 = 1.0 - jnp.power(beta1, step.astype(jnp.float32))
                bc2 = 1.0 - jnp.power(beta2, step.astype(jnp.float32))
            else:
                bc1 = bc2 = 1.0
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            return (p32 - lr * update).astype(p.dtype), m_new, v_new, err

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state["exp_avg"])
        flat_v = treedef.flatten_up_to(state["exp_avg_sq"])
        flat_e = treedef.flatten_up_to(state["worker_error"])
        out = [leaf(*xs) for xs in zip(flat_p, flat_g, flat_m, flat_v, flat_e)]
        unflatten = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in out])
        return unflatten(0), {
            "step": step,
            "exp_avg": unflatten(1),
            "exp_avg_sq": unflatten(2),
            "worker_error": unflatten(3),
        }
