"""1-bit Adam: communication-compressed Adam.

Reference parity: deepspeed/runtime/fp16/onebit/adam.py. Two phases:
  * warmup (< freeze_step): exact Adam — full-precision gradient averaging;
  * compression (>= freeze_step): the variance (exp_avg_sq) is frozen and
    the *momentum* is what crosses the wire, sign-compressed with error
    feedback (reference :201-219 via NcclBackend.compressed_allreduce).

The sign-pack + all_to_all + all_gather transport lives in
runtime/comm/compressed.py. Under the engine's GSPMD path gradients arrive
globally averaged, so every rank's momentum is identical and the reference's
compressed allreduce degenerates to its two quantization stages (worker
compress -> server average of equal values -> server compress), each with
its own error-feedback accumulator. That exact degenerate pipeline is what
``update`` applies in the frozen phase — numerics match the reference's
convergence behavior, and the same ``_compress``/``unpack_signs`` kernels
carry the real multi-worker exchange when driven through
``CompressedBackend`` under shard_map.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ...ops.adam.fused_adam import FusedAdam
from ..comm.compressed import masked_compress


def _padded_flat_size(shape):
    n = int(np.prod(shape)) if shape else 1
    return ((n + 7) // 8) * 8


def _quantize_with_feedback(x, worker_error, server_error):
    """Worker-compress then server-compress one buffer, updating both error
    accumulators (the all-equal-workers form of compressed_allreduce_local).
    Pad-lane masking lives in comm.compressed.masked_compress."""
    n = x.size
    padded = worker_error.size
    flat = jnp.pad(x.reshape(-1), (0, padded - n))
    mask = (jnp.arange(padded) < n).astype(jnp.float32)
    corrected = flat + worker_error
    _, _, worker_q, new_worker_error = masked_compress(corrected, mask,
                                                       float(n))
    server_in = worker_q + server_error
    _, _, server_q, new_server_error = masked_compress(server_in, mask,
                                                       float(n))
    return server_q[:n].reshape(x.shape), new_worker_error, new_server_error


class OnebitAdam(FusedAdam):
    name = "onebitadam"
    supports_zero = False  # reference restricts to stage < 2

    def __init__(self, lr=1e-3, freeze_step=100000, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 max_coeff=None, min_coeff=None, amsgrad=False,
                 cuda_aware=False, mesh=None, comm_backend_name="xla",
                 **kwargs):
        kwargs.pop("use_pallas", None)
        super().__init__(lr=lr, bias_correction=bias_correction, betas=betas,
                         eps=eps, adam_w_mode=False, weight_decay=weight_decay,
                         amsgrad=amsgrad, use_pallas=False)
        self.freeze_step = int(freeze_step)
        self.mesh = mesh
        self.comm_backend_name = comm_backend_name

    def init_state(self, params):
        state = super().init_state(params)
        # error-feedback accumulators for the compression phase, padded to
        # the sign-pack lane width
        state["worker_error"] = jax.tree_util.tree_map(
            lambda p: jnp.zeros(_padded_flat_size(p.shape),
                                dtype=jnp.float32), params)
        state["server_error"] = jax.tree_util.tree_map(
            lambda p: jnp.zeros(_padded_flat_size(p.shape),
                                dtype=jnp.float32), params)
        return state

    def update(self, grads, state, params, lr, beta1, beta2, eps, weight_decay):
        step = state["step"] + 1
        frozen = step > self.freeze_step

        def leaf(p, g, m, v, werr, serr):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            g = g + weight_decay * p32
            m_exact = beta1 * m + (1.0 - beta1) * g

            # lax.cond so the warmup phase (typically thousands of steps)
            # never executes the compression pipeline.
            def frozen_branch(args):
                m_ex, v_old, we, se, _ = args
                m_comp, nwe, nse = _quantize_with_feedback(m_ex, we, se)
                return m_comp, v_old, nwe, nse

            def warmup_branch(args):
                m_ex, v_old, we, se, g_ = args
                return (m_ex, beta2 * v_old + (1.0 - beta2) * (g_ * g_),
                        we, se)

            m_new, v_new, new_werr, new_serr = jax.lax.cond(
                frozen, frozen_branch, warmup_branch,
                (m_exact, v, werr, serr, g))
            if self.bias_correction:
                bc1 = 1.0 - jnp.power(beta1, step.astype(jnp.float32))
                bc2 = 1.0 - jnp.power(beta2, step.astype(jnp.float32))
            else:
                bc1 = bc2 = 1.0
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            return ((p32 - lr * update).astype(p.dtype), m_new, v_new,
                    new_werr, new_serr)

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state["exp_avg"])
        flat_v = treedef.flatten_up_to(state["exp_avg_sq"])
        flat_we = treedef.flatten_up_to(state["worker_error"])
        flat_se = treedef.flatten_up_to(state["server_error"])
        out = [leaf(*xs) for xs in zip(flat_p, flat_g, flat_m, flat_v,
                                       flat_we, flat_se)]
        unflatten = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in out])
        return unflatten(0), {
            "step": step,
            "exp_avg": unflatten(1),
            "exp_avg_sq": unflatten(2),
            "worker_error": unflatten(3),
            "server_error": unflatten(4),
        }
