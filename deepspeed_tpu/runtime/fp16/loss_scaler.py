"""Static and dynamic loss scaling as pure functions of a small state pytree.

Reference parity: deepspeed/runtime/fp16/loss_scaler.py (LossScaler :56,
DynamicLossScaler :79). The reference mutates Python attributes per step; here
the scaler state lives inside the jitted train step and is updated
branchlessly with ``jnp.where`` so an overflow-skip step compiles to the same
program as a normal step (SURVEY §7 "hard parts").

Semantics preserved:
  * overflow: if hysteresis exhausted, scale = max(scale/2, min_scale) and
    the hysteresis window restarts on the next overflow; else hysteresis -= 1
  * ``scale_window`` consecutive clean steps: scale *= 2 and hysteresis
    resets to ``delayed_shift``
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"


class LossScalerState(NamedTuple):
    cur_scale: jnp.ndarray        # f32 scalar
    cur_hysteresis: jnp.ndarray   # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    cur_iter: jnp.ndarray         # i32 scalar
    dynamic: bool                 # static python flag (baked into the jit)
    scale_factor: float
    scale_window: int
    delayed_shift: int
    min_scale: float


# Register so that only the four counters are traced leaves; the config
# fields ride along as static aux data (a plain NamedTuple would trace them
# and break `if not state.dynamic` under jit).
jax.tree_util.register_pytree_node(
    LossScalerState,
    lambda s: ((s.cur_scale, s.cur_hysteresis, s.last_overflow_iter,
                s.cur_iter),
               (s.dynamic, s.scale_factor, s.scale_window, s.delayed_shift,
                s.min_scale)),
    lambda aux, children: LossScalerState(*children, *aux))


def create_loss_scaler(static_loss_scale=None, init_scale=2 ** 32,
                       scale_factor=2.0, scale_window=1000, min_scale=1.0,
                       delayed_shift=1):
    """Build initial scaler state. ``static_loss_scale`` > 0 disables dynamics."""
    dynamic = static_loss_scale is None or static_loss_scale == 0
    scale = float(init_scale if dynamic else static_loss_scale)
    return LossScalerState(
        cur_scale=jnp.asarray(scale, dtype=jnp.float32),
        cur_hysteresis=jnp.asarray(delayed_shift, dtype=jnp.int32),
        last_overflow_iter=jnp.asarray(-1, dtype=jnp.int32),
        cur_iter=jnp.asarray(0, dtype=jnp.int32),
        dynamic=dynamic,
        scale_factor=float(scale_factor),
        scale_window=int(scale_window),
        delayed_shift=int(delayed_shift),
        min_scale=float(min_scale),
    )


def loss_scaler_from_config(config):
    """Build from a DeepSpeedConfig's fp16 block."""
    if not getattr(config, "fp16_enabled", False):
        return create_loss_scaler(static_loss_scale=1.0)
    if config.loss_scale and config.loss_scale > 0:
        return create_loss_scaler(static_loss_scale=config.loss_scale)
    args = config.dynamic_loss_scale_args or {}
    return create_loss_scaler(
        static_loss_scale=None,
        init_scale=args.get(INITIAL_LOSS_SCALE, config.initial_dynamic_scale),
        scale_window=args.get(SCALE_WINDOW, 1000),
        min_scale=args.get(MIN_LOSS_SCALE, 1.0),
        delayed_shift=args.get(DELAYED_SHIFT, 1),
    )


def update_scale(state: LossScalerState, has_overflow) -> LossScalerState:
    """One scaler step; ``has_overflow`` is a traced bool. Branchless."""
    if not state.dynamic:
        return state._replace(cur_iter=state.cur_iter + 1)

    has_overflow = jnp.asarray(has_overflow)

    # Overflow path: drop scale only when hysteresis is (or would be) spent.
    hysteresis_spent = jnp.logical_or(state.delayed_shift == 1,
                                      state.cur_hysteresis <= 1)
    dropped_scale = jnp.maximum(state.cur_scale / state.scale_factor,
                                state.min_scale)
    overflow_scale = jnp.where(hysteresis_spent, dropped_scale, state.cur_scale)
    overflow_hysteresis = jnp.where(hysteresis_spent, state.cur_hysteresis,
                                    state.cur_hysteresis - 1)

    # Clean path: grow scale every scale_window clean steps.
    window_elapsed = (state.cur_iter - state.last_overflow_iter) % \
        state.scale_window == 0
    grown_scale = jnp.where(window_elapsed,
                            state.cur_scale * state.scale_factor,
                            state.cur_scale)
    grown_hysteresis = jnp.where(window_elapsed,
                                 jnp.asarray(state.delayed_shift,
                                             dtype=jnp.int32),
                                 state.cur_hysteresis)

    return state._replace(
        cur_scale=jnp.where(has_overflow, overflow_scale, grown_scale),
        cur_hysteresis=jnp.where(has_overflow, overflow_hysteresis,
                                 grown_hysteresis),
        last_overflow_iter=jnp.where(has_overflow, state.cur_iter,
                                     state.last_overflow_iter),
        cur_iter=state.cur_iter + 1,
    )


# Convenience views matching the reference's attribute names.
def loss_scale(state: LossScalerState):
    return state.cur_scale


def backward_scale(loss, state: LossScalerState):
    """Scale a loss before differentiation (reference backward(scaled_loss))."""
    return loss * state.cur_scale
