"""FP16_Optimizer: standalone mixed-precision wrapper.

Reference parity: deepspeed/runtime/fp16/fused_optimizer.py (FP16_Optimizer
:17) and unfused_optimizer.py (FP16_UnfusedOptimizer :17). Inside the
engine this machinery is inlined into the jitted apply-step
(engine._apply_step_fn); these classes exist for users driving an optimizer
directly, with the reference's surface — flat fp32 master copy, overflow
check -> dynamic loss scale update -> unscale/clip -> base step — in
functional form: the torch version's ``backward(loss)`` becomes "hand me
the (scaled) grads", since grads come from jax.value_and_grad, not
autograd tape hooks.

The "fused" vs "unfused" split (flat master buffer vs per-tensor masters,
needed because LAMB wants per-tensor trust ratios) disappears: pytrees are
per-tensor already, and the fused Adam/LAMB kernels consume them directly —
both names are provided, one implementation.
"""
import jax
import jax.numpy as jnp

from ..utils import CheckOverflow, clip_grad_norm_
from . import loss_scaler as ls


class FP16_Optimizer:
    """Functional mixed-precision wrapper around a deepspeed_tpu optimizer
    (FusedAdam / FusedLamb / ...)."""

    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, initial_dynamic_scale=2 ** 32,
                 dynamic_loss_args=None, verbose=False, mpu=None,
                 clip_grad=0.0, fused_adam_legacy=False):
        self.optimizer = init_optimizer
        self.clip_grad = clip_grad
        args = dynamic_loss_args or {}
        if dynamic_loss_scale:
            self.scaler = ls.create_loss_scaler(
                static_loss_scale=None,
                init_scale=args.get("init_scale", initial_dynamic_scale),
                scale_window=args.get("scale_window", 1000),
                min_scale=args.get("min_scale", 1.0),
                delayed_shift=args.get("delayed_shift", 1))
        else:
            self.scaler = ls.create_loss_scaler(
                static_loss_scale=static_loss_scale)
        self.overflow = False
        self._master = None
        self._opt_state = None

    # -- state ---------------------------------------------------------------
    def initialize_state(self, params):
        """fp32 master copy + base optimizer state from (half) params."""
        self._master = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, jnp.float32), params)
        self._opt_state = self.optimizer.init_state(self._master)
        return self._master

    @property
    def loss_scale(self):
        return float(self.scaler.cur_scale)

    @property
    def cur_scale(self):
        return self.scaler.cur_scale

    # -- the reference's backward(loss) half: scale ---------------------------
    def scale_loss(self, loss):
        """Multiply the loss by the current scale before value_and_grad
        (reference backward() :181-186)."""
        return ls.backward_scale(loss, self.scaler)

    # -- step -----------------------------------------------------------------
    def step(self, grads, params):
        """Overflow check -> unscale -> clip -> base step -> recast.

        ``grads`` are SCALED half/float grads of the half ``params``.
        Returns (new_params, overflow: bool). Master/opt state carried
        internally (reference step :33-132).
        """
        if self._master is None:
            self.initialize_state(params)
        overflow = CheckOverflow.has_overflow(grads)
        inv = 1.0 / self.scaler.cur_scale
        grads32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)
        if self.clip_grad > 0:
            grads32, _ = clip_grad_norm_(grads32, self.clip_grad)
        h = self.optimizer.hyperparams()
        new_master, new_opt = self.optimizer.update(
            grads32, self._opt_state, self._master, **h)
        keep = lambda new, old: jax.tree_util.tree_map(
            lambda n, o: jnp.where(overflow, o, n), new, old)
        self._master = keep(new_master, self._master)
        self._opt_state = keep(new_opt, self._opt_state)
        self.scaler = ls.update_scale(self.scaler, overflow)
        self.overflow = bool(overflow)
        new_params = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype), self._master, params)
        return new_params, self.overflow

    # -- checkpoint -----------------------------------------------------------
    def state_dict(self):
        return {
            "dynamic_loss_scale": self.scaler.dynamic,
            "cur_scale": float(self.scaler.cur_scale),
            "cur_iter": int(self.scaler.cur_iter),
            "last_overflow_iter": int(self.scaler.last_overflow_iter),
            "cur_hysteresis": int(self.scaler.cur_hysteresis),
            "optimizer_state_dict": self._opt_state,
            "fp32_groups_flat": self._master,
            "clip_grad": self.clip_grad,
        }

    def load_state_dict(self, sd, load_optimizer_states=True):
        # the full scaler schedule state must survive resume: growth window
        # keys off last_overflow_iter, overflow response off hysteresis
        self.scaler = self.scaler._replace(
            cur_scale=jnp.asarray(sd["cur_scale"], jnp.float32),
            cur_iter=jnp.asarray(sd["cur_iter"], jnp.int32),
            last_overflow_iter=jnp.asarray(
                sd.get("last_overflow_iter", -1), jnp.int32),
            cur_hysteresis=jnp.asarray(
                sd.get("cur_hysteresis", self.scaler.delayed_shift),
                jnp.int32))
        self.clip_grad = sd.get("clip_grad", self.clip_grad)
        if sd.get("fp32_groups_flat") is not None:
            self._master = sd["fp32_groups_flat"]
        if load_optimizer_states and sd.get("optimizer_state_dict") is not None:
            self._opt_state = sd["optimizer_state_dict"]


# Per-tensor-master variant needed for LAMB in the reference
# (unfused_optimizer.py) — identical here, pytrees are per-tensor.
FP16_UnfusedOptimizer = FP16_Optimizer
