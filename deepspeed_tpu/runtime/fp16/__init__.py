from . import loss_scaler
from .fused_optimizer import FP16_Optimizer, FP16_UnfusedOptimizer
from .onebit_adam import OnebitAdam
