"""DeepSpeedEngine: the central training wrapper.

Reference parity: deepspeed/runtime/engine.py (DeepSpeedEngine :97). The
user-facing semantics — ``loss = engine(batch); engine.backward(loss);
engine.step()``, gradient-accumulation boundaries, loss scaling,
overflow-skip, LR schedules, checkpoint save/load — are preserved. The
internals are re-founded for TPU:

  * one fp32-master train-state pytree of ``jax.Array``s, placed with
    NamedShardings computed from the ZeRO stage (zero/partition.py);
  * ``forward`` runs a single jitted value-and-grad micro-step that
    accumulates scaled gradients into a sharded buffer (the reference's
    backward hooks + IPG buckets, stage2.py:585-649, become dataflow);
  * ``step`` runs a jitted apply-step: overflow check (psum'd isfinite),
    unscale, clip, optimizer update on the master shard, branchless
    overflow-skip (``jnp.where``), re-cast/all-gather of compute params, and
    the dynamic loss-scale update — all one XLA program;
  * a fused ``train_batch`` path lax.scans the micro-steps for benchmarks.

No torch, no NCCL: collectives are inserted by XLA from shardings.
"""
import os
import time
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.topology import MeshGrid, DATA_AXIS, build_mesh
from ..utils.logging import logger, log_dist
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from . import checkpointing as ckpt
from .config import DeepSpeedConfig
from .constants import (ADAM_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
                        ROUTE_TRAIN)
from .dataloader import DeepSpeedDataLoader, RepeatingLoader
from .fp16 import loss_scaler as ls
from .lr_schedules import SCHEDULE_CLASSES
from .model import Model, as_model
from .progressive_layer_drop import ProgressiveLayerDrop
from .utils import (CheckOverflow, clip_grad_norm_, get_grad_norm,
                    count_parameters, see_memory_usage)
from .zero.partition import ZeroShardingPlan
from .zero.constants import (
    ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT as ZERO_SUB_GROUP_DEFAULT,
    ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT as ZERO_PREFETCH_DEFAULT)

MEMORY_OPT_ALLREDUCE_SIZE = 500000000

FORWARD_MICRO_TIMER = "forward_microstep"
BACKWARD_MICRO_TIMER = "backward_microstep"
STEP_MICRO_TIMER = "step_microstep"


# the checkpoint-format-defining helpers live with the serialization code;
# aliased here for the engine's many call sites
_shard_key = ckpt.shard_key
_key_to_index = ckpt.key_to_index


def _unique_shard_indices(arr):
    """This process's unique addressable shard indices of a jax array
    (replicated placements collapse to one entry)."""
    seen, out = set(), []
    for sh in arr.addressable_shards:
        key = _shard_key(sh.index)
        if key not in seen:
            seen.add(key)
            out.append(sh.index)
    return out


class DeepSpeedEngine:
    """Wraps a model to provide distributed data-parallel (+ZeRO) training on
    a TPU mesh with the DeepSpeed train API."""

    # ZeRO-Offload D2H prefetch depth (shards in flight ahead of the host
    # Adam); each in-flight copy pins a device staging buffer, so this
    # bounds the extra HBM the overlapped step may use.
    _D2H_WINDOW = 4

    def __init__(self, args=None, model=None, optimizer=None,
                 model_parameters=None, training_data=None, lr_scheduler=None,
                 mpu=None, dist_init_required=None, collate_fn=None,
                 config_params=None, dont_change_device=False, mesh=None):
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.loaded_checkpoint_dp_world_size = None
        self.warn_unscaled_loss = True

        self._resolve_config(args, config_params)
        self._configure_mesh(mpu, mesh)
        self._config = DeepSpeedConfig(self._config_file, mpu=None,
                                       param_dict=self._config_dict,
                                       mesh=self.mesh)
        # transient-IO retry policy for every checkpoint read/write
        # (ds_config "checkpoint" block; process-wide by design — the
        # storage backend is shared, so the last engine configured wins)
        ckpt.set_retry_policy(
            retries=self._config.checkpoint_io_retries,
            backoff_seconds=self._config.checkpoint_io_backoff_seconds)
        # concurrency sanitizer (analysis.concurrency, docs/
        # concurrency.md): installed BEFORE the telemetry subsystems so
        # the recorder/watchdog locks they create come out instrumented;
        # process-global (the lock-order graph spans engines), so a
        # second engine reuses the active instance
        if self._config.analysis_config.concurrency_enabled:
            from ..analysis.concurrency import locksan
            if locksan.current() is None:
                locksan.install(locksan.LockSanitizer(
                    stack_depth=self._config.analysis_config
                    .concurrency_stack_depth))
        self.model = as_model(model, model_parameters)
        # resolved kernel tri-states (observable via telemetry_snapshot,
        # like the serving engine's paged_attention_kernel); None = the
        # ds_config key was absent
        self.flash_attention_backend = None
        self.fused_optimizer_kernel = None
        self._configure_precision()
        self._configure_zero()
        self._configure_comm()
        self._apply_transformer_overrides()
        self._configure_optimizer(optimizer)
        self._configure_lr_scheduler(lr_scheduler)
        self._configure_pld()
        if "activation_checkpointing" in (self._config._param_dict or {}):
            # reference: user calls deepspeed.checkpointing.configure();
            # when the config section is present the engine applies it —
            # unless the user already configured (their kwargs win), and
            # never fatally (configs like contiguous+no-num_checkpoints
            # need the manual call with explicit kwargs)
            from .activation_checkpointing import checkpointing as act_ckpt
            if not act_ckpt.is_configured():
                try:
                    act_ckpt.configure(self.mpu,
                                       deepspeed_config=self._config)
                except Exception as err:  # noqa: BLE001
                    logger.warning(
                        "activation_checkpointing config could not be "
                        "auto-applied (%s); call deepspeed_tpu."
                        "checkpointing.configure() with explicit kwargs",
                        err)
        self._init_state()

        self.training_dataloader = self.deepspeed_io(training_data) \
            if training_data is not None else None

        from ..utils.monitor import SummaryMonitor
        # rank-0 writer (reference :154); gate BEFORE construction so
        # non-writer ranks never create files/handles
        self.monitor = SummaryMonitor.from_config(
            self._config, enabled=jax.process_index() == 0)

        # unified per-step telemetry (docs/telemetry.md): None unless the
        # "telemetry" config section enables it — the hot paths pay one
        # `is not None` check when off
        from ..telemetry import TelemetryCollector
        self.telemetry = TelemetryCollector.from_config(
            self._config, job_name="train", monitor=self.monitor,
            enabled=jax.process_index() == 0)
        self._tele_flops_cache = {}
        self._tele_wire = "unset"
        self._window_t0 = None
        self._window_step = 0
        self._window_tokens = 0
        self._window_flops = 0.0
        self._step_hbm = None
        self._step_path = "micro"
        # segment-plan executor (runtime/executor/, docs/executor.md):
        # every step path runs as a SegmentPlan through one scheduler;
        # runtime.executor "off" = serial oracle, "on"/"auto" = the
        # overlap-constructing schedule (built lazily on first use)
        self._executor_mode = "serial" \
            if self._config.runtime_executor == "off" else "overlap"
        # plan rewrite passes (runtime/executor/rewrite.py): the
        # strict-validated runtime.executor_rewrites dict (enabled,
        # passes, bounds); applied in overlap mode only
        self._executor_rewrites = self._config.runtime_executor_rewrites
        self._plan_executor = None
        # elastic rescale trail (runtime/elastic/): an ElasticRunner
        # swaps in its SHARED events list so the crash bundle's topology
        # section survives engine rebuilds; a never-rescaled engine
        # carries an empty history
        self._rescale_history = []
        self._onebit_pristine = None
        if self.telemetry is not None and \
                self.telemetry.recorder is not None:
            # flight recorder context (docs/diagnostics.md): resolved at
            # DUMP time, so the bundle reflects the state at the crash
            self.telemetry.recorder.set_context(
                "ds_config", lambda: self._config._param_dict)
            self.telemetry.recorder.set_context(
                "engine", self._flight_state)
            self.telemetry.recorder.set_context(
                "topology", self._topology_context)
        self._check_memory_breakdown()

        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu(),
            num_workers=self.dp_world_size,
            steps_per_output=self.steps_per_print(),
            monitor_memory=False)

        self._jit_cache: Dict[Any, Any] = {}
        # first-seen batch shapes, kept as ShapeDtypeStructs so
        # engine.audit() can abstract-eval the step programs without a
        # sample batch (one is-None check per _to_device call)
        self._audit_batch_struct = None
        self._audit_batch_struct_stacked = None
        self._mode = ROUTE_TRAIN
        self._last_loss = None
        self._step_metrics = {}
        self._rng = jax.random.PRNGKey(
            int(os.environ.get("DEEPSPEED_SEED", 42)))

        # sparse embedding-gradient exchange (reference CSR allreduce,
        # engine.py:1285-1341): models opt in via their config (e.g.
        # GPT2Config.sparse_embedding_grads -> ops/sparse_grads.py); the
        # engine records the module names for checkpoint parity and flags
        # a config/model mismatch
        self.csr_tensor_module_names = set()
        model_cfg = getattr(self.model, "config", None)
        if getattr(model_cfg, "sparse_embedding_grads", False):
            # only record when the exchange is actually LIVE: without a
            # nontrivial mesh axis sparse_embedding_lookup falls back to
            # the dense path and the checkpoint must not claim otherwise
            grad_mesh = getattr(model_cfg, "embedding_grad_mesh", None)
            axis_size = (int(dict(grad_mesh.shape).get(DATA_AXIS, 1))
                         if grad_mesh is not None else 1)
            if axis_size > 1:
                self.csr_tensor_module_names.add("wte")
            else:
                logger.warning(
                    "sparse_embedding_grads is set but embedding_grad_mesh "
                    "has no nontrivial '%s' axis — the lookup falls back "
                    "to dense gradients", DATA_AXIS)
        if self.sparse_gradients_enabled() and \
                not self.csr_tensor_module_names:
            logger.warning(
                "sparse_gradients is enabled in ds_config but the model "
                "does not route any embedding through "
                "sparse_embedding_lookup (e.g. "
                "GPT2Config.sparse_embedding_grads=True with "
                "embedding_grad_mesh); gradients stay dense")

        # closed-loop controller (runtime/controller/, docs/
        # controller.md): None unless the strict-validated "controller"
        # section enables it — off is structurally absent (no ledger
        # file, no policies; the emit path pays one is-not-None check).
        # Constructed LAST so its knob bindings see the resolved
        # zero_plan / executor / quantization state.
        self.controller = None
        if self._config.controller_config is not None:
            if self.telemetry is None:
                from ..telemetry.config import warn_or_raise_noop
                warn_or_raise_noop(
                    "controller is enabled but telemetry is not — the "
                    "controller observes/actuates through telemetry "
                    "seams, so it cannot run (enable the telemetry "
                    "section)", self._config.telemetry_config.strict
                    if self._config.telemetry_config else False)
            else:
                from .controller.adapters import attach_train_controller
                self.controller = attach_train_controller(
                    self, self._config.controller_config)

        if self._config.dump_state:
            self._config.print("DeepSpeedEngine configuration")

        n_params = count_parameters(self.state["params"]) \
            if self.state.get("params") is not None else sum(
                int(np.prod(s)) if s else 1
                for s in self.host_state["leaf_shapes"])
        log_dist(
            "DeepSpeedEngine ready: params={:,} zero_stage={} dtype={} "
            "mesh={}".format(n_params,
                             self.zero_optimization_stage(),
                             self.compute_dtype, dict(self.mesh.shape)),
            ranks=[0])

    # ------------------------------------------------------------------ setup
    def _resolve_config(self, args, config_params):
        config_file = None
        config_dict = None
        if config_params is not None:
            if isinstance(config_params, str):
                config_file = config_params
            else:
                config_dict = config_params
        elif args is not None and getattr(args, "deepspeed_config", None):
            config_file = args.deepspeed_config
        assert config_file is not None or config_dict is not None, \
            "DeepSpeed requires --deepspeed_config or a config dict"
        self._config_file = config_file
        self._config_dict = config_dict

    def _configure_mesh(self, mpu, mesh):
        if mesh is not None:
            self.mesh = mesh
        elif mpu is not None and hasattr(mpu, "mesh"):
            self.mesh = mpu.mesh
        elif mpu is not None and hasattr(mpu, "get_model_parallel_world_size"):
            # Foreign (Megatron-style) mpu: honor its model-parallel degree by
            # building a (data, model) mesh (reference engine.py:568-579).
            mp = int(mpu.get_model_parallel_world_size())
            assert jax.device_count() % mp == 0, \
                "device count {} not divisible by model parallel size {}".format(
                    jax.device_count(), mp)
            self.mesh = build_mesh(data=jax.device_count() // mp, model=mp)
        else:
            self.mesh = build_mesh(data=jax.device_count())
        self.grid = mpu if isinstance(mpu, MeshGrid) else None
        # one source of truth for batch-dim sharding; meshes may drop the
        # size-1 data axis (e.g. pure-sequence meshes)
        self._batch_axis = DATA_AXIS if DATA_AXIS in self.mesh.shape else None
        if self._batch_axis is None and jax.process_count() > 1:
            # each process feeds different samples (deepspeed_io), which a
            # replicated batch sharding would silently mis-treat as equal
            raise NotImplementedError(
                "multi-process runs need a 'data' mesh axis to shard the "
                "batch over")
        self.dp_world_size = int(self.mesh.shape.get(DATA_AXIS, 1))
        self.mp_world_size = int(self.mesh.shape.get("model", 1))
        self.global_rank = jax.process_index()
        self.world_size = self.dp_world_size

    def _configure_precision(self):
        if self._config.amp_enabled:
            # Reference routes "amp" through NVIDIA apex (engine.py:580-600);
            # on TPU the equivalent mixed-precision mode is bf16 compute with
            # fp32 master state, so amp is reinterpreted — loudly, because any
            # apex-specific opts (opt_level, ...) are dropped.
            log_dist(
                "'amp' config block is reinterpreted as bf16 mixed precision "
                "on TPU; amp-specific options {} are ignored".format(
                    self._config.amp_params or "{}"), ranks=[0])
        if self._config.bf16_enabled or self._config.amp_enabled:
            self.compute_dtype = jnp.bfloat16
        elif self._config.fp16_enabled:
            # On TPU bf16 is the fast half type; fp16 kept for parity runs on
            # other backends (reference does module.half(), engine.py:560).
            self.compute_dtype = jnp.float16 \
                if jax.default_backend() != "tpu" else jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32
        self.mixed_precision = self.compute_dtype != jnp.float32

    def _configure_zero(self):
        zc = self._config.zero_config
        stage = self._config.zero_optimization_stage
        hpz = int(zc.hierarchical_partition or 0)
        if hpz > 1 and not self._config.zero_enabled:
            logger.warning(
                "zero_hierarchical_partition=%d ignored: ZeRO is "
                "disabled (zero_optimization.stage=0)", hpz)
        if hpz > 1 and self._config.zero_enabled:
            # hpZ (ZeRO++ hierarchical partitioning): factor the data axis
            # into (replica, shard) sub-axes so stage-3 params shard only
            # within the shard group and per-step gathers ride the short
            # intra-replica hop. Placement of master/opt/grad state is
            # unchanged (they shard over BOTH sub-axes).
            from ..parallel.topology import (factor_data_axis, PIPE_AXIS,
                                             DATA_REPLICA_AXIS,
                                             DATA_SHARD_AXIS)
            if stage < 3:
                logger.warning(
                    "zero_hierarchical_partition=%d has no effect below "
                    "ZeRO stage 3 (params are not data-sharded); ignoring",
                    hpz)
            elif PIPE_AXIS in self.mesh.shape:
                raise ValueError(
                    "zero_hierarchical_partition is not a certified "
                    "combination with pipeline parallelism (the pipe "
                    "loop's shard_map specs name the flat 'data' axis)")
            elif self._batch_axis != DATA_AXIS:
                raise ValueError(
                    "zero_hierarchical_partition needs a 'data' mesh axis "
                    "to factor; mesh has {}".format(dict(self.mesh.shape)))
            else:
                self.mesh = factor_data_axis(self.mesh, hpz)
                self._batch_axis = (DATA_REPLICA_AXIS, DATA_SHARD_AXIS)
        # comm.quantized_collectives.hierarchical=N: factor the data axis
        # for the two-level in-collective decomposition (2504.18658) even
        # below stage 3 (where hpZ itself is inert). Placement of
        # master/grad state is identical to the flat plan (it shards over
        # BOTH sub-axes); only the collective decomposition changes.
        qc = self._config.comm_config.quantized_collectives
        if qc.enabled and qc.hierarchical >= 2:
            from ..parallel.topology import (factor_data_axis as _factor,
                                             DATA_REPLICA_AXIS as _DR,
                                             DATA_SHARD_AXIS as _DS,
                                             DATA_AXIS as _DA)
            if _DS in self.mesh.shape:
                if int(self.mesh.shape[_DS]) != qc.hierarchical:
                    raise ValueError(
                        "comm.quantized_collectives.hierarchical={} "
                        "conflicts with the hpZ-factored mesh (data_shard"
                        "={}); use hierarchical=0 to follow the mesh"
                        .format(qc.hierarchical,
                                int(self.mesh.shape[_DS])))
            elif self._batch_axis != _DA:
                raise ValueError(
                    "comm.quantized_collectives.hierarchical needs a "
                    "'data' mesh axis to factor; mesh has {}".format(
                        dict(self.mesh.shape)))
            elif int(self.mesh.shape[_DA]) <= 1:
                # leave the mesh flat: _configure_quantized_collectives
                # warns the documented dp<=1 no-op (raises under strict)
                pass
            elif int(self.mesh.shape[_DA]) % qc.hierarchical != 0:
                # name OUR key — factor_data_axis's own error names
                # zero_hierarchical_partition, which the user never set
                raise ValueError(
                    "comm.quantized_collectives.hierarchical={} must "
                    "divide the data-parallel degree {}".format(
                        qc.hierarchical, int(self.mesh.shape[_DA])))
            else:
                self.mesh = _factor(self.mesh, qc.hierarchical)
                self._batch_axis = (_DR, _DS)
        self.zero_plan = ZeroShardingPlan(
            self.mesh, stage=stage,
            param_persistence_threshold=zc.param_persistence_threshold,
            model_spec_fn=self.model.partition_spec_fn,
            max_live_parameters=(int(zc.max_live_parameters)
                                 if stage >= 3 and zc.max_live_parameters
                                 is not None else None))
        if self.zero_plan.max_live_parameters is not None and \
                self.model.params is not None:
            persistent, demoted = \
                self.zero_plan.configure_live_budget(self.model.params)
            if demoted:
                log_dist(
                    "stage3_max_live_parameters={:,}: demoted {} "
                    "persistent leaves to data-sharded (persistent set "
                    "now {:,} elements)".format(
                        self.zero_plan.max_live_parameters, len(demoted),
                        persistent), ranks=[0])
            if persistent is not None and \
                    persistent > self.zero_plan.max_live_parameters:
                self._zero_key_noop(
                    "stage3_max_live_parameters",
                    "un-shardable persistent parameters alone hold {:,} "
                    "elements > budget {:,} — the budget cannot be "
                    "honored on this model/mesh".format(
                        persistent, self.zero_plan.max_live_parameters))
        self._validate_zero_keys(zc, stage)
        # qwZ / qgZ (ZeRO++ quantized collectives): resolved here so the
        # jitted step builders can close over plain bools
        self._qwz_enabled = bool(zc.quantized_weights) and stage >= 3 \
            and self.zero_plan.param_data_axes != ()
        if zc.quantized_weights and stage < 3:
            logger.warning(
                "zero_quantized_weights has no effect below ZeRO stage 3 "
                "(there is no per-step weight all-gather); ignoring")
        self._qgz_enabled = bool(zc.quantized_gradients) and \
            self._config.zero_enabled and stage >= 2
        if zc.quantized_gradients and not self._qgz_enabled:
            logger.warning(
                "zero_quantized_gradients needs ZeRO stage >= 2 (the "
                "gradient reduce-scatter partition); ignoring")
        # cpu_offload_params: streamed parameter offload (beyond-HBM
        # ZeRO-3; runtime/zero/stream.py). Params are host-resident and
        # streamed per layer group into HBM inside the step.
        self._params_offload = bool(zc.cpu_offload_params) and \
            self._config.zero_enabled
        if zc.cpu_offload_params and not self._config.zero_enabled:
            raise ValueError(
                "zero_optimization.cpu_offload_params requires ZeRO "
                "(zero_optimization.stage=3)")
        if self._params_offload and stage < 3:
            raise ValueError(
                "zero_optimization.cpu_offload_params is a ZeRO-3 "
                "feature (params must be partitionable); got stage {}"
                .format(stage))
        if self._params_offload and not zc.cpu_offload:
            log_dist(
                "cpu_offload_params without cpu_offload: the fp32 master "
                "and Adam moments are host-resident anyway (the streamed "
                "step's optimizer runs on host)", ranks=[0])
        # sub_group_size: element chunk size of the offload shard
        # pipeline's D2H->host-Adam work items (reference stage3.py
        # sub_group partitioning of the optimizer step); the huge default
        # leaves one chunk per shard.
        self._sub_group_size = int(zc.sub_group_size) \
            if zc.sub_group_size else ZERO_SUB_GROUP_DEFAULT
        # stage3_prefetch_bucket_size: element size of each coalesced
        # host->device transfer bucket (offload param uploads ride few
        # large device_puts instead of one per shard — see _H2DBatcher)
        self._h2d_bucket_elems = int(zc.prefetch_bucket_size) \
            if zc.prefetch_bucket_size else ZERO_PREFETCH_DEFAULT

    def _configure_comm(self):
        """comm.collective_matmul: ring-decomposed all-gather/reduce-
        scatter GEMMs (parallel/collective_matmul.py). Resolves which
        fusion sites are live on this mesh/config:

          * ``_cm_zero3``: the stage-3 per-leaf weight all-gather runs
            as an explicit ppermute ring (composing with qwZ so the
            rotated chunks stay int8 blocks + scales on the wire);
          * ``_cm_tp``: the model's TP matmul sites run the fused
            column/row ops — communicated to the model by attaching a
            CollectiveMatmulBinding to its config.

        Off (the default) leaves every path exactly as before; the
        unfused XLA program stays the numerics oracle."""
        self._configure_quantized_collectives()
        cm = self._config.comm_config.collective_matmul
        self._cm = cm
        self._cm_zero3 = False
        self._cm_tp = False
        model_cfg = getattr(self.model, "config", None)
        if getattr(model_cfg, "collective_matmul", None) is not None and \
                not (cm.enabled and cm.tensor_parallel):
            # the binding lives on the (possibly shared) model config
            # object because the model's apply_fn closed over it — a
            # previous engine's attach leaks into this one. This engine
            # would RUN fused TP GEMMs while reporting them unfused;
            # A/B comparisons need models built from fresh configs.
            logger.warning(
                "model config already carries a collective_matmul "
                "binding (attached by a caller or a previous engine) "
                "but this engine's comm.collective_matmul does not "
                "enable TP fusion — the fused GEMMs still run, and "
                "this engine's telemetry will not flag them; build "
                "models from fresh configs for fused-vs-unfused "
                "comparisons")
        if not cm.enabled:
            return
        from ..parallel.topology import PIPE_AXIS, MODEL_AXIS
        from ..telemetry.config import warn_or_raise_noop
        if PIPE_AXIS in self.mesh.shape:
            raise ValueError(
                "comm.collective_matmul is not a certified combination "
                "with pipeline parallelism (the pipe loop owns its "
                "shard_map specs)")
        stage = self._config.zero_optimization_stage
        zc = self._config.zero_config
        self._cm_zero3 = bool(
            cm.zero_gather and stage >= 3 and
            self.zero_plan.param_data_axes != () and
            not bool(zc.cpu_offload_params))
        mp = int(self.mesh.shape.get(MODEL_AXIS, 1))
        if cm.tensor_parallel and mp > 1:
            if hasattr(model_cfg, "collective_matmul"):
                from ..parallel.collective_matmul import \
                    CollectiveMatmulBinding
                model_cfg.collective_matmul = CollectiveMatmulBinding(
                    mesh=self.mesh, axis=MODEL_AXIS,
                    chunks=int(cm.chunks), dtype=cm.dtype,
                    backend=cm.backend)
                self._cm_tp = True
            else:
                warn_or_raise_noop(
                    "comm.collective_matmul.tensor_parallel has NO "
                    "effect: model {!r} exposes no collective_matmul "
                    "config field".format(self.model.name), cm.strict,
                    flag="comm.collective_matmul.strict")
        if not (self._cm_zero3 or self._cm_tp):
            warn_or_raise_noop(
                "comm.collective_matmul is enabled but no fusion site "
                "is live (needs ZeRO stage >= 3 data-sharded params "
                "without cpu_offload_params, and/or a model mesh axis "
                "> 1 on a binding-aware model)", cm.strict,
                flag="comm.collective_matmul.strict")
        else:
            log_dist(
                "collective_matmul ON: zero3_ring_gather={} tp_fused={} "
                "chunks={} dtype={} backend={}".format(
                    self._cm_zero3, self._cm_tp, cm.chunks, cm.dtype,
                    cm.backend),
                ranks=[0])

    def _configure_quantized_collectives(self):
        """comm.quantized_collectives: replace the data-parallel gradient
        allreduce with the in-collective int8 exchange
        (runtime/comm/quantize.py, EQuARX 2506.17615). The micro step
        computes per-device LOCAL gradients inside shard_map and averages
        them through the quantized ring, so the compiled program's
        data-axis wire is int8 blocks + scales instead of fp32 — the PR
        10 HLO census verifies the bytes. Certified combinations only:
        the local-grad body runs the model fully manual over the data
        axis, so tensor/sequence/pipeline parallelism are rejected, and
        ZeRO-3 (data-sharded compute params) cannot feed it."""
        from ..telemetry.config import warn_or_raise_noop
        qc = self._config.comm_config.quantized_collectives
        self._qc = qc
        self._qc_enabled = False
        if not qc.enabled:
            return
        self._certify_local_grad_comm("comm.quantized_collectives")
        if bool(self._config.zero_config.cpu_offload_params):
            raise ValueError(
                "comm.quantized_collectives is not a certified "
                "combination with cpu_offload_params (the streamed "
                "runner owns its own gradient path)")
        dp = int(np.prod([self.mesh.shape[a] for a in
                          (self._batch_axis if isinstance(
                              self._batch_axis, tuple)
                           else (self._batch_axis,))], dtype=np.int64))
        if dp <= 1:
            warn_or_raise_noop(
                "comm.quantized_collectives has NO effect: the mesh has "
                "no data-parallel degree to exchange over", qc.strict,
                flag="comm.quantized_collectives.strict")
            return
        self._qc_enabled = True
        log_dist(
            "quantized_collectives ON: dtype={} block_size={} "
            "hierarchical={} mesh={}".format(
                qc.dtype, qc.block_size,
                "({})".format(dict(self.mesh.shape))
                if isinstance(self._batch_axis, tuple) else "flat",
                dict(self.mesh.shape)), ranks=[0])

    def _apply_transformer_overrides(self):
        """``transformer.flash_attention``: resolve the tri-state
        ("auto"|"pallas"|"xla", bools legacy) against the live backend
        (ops.transformer.attention.resolve_flash_backend — a forced
        "pallas" off-TPU runs the interpreter with a loud one-time
        warning instead of silently flipping the dense flag) and pin the
        result on the model config. The resolved value is observable as
        ``self.flash_attention_backend`` and in ``telemetry_snapshot()``,
        mirroring the serving engine's ``paged_attention_kernel``."""
        flash = self._config.transformer_flash_attention
        if flash is None:
            return
        from ..ops.transformer.attention import resolve_flash_backend
        resolved = resolve_flash_backend(flash)
        self.flash_attention_backend = resolved
        model_cfg = getattr(self.model, "config", None)
        if hasattr(model_cfg, "use_flash_attention"):
            model_cfg.use_flash_attention = resolved != "xla"
            if hasattr(model_cfg, "flash_attention_backend"):
                model_cfg.flash_attention_backend = resolved
            log_dist("transformer.flash_attention={} resolved to {!r} "
                     "for model {!r}".format(flash, resolved,
                                             self.model.name),
                     ranks=[0])
        else:
            logger.warning(
                "transformer.flash_attention has NO effect: model %r "
                "exposes no use_flash_attention config field",
                self.model.name)

    def _zero_key_noop(self, key, why):
        """A zero_optimization key this runtime cannot honor: warn
        loudly, or raise when zero_optimization.strict is set — never a
        silent no-op (docs/zero3_offload.md)."""
        from ..telemetry.config import warn_or_raise_noop
        warn_or_raise_noop(
            "zero_optimization.{} has NO effect in this runtime: {}"
            .format(key, why),
            getattr(self._config.zero_config, "strict", False),
            flag="zero_optimization.strict")

    def _validate_zero_keys(self, zc, stage):
        """Every parsed zero_optimization key either drives a mechanism
        or is loudly rejected here (VERDICT round 5: silent config no-ops
        are the worst option). Live keys after this PR:
        cpu_offload/cpu_offload_params (offload paths),
        sub_group_size (offload shard-pipeline chunk),
        stage3_max_live_parameters (persistence demotion + streamed
        group sizing), stage3_prefetch_bucket_size (coalesced H2D bucket),
        stage3_param_persistence_threshold (plan),
        ZeRO++ keys (quantize/hpZ). Subsumed-by-XLA keys (overlap_comm,
        reduce_scatter, bucket sizes, contiguous_gradients,
        allgather_partitions) are semantically satisfied by GSPMD —
        documented in docs/zero3_offload.md, not no-ops."""
        from .zero.constants import (
            ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT)
        if zc.max_reuse_distance is not None and \
                zc.max_reuse_distance != \
                ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT:
            self._zero_key_noop(
                "stage3_max_reuse_distance",
                "gather/release distance is XLA's memory-aware latency-"
                "hiding schedule; there is no trace-order coordinator to "
                "give the knob meaning")
        if zc.cpu_offload_use_pin_memory:
            self._zero_key_noop(
                "cpu_offload_use_pin_memory",
                "jax exposes no host-pinning control; offload staging "
                "buffers are plain (already DMA-able) host memory")
        if zc.gather_fp16_weights_on_model_save and stage >= 3:
            # trivially satisfied, not a no-op: save_checkpoint always
            # writes the FULL gathered compute-dtype module tree
            # (checkpointing.tree_to_numpy gathers sharded leaves)
            log_dist(
                "stage3_gather_fp16_weights_on_model_save: checkpoint "
                "saves always gather the full compute-dtype weights on "
                "this runtime", ranks=[0])

    def _configure_optimizer(self, client_optimizer):
        from ..ops.adam.fused_adam import FusedAdam, DeepSpeedCPUAdam
        from ..ops.lamb.fused_lamb import FusedLamb

        if client_optimizer is not None:
            if self.zero_cpu_offload() and \
                    getattr(client_optimizer, "adam_w_mode", None) is None:
                # the host step implements Adam only; a client optimizer
                # without Adam semantics would be silently replaced by it
                raise ValueError(
                    "zero_optimization.cpu_offload requires an Adam-family "
                    "optimizer; got client optimizer {}".format(
                        type(client_optimizer).__name__))
            self.optimizer = client_optimizer
            log_dist("Using client optimizer {}".format(
                type(client_optimizer).__name__), ranks=[0])
            self._resolve_onebit_mode()
            return

        name = (self._config.optimizer_name or "adam").lower()
        params = dict(self._config.optimizer_params or {})
        # Route optimizer-level max_grad_norm into the engine's clipping
        # (reference passes it to the FP16 wrapper, config.py warning path).
        max_grad_norm = params.pop("max_grad_norm", None)
        if max_grad_norm and not self._config.gradient_clipping:
            self._config.gradient_clipping = float(max_grad_norm)
        # optimizer.params.fused_kernel: tri-state for the Pallas apply
        # kernels (ops/adam/pallas_adam.py, ops/lamb/pallas_lamb.py),
        # same spelling as transformer.flash_attention. "auto" (default)
        # leaves the optimizer's own backend pick (default_use_pallas);
        # "pallas" forces the kernel — off-TPU it runs the interpreter
        # (the optimizer's update() resolves that) with a loud warning
        # here; "xla" pins the jnp oracle.
        fused_kernel = params.pop("fused_kernel", None)
        if fused_kernel is not None:
            if not isinstance(fused_kernel, str) or \
                    fused_kernel.lower() not in ("auto", "pallas", "xla"):
                raise ValueError(
                    "optimizer.params.fused_kernel must be one of "
                    "auto|pallas|xla, got {!r}".format(fused_kernel))
            fused_kernel = fused_kernel.lower()
            if name not in (ADAM_OPTIMIZER, "adamw", LAMB_OPTIMIZER):
                logger.warning(
                    "optimizer.params.fused_kernel has NO effect: "
                    "optimizer %r has no Pallas apply kernel", name)
            elif fused_kernel != "auto":
                params.setdefault("use_pallas", fused_kernel == "pallas")
                if fused_kernel == "pallas" and \
                        jax.default_backend() != "tpu":
                    logger.warning(
                        "optimizer.params.fused_kernel: 'pallas' forced "
                        "on the %s backend — the fused %s apply runs "
                        "under the Pallas INTERPRETER (orders of "
                        "magnitude slower; parity/debug only)",
                        jax.default_backend(), name)
        self.fused_optimizer_kernel = fused_kernel
        if name in (ADAM_OPTIMIZER, "adamw"):
            if self.zero_cpu_offload():
                self.optimizer = DeepSpeedCPUAdam(**params)
            else:
                self.optimizer = FusedAdam(**params)
        elif name == LAMB_OPTIMIZER:
            self.optimizer = FusedLamb(**params)
        elif name == ONEBIT_ADAM_OPTIMIZER:
            from ..runtime.fp16.onebit_adam import OnebitAdam
            self.optimizer = OnebitAdam(mesh=self.mesh, **params)
        elif name == "sgd":
            from ..ops.sgd import SGD
            self.optimizer = SGD(**params)
        else:
            raise ValueError("Unknown optimizer: {}".format(name))
        if self.zero_optimization() and \
                not getattr(self.optimizer, "supports_zero", True):
            # reference zero/utils.py is_zero_supported_optimizer
            raise ValueError(
                "{} is not compatible with ZeRO (zero_optimization.stage "
                ">= 1)".format(type(self.optimizer).__name__))
        if self.zero_cpu_offload() \
                and name not in (ADAM_OPTIMIZER, "adamw"):
            # the host step is Adam-only (reference restricts offload to
            # DeepSpeedCPUAdam the same way)
            raise ValueError(
                "zero_optimization.cpu_offload requires the Adam/AdamW "
                "optimizer, got '{}'".format(name))
        self._resolve_onebit_mode()
        log_dist("Using DeepSpeed optimizer: {}".format(name), ranks=[0])

    def _certify_local_grad_comm(self, feature):
        """The ONE certified-combination gate every local-grad comm
        feature (quantized_collectives, OneBitAdam) passes: the body
        runs the model fully manual over the data axis, so non-data mesh
        axes are rejected; ZeRO-3's data-sharded compute params cannot
        feed it; qgZ would double-quantize the same reduction."""
        from ..parallel.topology import (MODEL_AXIS, PIPE_AXIS,
                                         SEQUENCE_AXIS)
        for axis in (PIPE_AXIS, MODEL_AXIS, SEQUENCE_AXIS):
            if axis in self.mesh.shape and self.mesh.shape[axis] > 1:
                raise ValueError(
                    "{} is not a certified combination with the '{}' "
                    "mesh axis (the local-grad exchange runs the model "
                    "fully manual over the data axis only)".format(
                        feature, axis))
        if self._config.zero_optimization_stage >= 3:
            raise ValueError(
                "{} is not compatible with ZeRO stage 3 (data-sharded "
                "compute params cannot feed the local-grad shard_map "
                "body; stages 0-2 are supported — use "
                "zero_quantized_weights/zero_quantized_gradients at "
                "stage 3, docs/onebit_adam.md)".format(feature))
        if self._config.zero_config.quantized_gradients:
            raise ValueError(
                "{} with zero_quantized_gradients (qgZ) double-"
                "quantizes the gradient reduction — enable one (the "
                "local-grad exchange moves real compressed wire; qgZ "
                "models the codec on the GSPMD path)".format(feature))

    def _resolve_onebit_mode(self):
        """OneBitAdam: the micro step computes per-worker LOCAL grads
        (stacked, shard_map over the data axis) and the apply step runs
        the compressed momentum exchange — certified combinations only
        (docs/onebit_adam.md)."""
        from .fp16.onebit_adam import OnebitAdam
        self._onebit_mode = isinstance(self.optimizer, OnebitAdam)
        if not self._onebit_mode:
            return
        self._certify_local_grad_comm("OneBitAdam")
        stage = self._config.zero_optimization_stage
        if self.zero_cpu_offload():
            raise ValueError(
                "OneBitAdam is not compatible with cpu_offload (the "
                "compressed exchange runs on device; the host step is "
                "plain Adam)")
        if self.gradient_clipping():
            raise ValueError(
                "OneBitAdam does not support gradient_clipping: the "
                "global grad norm is never materialized in the "
                "compressed regime (grads stay per-worker local)")
        if float(getattr(self.optimizer, "weight_decay", 0.0) or 0.0) \
                and stage >= 1:
            raise ValueError(
                "OneBitAdam weight_decay needs replicated params (the "
                "L2 term feeds the fused flat momentum on every "
                "worker); use ZeRO stage 0 or weight_decay=0")
        self.optimizer.configure_comm(self.mesh)

    def _configure_lr_scheduler(self, client_lr_scheduler):
        if client_lr_scheduler is not None:
            self.lr_scheduler = client_lr_scheduler
            return
        name = self._config.scheduler_name
        if name is not None:
            cls = SCHEDULE_CLASSES.get(name)
            if cls is None:
                raise ValueError("Unknown lr schedule: {}".format(name))
            params = self._config.scheduler_params or {}
            self.lr_scheduler = cls(self.optimizer, **params)
            log_dist("DeepSpeed using configured LR scheduler = {}".format(name),
                     ranks=[0])
        else:
            self.lr_scheduler = None

    def _configure_pld(self):
        if self._config.pld_enabled:
            pld_params = self._config.pld_params or {}
            self.progressive_layer_drop = ProgressiveLayerDrop(**pld_params)
        else:
            self.progressive_layer_drop = None

    def _init_state(self):
        """Place params/master/opt/grad-accum arrays with ZeRO shardings."""
        plan = self.zero_plan
        self.host_state = None
        self.stream_runner = None
        if self.zero_params_offload():
            # Streamed parameter offload (cpu_offload_params): the fp32
            # master + Adam moments live in HOST memory like classic
            # ZeRO-Offload, but compute params have NO resident device
            # copy — each step streams them into HBM one layer group at
            # a time (runtime/zero/stream.py). The host registry keeps
            # the classic offload layout (one full-leaf entry per
            # master leaf) so every checkpoint path works unchanged.
            master_np = jax.tree_util.tree_map(
                lambda p: np.array(p, dtype=np.float32, copy=True),
                self.model.params)
            flat_master, treedef = jax.tree_util.tree_flatten(master_np)
            from .zero.stream import _full_index
            self.host_state = {
                "shard_leaves": [
                    [(_full_index(p.shape), p,
                      np.zeros(p.shape, np.float32),
                      np.zeros(p.shape, np.float32))]
                    for p in flat_master],
                "treedef": treedef,
                "leaf_shapes": [p.shape for p in flat_master],
                "step": 0,
                "streamed": True,
            }
            self.state = {
                "params": None,      # transient, streamed per group
                "master": None,
                "opt": None,
                "acc_grads": None,   # accumulated in host buffers
                "scaler": ls.loss_scaler_from_config(self._config),
            }
            del master_np, flat_master
            self.model.params = None
            from .zero.stream import StreamedOffloadRunner
            self.stream_runner = StreamedOffloadRunner(self)
            return
        if self.zero_cpu_offload():
            # True ZeRO-Offload (reference stage2/3 cpu_offload): fp32
            # master + Adam moments live in HOST memory as numpy; HBM only
            # holds compute-dtype params + fp32 grad accumulators. The
            # optimizer step runs on host cores (_host_apply_step).
            #
            # Multi-process (reference stage2.py:780-908 distributed
            # offload): every process keeps only the host shards matching
            # its ADDRESSABLE acc_grad shards (the ZeRO grad partition), so
            # host memory, PCIe transfer and the host Adam all split
            # process-ways. Single-process is the degenerate one-shard (or
            # all-shards) case of the same machinery.
            #
            # the bf16-state HBM levers do not apply here: the host step
            # consumes fp32 numpy shards end to end
            if self._config.grad_accum_dtype == "bf16":
                logger.warning(
                    "data_types.grad_accum_dtype=bf16 ignored: the host "
                    "offload step consumes fp32 accumulated grads")
            if getattr(self.optimizer, "moments_dtype", jnp.float32) \
                    != jnp.float32:
                logger.warning(
                    "optimizer moments_dtype=%s ignored under "
                    "cpu_offload: host shard moments are fp32 numpy",
                    jnp.dtype(self.optimizer.moments_dtype).name)
            # np.array(copy=True): np.asarray of a jax array is a READ-ONLY
            # view aliasing the runtime's buffer — the in-place host Adam
            # would crash (or scribble on JAX-owned memory via the C ptr)
            master_np = jax.tree_util.tree_map(
                lambda p: np.array(p, dtype=np.float32, copy=True),
                self.model.params)
            param_sh = plan.tree_shardings(master_np, "param")
            grad_sh = plan.tree_shardings(master_np, "grad")
            compute_params = jax.tree_util.tree_map(
                self._host_to_device, master_np, param_sh)
            acc_grads = jax.tree_util.tree_map(
                lambda p, s: jax.device_put(
                    jnp.zeros(p.shape, jnp.float32), s), master_np, grad_sh)
            # flat per-leaf shard lists [(index, master, exp_avg,
            # exp_avg_sq)], one entry per UNIQUE addressable shard index of
            # the grad sharding (replicated leaves dedupe to one full-size
            # entry); aligned with tree_flatten(acc_grads)
            flat_master, treedef = jax.tree_util.tree_flatten(master_np)
            flat_acc = treedef.flatten_up_to(acc_grads)
            shard_leaves = [
                [(idx, np.array(p[idx], dtype=np.float32, copy=True),
                  np.zeros(p[idx].shape, np.float32),
                  np.zeros(p[idx].shape, np.float32))
                 for idx in _unique_shard_indices(g)]
                for p, g in zip(flat_master, flat_acc)]
            self.host_state = {
                "shard_leaves": shard_leaves,
                "treedef": treedef,
                "leaf_shapes": [np.shape(p) for p in flat_master],
                "step": 0,
                # static for the engine's life; cached for the per-step H2D
                "param_shardings": param_sh,
            }
            self.state = {
                "params": compute_params,
                "master": None,
                "opt": None,
                "acc_grads": acc_grads,
                "scaler": ls.loss_scaler_from_config(self._config),
                # no skip_count here: the host optimizer step observes the
                # overflow flag every step, so the host counter is already
                # exact on the offload path
            }
            self._init_qg_error(acc_grads)
            self.model.params = None
            return

        # copy=True: jnp.asarray of same-dtype input is a VIEW of the
        # caller's arrays; the jitted step donates engine state, so an
        # aliased user array would be invalidated ("Buffer has been deleted
        # or donated") if the caller builds a second engine from it
        params_f32 = jax.tree_util.tree_map(
            lambda p: jnp.array(p, dtype=jnp.float32, copy=True),
            self.model.params)

        param_sh = plan.tree_shardings(params_f32, "param")
        master_sh = plan.tree_shardings(params_f32, "master")
        grad_sh = plan.tree_shardings(params_f32, "grad")

        compute_params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(jnp.asarray(p, self.compute_dtype), s),
            params_f32, param_sh)

        if self.mixed_precision:
            master = jax.tree_util.tree_map(
                lambda p, s: jax.device_put(p, s), params_f32, master_sh)
        else:
            master = None

        opt_target = master if self.mixed_precision else compute_params
        opt_state = self.optimizer.init_state(opt_target)
        # all per-param moments/buffers live with the master shards; state
        # shapes may differ from param shapes (e.g. OnebitAdam's flat error
        # buffers), so shardings come from each subtree's own leaves —
        # unless the optimizer declares a placement (state_placements():
        # OnebitAdam keeps the fused momentum replicated and the error
        # tensors per-worker)
        opt_state = {
            key: val if key == "step" else jax.tree_util.tree_map(
                lambda m, s: jax.device_put(m, s), val,
                self._opt_state_shardings(key, val))
            for key, val in opt_state.items()
        }
        acc_dtype = jnp.float32
        if self._config.grad_accum_dtype == "bf16":
            # (the cpu_offload path warned and returned above)
            if self.gradient_accumulation_steps() > 1:
                logger.warning(
                    "grad_accum_dtype=bf16 with gradient_accumulation_"
                    "steps=%d: bf16 summation across micro-steps is "
                    "lossy (it is exact only at 1 step)",
                    self.gradient_accumulation_steps())
            elif self.compute_dtype != jnp.bfloat16:
                logger.warning(
                    "grad_accum_dtype=bf16 truncates %s gradients: "
                    "storage is lossless only when the compute dtype "
                    "is bf16 too", jnp.dtype(self.compute_dtype).name)
            acc_dtype = jnp.bfloat16
        if self._onebit_mode:
            # per-worker LOCAL gradient accumulators: a leading (world,)
            # dim sharded one row per device — the local-grad micro step
            # writes its own row, the 1-bit exchange consumes them. The
            # accumulation dtype stays fp32 (the exchange math is fp32).
            if acc_dtype != jnp.float32:
                logger.warning(
                    "grad_accum_dtype=bf16 ignored under OneBitAdam: the "
                    "compressed exchange consumes fp32 local grads")
            w = self.dp_world_size
            stacked_sh = self._stacked_grad_sharding()
            acc_grads = jax.tree_util.tree_map(
                lambda p: jax.device_put(
                    jnp.zeros((w,) + p.shape, dtype=jnp.float32),
                    stacked_sh), params_f32)
        else:
            acc_grads = jax.tree_util.tree_map(
                lambda p, s: jax.device_put(
                    jnp.zeros(p.shape, dtype=acc_dtype), s),
                params_f32, grad_sh)

        self.state = {
            "params": compute_params,
            "master": master,
            "opt": opt_state,
            "acc_grads": acc_grads,
            "scaler": ls.loss_scaler_from_config(self._config),
            # device-resident skipped-step counter: keeps skipped_steps exact
            # even when the overflow flag is only fetched periodically
            "skip_count": jnp.int32(0),
        }
        self._init_qg_error(acc_grads)
        del params_f32
        self.model.params = None  # single source of truth is the state

    def _init_qg_error(self, acc_grads):
        """qgZ error-feedback accumulator, sharded like the grads it
        compensates (fp32: residuals are sub-int8-lsb sized; stored in
        unscaled units — see _micro_step_fn)."""
        if not self._qgz_enabled:
            return
        self.state["qg_error"] = jax.tree_util.tree_map(
            lambda g: jax.device_put(
                jnp.zeros(g.shape, jnp.float32), g.sharding),
            acc_grads)

    # ----------------------------------------------------------- data plumbing
    def deepspeed_io(self, dataset, batch_size=None, route=ROUTE_TRAIN,
                     data_sampler=None, collate_fn=None, num_local_io_workers=None):
        if batch_size is None:
            batch_size = self.train_micro_batch_size_per_gpu() * \
                self._local_dp_share()
        return DeepSpeedDataLoader(
            dataset, batch_size=batch_size,
            collate_fn=collate_fn or self.collate_fn,
            data_parallel_world_size=jax.process_count(),
            data_parallel_rank=jax.process_index(),
            shuffle=(route == ROUTE_TRAIN))

    def _local_dp_share(self):
        """How many of the dp shards this process feeds."""
        return max(self.dp_world_size // jax.process_count(), 1)

    def _batch_sharding(self, ndim):
        return NamedSharding(self.mesh,
                             P(self._batch_axis, *([None] * (ndim - 1))))

    def _to_device(self, batch):
        """Numpy batch (global or per-process) -> sharded jax.Arrays."""
        def put(x):
            x = np.asarray(x)
            if x.ndim == 0 or x.shape[0] % self.dp_world_size != 0:
                return jax.device_put(x, NamedSharding(self.mesh, P()))
            sharding = self._batch_sharding(x.ndim)
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)
        placed = jax.tree_util.tree_map(put, batch)
        # TRAIN-mode forwards only: an eval batch (arbitrary rows, often
        # replicated) must never stand in for the training micro-batch
        # the audit abstract-evals the step programs with
        if self._audit_batch_struct is None and self._mode == ROUTE_TRAIN:
            self._audit_batch_struct = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=a.sharding),
                placed)
        return placed

    # ------------------------------------------------------------- jitted fns
    def _hyper(self):
        h = self.optimizer.hyperparams()
        return {k: np.asarray(v, dtype=np.float32) for k, v in h.items()}

    def _loss_of(self, out):
        if isinstance(out, (tuple, list)):
            return out[0]
        return out

    def _qwz_gather_tree_fn(self):
        """qwZ: params tree -> gathered-params tree (None when disabled).

        Each data-sharded stage-3 leaf goes through ``qwz_gather``: the
        all-gather XLA emits moves int8 blocks + per-block scales instead
        of the compute dtype, and the straight-through vjp routes the
        cotangent back as the sharded-layout reduce-scatter."""
        if not getattr(self, "_qwz_enabled", False):
            return None
        from .comm.quantize import qwz_gather
        from .zero.partition import _path_str
        plan = self.zero_plan

        def gather(params):
            def leaf(path, p):
                shape = np.shape(p)
                if not plan.param_is_data_sharded(path, shape):
                    return p
                return qwz_gather(p, plan.gather_sharding(path, shape),
                                  plan.param_sharding(path, shape))
            return jax.tree_util.tree_map_with_path(
                lambda kp, p: leaf(_path_str(kp), p), params)

        return gather

    def _param_gather_tree_fn(self):
        """The stage-3 weight-materialization seam of the jitted steps:
        the collective-matmul ring gather when comm.collective_matmul
        is live for ZeRO-3 (carrying qwZ's int8 blocks + scales on the
        rotated chunks when both are on), else the qwZ sharding-
        constraint gather, else None (plain GSPMD gathers)."""
        if getattr(self, "_cm_zero3", False):
            from ..parallel.collective_matmul import make_zero3_gather_fn
            from .comm.quantize import DEFAULT_BLOCK_SIZE
            return make_zero3_gather_fn(
                self.zero_plan, self.mesh, chunks=self._cm.chunks,
                quantized=getattr(self, "_qwz_enabled", False),
                block_size=DEFAULT_BLOCK_SIZE)
        return self._qwz_gather_tree_fn()

    def _opt_state_shardings(self, key, val):
        """Sharding tree for one optimizer-state subtree, honoring the
        optimizer's placement hints (state_placements()): "replicated"
        (OnebitAdam's fused momentum — every worker compresses the full
        buffer), "stacked" (per-worker rows over the data axis), default
        = the master-shard plan."""
        hints = getattr(self.optimizer, "state_placements", None)
        kind = (hints() if hints is not None else {}).get(key, "master")
        if kind == "replicated":
            rep = self.zero_plan.replicated()
            return jax.tree_util.tree_map(lambda _: rep, val)
        if kind == "stacked":
            sh = self._stacked_grad_sharding()
            return jax.tree_util.tree_map(lambda _: sh, val)
        return self.zero_plan.tree_shardings(val, "master")

    def _opt_constrain(self, key, val):
        """with_sharding_constraint one optimizer-state subtree to its
        resolved placement (the in-jit twin of _opt_state_shardings)."""
        return jax.tree_util.tree_map(
            lambda x, s: jax.lax.with_sharding_constraint(x, s), val,
            self._opt_state_shardings(key, val))

    def _stacked_grad_sharding(self):
        """One row per device over the data axis (or its factored
        sub-axes): the layout of per-worker local grads / error state."""
        return NamedSharding(self.mesh, P(self._batch_axis))

    def _constrain_grads(self, tree):
        """Sharding constraint for the accumulated-gradient tree: the
        stacked per-worker layout under OneBitAdam, the ZeRO grad plan
        otherwise."""
        if getattr(self, "_onebit_mode", False):
            sh = self._stacked_grad_sharding()
            return jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(x, sh), tree)
        return self.zero_plan.constrain(tree, "grad")

    def _local_grad_mode(self):
        """Which local-gradient micro-step variant is live: "stacked"
        (OneBitAdam — grads stay per-worker for the momentum exchange),
        "exchange" (quantized_collectives with a plain optimizer — grads
        average through the in-collective int8 ring inside the micro
        step), or None (the GSPMD oracle path)."""
        if getattr(self, "_onebit_mode", False):
            return "stacked"
        if getattr(self, "_qc_enabled", False):
            return "exchange"
        return None

    def _flat_grad_meta(self):
        """The fused flat-gradient-buffer layout the quantized exchange
        rides (comm.quantize.FusedFlatLayout — the SAME layout helper
        OnebitAdam's momentum buffer uses), padded to whole blocks per
        rank chunk (qc_padded_size)."""
        if getattr(self, "_flat_meta_cache", None) is not None:
            return self._flat_meta_cache
        from .comm.quantize import FusedFlatLayout, qc_padded_size
        params = self.state["params"] if self.state is not None and \
            self.state.get("params") is not None else self.model.params
        self._flat_meta_cache = FusedFlatLayout(
            params, lambda n: qc_padded_size(n, self.dp_world_size,
                                             self._qc.block_size))
        return self._flat_meta_cache

    def _qc_exchange_fn(self):
        """The in-collective quantized all-reduce over a fused flat fp32
        buffer, resolved for this mesh: the two-level hierarchical
        decomposition on a factored data axis, the flat EQuARX ring
        otherwise. Returns a per-device body: (padded,) local partials ->
        (padded,) fp32 global SUM (call inside shard_map)."""
        from .comm.quantize import (hierarchical_all_reduce_local,
                                    quantized_all_reduce_local)
        block = self._qc.block_size
        if isinstance(self._batch_axis, tuple):
            replica_axis, shard_axis = self._batch_axis
            wr = int(self.mesh.shape[replica_axis])
            ws = int(self.mesh.shape[shard_axis])

            def exchange(flat):
                return hierarchical_all_reduce_local(
                    flat, shard_axis, replica_axis, ws, wr, block)
        else:
            axis = self._batch_axis
            world = self.dp_world_size

            def exchange(flat):
                return quantized_all_reduce_local(flat, axis, world,
                                                  block)
        return exchange

    def _micro_step_fn(self):
        if self._local_grad_mode() is not None:
            return self._local_grad_micro_fn()
        apply_fn = self.model.apply_fn
        gas = self.gradient_accumulation_steps()
        plan = self.zero_plan
        model = self.model
        qwz = self._param_gather_tree_fn()
        qgz = getattr(self, "_qgz_enabled", False)
        if qgz:
            from .comm.quantize import quantize_with_error_feedback

        def micro(state, batch, rng, pld_theta=None):
            kwargs = {**model.rng_kwargs(rng), **model.mode_kwargs(True)}
            if self.progressive_layer_drop:
                # theta must arrive as a TRACED operand — reading
                # get_theta() here would constant-fold the schedule's
                # initial value into the compiled step
                if model.accepts_kwarg("progressive_layer_drop"):
                    kwargs["progressive_layer_drop"] = True
                if model.accepts_kwarg("pld_theta"):
                    kwargs["pld_theta"] = pld_theta

            def loss_fn(compute_params):
                if qwz is not None:
                    compute_params = qwz(compute_params)
                out = apply_fn(compute_params, *batch, **kwargs)
                loss = self._loss_of(out)
                scaled = loss.astype(jnp.float32) * \
                    (state["scaler"].cur_scale / gas)
                return scaled, loss

            (_, loss), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state["params"])
            new_state = dict(state)
            if qgz:
                # qgZ: each micro-step's gradient contribution passes
                # through the error-compensated int8 codec before
                # accumulation — the numerics of a quantized gradient
                # reduce-scatter, with the residual carried across steps
                # so the long-run average stays unbiased. The residual is
                # stored in UNSCALED units (grads carry the loss scale),
                # so a dynamic-scale change between steps cannot inject a
                # wrong-magnitude correction.
                cur_scale = state["scaler"].cur_scale
                qd_and_err = jax.tree_util.tree_map(
                    lambda g, e: quantize_with_error_feedback(
                        g, e, scale=cur_scale),
                    grads, state["qg_error"])
                grads = jax.tree_util.tree_map(
                    lambda p, qe: qe[0], grads, qd_and_err)
                new_state["qg_error"] = plan.constrain(
                    jax.tree_util.tree_map(
                        lambda p, qe: qe[1], grads, qd_and_err),
                    "grad")
            new_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), state["acc_grads"],
                grads)
            new_acc = plan.constrain(new_acc, "grad")
            new_state["acc_grads"] = new_acc
            return new_state, loss

        return micro

    def _local_grad_micro_fn(self):
        """The local-gradient micro step (OneBitAdam / quantized
        collectives): forward + backward run FULLY MANUAL over the data
        axis inside shard_map, so each device's gradients are its OWN
        micro-batch shard's — no GSPMD fp32 gradient psum is ever
        emitted. "stacked" mode (OneBitAdam) accumulates the per-worker
        grads as (world, ...) rows for the 1-bit momentum exchange;
        "exchange" mode averages them through the in-collective int8
        ring (EQuARX) right here, so downstream the step is byte-for-
        byte the GSPMD program minus the fp32 reduce. The scalar loss is
        pmean'd for reporting (a handful of wire bytes)."""
        apply_fn = self.model.apply_fn
        gas = self.gradient_accumulation_steps()
        model = self.model
        mode = self._local_grad_mode()
        mesh = self.mesh
        axes = self._batch_axis
        world = self.dp_world_size
        meta = self._flat_grad_meta() if mode == "exchange" else None
        exchange = self._qc_exchange_fn() if mode == "exchange" else None
        pld_live = self.progressive_layer_drop is not None
        from ..parallel.topology import shard_map_compat

        def micro(state, batch, rng, pld_theta=None):
            leaves, batch_def = jax.tree_util.tree_flatten(batch)
            specs = tuple(
                P(axes) if getattr(leaf, "ndim", 0) >= 1 and
                leaf.shape[0] % world == 0 else P()
                for leaf in leaves)
            scale = state["scaler"].cur_scale

            def per_dev(compute_params, *local_leaves):
                local_batch = jax.tree_util.tree_unflatten(
                    batch_def, list(local_leaves))
                lrng = rng
                if lrng is not None and world > 1:
                    # honest per-device dropout masks: fold the device's
                    # position into the key (the GSPMD path draws one
                    # global mask; statistically equivalent)
                    lrng = jax.random.fold_in(
                        lrng, jax.lax.axis_index(axes))
                kwargs = {**model.rng_kwargs(lrng),
                          **model.mode_kwargs(True)}
                if pld_live:
                    if model.accepts_kwarg("progressive_layer_drop"):
                        kwargs["progressive_layer_drop"] = True
                    if model.accepts_kwarg("pld_theta"):
                        kwargs["pld_theta"] = pld_theta

                def loss_fn(p):
                    out = apply_fn(p, *local_batch, **kwargs)
                    loss = self._loss_of(out)
                    scaled = loss.astype(jnp.float32) * (scale / gas)
                    return scaled, loss

                (_, loss), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(compute_params)
                loss = jax.lax.pmean(loss, axes)
                if mode == "exchange":
                    flat = meta.flatten(grads)
                    summed = exchange(flat)
                    mean = summed * jnp.float32(1.0 / world)
                    return loss, meta.unflatten_like(mean, grads)
                return loss, jax.tree_util.tree_map(
                    lambda g: g[None].astype(jnp.float32), grads)

            out_spec = P() if mode == "exchange" else P(axes)
            sharded = shard_map_compat(
                per_dev, mesh=mesh, in_specs=(P(),) + specs,
                out_specs=(P(), out_spec))
            loss, grads = sharded(state["params"], *leaves)
            new_state = dict(state)
            new_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), state["acc_grads"],
                grads)
            new_state["acc_grads"] = self._constrain_grads(new_acc)
            return new_state, loss

        return micro

    def _onebit_frozen(self):
        """Whether the NEXT optimizer step runs OneBitAdam's compressed
        regime — host-side, so the engine compiles one program per
        regime (global_steps counts attempted steps; under overflow
        skips it can run ahead of the device step counter by
        skipped_steps, documented in docs/onebit_adam.md)."""
        return getattr(self, "_onebit_mode", False) and \
            self.optimizer.frozen_at(self.global_steps)

    def _regime_jit_key(self, base):
        """Jit-cache key for a step program that differs by OneBitAdam
        regime; invalidates the cached wire estimate when the regime
        flips (the compressed wire differs from warmup's)."""
        if not getattr(self, "_onebit_mode", False):
            return base
        frozen = self._onebit_frozen()
        if frozen != getattr(self, "_onebit_last_regime", None):
            self._onebit_last_regime = frozen
            self._tele_wire = "unset"
        return base + ("@ob_frozen" if frozen else "@ob_warmup")

    def _apply_step_fn(self, frozen=None):
        plan = self.zero_plan
        optimizer = self.optimizer
        clip = self.gradient_clipping()
        mixed = self.mixed_precision
        compute_dtype = self.compute_dtype
        onebit = getattr(self, "_onebit_mode", False)
        if frozen is None:
            frozen = self._onebit_frozen()
        qc_meta = qc_exchange = None
        if onebit and not frozen and getattr(self, "_qc_enabled", False):
            qc_meta = self._flat_grad_meta()
            qc_exchange = self._qc_exchange_fn()
        world = self.dp_world_size

        def _onebit_grads(grads):
            """Per-worker stacked grads -> (update grads, grad_norm).
            Warmup: average the workers (through the in-collective int8
            ring when quantized_collectives is on, the plain fp32
            allreduce otherwise) — exact Adam follows. Frozen: grads
            STAY per-worker (the 1-bit momentum exchange consumes them);
            grad_norm is the RMS-over-workers estimate
            sqrt(sum_w ||g_w||^2 / w) — equal to the true norm when
            workers agree, an upper bound otherwise (the averaged
            gradient is never materialized in this regime)."""
            if frozen:
                norm = get_grad_norm(grads) / \
                    jnp.sqrt(jnp.float32(world))
                return grads, True, norm
            if qc_exchange is not None:
                from jax.sharding import PartitionSpec as SMP
                from ..parallel.topology import shard_map_compat

                def per_dev(stacked_leaves):
                    flat = qc_meta.flatten(
                        jax.tree_util.tree_map(lambda g: g[0],
                                               stacked_leaves))
                    summed = qc_exchange(flat)
                    return summed * jnp.float32(1.0 / world)

                sharded = shard_map_compat(
                    per_dev, mesh=self.mesh,
                    in_specs=(SMP(self._batch_axis),), out_specs=SMP())
                mean_flat = sharded(grads)
                like = jax.tree_util.tree_map(lambda g: g[0], grads)
                avg = qc_meta.unflatten_like(mean_flat, like)
            else:
                avg = jax.tree_util.tree_map(
                    lambda g: g.mean(axis=0), grads)
            return avg, False, get_grad_norm(avg)

        def apply_step(state, hyper):
            scaler = state["scaler"]
            grads = state["acc_grads"]
            overflow = CheckOverflow.has_overflow(grads)
            inv_scale = 1.0 / scaler.cur_scale
            # accumulation may be stored bf16 (grad_accum_dtype); the
            # unscale/clip/update math always runs fp32
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * inv_scale, grads)
            target = state["master"] if mixed else state["params"]
            if onebit:
                # clip is rejected at config time for OneBitAdam
                grads, stacked, grad_norm = _onebit_grads(grads)
                new_target, new_opt = optimizer.update(
                    grads, state["opt"], target, lr=hyper["lr"],
                    beta1=hyper["beta1"], beta2=hyper["beta2"],
                    eps=hyper["eps"],
                    weight_decay=hyper["weight_decay"],
                    frozen=frozen, averaged=not stacked)
            else:
                if clip > 0:
                    grads, grad_norm = clip_grad_norm_(grads, clip)
                else:
                    grad_norm = get_grad_norm(grads)
                new_target, new_opt = optimizer.update(
                    grads, state["opt"], target, lr=hyper["lr"],
                    beta1=hyper["beta1"], beta2=hyper["beta2"],
                    eps=hyper["eps"],
                    weight_decay=hyper["weight_decay"])

            # Branchless overflow-skip (reference engine.py:1073-1083 +
            # stage2.py overflow path): select old state when overflowed.
            keep = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new, old)
            new_target = keep(new_target, target)
            new_opt = keep(new_opt, state["opt"])

            new_state = dict(state)
            new_state["opt"] = new_opt
            if mixed:
                new_state["master"] = plan.constrain(new_target, "master")
                new_params = jax.tree_util.tree_map(
                    lambda m: m.astype(compute_dtype), new_target)
                # stage<3: re-replicate (the all-gather of updated partitions,
                # stage2.py:1419-1513); stage 3: stays sharded.
                new_state["params"] = plan.constrain(new_params, "param")
            else:
                new_state["params"] = plan.constrain(new_target, "param")
            new_state["acc_grads"] = self._constrain_grads(
                jax.tree_util.tree_map(jnp.zeros_like,
                                       state["acc_grads"]))
            new_state["opt"] = {
                key: val if key == "step" else self._opt_constrain(key,
                                                                   val)
                for key, val in new_opt.items()
            }
            # an overflowed window compressed inf/nan through the 1-bit
            # codec — the worker/server residuals are poisoned; zero them
            # with the skip, like qg_error below (the optimizer declares
            # which subtrees are error feedback)
            for err_key in getattr(optimizer, "error_state_keys", ()):
                if err_key in new_state["opt"]:
                    new_state["opt"][err_key] = jax.tree_util.tree_map(
                        lambda e: jnp.where(overflow, jnp.zeros_like(e),
                                            e),
                        new_state["opt"][err_key])
            new_state["scaler"] = ls.update_scale(scaler, overflow)
            if "qg_error" in state:
                # an overflowed micro window quantized inf/nan grads, so
                # the qgZ residual is poisoned — reset it with the skip
                # (a stale-scale residual is also dropped here, matching
                # the reference's error-state reset on overflow)
                new_state["qg_error"] = jax.tree_util.tree_map(
                    lambda e: jnp.where(overflow, jnp.zeros_like(e), e),
                    state["qg_error"])
            if "skip_count" in state:
                new_state["skip_count"] = (
                    state["skip_count"] + overflow.astype(jnp.int32))

            metrics = {
                "overflow": overflow,
                "grad_norm": grad_norm,
                "loss_scale": scaler.cur_scale,
            }
            return new_state, metrics

        return apply_step

    def _get_jit(self, key, builder, donate=(), **jit_kwargs):
        if key not in self._jit_cache:
            from .executor.jit import jit_program
            self._jit_cache[key] = jit_program(builder(), donate=donate,
                                               **jit_kwargs)
        return self._jit_cache[key]

    # -------------------------------------------------------------- telemetry
    def _check_memory_breakdown(self):
        """``memory_breakdown`` drives per-step HBM reporting (telemetry
        records + monitor scalars + see_memory_usage at print
        boundaries). A backend without ``memory_stats()`` cannot honor
        it: warn loudly, raise under telemetry.strict — never a silent
        no-op (the PR 4 stage-3 key policy)."""
        if not self._config.memory_breakdown:
            return
        from ..telemetry.collector import collect_memory_stats
        if collect_memory_stats()["available"]:
            return
        from ..telemetry.config import warn_or_raise_noop
        warn_or_raise_noop(
            "memory_breakdown=true but backend {!r} exposes no "
            "memory_stats() — per-step HBM live/peak reporting is "
            "unavailable on this runtime".format(jax.default_backend()),
            getattr(self._config.telemetry_config, "strict", False))

    def telemetry_snapshot(self):
        """Rolling-window aggregate of the emitted StepRecords (p50/p95
        step time, MFU, tokens/s/chip, phase means, wire bytes) — ``{}``
        when telemetry is disabled. Benches embed this under
        ``extra.telemetry``. Resolved kernel tri-states ride along under
        ``kernels`` (observable like the serving engine's
        paged_attention_kernel) whenever either ds_config key was set."""
        out = self.telemetry.snapshot() if self.telemetry is not None \
            else {}
        if out and (self.flash_attention_backend is not None or
                    self.fused_optimizer_kernel is not None):
            out = dict(out)
            out["kernels"] = {
                "flash_attention": self.flash_attention_backend,
                "fused_optimizer": self.fused_optimizer_kernel,
            }
        return out

    def _tele_flops(self, key, fn, *args):
        """Executed flops of the jitted program behind ``key`` via XLA
        cost_analysis, computed ONCE per key (training shapes are static
        per program; a re-jit under the same key at new shapes keeps the
        first estimate) and cached — so the per-step cost is one dict
        lookup. Must be called BEFORE invoking fns that donate their
        arguments."""
        cached = self._tele_flops_cache.get(key)
        if cached is not None:
            return cached
        from ..telemetry import costs_of_compiled
        try:
            t0 = time.time()
            costs = costs_of_compiled(fn, *args)
            price_wall = time.time() - t0
            flops = float(costs.get("flops", 0.0) or 0.0)
            # compile observatory: the registry keeps the FULL cost dict
            # and the pricing wall (an honest compile-cost proxy on
            # backends where pricing is an AOT compile)
            self.telemetry.programs.price(key, costs,
                                          price_wall_s=price_wall)
        except Exception as err:  # noqa: BLE001 - never perturb the step
            logger.info("telemetry: cost_analysis unavailable for %r (%s)",
                        key, err)
            flops = 0.0
        self._tele_flops_cache[key] = flops
        return flops

    def _tele_add_flops(self, key, fn, *args):
        """Accumulate ``fn``'s executed flops into the live step window
        (no-op when telemetry is off) — the ONE accounting seam, also
        used by runners that own their own jit caches (zero/stream.py's
        ``_run``); the engine's window privates are never mutated from
        another module. The compile observatory rides the same seam:
        every priced program is registered/counted here."""
        if self.telemetry is not None:
            self._window_flops += self._tele_flops(key, fn, *args)
            self.telemetry.programs.observe_call(key, fn, args)

    def _jit_priced(self, key, builder, *args, donate=(0,)):
        """``_get_jit`` plus telemetry flops accounting in one place,
        priced with ``args`` BEFORE the returned fn runs (it donates
        them). Every jitted train path must obtain its fn through this
        (zero/stream.py's ``_run`` is the offload twin) or
        ``_window_flops`` silently undercounts and MFU deflates."""
        fn = self._get_jit(key, builder, donate=donate)
        self._tele_add_flops(key, fn, *args)
        return fn

    def _telemetry_wire(self):
        """wire.py per-step bytes-on-wire estimate for the live config,
        computed once (static across steps at fixed shapes)."""
        if self._tele_wire == "unset":
            try:
                from .comm.wire import estimate_engine_comm_bytes
                self._tele_wire = estimate_engine_comm_bytes(self)
            except Exception as err:  # noqa: BLE001
                logger.info("telemetry: wire estimate unavailable (%s)",
                            err)
                self._tele_wire = None
        return self._tele_wire

    def _telemetry_comm_overlap(self, step_time_s):
        """Per-class overlap efficiency for this step's StepRecord:
        wire.py's analytic compute/(compute+exposed-collective) model
        against the measured step wall, with each class marked fused
        only when THIS config's decomposition actually hides it.
        wire.py's classes are the ZeRO collectives: the allgather class
        (stage-3 weight gathers / stage-1-2 re-replication) is fused
        exactly by the zero3 ring gather; the reduce class (the DP
        gradient reduce-scatter) is never fused here — the ring
        gather's backward deliberately leaves it to GSPMD. The TP
        activation gathers/scatters the row/column ops hide are not in
        wire's classes at all: their scoreboard is step_time_s/MFU."""
        if self.telemetry is None:
            return None
        from .comm.wire import overlap_report
        fused = {
            "allgather": bool(getattr(self, "_cm_zero3", False)),
            "reduce": False,
            # the 1-bit momentum exchange (its class appears when live)
            # is never ring-fused into compute
            "optimizer": False,
        }
        return overlap_report(self._telemetry_wire(), step_time_s, fused,
                              self.telemetry._device)

    def _telemetry_window_begin(self):
        """Open the per-optimizer-step measurement window (wall clock,
        token and flops accumulators) and advance the trace window."""
        if self.telemetry is None:
            return
        self._window_t0 = time.time()
        self._window_step = self.global_steps
        self._window_tokens = 0
        self._window_flops = 0.0
        self.telemetry.on_step_begin(self._window_step)

    def _telemetry_micro_begin(self, batch):
        """Micro-path hook: open the window at the first micro of a
        grad-accum window, and count this micro's tokens."""
        if self.telemetry is None or self._mode != ROUTE_TRAIN:
            return
        if self.micro_steps % self.gradient_accumulation_steps() == 0:
            self._telemetry_window_begin()
        self._telemetry_add_tokens(batch)

    def _telemetry_add_tokens(self, batch):
        """Count the first input leaf's elements as this micro's tokens
        (ids batches: batch x seq; the labels leaf is not re-counted)."""
        if self.telemetry is None:
            return
        leaves = jax.tree_util.tree_leaves(batch)
        if leaves:
            shape = getattr(leaves[0], "shape", None)
            self._window_tokens += int(np.prod(shape)) if shape else 1

    def _telemetry_phases(self):
        """The step's disjoint phase clocks: the synchronized micro
        timers when wall_clock_breakdown is on, merged with the offload/
        streamed phase dict when that path ran. Overlapping clocks are
        excluded so phases stay disjoint: classic offload spans only the
        optimizer apply (the step timer would double-bill it); the
        STREAMED phase dict covers the whole step — fwd, bwd, and
        transfers all run inside micro_step — so there the micro timers
        are drained but not billed."""
        phases = {}
        offload = getattr(self, "offload_phase_times", None) or {}
        streamed = self.stream_runner is not None
        if self.wall_clock_breakdown():
            for name in (FORWARD_MICRO_TIMER, BACKWARD_MICRO_TIMER,
                         STEP_MICRO_TIMER):
                t = self.timers.timers.get(name)
                if t is not None and not t.started_:
                    # drained on EVERY path so timer state stays
                    # per-step; the value is only REPORTED where it is
                    # not already covered (streamed phase dicts replace
                    # the micro timers; the offload dict owns the step
                    # phase — reporting both would double-bill the wall)
                    val = t.elapsed(reset=True)
                    if val > 0 and not streamed and not (
                            offload and name == STEP_MICRO_TIMER):
                        phases[name] = val
        for key, val in offload.items():
            phases[key] = phases.get(key, 0.0) + float(val)
        return phases

    def _telemetry_offload_stats(self, exec_stats=None):
        """The StepRecord's ``offload`` sub-dict in the unified
        SEGMENT_KEYS schema (telemetry/record.py): per-kind executed-
        segment walls from the PlanExecutor joined with the path's
        upload counters — one shape for the streamed and classic
        offload paths (validated by bin/check_bench_schema.py)."""
        if self.stream_runner is not None:
            snap = self.stream_runner.transfer_snapshot(
                exec_stats=exec_stats)
            self.stream_runner.reset_step_counters()
            return snap
        if self.host_state is not None:
            exec_stats = exec_stats or {}
            occ = getattr(self, "h2d_bucket_occupancy", None)
            elems = int(getattr(self, "h2d_elems", 0) or 0)
            itemsize = np.dtype(self.compute_dtype).itemsize
            return {
                "plan_segments": int(exec_stats.get("plan_segments", 0)),
                "per_kind": exec_stats.get("per_kind", {}),
                # constructed transfer/compute overlap: host-Adam wall
                # the D2H stream hid vs the residual it could not (the
                # bespoke pre-executor path reported NO efficiency here)
                "overlap_efficiency": exec_stats.get(
                    "overlap_efficiency"),
                "upload_batches": int(getattr(self, "h2d_batches", 0)
                                      or 0),
                "upload_elems": elems,
                "upload_bytes": elems * itemsize,
                "bucket_elems": self._h2d_bucket_elems,
                "bucket_occupancy": round(occ, 4) if occ else None,
                "work_chunks": int(getattr(self, "offload_work_chunks",
                                           0) or 0),
            }
        return None

    def _emit_train_telemetry(self, loss, pipe=None):
        """Assemble and emit this optimizer step's StepRecord. NOTE:
        reading grad_norm/overflow forces one device value fetch per
        step on paths that otherwise defer it — part of telemetry's
        documented <5% overhead budget (docs/telemetry.md)."""
        # executor per-step accounting: snapshot the per-kind stats,
        # then drain the segment records (the drain also opens the next
        # step's window, so it runs even when telemetry is off)
        ex = self._plan_executor
        exec_stats = ex.step_snapshot() if ex is not None else None
        exec_segments = ex.drain_step_records() if ex is not None \
            else None
        tel = self.telemetry
        if tel is None or self._window_t0 is None:
            return
        metrics = self._step_metrics or {}
        grad_norm = metrics.get("grad_norm")
        try:
            grad_norm = None if grad_norm is None else float(grad_norm)
        except Exception:  # noqa: BLE001
            grad_norm = None
        loss = None if loss is None else float(loss)
        overflow = bool(metrics.get("overflow", False))
        # the wall clock is read only AFTER the value fetches above:
        # grad_norm/overflow are outputs of the step's jitted program on
        # every device path, so on async backends the fetch blocks until
        # the step actually finishes — otherwise step_time_s would price
        # host dispatch only and overstate MFU/tokens-per-sec (paths with
        # wall_clock_breakdown on are synced by the timers already)
        dt = time.time() - self._window_t0
        self._window_t0 = None
        loss_scale = metrics.get("loss_scale")
        loss_scale = float(loss_scale) if loss_scale is not None \
            else float(self.state["scaler"].cur_scale)
        # memory_breakdown's monitor mirror already polled memory_stats()
        # this step; hand it over instead of polling every device twice
        hbm = self._step_hbm
        self._step_hbm = None
        tel.emit_train_step(
            path=self._resolved_step_path(),
            step=self._window_step,
            hbm=hbm,
            step_time_s=dt,
            loss=loss,
            grad_norm=grad_norm,
            loss_scale=loss_scale,
            overflow=overflow,
            skipped_steps=self.skipped_steps,
            micro_steps=self.gradient_accumulation_steps(),
            tokens_per_step=self._window_tokens,
            model_flops_per_step=self._window_flops,
            phases=self._telemetry_phases(),
            wire=self._telemetry_wire(),
            comm_overlap=self._telemetry_comm_overlap(dt),
            offload=self._telemetry_offload_stats(exec_stats),
            pipe=pipe,
            # segment-derived span trees on the multi-segment lowered
            # paths (span tree == executed plan); micro/fused keep the
            # phase-derived tree (their plan is one segment — the phase
            # clocks say more)
            segments=exec_segments if exec_segments and (
                self.stream_runner is not None or
                self.host_state is not None) else None)
        if self.controller is not None:
            # closed-loop tick (docs/controller.md): fold this step's
            # wall into the objective window, finalize due override
            # evaluations, and every interval_steps let the policies
            # propose moves from the signals assembled off the seams
            # this record was just built from
            from .controller.adapters import train_signals
            self.controller.on_step(self._window_step, dt,
                                    train_signals(self))

    # ----------------------------------------------------------- diagnostics
    def _resolved_step_path(self):
        """The executing step path's label — shared by the span tree's
        ``path`` attr and the crash bundle's ``step_path`` so the two
        diagnostics surfaces cannot drift."""
        if self.stream_runner is not None:
            return "streamed"
        if self.host_state is not None:
            return "offload"
        return self._step_path

    def _flight_state(self):
        """Engine snapshot for crash bundles (resolved at dump time)."""
        return {
            "role": "train",
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "mode": self._mode,
            "step_path": self._resolved_step_path(),
            "zero_stage": self.zero_optimization_stage(),
            "compute_dtype": str(np.dtype(self.compute_dtype)),
            "mesh": {str(k): int(v) for k, v in self.mesh.shape.items()},
            "jit_programs": sorted(str(k) for k in self._jit_cache),
        }

    def _topology_context(self):
        """Crash-bundle ``topology`` section (resolved at dump time):
        which topology was LIVE at the crash, plus the elastic rescale
        history shared across engine generations by an ElasticRunner."""
        import jax
        return {
            "mesh": {str(k): int(v) for k, v in self.mesh.shape.items()},
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "dp_world_size": self.dp_world_size,
            "zero_plan": self.zero_plan.topology()
            if getattr(self, "zero_plan", None) is not None else None,
            "rescale_history": list(self._rescale_history),
        }

    def _tele_crash(self, where, err):
        """Flight-recorder hook for unhandled step-path exceptions: dump
        a crash bundle (once per exception object — nested wrappers and
        watchdog raise-trips are deduplicated), never mask the error."""
        tel = self.telemetry
        if tel is None or tel.recorder is None:
            return
        try:
            tel.recorder.dump("exception:" + where, exc=err)
        except Exception:  # noqa: BLE001 - the real error must propagate
            logger.warning("flight recorder dump failed during %s",
                           where, exc_info=True)

    def debug_dump(self, reason="debug_dump"):
        """Write a flight-recorder crash bundle on demand (the operator
        seam: inspect a LIVE run that looks wrong without killing it).
        Returns the bundle path, or None (loudly) when
        ``telemetry.flight_recorder`` is off."""
        tel = self.telemetry
        if tel is None or tel.recorder is None:
            logger.warning(
                "debug_dump: telemetry.flight_recorder is not enabled — "
                "no bundle written (add the flight_recorder section to "
                "the telemetry config)")
            return None
        return tel.recorder.dump(reason)

    def audit(self, batch=None, hlo=None, report_path=None, strict=None):
        """Ahead-of-time shard-lint (docs/analysis.md): abstract-eval
        this engine's resolved step programs from ShapeDtypeStructs +
        the ZeroShardingPlan and walk the jaxpr for sharding drift,
        donation misses, fp32 upcasts in the bf16 GEMM path, host
        callbacks and recompile hazards — before anything compiles.

        ``batch``: one sample micro-batch (arrays or structs); optional
        after the first training step (the engine records the shapes).
        ``hlo=True`` additionally compiles the step programs and
        ground-truths the wire estimator against the HLO collective
        census. Findings warn (raise under ``analysis.strict``; the
        ``strict`` argument overrides); returns the AnalysisReport."""
        from ..analysis import audit_engine
        return audit_engine(self, batch=batch, hlo=hlo,
                            report_path=report_path, strict=strict)

    # -------------------------------------------------------------- train API
    def train(self, mode=True):
        self._mode = ROUTE_TRAIN if mode else "eval"
        return self

    def eval(self):
        return self.train(False)

    @property
    def module(self):
        return self.model

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    def forward(self, *inputs, **kwargs):
        """Run a micro-batch. In train mode also computes and accumulates
        gradients (the reference's separate autograd backward becomes part of
        the same XLA program; ``backward()`` is then bookkeeping)."""
        try:
            return self._forward_impl(*inputs, **kwargs)
        except BaseException as err:
            # BaseException on purpose: a SimulatedKill/KeyboardInterrupt
            # mid-step is exactly when the flight recorder must fire
            self._tele_crash("forward", err)
            raise

    def _forward_impl(self, *inputs, **kwargs):
        if len(inputs) == 1 and isinstance(inputs[0], (tuple, list)):
            inputs = tuple(inputs[0])
        batch = self._to_device(inputs)
        if self.stream_runner is not None:
            # streamed parameter offload: forward AND backward run as
            # one segment-streamed pass (grads accumulate into the host
            # buffers), exactly as the monolithic train forward fuses
            # value_and_grad; backward() stays bookkeeping
            if self._mode != ROUTE_TRAIN:
                loss = self.stream_runner.eval_loss(batch)
                self._last_loss = loss
                return loss
            self._telemetry_micro_begin(batch)
            if self.wall_clock_breakdown():
                self.timers(FORWARD_MICRO_TIMER).start()
            self._rng, step_rng = jax.random.split(self._rng)
            loss = self.stream_runner.micro_step(batch, step_rng)
            if self.wall_clock_breakdown():
                self.timers(FORWARD_MICRO_TIMER).stop()
            self._last_loss = loss
            self._pending_backward = True
            return loss
        flops_profiler = self._maybe_start_flops_profiler()

        if self._mode != ROUTE_TRAIN:
            eval_fn = self._get_jit("eval", self._eval_fn)
            loss = eval_fn(self.state["params"], batch)
            self._last_loss = loss
            return loss

        self._telemetry_micro_begin(batch)
        self._step_path = "micro"
        if self.wall_clock_breakdown():
            self.timers(FORWARD_MICRO_TIMER).start()
        self._rng, step_rng = jax.random.split(self._rng)
        micro = self._jit_priced("micro", self._micro_step_fn,
                                 self.state, batch, step_rng,
                                 self._pld_theta())
        if flops_profiler:
            # cost-analyze the EXACT executable about to run, via the
            # telemetry helper that owns the compiled-object fallback
            from ..telemetry.collector import costs_of_compiled
            # actual profiled sequence length (per-module attribution must
            # price the run's shapes, not config.max_seq_len)
            leaf = jax.tree_util.tree_leaves(batch)[0]
            self._profile_seq = (int(leaf.shape[1])
                                 if getattr(leaf, "ndim", 0) >= 2 else None)
            self._flops_costs = costs_of_compiled(
                micro, self.state, batch, step_rng, self._pld_theta())
        self.state, loss = micro(self.state, batch, step_rng,
                                 self._pld_theta())
        if self.wall_clock_breakdown():
            self.timers(FORWARD_MICRO_TIMER).stop()
        self._last_loss = loss
        self._pending_backward = True
        if flops_profiler:
            self._stop_flops_profiler()
        return loss

    def _eval_fn(self):
        apply_fn = self.model.apply_fn
        model = self.model
        qwz = self._param_gather_tree_fn()

        def eval_step(params, batch):
            if qwz is not None:
                # eval sees the same int8-gathered weights training does
                params = qwz(params)
            out = apply_fn(params, *batch, **model.mode_kwargs(False))
            return self._loss_of(out)

        return eval_step

    def backward(self, loss, allreduce_gradients=True, release_loss=False):
        """Bookkeeping for API parity: gradients were produced (and
        constrained to their ZeRO sharding) during ``forward``; the DP mean is
        inserted by XLA at the boundary."""
        assert getattr(self, "_pending_backward", False), \
            "backward() called without a prior train-mode forward()"
        self._pending_backward = False
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_MICRO_TIMER).start()
            self.timers(BACKWARD_MICRO_TIMER).stop()
        return loss

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def zero_grad(self):
        if self.stream_runner is not None:
            self.stream_runner.zero_grads()
            return
        self.state["acc_grads"] = jax.tree_util.tree_map(
            jnp.zeros_like, self.state["acc_grads"])

    def step(self, lr_kwargs=None):
        """Optimizer step at gradient-accumulation boundaries
        (reference engine.py:1088-1173)."""
        try:
            return self._step_impl(lr_kwargs)
        except BaseException as err:
            self._tele_crash("train_step", err)
            raise

    def _step_impl(self, lr_kwargs=None):
        if self.wall_clock_breakdown():
            self.timers(STEP_MICRO_TIMER).start()

        boundary = self.is_gradient_accumulation_boundary()
        if boundary:
            self._take_model_step(lr_kwargs)

        self.micro_steps += 1
        self.global_samples += self.train_micro_batch_size_per_gpu() * \
            self.dp_world_size
        if boundary:
            self._write_monitor_scalars(self._last_loss)
        if self.wall_clock_breakdown():
            self.timers(STEP_MICRO_TIMER).stop()
        if boundary:
            self._emit_train_telemetry(self._last_loss)

    def _write_monitor_scalars(self, loss):
        """Train/Samples/{lr,train_loss,loss_scale} at each global step
        (reference engine.py:1110-1124)."""
        if not self.monitor.enabled:
            return
        self.monitor.add_scalar("Train/Samples/lr", self.get_lr()[0],
                                self.global_samples)
        if loss is not None:
            self.monitor.add_scalar("Train/Samples/train_loss", float(loss),
                                    self.global_samples)
        self.monitor.add_scalar("Train/Samples/loss_scale",
                                float(self._step_metrics["loss_scale"]),
                                self.global_samples)
        if self.memory_breakdown():
            # memory_breakdown wired to PER-STEP HBM reporting (telemetry
            # records always carry hbm; this mirrors it into the monitor
            # stream). Unavailable backends warned/raised at engine init.
            from ..telemetry.collector import collect_memory_stats
            stats = collect_memory_stats()
            self._step_hbm = stats  # reused by this step's StepRecord
            if stats["available"]:
                self.monitor.add_scalar("Train/Samples/hbm_bytes_in_use",
                                        stats["bytes_in_use"],
                                        self.global_samples)
                self.monitor.add_scalar(
                    "Train/Samples/hbm_peak_bytes_in_use",
                    stats["peak_bytes_in_use"], self.global_samples)
        self.monitor.flush()

    def _offload_check_fn(self):
        """(all-finite, UNSCALED sum of squares) over the GLOBAL
        acc_grads — a tiny jitted reduction whose replicated outputs every
        process can fetch, replacing a host-side full-gradient scan (which
        a process with only its shards could not do). The squares are taken
        AFTER unscaling so a large loss scale cannot push a finite
        gradient's square past fp32 range; a non-finite sumsq that survives
        the elementwise check is treated as overflow by the caller."""

        def check(grads, inv_scale):
            leaves = jax.tree_util.tree_leaves(grads)
            finite = jnp.bool_(True)
            sumsq = jnp.float32(0)
            for g in leaves:
                finite = jnp.logical_and(finite, jnp.isfinite(g).all())
                sumsq = sumsq + jnp.sum(
                    (g.astype(jnp.float32) * inv_scale) ** 2)
            return finite, sumsq

        return check

    def _host_apply_step(self):
        """ZeRO-Offload optimizer step, shard-wise and OVERLAPPED
        (reference stage2.py:283-286, 780-908 + csrc/adam/cpu_adam.cpp),
        lowered onto the segment executor (runtime/executor/offload.py,
        docs/executor.md): each process D2Hs only its ADDRESSABLE
        acc_grad shards, runs the host Adam on its host master/moment
        shards, H2Ds the updated shards and reshards to the param
        layout on device. The transfer/compute overlap the bespoke
        shard pipeline hand-threaded here is now CONSTRUCTED by the
        PlanExecutor from the declared segment deps (async D2H fetches
        in a bounded window ahead of the host Adam, leaf uploads riding
        the coalescing batcher behind the remaining chunks)."""
        from .executor.offload import run_offload_apply
        return run_offload_apply(self)

    def plan_executor(self):
        """The engine's PlanExecutor (runtime/executor/scheduler.py),
        built lazily: mode resolves from the strict-validated
        ``runtime.executor`` tri-state (off = serial oracle, on/auto =
        constructed overlap)."""
        if self._plan_executor is None:
            from .executor import PlanExecutor
            self._plan_executor = PlanExecutor(
                mode=self._executor_mode,
                windows={"d2h": self._D2H_WINDOW},
                rewrites=self._executor_rewrites
                if self._executor_rewrites.get("enabled") else None)
        return self._plan_executor

    def executor_snapshot(self):
        """Engine-lifetime executor counters (mode, plans/segments
        executed, per-kind walls, constructed overlap) — the payload of
        the benches' ``extra.executor``."""
        if self._plan_executor is None:
            return {"mode": self._executor_mode, "plans_executed": 0,
                    "segments_executed": 0, "last_plan_segments": 0}
        return self._plan_executor.lifetime_snapshot()

    def _finish_offload_step(self, flat_params, acc_specs, acc_shardings,
                             hs):
        """Reshard the uploaded grad-layout leaves into the param layout
        and re-zero the accumulators on device."""
        grad_layout = hs["treedef"].unflatten(flat_params)
        reshard = self._get_jit(
            "offload_reshard",
            lambda: lambda t: t,
            out_shardings=hs["param_shardings"])
        self.state["params"] = reshard(grad_layout)
        del grad_layout
        # fresh zero accumulators, allocated ON DEVICE from the saved
        # specs (a host-side zeros + device_put would push the full
        # fp32 gradient over the wire every step); the cache key carries
        # the specs VERBATIM (not a truncated hash — a collision across
        # spec changes would replay a stale-shaped closure) so a
        # shape/sharding change across steps can never alias
        zeros_fn = self._get_jit(
            "acc_zeros:%s" % repr(acc_specs),
            lambda: (lambda: tuple(jnp.zeros(s, d)
                                   for s, d in acc_specs)),
            out_shardings=tuple(acc_shardings))
        self.state["acc_grads"] = hs["treedef"].unflatten(
            list(zeros_fn()))

    def _restore_params_from_host(self, acc_specs, acc_shardings, hs):
        """Disaster path: rebuild device params + zero accumulators from
        the host master shards after a failed overlapped step."""
        flat_params = [
            self._leaf_shards_to_device(spec[0], sh, shards)
            for spec, sh, shards in zip(acc_specs, acc_shardings,
                                        hs["shard_leaves"])]
        self._finish_offload_step(flat_params, acc_specs, acc_shardings,
                                  hs)

    def _upload_pool(self):
        from .executor.pools import upload_pool
        if getattr(self, "_h2d_pool", None) is None:
            self._h2d_pool = upload_pool()
        return self._h2d_pool

    def _h2d_split_cache(self):
        """Jitted bucket-split programs, shared across steps so each
        bucket layout compiles once."""
        if getattr(self, "_h2d_splits", None) is None:
            self._h2d_splits = {}
        return self._h2d_splits

    def _enqueue_leaf_upload(self, batcher, i, shape, sharding, shards):
        """Queue one leaf's updated host master shards on the upload
        batcher, keyed so _assemble_uploaded_leaf can rebuild the global
        array."""
        by_key = {_shard_key(idx): p for idx, p, _, _ in shards}
        for dev, idx in \
                sharding.addressable_devices_indices_map(shape).items():
            batcher.add((i, _shard_key(idx)), by_key[_shard_key(idx)],
                        dev)

    def _assemble_uploaded_leaf(self, uploaded, i, shape, sharding):
        """Batched-upload results for leaf ``i`` -> a grad-layout global
        device array."""
        singles = [
            uploaded[(i, _shard_key(idx))][dev]
            for dev, idx in
            sharding.addressable_devices_indices_map(shape).items()]
        return jax.make_array_from_single_device_arrays(
            shape, sharding, singles)

    def _leaf_shards_to_device(self, shape, sharding, shards):
        """One leaf's updated host master shards -> a grad-layout global
        device array (synchronous coalesced H2D in compute dtype). Takes
        the leaf's (shape, sharding) spec rather than the grad array so
        the caller can free the gradient buffer first. Only the disaster
        path uses this now — the hot path batches leaves across the step
        (_enqueue_leaf_upload)."""
        from .zero.transfer import H2DBatcher
        batcher = H2DBatcher(self._h2d_bucket_elems, self.compute_dtype,
                             jit_cache=self._h2d_split_cache())
        self._enqueue_leaf_upload(batcher, 0, shape, sharding, shards)
        return self._assemble_uploaded_leaf(batcher.finish(), 0, shape,
                                            sharding)

    def _host_to_device(self, p_np, sharding):
        """Host fp32 leaf -> sharded compute-dtype device array WITHOUT
        materializing the full array on one device (jnp.asarray-then-
        device_put would transit device 0 unsharded — fatal for exactly
        the large-model case offload targets). Cast in numpy first
        (np.dtype(bf16) resolves via ml_dtypes, halving the transfer),
        then device_put straight onto the NamedSharding."""
        return jax.device_put(p_np.astype(np.dtype(self.compute_dtype)),
                              sharding)

    def _offload_lib(self):
        """The native SIMD Adam when built; None -> numpy fallback. Only
        plain Adam/AdamW offloads (reference restricts the same way)."""
        if getattr(self, "_offload_lib_cache", "unset") != "unset":
            return self._offload_lib_cache
        lib = None
        if not getattr(self.optimizer, "adam_w_mode", None) is None:
            try:
                from ..ops.op_builder.cpu_adam import CPUAdamBuilder
                lib = CPUAdamBuilder().load()
            except Exception as err:  # noqa: BLE001
                logger.warning(
                    "ZeRO-Offload: native CPU Adam unavailable (%s); "
                    "using the numpy fallback", err)
        self._offload_lib_cache = lib
        return lib

    def _adapt_state_dict(self, sd):
        """Hook for subclasses to re-partition a loaded state dict before
        placement (PipelineEngine re-shards body layers across a different
        stage count)."""
        return sd

    def _pld_theta(self):
        """Current PLD keep-prob as a traced-operand scalar (1.0 = off)."""
        if self.progressive_layer_drop:
            return jnp.float32(self.progressive_layer_drop.get_theta())
        return jnp.float32(1.0)

    def _overflow_fetch_needed(self):
        """Whether the optimizer step's overflow flag must be read back to
        the host this step. Only dynamic loss scaling (fp16) needs it per
        step — skipped_steps/lr-skip semantics depend on it. With a static
        scale the reference does no overflow bookkeeping either, and the
        fetch is a per-step device sync worth avoiding."""
        if self.host_state is not None:
            return True     # offload: metrics are already host values
        # fp16 checks overflow per step even with a STATIC scale (the
        # reference's FP16_Optimizer always runs CheckOverflow); only
        # bf16/fp32 — where the reference has no overflow machinery — skip
        return (bool(self.state["scaler"].dynamic)
                or self.compute_dtype == jnp.float16)

    def _read_overflow(self, metrics):
        """The optimizer step's overflow flag, fetched per-step for fp16
        (reference FP16_Optimizer semantics) and only at steps_per_print
        boundaries for bf16/fp32 — the in-jit guard still no-ops a
        non-finite step on device every step, and the periodic check keeps
        a persistently-overflowing run observable (skipped_steps/log)
        without a per-step device sync. At those boundaries skipped_steps
        is re-synced from the device-resident skip_count counter, so the
        host total stays exact over the unfetched window (the lr scheduler
        still advances on unfetched skipped steps — the documented cost of
        avoiding the sync)."""
        if self._overflow_fetch_needed():
            return bool(metrics["overflow"])
        if (self.global_steps + 1) % self.steps_per_print() == 0:
            # one device fetch only (a tunneled round-trip costs ~94 ms);
            # -1 compensates the caller's += 1 for this step's overflow
            overflow = bool(metrics["overflow"])
            self._sync_skipped_steps(exclude_current_overflow=overflow)
            return overflow
        return False

    def _sync_skipped_steps(self, exclude_current_overflow=False):
        """Re-sync the host skipped_steps counter from the device-resident
        skip_count, which is exact even over windows where the overflow
        flag was never fetched. max() keeps paths where the host counter
        is already authoritative (per-step fetch, host offload) intact."""
        if self.state is None or "skip_count" not in self.state:
            return
        device_skips = int(self.state["skip_count"])
        if exclude_current_overflow:
            device_skips -= 1
        self.skipped_steps = max(self.skipped_steps, device_skips)

    def _stream_apply_step(self):
        """Streamed-offload optimizer step + scaler update; exposes the
        streamed phase clocks under the name the offload benches read."""
        metrics = self.stream_runner.apply_step()
        self.state["scaler"] = ls.update_scale(
            self.state["scaler"], metrics["overflow"])
        self.offload_phase_times = self.stream_runner.phase_times
        self.stream_runner.phase_times = {}
        return metrics

    def _take_model_step(self, lr_kwargs=None):
        if self.stream_runner is not None:
            metrics = self._stream_apply_step()
        elif self.host_state is not None:
            metrics = self._host_apply_step()
        else:
            apply_fn = self._jit_priced(self._regime_jit_key("apply"),
                                        self._apply_step_fn,
                                        self.state, self._hyper())
            # one-segment plan: the apply program rides the same
            # executor (and per-step accounting) as the offload plans
            self.state, metrics = self.plan_executor().run_program(
                "apply", "compute",
                lambda: apply_fn(self.state, self._hyper()))
        self._step_metrics = {k: v for k, v in metrics.items()}
        overflow = self._read_overflow(metrics)
        if overflow:
            self.skipped_steps += 1
            log_dist("OVERFLOW! Skipping step. Attempted loss scale: {}".format(
                float(metrics["loss_scale"])), ranks=[0])
        else:
            if self.lr_scheduler is not None:
                self.lr_scheduler.step(**(lr_kwargs or {}))
        if self.progressive_layer_drop:
            self.progressive_layer_drop.update_state(self.global_steps)
        self.global_steps += 1
        if self.global_steps % self.steps_per_print() == 0:
            log_dist("step={}, lr={}, loss_scale={}".format(
                self.global_steps, self.get_lr(),
                float(metrics["loss_scale"])), ranks=[0])
            if self.memory_breakdown():
                see_memory_usage(
                    "step {}".format(self.global_steps), force=True)

    # -------------------------------------------------- fused train-batch path
    def train_batch(self, data_iter=None, batch=None):
        """TPU-idiomatic fused path: all grad-accum micro-steps + the
        optimizer step in ONE jitted program (lax.scan over micro-batches)."""
        try:
            return self._train_batch_impl(data_iter=data_iter, batch=batch)
        except BaseException as err:
            self._tele_crash("train_batch", err)
            raise

    def _train_batch_impl(self, data_iter=None, batch=None):
        self._step_path = "fused"
        gas = self.gradient_accumulation_steps()
        if batch is None:
            assert data_iter is not None
            micro_batches = [next(data_iter) for _ in range(gas)]
            batch = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *micro_batches)
        self._telemetry_window_begin()
        if self.stream_runner is not None:
            # streamed parameter offload: the micro-steps stream layer
            # groups host->HBM; there is no fused lax.scan (params never
            # all co-reside on device)
            losses = []
            for i in range(gas):
                micro = jax.tree_util.tree_map(
                    lambda x: np.asarray(x)[i], batch)
                dev_micro = self._to_device(tuple(
                    jax.tree_util.tree_leaves(micro)))
                self._telemetry_add_tokens(dev_micro)
                self._rng, step_rng = jax.random.split(self._rng)
                losses.append(self.stream_runner.micro_step(dev_micro,
                                                            step_rng))
            mean_loss = float(np.mean([float(x) for x in losses]))
            metrics = self._stream_apply_step()
        elif self.host_state is not None:
            batch = self._to_device_stacked(batch)
            self._telemetry_add_tokens(batch)
            self._rng, step_rng = jax.random.split(self._rng)
            fused = self._jit_priced("fused_micros", self._fused_micros_fn,
                                     self.state, batch, step_rng,
                                     self._pld_theta())
            self.state, mean_loss = self.plan_executor().run_program(
                "fused_micros", "compute",
                lambda: fused(self.state, batch, step_rng,
                              self._pld_theta()))
            metrics = self._host_apply_step()
        else:
            batch = self._to_device_stacked(batch)
            self._telemetry_add_tokens(batch)
            self._rng, step_rng = jax.random.split(self._rng)
            fused = self._jit_priced(self._regime_jit_key("fused_train"),
                                     self._fused_train_fn,
                                     self.state, batch, step_rng,
                                     self._hyper(), self._pld_theta())
            # one-segment plan: the fused train program rides the same
            # executor (and per-step accounting) as the offload plans
            self.state, (mean_loss, metrics) = \
                self.plan_executor().run_program(
                    "fused_train", "compute",
                    lambda: fused(self.state, batch, step_rng,
                                  self._hyper(), self._pld_theta()))
        overflow = self._read_overflow(metrics)
        if overflow:
            self.skipped_steps += 1
            log_dist("OVERFLOW! Skipping step. Attempted loss scale: {}"
                     .format(float(metrics["loss_scale"])), ranks=[0])
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self.progressive_layer_drop:
            self.progressive_layer_drop.update_state(self.global_steps)
        self.global_steps += 1
        self.micro_steps += gas
        self.global_samples += self.train_batch_size()
        self._step_metrics = metrics
        self._last_loss = mean_loss
        self._write_monitor_scalars(mean_loss)
        self._emit_train_telemetry(mean_loss)
        return mean_loss

    def _to_device_stacked(self, batch):
        """Batch stacked as (gas, global_batch, ...) -> sharded arrays."""
        def put(x):
            x = np.asarray(x)
            if x.ndim <= 1 or x.shape[1] % self.dp_world_size != 0:
                return jax.device_put(x, NamedSharding(self.mesh, P()))
            sharding = NamedSharding(
                self.mesh,
                P(None, self._batch_axis, *([None] * (x.ndim - 2))))
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)
        placed = jax.tree_util.tree_map(put, batch)
        if self._audit_batch_struct_stacked is None:
            self._audit_batch_struct_stacked = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=a.sharding),
                placed)
        return placed

    def _fused_micros_fn(self):
        """Offload variant of the fused path: scan the micro-steps on
        device, leave the optimizer apply to the host."""
        micro = self._micro_step_fn()
        gas = self.gradient_accumulation_steps()

        def fused(state, stacked_batch, rng, pld_theta):
            rngs = jax.random.split(rng, gas)
            leaves, treedef = jax.tree_util.tree_flatten(stacked_batch)

            def scan_body(carry, xs):
                rng_i = xs[0]
                batch_i = jax.tree_util.tree_unflatten(treedef, list(xs[1:]))
                return micro(carry, batch_i, rng_i, pld_theta)

            state, losses = jax.lax.scan(scan_body, state,
                                         (rngs, *leaves), length=gas)
            return state, jnp.mean(losses)

        return fused

    def _fused_train_fn(self):
        micro = self._micro_step_fn()
        apply_step = self._apply_step_fn()
        gas = self.gradient_accumulation_steps()

        def fused(state, stacked_batch, rng, hyper, pld_theta):
            rngs = jax.random.split(rng, gas)

            def body(carry, xs):
                batch_i, rng_i = xs
                new_state, loss = micro(carry, batch_i, rng_i, pld_theta)
                return new_state, loss

            leaves, treedef = jax.tree_util.tree_flatten(stacked_batch)
            def scan_body(carry, xs):
                rng_i = xs[0]
                batch_i = jax.tree_util.tree_unflatten(treedef, list(xs[1:]))
                return body(carry, (batch_i, rng_i))

            state, losses = jax.lax.scan(scan_body, state,
                                         (rngs, *leaves), length=gas)
            state, metrics = apply_step(state, hyper)
            return state, (jnp.mean(losses), metrics)

        return fused

    # ------------------------------------------------------------- accessors
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def steps_per_print(self):
        return self._config.steps_per_print

    def sparse_attention_config(self):
        """The parsed ds_config "sparse_attention" dict, or None — the
        reference engine's accessor (engine.py sparse_attention_config):
        models consume it to build their sparse attention, e.g.
        GPT2Config(sparse_attention=engine.sparse_attention_config())
        or SparseAttentionUtils for BERT."""
        return self._config.sparse_attention

    def zero_optimization(self):
        return self._config.zero_enabled

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def zero_cpu_offload(self):
        # offload is a ZeRO feature: a stage-0 config with the flag set
        # must not activate the host Adam path (reference ties it to the
        # ZeRO optimizers too). cpu_offload_params implies the optimizer
        # state is host-resident as well (the streamed step's Adam runs
        # on host by construction).
        return self.zero_optimization() and \
            (self._config.zero_config.cpu_offload or
             self.zero_params_offload())

    def zero_params_offload(self):
        """Streamed parameter offload live (cpu_offload_params): compute
        params are host-resident, streamed per layer group into HBM
        inside the step (runtime/zero/stream.py)."""
        return getattr(self, "_params_offload", False)

    def zero_quantized_weights(self):
        """qwZ live: stage-3 weight all-gathers ride int8 blocks."""
        return getattr(self, "_qwz_enabled", False)

    def zero_hierarchical_partition(self):
        """hpZ live: the secondary-partition (shard sub-axis) size, or 0."""
        plan = getattr(self, "zero_plan", None)
        if plan is not None and plan.hierarchical:
            return plan.param_shard_size
        return 0

    def zero_quantized_gradients(self):
        """qgZ live: micro-step grads pass the error-compensated codec."""
        return getattr(self, "_qgz_enabled", False)

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def bfloat16_enabled(self):
        return self._config.bf16_enabled

    def amp_enabled(self):
        return self._config.amp_enabled

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def allreduce_always_fp32(self):
        return self._config.allreduce_always_fp32

    def postscale_gradients(self):
        return not self._config.prescale_gradients

    def gradient_predivide_factor(self):
        return self._config.gradient_predivide_factor

    def sparse_gradients_enabled(self):
        return self._config.sparse_gradients_enabled

    def wall_clock_breakdown(self):
        return self._config.wall_clock_breakdown

    def memory_breakdown(self):
        return self._config.memory_breakdown

    def progressive_layer_drop_enabled(self):
        return self._config.pld_enabled

    def elasticity_enabled(self):
        return self._config.elasticity_enabled

    def get_lr(self):
        return [float(getattr(self.optimizer, "lr", 0.0))]

    def get_mom(self):
        betas = getattr(self.optimizer, "betas", None)
        return [betas] if betas is not None else None

    def loss_scale(self):
        return float(self.state["scaler"].cur_scale)

    @property
    def cur_scale(self):
        return self.loss_scale()

    def get_global_grad_norm(self):
        gn = self._step_metrics.get("grad_norm")
        return float(gn) if gn is not None else None

    def get_params(self):
        """Current compute-dtype parameter pytree."""
        return self._module_view()

    def _module_view(self):
        """The checkpoint/module view of the compute parameters. Under
        streamed offload there is no resident device copy — the view is
        the host master cast to compute dtype."""
        if self.state.get("params") is not None:
            return self.state["params"]
        if self.stream_runner is not None:
            cd = np.dtype(self.compute_dtype)
            return jax.tree_util.tree_map(
                lambda p: p.astype(cd), self.get_master_params())
        return self.state["params"]

    def get_master_params(self):
        if self.host_state is not None:
            return self._assemble_host_tree(field=1)
        return self.state["master"] if self.mixed_precision \
            else self.state["params"]

    def _assemble_host_tree(self, field):
        """Full fp32 tree from the host shards (field: 1 master, 2 exp_avg,
        3 exp_avg_sq). Only possible when this process's shards cover every
        leaf (single-process, or replicated layouts) — a partitioned
        multi-process layout raises; the per-process zero checkpoint files
        own the shards there."""
        hs = self.host_state
        leaves = []
        for shape, shards in zip(hs["leaf_shapes"], hs["shard_leaves"]):
            out = np.empty(shape, np.float32)
            covered = 0
            for tup in shards:
                out[tup[0]] = tup[field]
                covered += int(tup[field].size)
            if covered < int(np.prod(shape)):
                raise RuntimeError(
                    "host optimizer state is partitioned across processes; "
                    "use the per-process zero checkpoint files instead of a "
                    "gathered view")
            leaves.append(out)
        return hs["treedef"].unflatten(leaves)

    def _opt_state_view(self):
        if self.host_state is not None:
            return {
                "step": self.host_state["step"],
                "exp_avg": self._assemble_host_tree(field=2),
                "exp_avg_sq": self._assemble_host_tree(field=3),
            }
        return self.state["opt"]

    # --------------------------------------------------------------- profiler
    def _maybe_start_flops_profiler(self):
        cfg = self._config.flops_profiler_config
        if cfg.enabled and self.global_steps == cfg.profile_step \
                and self._mode == ROUTE_TRAIN:
            self._flops_profiler_active = True
            return True
        return False

    def _stop_flops_profiler(self):
        if getattr(self, "_flops_profiler_active", False):
            from ..profiling.flops_profiler.profiler import FlopsProfiler
            prof = FlopsProfiler(self)
            costs = getattr(self, "_flops_costs", None) or {}
            prof.flops = costs.get("flops", 0.0)
            prof.bytes_accessed = costs.get("bytes accessed", 0.0)
            self.flops_profiler = prof
            prof.print_model_profile()
            # per-module table (reference profiler.py:515-677) when the
            # model ships a profile spec (e.g. models/gpt2.py)
            spec_fn = getattr(self.model, "profile_spec_fn", None)
            if spec_fn is not None:
                cfg = self._config.flops_profiler_config
                try:
                    spec = spec_fn(self.train_micro_batch_size_per_gpu(),
                                   seq=getattr(self, "_profile_seq", None))
                except TypeError:   # spec builder without a seq kwarg
                    spec = spec_fn(self.train_micro_batch_size_per_gpu())
                prof.print_module_table(
                    spec,
                    module_depth=cfg.module_depth,
                    top_modules=cfg.top_modules,
                    detailed=cfg.detailed)
            self._flops_profiler_active = False

    # ------------------------------------------------------------- checkpoint
    def _get_ckpt_tag(self, tag):
        return tag if tag is not None else "global_step{}".format(
            self.global_steps)

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, async_save=False,
                        _write_manifest=True):
        """Save model+optimizer+scheduler+counters
        (reference engine.py:1569-1685).

        Every file write is atomic (tmp + fsync + rename), the tag's
        ``manifest.json`` (file list + CRC32s) is written after every
        content file, and ``latest`` moves only after the manifest — a
        crash at any point leaves ``latest`` naming a complete,
        checksum-verifiable checkpoint (docs/checkpoint_recovery.md).
        ``async_save``: pickle+write runs on a serial background thread
        (device state is still gathered synchronously, so training may
        continue mutating it); single-process only — multi-process saves
        need the inter-file barrier and stay synchronous.
        ``_write_manifest=False`` is for subclasses (pipe engine) that
        append more tag files and must finalize the manifest themselves."""
        tag = self._get_ckpt_tag(tag)
        self._validate_tag(tag)
        client_state = client_state or {}
        async_save = async_save and jax.process_count() == 1
        # at most one save in flight: surface any prior async failure
        # here rather than silently dropping it, and let still-queued
        # background writes land before we re-write the same paths
        self._drain_ckpt_writes()
        ckpt.wait_pending_writes()

        is_writer = jax.process_index() == 0
        # bf16/static-scale runs only fetch the overflow flag at print
        # boundaries; without this the saved value would freeze the
        # unfetched window's drift into the checkpoint
        self._sync_skipped_steps()
        # partitioned multi-process offload: the gathered master/opt views
        # are unavailable (each process owns shards); the per-process zero
        # files below carry the state instead
        offload_sharded = (self.host_state is not None
                           and jax.process_count() > 1)
        # device-state ZeRO: master/opt go ONLY into per-process zero shard
        # files (reference zero_pp_rank layout, engine.py:1350-1377) — the
        # model file carries neither, so nothing funnels the full optimizer
        # tree through rank 0 and nothing is stored twice
        zero_sharded = self.host_state is None and self.zero_optimization()
        sd = {
            "module": ckpt.tree_to_numpy(self._module_view()),
            "optimizer": None if (offload_sharded or zero_sharded)
                else ckpt.tree_to_numpy(self._opt_state_view()),
            "master": ckpt.tree_to_numpy(self.get_master_params())
                if ((self.mixed_precision or self.host_state is not None)
                    and not offload_sharded and not zero_sharded)
                else None,
            "scaler": ckpt.tree_to_numpy(
                {"cur_scale": self.state["scaler"].cur_scale,
                 "cur_hysteresis": self.state["scaler"].cur_hysteresis,
                 "last_overflow_iter": self.state["scaler"].last_overflow_iter,
                 "cur_iter": self.state["scaler"].cur_iter}),
            "lr_scheduler": self.lr_scheduler.state_dict()
                if self.lr_scheduler is not None else None,
            # qgZ error feedback (docs/zeropp.md): leaves are
            # param-shaped, so the gathered tree reshards structurally
            # on an elastic restore like master/opt do; the zero-sharded
            # path carries it in the per-process shard files instead
            "qg_error": ckpt.tree_to_numpy(self.state["qg_error"])
                if (self.state is not None
                    and self.state.get("qg_error") is not None
                    and not offload_sharded and not zero_sharded)
                else None,
            "csr_tensor_module_names": set(self.csr_tensor_module_names),
            "skipped_steps": self.skipped_steps,
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "dp_world_size": self.dp_world_size,
            "mp_world_size": self.mp_world_size,
        }
        pristine = getattr(self, "_onebit_pristine", None)
        if pristine is not None and \
                pristine.get("steps") == self.global_steps:
            # 1-bit elastic pass-through: no step has consumed the
            # folded worker residuals since the resharded load, so the
            # ORIGINAL per-worker rows are still the truth — re-emit
            # them and a later rescale back to their world restores the
            # error feedback bit-exactly (runtime/fp16/onebit_adam.py)
            sd["onebit_pristine"] = pristine["payload"]
        if self.host_state is not None and "torn_step" in self.host_state:
            # a failed overlapped offload step left the host masters
            # PARTIALLY stepped (see _host_apply_step's disaster path);
            # surface it so a resumed run knows the optimizer step was
            # torn rather than trusting the checkpoint as whole
            sd["torn_offload_step"] = self.host_state["torn_step"]
        sd.update(client_state)

        futures, records = [], []

        def note(res):
            # sync writes return integrity records, async ones futures of
            # those records; both feed the tag manifest
            if res is not None:
                (futures if hasattr(res, "result") else records).append(res)

        if is_writer:
            path = ckpt.model_ckpt_name(save_dir, tag,
                                        mp_rank=0)
            note(ckpt.save_state_dict(path, sd, async_save=async_save))
            logger.info("Saved checkpoint: {}".format(path))
        if offload_sharded:
            # EVERY process writes its own zero file with its host shards
            # (reference zero_pp_rank_N layout); keys serialize the shard
            # index so load re-slots them exactly
            zpath = ckpt.zero_ckpt_name(save_dir, tag,
                                        dp_rank=jax.process_index())
            note(ckpt.save_state_dict(zpath, {
                "offload_shards": [
                    [(_shard_key(idx), p, m, v) for idx, p, m, v in shards]
                    for shards in self.host_state["shard_leaves"]],
                "offload_step": self.host_state["step"],
                # a torn step is RANK-LOCAL (one process's update loop
                # failed); persist it in this rank's own zero file so a
                # multi-process resume sees it even when the writer rank
                # was healthy
                "torn_step": self.host_state.get("torn_step"),
            }, async_save=async_save))
        elif zero_sharded:
            # EVERY process writes its addressable master/opt shards to its
            # own zero file; keys serialize the shard index so load
            # re-slots them exactly — and, because every shard carries its
            # index into the FULL leaf, any process set can reassemble the
            # gathered tree, keeping elastic resharding on load
            zpath = ckpt.zero_ckpt_name(save_dir, tag,
                                        dp_rank=jax.process_index())
            note(ckpt.save_state_dict(zpath, {
                "device_shards": self._device_zero_shard_payload(is_writer),
            }, async_save=async_save))
        if jax.process_count() > 1:
            # EVERY process's files must land before the manifest and
            # `latest` move: a crash after the pointer update may
            # otherwise leave `latest` naming a checkpoint whose zero
            # shards never finished (reference barriers around checkpoint
            # IO, engine.py:1610)
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                "save_checkpoint_files:{}".format(tag))
        if _write_manifest:
            self._finalize_ckpt_tag(save_dir, tag, records, futures,
                                    save_latest, async_save)
        self._ckpt_futures = [f for f in futures if f is not None]
        self._ckpt_records = records
        if jax.process_count() > 1:
            # a process must not proceed to (and possibly load) a
            # checkpoint other writers haven't finished
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                "save_checkpoint:{}".format(tag))
        return True

    def _ckpt_manifest_meta(self):
        return {"global_step": int(self.global_steps),
                "dp_world_size": int(self.dp_world_size),
                "mp_world_size": int(self.mp_world_size)}

    def _finalize_ckpt_tag(self, save_dir, tag, records, futures,
                           save_latest, async_save):
        """Close out a checkpoint tag, writer-rank only: manifest.json
        LAST among the tag's files (its presence defines completeness),
        then the ``latest`` pointer, then retention GC. In async mode
        each step is queued on the serial writer pool gated on everything
        before it, so a failure anywhere leaves the manifest unwritten
        and ``latest`` naming the previous complete tag."""
        if jax.process_index() != 0:
            return
        meta = self._ckpt_manifest_meta()
        if async_save:
            futures.append(ckpt.write_manifest_after(
                save_dir, tag, futures, meta))
        else:
            records.append(ckpt.write_manifest(save_dir, tag, records, meta))
        if not save_latest:
            return
        if async_save:
            # the serial pool guarantees the latest task runs after this
            # process's shard+manifest writes; save_latest_after also
            # REFUSES the update if any of them failed, so `latest` can
            # never name a tag with a missing or unverifiable file
            futures.append(ckpt.save_latest_after(save_dir, tag, futures))
        else:
            ckpt.save_latest(save_dir, tag)
        keep_last_n = getattr(self._config, "checkpoint_keep_last_n", None)
        if keep_last_n:
            if async_save:
                futures.append(ckpt.prune_after(
                    save_dir, keep_last_n, futures))
            else:
                ckpt.prune_checkpoints(save_dir, keep_last_n)

    def wait_pending_writes(self):
        """Block until every queued checkpoint write has landed — this
        engine's in-flight async futures (re-raising the first failure)
        and anything else on the global background writer pool. Call
        before handing the checkpoint dir to another consumer."""
        self._drain_ckpt_writes()
        ckpt.wait_pending_writes()

    def close(self):
        """Tear this engine down for replacement (elastic rescale): land
        in-flight checkpoint writes, stop the background upload worker,
        release streamed-offload buffers, and close telemetry/monitor —
        the collector's close() releases its claimed host directory so
        the NEXT engine generation reuses the same telemetry dir
        (append-mode JSONL keeps one continuous record stream).
        Idempotent; the engine must not step afterwards."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        try:
            self._drain_ckpt_writes()
            ckpt.wait_pending_writes()
        except BaseException:  # noqa: BLE001 - teardown must not mask
            logger.warning("close: pending checkpoint writes failed",
                           exc_info=True)
        if getattr(self, "stream_runner", None) is not None:
            self.stream_runner.release()
        pool = getattr(self, "_h2d_pool", None)
        if pool is not None:
            pool.shutdown(wait=True)
            self._h2d_pool = None
        if self.telemetry is not None:
            self.telemetry.close()
        if self.monitor is not None:
            self.monitor.close()

    def _drain_ckpt_writes(self):
        """Block on any in-flight async checkpoint writes (re-raising the
        first background failure). Called before the next save, before a
        load, and available to callers that need the files on disk NOW.
        The list is cleared FIRST so one failed write raises once, not on
        every subsequent save/load forever."""
        futs = getattr(self, "_ckpt_futures", ())
        self._ckpt_futures = []
        first_err = None
        for fut in futs:  # serial pool: results arrive in submit order
            try:
                fut.result()
            except BaseException as err:  # noqa: BLE001
                first_err = first_err or err
        if first_err is not None:
            raise first_err

    def _device_zero_shard_payload(self, is_writer):
        """This process's addressable master/opt shards (device-state ZeRO
        save; reference per-rank zero files, engine.py:1350-1377)."""
        payload = {
            "master": ckpt.shard_lists_of_tree(self.state["master"],
                                               is_writer)
            if self.mixed_precision else None,
            "opt": {
                key: (np.asarray(val) if key == "step"
                      else ckpt.shard_lists_of_tree(val, is_writer))
                for key, val in self.state["opt"].items()
            },
            "qg_error": ckpt.shard_lists_of_tree(
                self.state["qg_error"], is_writer)
            if self.state.get("qg_error") is not None else None,
        }
        return payload

    def _zero_shard_paths(self, load_dir, tag):
        import glob
        pattern = os.path.join(
            load_dir, str(tag), "zero_pp_rank_*_mp_rank_00_optim_states.pt")
        return sorted(glob.glob(pattern))

    def _load_device_zero_state(self, load_dir, tag, sd,
                                load_optimizer_states):
        """Reassemble master/opt from per-process zero shard files into the
        gathered ``sd`` slots, so the normal (elastic, plan-agnostic)
        placement code runs unchanged. Understands both the device-state
        layout (``device_shards``) and, for cross-engine resume, the
        offload layout (``offload_shards``: (key, master, m, v) per
        acc-grad leaf)."""
        paths = self._zero_shard_paths(load_dir, tag)
        if not paths:
            if load_optimizer_states:
                # a ZeRO checkpoint with neither gathered state nor shard
                # files would otherwise silently resume with zeroed
                # moments (round-2 ADVICE)
                logger.warning(
                    "checkpoint %s/%s carries no optimizer state (no "
                    "gathered tree, no zero shard files) — optimizer "
                    "state starts fresh", load_dir, tag)
            return
        payloads = [ckpt.load_state_dict(p) for p in paths]

        if "offload_shards" in payloads[0]:
            # offload-written checkpoint loaded into a device-state engine:
            # entries are (key, master, exp_avg, exp_avg_sq) per leaf;
            # leaves are param-shaped, so the SAVED module tree supplies
            # shapes/structure
            module_flat, module_def = jax.tree_util.tree_flatten(
                sd["module"])

            def per_file(field):
                return [[(np.shape(module_flat[i]),
                          [(e[0], e[field]) for e in shards])
                         for i, shards in enumerate(p["offload_shards"])]
                        for p in payloads]

            master = ckpt.assemble_shard_lists(per_file(1), "master")
            sd["master"] = jax.tree_util.tree_unflatten(module_def, master)
            if load_optimizer_states:
                ea = ckpt.assemble_shard_lists(per_file(2), "exp_avg")
                ev = ckpt.assemble_shard_lists(per_file(3), "exp_avg_sq")
                sd["optimizer"] = {
                    "step": int(payloads[0]["offload_step"]),
                    "exp_avg": jax.tree_util.tree_unflatten(module_def, ea),
                    "exp_avg_sq": jax.tree_util.tree_unflatten(module_def,
                                                               ev),
                }
            return

        device = [p["device_shards"] for p in payloads]
        # streamed offload has no device params tree; the host registry's
        # treedef is the same structure
        params_def = (self.host_state["treedef"]
                      if self.state.get("params") is None
                      and self.host_state is not None
                      else jax.tree_util.tree_flatten(
                          self.state["params"])[1])
        mixed = self.mixed_precision or self.host_state is not None
        if device[0].get("master") is not None and mixed:
            master = ckpt.assemble_shard_lists(
                [d["master"] for d in device], "master")
            sd["master"] = jax.tree_util.tree_unflatten(params_def, master)
        if load_optimizer_states:
            # opt subtree structure comes from the live state; an OFFLOAD
            # engine loading a device checkpoint has opt=None (moments live
            # on host) — its Adam moments are params-structured
            live_opt = self.state.get("opt")
            keys = (live_opt.keys() if live_opt is not None
                    else device[0]["opt"].keys())
            opt = {}
            for key in keys:
                if key not in device[0]["opt"]:
                    logger.warning(
                        "zero shard files carry no '%s' optimizer state "
                        "(saved under a different optimizer) — it starts "
                        "fresh", key)
                    continue
                if key == "step":
                    opt["step"] = np.asarray(device[0]["opt"]["step"])
                    continue
                tmpl_def = (jax.tree_util.tree_flatten(live_opt[key])[1]
                            if live_opt is not None else params_def)
                leaves = ckpt.assemble_shard_lists(
                    [d["opt"][key] for d in device], "opt/" + key)
                opt[key] = jax.tree_util.tree_unflatten(tmpl_def, leaves)
            sd["optimizer"] = opt
        if device[0].get("qg_error") is not None:
            qg = ckpt.assemble_shard_lists(
                [d["qg_error"] for d in device], "qg_error")
            sd["qg_error"] = jax.tree_util.tree_unflatten(params_def, qg)

    def _load_host_state(self, load_dir, tag, sd, load_optimizer_states,
                         load_from_fp32_weights):
        """Restore the ZeRO-Offload host shards.

        A checkpoint written by a MULTI-process offload run carries its
        master/optimizer state ONLY in per-process zero shard files
        (sd["master"] is None there) — resuming it requires the exact same
        shard layout (same process count / ZeRO partitioning); a mismatch
        raises instead of silently resetting state differently per rank.
        Checkpoints with full gathered trees restore by slicing this
        process's shard indices out of them."""
        hs = self.host_state
        zpath = ckpt.zero_ckpt_name(load_dir, tag,
                                    dp_rank=jax.process_index())
        zsd = None
        if os.path.isfile(zpath):
            zsd = ckpt.load_state_dict(zpath)
        if zsd is not None and zsd.get("torn_step") is not None:
            logger.warning(
                "Zero shard file {} records a TORN offload step ({}): "
                "this rank's masters were partially stepped when the "
                "checkpoint was written. Resume is usable but re-run the "
                "step's batch; loss may blip.".format(
                    zpath, zsd["torn_step"]))
        if zsd is not None and "device_shards" in zsd:
            # device-state ZeRO checkpoint loaded into an OFFLOAD engine:
            # reassemble the gathered trees from every process's shard
            # file, then restore through the gathered path below
            self._load_device_zero_state(load_dir, tag, sd,
                                         load_optimizer_states)
            zsd = None
        sharded_only = sd.get("master") is None and \
            sd.get("optimizer") is None
        if zsd is not None and "offload_shards" in zsd:
            want = [[_shard_key(idx) for idx, *_ in shards]
                    for shards in hs["shard_leaves"]]
            got = [[tuple(map(tuple, key)) for key, *_ in shards]
                   for shards in zsd["offload_shards"]]
            if want == got:
                # master always restores from the exact fp32 shards unless
                # the caller explicitly asked for a half-precision recast;
                # moments/step only when the optimizer state is wanted
                recast = not load_from_fp32_weights
                module_flat = hs["treedef"].flatten_up_to(sd["module"]) \
                    if recast else None
                hs["shard_leaves"] = [
                    [(_key_to_index(key),
                      np.array(np.asarray(module_flat[i])[_key_to_index(key)],
                               dtype=np.float32, copy=True) if recast
                      else np.array(p, np.float32),
                      np.array(m, np.float32) if load_optimizer_states
                      else np.zeros(np.shape(p), np.float32),
                      np.array(v, np.float32) if load_optimizer_states
                      else np.zeros(np.shape(p), np.float32))
                     for key, p, m, v in shards]
                    for i, shards in enumerate(zsd["offload_shards"])]
                hs["step"] = int(zsd["offload_step"]) \
                    if load_optimizer_states else 0
                return
            if sharded_only:
                raise RuntimeError(
                    "offload checkpoint {} was written with a different "
                    "shard layout (process count / ZeRO partitioning) and "
                    "has no gathered master to re-slice — resume with the "
                    "layout it was saved under".format(zpath))
            logger.warning(
                "zero shard file %s has a different shard layout; falling "
                "back to the gathered checkpoint trees", zpath)
        elif sharded_only:
            raise RuntimeError(
                "offload checkpoint has per-process shard files but none "
                "for process {} ({}) — it was written with a different "
                "process count; resume with the layout it was saved "
                "under".format(jax.process_index(), zpath))

        src = sd["master"] if (load_from_fp32_weights
                               and sd.get("master") is not None) \
            else sd["module"]
        flat_src = hs["treedef"].flatten_up_to(src)
        opt = sd.get("optimizer") if load_optimizer_states else None
        flat_m = hs["treedef"].flatten_up_to(opt["exp_avg"]) if opt else None
        flat_v = hs["treedef"].flatten_up_to(opt["exp_avg_sq"]) if opt \
            else None
        hs["shard_leaves"] = [
            [(idx,
              np.array(np.asarray(full)[idx], dtype=np.float32, copy=True),
              np.array(np.asarray(flat_m[i])[idx], dtype=np.float32,
                       copy=True) if opt else np.zeros(
                           np.asarray(full)[idx].shape, np.float32),
              np.array(np.asarray(flat_v[i])[idx], dtype=np.float32,
                       copy=True) if opt else np.zeros(
                           np.asarray(full)[idx].shape, np.float32))
             for idx, *_ in shards]
            for i, (full, shards) in enumerate(
                zip(flat_src, hs["shard_leaves"]))]
        hs["step"] = int(opt["step"]) if opt else 0

    def _validate_tag(self, tag):
        if not self._config.checkpoint_tag_validation_enabled:
            return
        # All processes must agree on the tag; with >1 process compare via a
        # broadcast-from-0 (reference uses min/max hash allreduce).
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            agreed = multihost_utils.broadcast_one_to_all(
                np.frombuffer(str(tag).encode()[:32].ljust(32), dtype=np.uint8))
            mine = np.frombuffer(str(tag).encode()[:32].ljust(32),
                                 dtype=np.uint8)
            if not np.array_equal(agreed, mine):
                msg = "Checkpoint tag '{}' differs across processes".format(tag)
                if self._config.checkpoint_tag_validation_fail:
                    raise ValueError(msg)
                logger.warning(msg)

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True,
                        load_from_fp32_weights=True):
        """Load a checkpoint; returns (path, client_state)
        (reference engine.py:1379-1482).

        Elastic resharding is structural: state dicts store FULL (gathered)
        trees, and loading device_puts each leaf with the CURRENT engine's
        plan — a checkpoint written at dp=8 loads into a dp=4 or 3D mesh
        unchanged (the reference needs bespoke re-slicing,
        stage1.py:1048-1107; GSPMD makes it a placement detail).

        ``load_from_fp32_weights``: restore the fp32 master from the saved
        fp32 shards (exact resume) vs recast from the fp16/bf16 params
        (reference stage2.py:1741-1763 toggle).

        Integrity + last-good fallback (docs/checkpoint_recovery.md): the
        chosen tag's manifest and file checksums are verified first; on
        any mismatch/missing file — or corruption surfacing mid-load —
        the scan walks backward through prior tags to the newest complete
        one, logging exactly what was rejected and why, instead of
        crashing or loading torn state. The fallback applies when
        ``tag=None`` (resume-from-latest); an explicitly named tag that
        fails returns ``(None, None)`` rather than silently substituting
        different weights. Tags predating the manifest format load
        unverified with a warning.
        """
        self._drain_ckpt_writes()
        ckpt.wait_pending_writes()
        requested = tag
        if tag is None:
            tag = ckpt.read_latest(load_dir)

        def _reject(bad_tag, why):
            logger.error("checkpoint tag %r under %s rejected: %s",
                         bad_tag, load_dir, why)

        tried = []
        verified_by_scan = False
        while True:
            if tag is None:
                if requested is not None:
                    # the caller named this tag explicitly: quietly
                    # loading some OTHER tag would resume on the wrong
                    # weights with no programmatic signal — fail instead
                    # (tag=None opts into the last-good fallback)
                    break
                tag = ckpt.newest_complete_tag(load_dir, exclude=tried,
                                               on_reject=_reject)
                if tag is None:
                    break
                verified_by_scan = True
                logger.warning(
                    "falling back to newest complete checkpoint tag %r "
                    "under %s", tag, load_dir)
            tried.append(tag)
            # a tag the scan returned already passed the full CRC check —
            # don't re-read a multi-GB checkpoint just to verify it twice
            ok, reason = (True, None) if verified_by_scan \
                else ckpt.verify_tag(load_dir, tag)
            if ok or reason == ckpt.NO_MANIFEST:
                if not ok:
                    logger.warning(
                        "checkpoint %s/%s predates the manifest format — "
                        "loading without integrity verification",
                        load_dir, tag)
                try:
                    return self._load_checkpoint_tag(
                        load_dir, tag, load_module_strict,
                        load_optimizer_states, load_lr_scheduler_states,
                        load_from_fp32_weights)
                except ckpt.CheckpointCorruptionError as err:
                    if ok:
                        # the bytes CRC-verified, yet unpickling failed:
                        # that is not bit-rot but an environment/pickle
                        # compatibility problem every other tag would
                        # repeat — crash loudly instead of silently
                        # walking back to (None, None) and a fresh start
                        raise
                    _reject(tag, err)
            else:
                _reject(tag, reason)
            tag = None  # scan for the next-newest complete tag

        logger.warning(
            "Unable to find a loadable checkpoint under {} (requested "
            "tag: {}); pass a valid tag or check the rejection log "
            "above".format(load_dir, requested if requested is not None
                           else "latest"))
        return None, None

    def _load_checkpoint_tag(self, load_dir, tag, load_module_strict,
                             load_optimizer_states,
                             load_lr_scheduler_states,
                             load_from_fp32_weights):
        path = ckpt.model_ckpt_name(load_dir, tag, mp_rank=0)
        if not os.path.isfile(path):
            raise ckpt.CheckpointCorruptionError(
                "model states file {} does not exist".format(path))
        sd = ckpt.load_state_dict(path)
        sd = self._adapt_state_dict(sd)

        if sd.get("torn_offload_step") is not None:
            logger.warning(
                "Checkpoint {} was written after a FAILED overlapped "
                "offload step (torn optimizer step {}): some master "
                "shards stepped, some did not. Resume is usable but the "
                "step's batch should be re-run; loss may blip.".format(
                    path, sd["torn_offload_step"]))

        if self.host_state is None and sd.get("optimizer") is None:
            # ZeRO-sharded checkpoint: reassemble gathered trees from the
            # per-process zero files before the plan-agnostic placement
            self._load_device_zero_state(load_dir, tag, sd,
                                         load_optimizer_states)
            sd = self._adapt_state_dict(sd)

        plan = self.zero_plan
        if self.state["params"] is not None:
            param_sh = plan.tree_shardings(self.state["params"], "param")
            self.state["params"] = jax.tree_util.tree_map(
                lambda x, old, s: jax.device_put(
                    jnp.asarray(x, dtype=old.dtype), s),
                sd["module"], self.state["params"], param_sh)

        if self.host_state is not None:
            self._load_host_state(load_dir, tag, sd, load_optimizer_states,
                                  load_from_fp32_weights)
        elif self.mixed_precision and load_from_fp32_weights and \
                sd.get("master") is not None:
            master_sh = plan.tree_shardings(self.state["master"], "master")
            self.state["master"] = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(jnp.asarray(x, jnp.float32), s),
                sd["master"], master_sh)
        elif self.mixed_precision:
            # recompute master from the (lower-precision) params
            master_sh = plan.tree_shardings(self.state["master"], "master")
            self.state["master"] = jax.tree_util.tree_map(
                lambda p, s: jax.device_put(jnp.asarray(p, jnp.float32), s),
                self.state["params"], master_sh)

        if self.host_state is None and load_optimizer_states and \
                sd.get("optimizer") is not None:
            opt = sd["optimizer"]
            saved_dp = sd.get("dp_world_size")
            pristine = sd.get("onebit_pristine")
            reshard = getattr(self.optimizer, "reshard_state", None)
            self._onebit_pristine = None
            if callable(reshard) and saved_dp is not None and \
                    int(saved_dp) != int(self.dp_world_size):
                # elastic restore across world sizes: world-size-
                # dependent subtrees (1-bit error feedback) are
                # canonicalised to this engine's layout; world-agnostic
                # ones pass through untouched
                opt = reshard(opt, int(saved_dp), pristine=pristine)
                pristine = getattr(self.optimizer, "_reshard_pristine",
                                   pristine)
            if pristine is not None:
                # carry the original per-worker error rows until a step
                # consumes them (save_checkpoint re-emits the sidecar
                # only while global_steps is unchanged)
                self._onebit_pristine = {"payload": pristine,
                                         "steps": None}
            # shardings from each subtree's own leaf shapes (error buffers
            # etc. are not param-shaped)
            self.state["opt"] = {
                key: jnp.asarray(val) if key == "step" else
                jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(jnp.asarray(x, jnp.float32), s),
                    val, self._opt_state_shardings(key, val))
                for key, val in opt.items()
            }

        if sd.get("qg_error") is not None and self.state is not None \
                and self.state.get("qg_error") is not None:
            # param-shaped leaves: device_put onto the LIVE buffers'
            # shardings reshards structurally across world sizes
            self.state["qg_error"] = jax.tree_util.tree_map(
                lambda x, live: jax.device_put(
                    jnp.asarray(x, jnp.float32), live.sharding),
                sd["qg_error"], self.state["qg_error"])

        if sd.get("scaler") is not None:
            sc = sd["scaler"]
            self.state["scaler"] = self.state["scaler"]._replace(
                cur_scale=jnp.asarray(sc["cur_scale"], jnp.float32),
                cur_hysteresis=jnp.asarray(sc["cur_hysteresis"], jnp.int32),
                last_overflow_iter=jnp.asarray(sc["last_overflow_iter"],
                                               jnp.int32),
                cur_iter=jnp.asarray(sc["cur_iter"], jnp.int32))

        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                sd.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(sd["lr_scheduler"])

        self.global_steps = sd.get("global_steps", 0)
        if getattr(self, "_onebit_pristine", None) is not None:
            self._onebit_pristine["steps"] = self.global_steps
        self.global_samples = sd.get(
            "global_samples", self.global_steps * self.train_batch_size())
        self.skipped_steps = sd.get("skipped_steps", 0)
        if self.state is not None and "skip_count" in self.state:
            # keep the device counter aligned so periodic re-syncs stay exact
            self.state["skip_count"] = jnp.int32(self.skipped_steps)
        self.loaded_checkpoint_dp_world_size = sd.get("dp_world_size")

        known = {"module", "optimizer", "master", "scaler", "lr_scheduler",
                 "qg_error", "onebit_pristine", "csr_tensor_module_names",
                 "skipped_steps", "global_steps", "global_samples",
                 "dp_world_size", "mp_world_size"}
        client_state = {k: v for k, v in sd.items() if k not in known}
        logger.info("Loaded checkpoint: {} @ global_step={}".format(
            path, self.global_steps))
        return path, client_state
