"""Small helpers shared by all config parsers.

Reference parity: deepspeed/runtime/config_utils.py (get_scalar_param,
duplicate-key-rejecting JSON load).
"""
import json


def get_scalar_param(param_dict, param_name, param_default_value):
    """Fetch ``param_name`` from a dict, falling back to a default."""
    if param_dict is None:
        return param_default_value
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """``json.load(..., object_pairs_hook=...)`` hook that rejects duplicate keys."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counts = {}
        for key, _ in ordered_pairs:
            counts[key] = counts.get(key, 0) + 1
        duplicates = [key for key, cnt in counts.items() if cnt > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(duplicates))
    return d

