from .compressed import (CompressedBackend, compressed_allreduce_local,
                         masked_compress)
from .quantize import (DEFAULT_BLOCK_SIZE, QuantizedCollectives,
                       dequantize_blockwise, dequantize_param, pack_signs,
                       quantize_blockwise, quantize_dequantize,
                       quantize_param, quantize_with_error_feedback,
                       quantized_all_gather_local,
                       quantized_reduce_scatter_local, qwz_gather,
                       sign_scale, unpack_signs)
