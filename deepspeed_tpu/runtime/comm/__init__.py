from .compressed import CompressedBackend
from .onebit import (compressed_allreduce_local, masked_compress,
                     onebit_all_gather_local, onebit_padded_size,
                     onebit_reduce_scatter_local)
from .quantize import (DEFAULT_BLOCK_SIZE, QuantizedCollectives,
                       dequantize_blockwise, dequantize_param,
                       hierarchical_all_reduce_local, pack_signs,
                       qc_padded_size, quantize_blockwise,
                       quantize_dequantize, quantize_param,
                       quantize_with_error_feedback,
                       quantized_all_gather_local,
                       quantized_all_reduce_local,
                       quantized_reduce_scatter_local,
                       ring_reduce_scatter_inline, qwz_gather,
                       sign_scale, unpack_signs)
