from .compressed import (CompressedBackend, compressed_allreduce_local,
                         pack_signs, unpack_signs)
