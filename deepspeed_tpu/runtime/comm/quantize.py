"""Blockwise int8 symmetric quantization codec for ZeRO collectives.

Reference parity: the ZeRO++ communication codecs (arXiv:2306.10209 —
qwZ quantized weight all-gather, qgZ quantized gradient reduce-scatter)
and EQuARX-style blockwise-quantized collectives inside XLA
(arXiv:2506.17615). One codec, three transports:

  * flat codec (``quantize_blockwise``/``dequantize_blockwise``): a flat
    buffer becomes ``(int8 blocks, per-block scales)``; the explicit
    shard_map collectives (``quantized_all_gather_local``,
    ``quantized_reduce_scatter_local``) exchange that representation, so
    wire volume is ~4x below fp32 (1 byte/lane + one scale per block);
  * shape-preserving codec (``quantize_param``/``dequantize_param``):
    blocks tile the LAST dimension and the int8 array keeps the input's
    shape, so GSPMD sharding annotations stay meaningful — this is what
    ``qwz_gather`` rides;
  * ``qwz_gather``: the ZeRO-3 quantized weight all-gather as pure
    dataflow — quantize the data-sharded parameter, constrain the int8
    blocks + scales to the gathered (data-axes-dropped) sharding so XLA
    emits the all-gather ON THE INT8 REPRESENTATION, dequantize
    on-device. A straight-through custom_vjp sends the cotangent back
    constrained to the sharded layout (XLA lowers it to the gradient
    reduce-scatter), exactly the ZeRO++ fused gather/scatter pair.

Scales follow the INPUT dtype (a bf16 buffer quantizes to bf16 scales):
the encode side casts the scale to the storage dtype BEFORE dividing, so
encode/decode agree bit-exactly and nothing upcasts mid-pipeline.

The 1-bit path (``onebit.py``/``compressed.py``) shares the sign-pack
helpers below (``pack_signs``/``unpack_signs``/``sign_scale``).

IN-COLLECTIVE mode (EQuARX, arXiv:2506.17615): instead of quantizing a
buffer once and letting the collective move it, quantization is pushed
INSIDE the ring — :func:`ring_reduce_scatter_inline` dequantizes each
arriving int8 hop to fp32, accumulates its local contribution in fp32,
and requantizes for the next hop, so every wire hop is int8 blocks +
scales while the reduction itself never leaves fp32.
:func:`hierarchical_all_reduce_local` is its two-level decomposition
("The Big Send-off", arXiv:2504.18658) over ``topology.factor_data_axis``
sub-axes: intra-``data_shard`` ring RS → cross-``data_replica`` ring
RS + int8 all-gather → intra-``data_shard`` int8 all-gather, keeping
most hops on the ICI-adjacent shard group.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp

# Per-block lane count. 256 fp32 lanes -> 256 int8 bytes + one scale:
# 3.9x below fp32 on the wire; small enough that one outlier lane only
# poisons 255 neighbors (EQuARX uses the same order of magnitude).
DEFAULT_BLOCK_SIZE = 256

_QMAX = 127.0  # symmetric int8 range [-127, 127]; -128 unused


# --------------------------------------------------------------- sign helpers
# (shared with the 1-bit path in compressed.py)
_BIT_WEIGHTS = 2 ** np.arange(8, dtype=np.uint8)


def pack_signs(x):
    """Pack sign bits of ``x`` (size divisible by 8) into uint8, 8 lanes per
    byte (cupy packbits equivalent, compression/cupy.py:20)."""
    bits = (x >= 0).astype(jnp.uint8).reshape(-1, 8)
    return (bits * jnp.asarray(_BIT_WEIGHTS)).sum(axis=-1).astype(jnp.uint8)


def unpack_signs(packed, scale):
    """uint8 bytes -> ±scale values, in the SCALE's dtype (a bf16 scale
    decodes to bf16 — nothing upcasts to fp32 mid-pipeline)."""
    scale = jnp.asarray(scale)
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    signs = (2 * bits.astype(scale.dtype) - 1).reshape(-1)
    return scale * signs


def sign_scale(masked, count):
    """The 1-bit codec's single scale ``||x||/sqrt(n)`` over the real
    lanes, in the input's dtype (norm computed fp32 for range safety)."""
    norm = jnp.linalg.norm(masked.astype(jnp.float32))
    return (norm / jnp.sqrt(jnp.maximum(count, 1.0))).astype(masked.dtype)


# ----------------------------------------------------------------- flat codec
def _block_count(n, block_size):
    return -(-n // block_size)


def _quantize_blocks(blocks, dtype):
    """The shared codec core over pre-blocked values (block dim LAST):
    symmetric per-block scale = absmax/127, cast to the storage ``dtype``
    BEFORE the divide so the decode side reconstructs with the identical
    scale value. Returns ``(q int8, scales[..., 1] in dtype)``."""
    blocks = blocks.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scales = (absmax / _QMAX).astype(dtype)
    safe = jnp.maximum(scales.astype(jnp.float32), jnp.float32(1e-30))
    q = jnp.clip(jnp.round(blocks / safe), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scales


def quantize_blockwise(x, block_size=DEFAULT_BLOCK_SIZE):
    """Flat buffer -> ``(q, scales)``: ``q`` int8 of shape
    ``(nblocks, block_size)`` (zero-padded past ``x.size``), ``scales`` of
    shape ``(nblocks,)`` in ``x``'s dtype."""
    dtype = x.dtype
    flat = x.reshape(-1)
    n = flat.size
    padded = _block_count(n, block_size) * block_size
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    q, scales = _quantize_blocks(flat.reshape(-1, block_size), dtype)
    return q, scales.reshape(-1)


def dequantize_blockwise(q, scales, size=None, dtype=None):
    """Inverse of ``quantize_blockwise``: flat array of ``size`` lanes in
    ``dtype`` (defaults: all lanes, the scales' dtype)."""
    dtype = scales.dtype if dtype is None else dtype
    out = (q.astype(jnp.float32)
           * scales.astype(jnp.float32)[:, None]).reshape(-1)
    if size is not None and size != out.size:
        out = out[:size]
    return out.astype(dtype)


def quantize_dequantize(x, block_size=DEFAULT_BLOCK_SIZE):
    """Round-trip through the flat codec, same shape/dtype as ``x``."""
    q, scales = quantize_blockwise(x, block_size)
    return dequantize_blockwise(q, scales, x.size, x.dtype).reshape(x.shape)


def quantize_with_error_feedback(x, err, block_size=DEFAULT_BLOCK_SIZE,
                                 scale=1.0):
    """Error-compensated round-trip (the qgZ accumulator): quantize
    ``x + err*scale``, return ``(dequantized, new_err)`` where ``new_err``
    is the residual the NEXT call folds back in — the long-run average is
    unbiased even though each step is int8.

    ``scale``: the unit ``x`` is expressed in (e.g. the dynamic loss
    scale). The residual is stored DIVIDED by it, so when the caller's
    scale changes between calls the carried correction keeps the right
    magnitude instead of injecting a 2x-off bias right after a scale
    halving/doubling."""
    scale = jnp.asarray(scale, jnp.float32)
    corrected = x.astype(jnp.float32) + err * scale
    qd = quantize_dequantize(corrected, block_size)
    return qd.astype(x.dtype), (corrected - qd) / scale


# ------------------------------------------------- shape-preserving codec
def _lastdim_block(last, block_size):
    """Largest divisor of ``last`` that is <= block_size (static shapes:
    plain python). A ragged tail block would change the array's shape and
    break the sharding annotation the qwZ path relies on."""
    block = min(int(block_size), int(last))
    while last % block:
        block -= 1
    return block


def quantize_param(x, block_size=DEFAULT_BLOCK_SIZE):
    """Shape-preserving codec: ``q`` is int8 with ``x``'s shape, scales
    have shape ``x.shape[:-1] + (nblocks,)`` where blocks tile the LAST
    dimension. Rank-0/1-lane inputs degrade to one block."""
    if x.ndim == 0:
        x = x.reshape(1)
    block = _lastdim_block(x.shape[-1], block_size)
    blocks = x.reshape(x.shape[:-1] + (x.shape[-1] // block, block))
    q, scales = _quantize_blocks(blocks, x.dtype)
    return q.reshape(x.shape), scales.squeeze(-1)


def dequantize_param(q, scales, dtype):
    """Inverse of ``quantize_param``."""
    nblocks = scales.shape[-1]
    block = q.shape[-1] // nblocks
    blocks = q.reshape(q.shape[:-1] + (nblocks, block))
    out = blocks.astype(jnp.float32) * \
        scales.astype(jnp.float32)[..., None]
    return out.reshape(q.shape).astype(dtype)


# ------------------------------------------------------------- qwZ gather
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def qwz_gather(x, gathered_sharding, sharded_sharding,
               block_size=DEFAULT_BLOCK_SIZE):
    """ZeRO++ quantized weight all-gather as GSPMD dataflow.

    ``x``: a data-axis-sharded parameter (``sharded_sharding``). The int8
    blocks + scales are constrained to ``gathered_sharding`` (the param's
    spec with data axes dropped), so the all-gather XLA inserts moves the
    QUANTIZED representation — ~4x less wire than an fp32 gather, 2x less
    than bf16. Dequantizes to ``x.dtype`` on-device.

    Backward is straight-through: the cotangent (the full gradient) is
    constrained to ``sharded_sharding``, which XLA lowers to the ZeRO
    gradient reduce-scatter. The quantization noise is NOT differentiated
    through (sign/round have useless gradients), matching ZeRO++.
    """
    return _qwz_fwd_value(x, gathered_sharding, block_size)


def _qwz_fwd_value(x, gathered_sharding, block_size):
    q, scales = quantize_param(x, block_size)
    if gathered_sharding is not None:
        q = jax.lax.with_sharding_constraint(q, gathered_sharding)
        scales = jax.lax.with_sharding_constraint(
            scales, _rank_adjusted(gathered_sharding, scales.ndim))
    return dequantize_param(q, scales, x.dtype).reshape(x.shape)


def _rank_adjusted(sharding, ndim):
    """``gathered_sharding`` is built for the param's rank; scales drop or
    keep rank (rank-0 params became rank-1). Pad/trim the spec to fit."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = list(sharding.spec)
    spec = (spec + [None] * ndim)[:ndim]
    return NamedSharding(sharding.mesh, P(*spec))


def _qwz_fwd(x, gathered_sharding, sharded_sharding, block_size):
    return _qwz_fwd_value(x, gathered_sharding, block_size), None


def _qwz_bwd(gathered_sharding, sharded_sharding, block_size, _res, ct):
    if sharded_sharding is not None:
        ct = jax.lax.with_sharding_constraint(ct, sharded_sharding)
    return (ct,)


qwz_gather.defvjp(_qwz_fwd, _qwz_bwd)


# ------------------------------------------------- shard_map collective bodies
def quantized_all_gather_local(x, axis_name,
                               block_size=DEFAULT_BLOCK_SIZE):
    """Per-device body (call inside shard_map over ``axis_name``): quantize
    this device's flat shard, all-gather int8 blocks + scales, dequantize.
    Returns the concatenated (world*n,) buffer in ``x.dtype``."""
    n = x.size
    q, scales = quantize_blockwise(x, block_size)
    qg = jax.lax.all_gather(q, axis_name)          # (world, nb, block)
    sg = jax.lax.all_gather(scales, axis_name)     # (world, nb)
    deq = jax.vmap(lambda qq, ss: dequantize_blockwise(qq, ss, n, x.dtype))(
        qg, sg)
    return deq.reshape(-1)


def quantized_reduce_scatter_local(x, axis_name, world_size,
                                   block_size=DEFAULT_BLOCK_SIZE,
                                   error=None):
    """qgZ-style quantized reduce-scatter per-device body.

    ``x``: this device's full-length partial-sum buffer, size divisible by
    ``world_size``; chunk w is destined to rank w. Each chunk is int8-
    quantized (optionally with persistent ``error`` feedback), the int8
    chunks + scales ride ``all_to_all``, and each rank dequantizes and
    sums its own chunk across workers — wire is int8+scales instead of
    fp32, the reduction itself stays full precision on-device.

    Returns ``(local_sum_chunk, new_error)`` (``new_error`` is None when
    no feedback buffer was passed).
    """
    chunk = x.size // world_size
    corrected = x if error is None else x + error.astype(x.dtype)
    rows = corrected.reshape(world_size, chunk)
    # quantize every destination chunk with its own block grid
    q, scales = jax.vmap(
        lambda r: quantize_blockwise(r, block_size))(rows)
    new_error = None
    if error is not None:
        deq = jax.vmap(
            lambda qq, ss: dequantize_blockwise(qq, ss, chunk, x.dtype))(
                q, scales)
        new_error = (corrected - deq.reshape(-1)).astype(jnp.float32)
    recv_q = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                                tiled=False)
    recv_s = jax.lax.all_to_all(scales, axis_name, split_axis=0,
                                concat_axis=0, tiled=False)
    deq = jax.vmap(
        lambda qq, ss: dequantize_blockwise(qq, ss, chunk, jnp.float32))(
            recv_q, recv_s)
    return deq.sum(axis=0).astype(x.dtype), new_error


# -------------------------------------------------- fused flat layout
class FusedFlatLayout:
    """Static layout of ONE fused flat fp32 buffer over a param tree —
    the contract both compressed exchanges ride (the engine's quantized
    gradient exchange and OnebitAdam's momentum buffer): leaves in jax
    tree-flatten order, row-major concatenated, padded to
    ``padded_size_fn(numel)`` (``qc_padded_size`` for the int8 ring,
    ``onebit_padded_size`` for the sign-pack exchange). One
    implementation so the two can never desynchronize."""

    def __init__(self, tree, padded_size_fn):
        flat, self.treedef = jax.tree_util.tree_flatten(tree)
        self.leaf_meta = []
        off = 0
        for p in flat:
            n = int(np.prod(np.shape(p))) if np.shape(p) else 1
            self.leaf_meta.append((off, n, tuple(np.shape(p))))
            off += n
        self.numel = off
        self.padded = int(padded_size_fn(off))

    def flatten(self, tree):
        """Tree -> (padded,) fp32 fused buffer."""
        rows = [jnp.asarray(x, jnp.float32).reshape(-1)
                for x in self.treedef.flatten_up_to(tree)]
        flat = jnp.concatenate(rows)
        pad = self.padded - self.numel
        return jnp.pad(flat, (0, pad)) if pad else flat

    def flatten_rows(self, stacked):
        """Stacked tree (leaves (w, *shape)) -> (w, padded) fp32."""
        rows = [g.reshape(g.shape[0], -1).astype(jnp.float32)
                for g in self.treedef.flatten_up_to(stacked)]
        flat = jnp.concatenate(rows, axis=1)
        pad = self.padded - self.numel
        return jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat

    def slices(self, flat):
        """(padded,) buffer -> per-leaf tree of reshaped views."""
        leaves = [flat[off:off + n].reshape(shape)
                  for off, n, shape in self.leaf_meta]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def unflatten_like(self, flat, like):
        """(padded,) buffer -> tree in the dtypes of ``like``."""
        return jax.tree_util.tree_map(
            lambda x, l: x.astype(l.dtype), self.slices(flat), like)


# ---------------------------------------------------- in-collective mode
def qc_padded_size(n, world_size, block_size=DEFAULT_BLOCK_SIZE):
    """Lanes the in-collective exchange needs: a multiple of
    ``world * block_size`` so every per-rank chunk (and, hierarchically,
    every sub-chunk) is whole blocks. ``world`` is the PRODUCT of the
    group sizes across levels."""
    mult = int(world_size) * int(block_size)
    return ((int(n) + mult - 1) // mult) * mult


def ring_reduce_scatter_inline(x, axis_name, world_size,
                               block_size=DEFAULT_BLOCK_SIZE):
    """EQuARX in-collective ring reduce-scatter per-device body (call
    inside shard_map over ``axis_name``).

    ``x``: this device's full-length partial-sum buffer of size
    ``world_size * chunk`` with ``chunk`` divisible by ``block_size``;
    chunk w is destined to rank w. Each of the ``world-1`` ring hops
    moves ONE quantized chunk (int8 blocks + per-block scales); the
    receiver dequantizes to fp32, accumulates its own fp32 contribution,
    and requantizes for the next hop — NOT quantize-once-then-sum, so
    the reduction itself never leaves fp32 and the final addition (my
    own chunk) is exact. Returns my rank's fp32-accumulated chunk.
    """
    chunk = x.size // world_size
    local = x.astype(jnp.float32).reshape(world_size, chunk)
    if world_size == 1:
        return local[0]
    rank = jax.lax.axis_index(axis_name)
    w = jnp.int32(world_size)

    def take(idx):
        return jnp.take(local, jnp.mod(idx, w), axis=0)

    perm = [(i, (i + 1) % world_size) for i in range(world_size)]
    # the partial for chunk c starts at device (c+1) mod w and terminates
    # (fully accumulated) at device c after world-1 hops
    acc = take(rank - 1)
    for s in range(world_size - 1):
        q, scales = quantize_blockwise(acc, block_size)
        q = jax.lax.ppermute(q, axis_name, perm)
        scales = jax.lax.ppermute(scales, axis_name, perm)
        incoming = dequantize_blockwise(q, scales, chunk, jnp.float32)
        acc = incoming + take(rank - 2 - s)
    return acc


def quantized_all_reduce_local(x, axis_name, world_size,
                               block_size=DEFAULT_BLOCK_SIZE):
    """Flat in-collective all-reduce SUM per-device body: EQuARX ring
    reduce-scatter then int8 all-gather. ``x``: (n,) local partials with
    n divisible by ``world * block``. Returns the (n,) fp32 global sum
    (the caller divides by world for a mean)."""
    chunk = ring_reduce_scatter_inline(x, axis_name, world_size,
                                       block_size)
    if world_size == 1:
        return chunk
    return quantized_all_gather_local(chunk, axis_name, block_size)


def hierarchical_all_reduce_local(x, shard_axis, replica_axis, shard_size,
                                  replica_size,
                                  block_size=DEFAULT_BLOCK_SIZE):
    """Two-level in-collective all-reduce SUM (The Big Send-off,
    arXiv:2504.18658), composing with the hpZ-factored mesh: intra-shard
    ring RS → cross-replica ring RS + int8 AG on the 1/shard chunk →
    intra-shard int8 AG. ``x``: (n,) with n divisible by
    ``shard * replica * block``. Most wire hops cross only the
    ICI-adjacent ``data_shard`` group; the ``data_replica`` hop moves
    ``1/shard`` of the payload. Returns the (n,) fp32 global sum."""
    chunk_s = ring_reduce_scatter_inline(x, shard_axis, shard_size,
                                         block_size)
    if replica_size > 1:
        chunk_r = ring_reduce_scatter_inline(chunk_s, replica_axis,
                                             replica_size, block_size)
        chunk_s = quantized_all_gather_local(chunk_r, replica_axis,
                                             block_size)
    if shard_size > 1:
        return quantized_all_gather_local(chunk_s, shard_axis, block_size)
    return chunk_s


# ------------------------------------------------------------ mesh transports
class QuantizedCollectives:
    """CompressedBackend-style façade: blockwise-int8 collectives over
    the mesh's data axis (or its hpZ-factored sub-axes), jitted through
    shard_map.

    ``all_gather(values)``: (world, n) stacked shards -> (world, world*n)
    gathered rows. ``reduce_scatter(values)``: (world, world*chunk)
    per-rank partials -> (world, chunk) summed chunks. ``all_reduce
    (values)``: (world, n) per-rank partials -> (world, n) summed rows
    through the IN-COLLECTIVE ring (EQuARX per-hop requantization), with
    the two-level hierarchical decomposition on a factored mesh.
    """

    def __init__(self, mesh, axis=None, block_size=DEFAULT_BLOCK_SIZE):
        from ...parallel.topology import (DATA_AXIS, DATA_REPLICA_AXIS,
                                          DATA_SHARD_AXIS)
        self.mesh = mesh
        if axis is None:
            axis = DATA_AXIS if DATA_AXIS in mesh.shape else \
                (DATA_REPLICA_AXIS, DATA_SHARD_AXIS)
        self.axis = axis
        axes = axis if isinstance(axis, tuple) else (axis,)
        self.world_size = int(np.prod([mesh.shape[a] for a in axes],
                                      dtype=np.int64))
        self.hierarchical = isinstance(axis, tuple) and len(axes) > 1
        self.block_size = block_size
        self._jit_cache = {}

    def _build(self, kind, n):
        from jax.sharding import PartitionSpec as P
        from ...parallel.topology import shard_map_compat
        key = (kind, n)
        if key in self._jit_cache:
            return self._jit_cache[key]
        axis, world, block = self.axis, self.world_size, self.block_size
        mesh = self.mesh

        if kind == "all_gather":
            def per_device(v):
                return quantized_all_gather_local(v[0], axis, block)[None]
        elif kind == "all_reduce":
            if self.hierarchical:
                replica_axis, shard_axis = axis
                wr = int(mesh.shape[replica_axis])
                ws = int(mesh.shape[shard_axis])

                def per_device(v):
                    return hierarchical_all_reduce_local(
                        v[0], shard_axis, replica_axis, ws, wr,
                        block)[None]
            else:
                def per_device(v):
                    return quantized_all_reduce_local(v[0], axis, world,
                                                      block)[None]
        else:
            def per_device(v):
                out, _ = quantized_reduce_scatter_local(v[0], axis, world,
                                                        block)
                return out[None]

        fn = jax.jit(shard_map_compat(
            per_device, mesh=self.mesh, in_specs=(P(axis),),
            out_specs=P(axis)))
        self._jit_cache[key] = fn
        return fn

    def all_gather(self, values):
        return self._build("all_gather", values.shape[-1])(values)

    def reduce_scatter(self, values):
        assert values.shape[-1] % self.world_size == 0, values.shape
        return self._build("reduce_scatter", values.shape[-1])(values)

    def all_reduce(self, values):
        """In-collective quantized SUM of the stacked (world, n) rows;
        n must be ``qc_padded_size``-aligned for the mesh's group
        sizes."""
        assert values.shape[-1] % (self.world_size * self.block_size) \
            == 0, (values.shape, self.world_size, self.block_size)
        return self._build("all_reduce", values.shape[-1])(values)
