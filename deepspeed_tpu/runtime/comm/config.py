"""``comm`` ds_config section: collective-communication behavior.

Currently one sub-section, ``comm.collective_matmul`` — the gate for
the ring-decomposed all-gather/reduce-scatter GEMMs
(``parallel/collective_matmul.py``). Off by default: the unfused XLA
path stays the reference oracle, and fusion is an explicit opt-in.

Shape::

    "comm": {
      "collective_matmul": {
        "enabled": false,          // master switch
        "tensor_parallel": true,   // fuse the TP qkv/fc gathers + proj/fc2 scatters
        "zero_gather": true,       // ring-decompose the ZeRO-3 weight all-gather
        "chunks": 1,               // ppermute pieces per ring hop (granularity only;
                                   // bytes == the one-shot collective, wire.py)
        "dtype": "compute",        // wire dtype policy: "compute" (bit-exact)
                                   // or "bf16" (half-width, lossy hop)
        "backend": "ppermute",     // ring backend: "ppermute" (XLA schedules the
                                   // overlap; the oracle) or "pallas" (explicit
                                   // async remote copies + semaphore waits,
                                   // ops/pallas/ring_gemm; docs/pallas_kernels.md)
        "strict": false            // unknown/unhonorable keys raise instead of warn
      }
    }

Validated with the PR 4/5 no-silent-no-ops policy: unknown keys warn,
and raise when ``comm.collective_matmul.strict`` is set.
"""
from ...telemetry.config import warn_or_raise_noop

COMM = "comm"
COLLECTIVE_MATMUL = "collective_matmul"

CM_ENABLED = "enabled"
CM_ENABLED_DEFAULT = False
CM_TENSOR_PARALLEL = "tensor_parallel"
CM_TENSOR_PARALLEL_DEFAULT = True
CM_ZERO_GATHER = "zero_gather"
CM_ZERO_GATHER_DEFAULT = True
CM_CHUNKS = "chunks"
CM_CHUNKS_DEFAULT = 1
CM_DTYPE = "dtype"
CM_DTYPE_DEFAULT = "compute"
CM_DTYPES = ("compute", "bf16")
CM_BACKEND = "backend"
CM_BACKEND_DEFAULT = "ppermute"
CM_BACKENDS = ("ppermute", "pallas")
CM_STRICT = "strict"

KNOWN_COMM_KEYS = {COLLECTIVE_MATMUL}
KNOWN_COLLECTIVE_MATMUL_KEYS = {
    CM_ENABLED, CM_TENSOR_PARALLEL, CM_ZERO_GATHER, CM_CHUNKS, CM_DTYPE,
    CM_BACKEND, CM_STRICT,
}


class CollectiveMatmulConfig(object):
    """Typed view of ``comm.collective_matmul``."""

    def __init__(self, d):
        d = d or {}
        if not isinstance(d, dict):
            raise ValueError(
                "comm.collective_matmul must be a dict, got {}".format(
                    type(d).__name__))
        self.strict = bool(d.get(CM_STRICT, False))
        unknown = sorted(k for k in d
                         if k not in KNOWN_COLLECTIVE_MATMUL_KEYS)
        if unknown:
            warn_or_raise_noop(
                "comm.collective_matmul.{} has NO effect: unknown key(s) "
                "(accepted: {})".format(
                    ", ".join(unknown),
                    sorted(KNOWN_COLLECTIVE_MATMUL_KEYS)),
                self.strict, flag="comm.collective_matmul.strict")
        self.enabled = bool(d.get(CM_ENABLED, CM_ENABLED_DEFAULT))
        self.tensor_parallel = bool(d.get(CM_TENSOR_PARALLEL,
                                          CM_TENSOR_PARALLEL_DEFAULT))
        self.zero_gather = bool(d.get(CM_ZERO_GATHER,
                                      CM_ZERO_GATHER_DEFAULT))
        chunks = d.get(CM_CHUNKS, CM_CHUNKS_DEFAULT)
        if isinstance(chunks, bool) or not isinstance(chunks, int) or \
                chunks < 1:
            raise ValueError(
                "comm.collective_matmul.{} must be an int >= 1, got "
                "{!r}".format(CM_CHUNKS, chunks))
        self.chunks = chunks
        dtype = str(d.get(CM_DTYPE, CM_DTYPE_DEFAULT)).lower()
        if dtype not in CM_DTYPES:
            raise ValueError(
                "comm.collective_matmul.{} must be one of {}, got "
                "{!r}".format(CM_DTYPE, CM_DTYPES, dtype))
        self.dtype = dtype
        backend = str(d.get(CM_BACKEND, CM_BACKEND_DEFAULT)).lower()
        if backend not in CM_BACKENDS:
            raise ValueError(
                "comm.collective_matmul.{} must be one of {}, got "
                "{!r}".format(CM_BACKEND, CM_BACKENDS, backend))
        self.backend = backend
        # backend="pallas" dispatches the TP ring kernels only — the
        # ZeRO-3 weight gather deliberately stays a ppermute ring (its
        # backward is a sharding constraint; docs/pallas_kernels.md).
        # With tensor_parallel off the key is fully inert: say so.
        # (chunks stays honored everywhere ppermute runs — the zero
        # gather and every loud-fallback path — so it is NOT flagged.)
        if backend == "pallas" and self.enabled and \
                not self.tensor_parallel:
            warn_or_raise_noop(
                "comm.collective_matmul.backend='pallas' has NO effect: "
                "tensor_parallel is disabled and the zero3 ring gather "
                "always runs the ppermute backend (its backward is a "
                "sharding constraint, not a ring — "
                "docs/pallas_kernels.md)", self.strict,
                flag="comm.collective_matmul.strict")
        if self.enabled and not (self.tensor_parallel or self.zero_gather):
            warn_or_raise_noop(
                "comm.collective_matmul.enabled has NO effect: both "
                "tensor_parallel and zero_gather are disabled",
                self.strict, flag="comm.collective_matmul.strict")


class DeepSpeedCommConfig(object):
    """Typed view of the ``comm`` section of a ds_config dict."""

    def __init__(self, param_dict):
        d = (param_dict or {}).get(COMM, {}) or {}
        if not isinstance(d, dict):
            raise ValueError(
                "comm section must be a dict, got {}".format(
                    type(d).__name__))
        self.collective_matmul = CollectiveMatmulConfig(
            d.get(COLLECTIVE_MATMUL))
