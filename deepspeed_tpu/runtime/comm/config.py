"""``comm`` ds_config section: collective-communication behavior.

Two sub-sections:

``comm.collective_matmul`` — the gate for the ring-decomposed
all-gather/reduce-scatter GEMMs (``parallel/collective_matmul.py``).
Off by default: the unfused XLA path stays the reference oracle, and
fusion is an explicit opt-in.

``comm.quantized_collectives`` — in-collective quantization of the
data-parallel gradient allreduce (EQuARX, arXiv:2506.17615): the micro
step computes per-device LOCAL gradients inside ``shard_map`` and
averages them through ``runtime/comm/quantize.py``'s in-collective ring
(int8 blocks + scales on every hop, fp32 accumulation on-device), with
a two-level hierarchical decomposition over ``topology.factor_data_axis``
sub-axes (arXiv:2504.18658). Also the warmup-phase transport of the
1-bit Adam optimizer (docs/onebit_adam.md).

Shape::

    "comm": {
      "collective_matmul": {
        "enabled": false,          // master switch
        "tensor_parallel": true,   // fuse the TP qkv/fc gathers + proj/fc2 scatters
        "zero_gather": true,       // ring-decompose the ZeRO-3 weight all-gather
        "chunks": 1,               // ppermute pieces per ring hop (granularity only;
                                   // bytes == the one-shot collective, wire.py)
        "dtype": "compute",        // wire dtype policy: "compute" (bit-exact)
                                   // or "bf16" (half-width, lossy hop)
        "backend": "ppermute",     // ring backend: "ppermute" (XLA schedules the
                                   // overlap; the oracle) or "pallas" (explicit
                                   // async remote copies + semaphore waits,
                                   // ops/pallas/ring_gemm; docs/pallas_kernels.md)
        "strict": false            // unknown/unhonorable keys raise instead of warn
      },
      "quantized_collectives": {
        "enabled": false,          // master switch
        "dtype": "int8",           // wire dtype of every hop (the only codec;
                                   // other values rejected loudly)
        "block_size": 256,         // lanes per quantization block
        "hierarchical": 0,         // 0 = flat ring (the engine's only factored-mesh
                                   // source, hpZ, is stage-3-only and stage 3 is a
                                   // rejected combination — the mesh-following mode
                                   // serves the QuantizedCollectives library facade);
                                   // N>1 = factor the data axis into (dp/N, N)
                                   // sub-axes for the two-level decomposition
        "strict": false            // unknown/unhonorable keys raise instead of warn
      }
    }

``comm.quantized_collectives.cuda_aware`` (a reference NCCL-backend key)
is REJECTED loudly — there is no CUDA here and silently accepting it
would misrepresent the transport.

Validated with the PR 4/5 no-silent-no-ops policy: unknown keys warn,
and raise when the sub-section's ``strict`` is set.
"""
from ...telemetry.config import warn_or_raise_noop

COMM = "comm"
COLLECTIVE_MATMUL = "collective_matmul"

CM_ENABLED = "enabled"
CM_ENABLED_DEFAULT = False
CM_TENSOR_PARALLEL = "tensor_parallel"
CM_TENSOR_PARALLEL_DEFAULT = True
CM_ZERO_GATHER = "zero_gather"
CM_ZERO_GATHER_DEFAULT = True
CM_CHUNKS = "chunks"
CM_CHUNKS_DEFAULT = 1
CM_DTYPE = "dtype"
CM_DTYPE_DEFAULT = "compute"
CM_DTYPES = ("compute", "bf16")
CM_BACKEND = "backend"
CM_BACKEND_DEFAULT = "ppermute"
CM_BACKENDS = ("ppermute", "pallas")
CM_STRICT = "strict"

QUANTIZED_COLLECTIVES = "quantized_collectives"

QC_ENABLED = "enabled"
QC_ENABLED_DEFAULT = False
QC_DTYPE = "dtype"
QC_DTYPE_DEFAULT = "int8"
QC_DTYPES = ("int8",)
QC_BLOCK_SIZE = "block_size"
QC_HIERARCHICAL = "hierarchical"
QC_HIERARCHICAL_DEFAULT = 0
QC_CUDA_AWARE = "cuda_aware"
QC_STRICT = "strict"

KNOWN_COMM_KEYS = {COLLECTIVE_MATMUL, QUANTIZED_COLLECTIVES}
KNOWN_COLLECTIVE_MATMUL_KEYS = {
    CM_ENABLED, CM_TENSOR_PARALLEL, CM_ZERO_GATHER, CM_CHUNKS, CM_DTYPE,
    CM_BACKEND, CM_STRICT,
}
KNOWN_QUANTIZED_COLLECTIVES_KEYS = {
    QC_ENABLED, QC_DTYPE, QC_BLOCK_SIZE, QC_HIERARCHICAL, QC_STRICT,
}


class CollectiveMatmulConfig(object):
    """Typed view of ``comm.collective_matmul``."""

    def __init__(self, d):
        d = d or {}
        if not isinstance(d, dict):
            raise ValueError(
                "comm.collective_matmul must be a dict, got {}".format(
                    type(d).__name__))
        self.strict = bool(d.get(CM_STRICT, False))
        unknown = sorted(k for k in d
                         if k not in KNOWN_COLLECTIVE_MATMUL_KEYS)
        if unknown:
            warn_or_raise_noop(
                "comm.collective_matmul.{} has NO effect: unknown key(s) "
                "(accepted: {})".format(
                    ", ".join(unknown),
                    sorted(KNOWN_COLLECTIVE_MATMUL_KEYS)),
                self.strict, flag="comm.collective_matmul.strict")
        self.enabled = bool(d.get(CM_ENABLED, CM_ENABLED_DEFAULT))
        self.tensor_parallel = bool(d.get(CM_TENSOR_PARALLEL,
                                          CM_TENSOR_PARALLEL_DEFAULT))
        self.zero_gather = bool(d.get(CM_ZERO_GATHER,
                                      CM_ZERO_GATHER_DEFAULT))
        chunks = d.get(CM_CHUNKS, CM_CHUNKS_DEFAULT)
        if isinstance(chunks, bool) or not isinstance(chunks, int) or \
                chunks < 1:
            raise ValueError(
                "comm.collective_matmul.{} must be an int >= 1, got "
                "{!r}".format(CM_CHUNKS, chunks))
        self.chunks = chunks
        dtype = str(d.get(CM_DTYPE, CM_DTYPE_DEFAULT)).lower()
        if dtype not in CM_DTYPES:
            raise ValueError(
                "comm.collective_matmul.{} must be one of {}, got "
                "{!r}".format(CM_DTYPE, CM_DTYPES, dtype))
        self.dtype = dtype
        backend = str(d.get(CM_BACKEND, CM_BACKEND_DEFAULT)).lower()
        if backend not in CM_BACKENDS:
            raise ValueError(
                "comm.collective_matmul.{} must be one of {}, got "
                "{!r}".format(CM_BACKEND, CM_BACKENDS, backend))
        self.backend = backend
        # backend="pallas" dispatches the TP ring kernels only — the
        # ZeRO-3 weight gather deliberately stays a ppermute ring (its
        # backward is a sharding constraint; docs/pallas_kernels.md).
        # With tensor_parallel off the key is fully inert: say so.
        # (chunks stays honored everywhere ppermute runs — the zero
        # gather and every loud-fallback path — so it is NOT flagged.)
        if backend == "pallas" and self.enabled and \
                not self.tensor_parallel:
            warn_or_raise_noop(
                "comm.collective_matmul.backend='pallas' has NO effect: "
                "tensor_parallel is disabled and the zero3 ring gather "
                "always runs the ppermute backend (its backward is a "
                "sharding constraint, not a ring — "
                "docs/pallas_kernels.md)", self.strict,
                flag="comm.collective_matmul.strict")
        if self.enabled and not (self.tensor_parallel or self.zero_gather):
            warn_or_raise_noop(
                "comm.collective_matmul.enabled has NO effect: both "
                "tensor_parallel and zero_gather are disabled",
                self.strict, flag="comm.collective_matmul.strict")


class QuantizedCollectivesConfig(object):
    """Typed view of ``comm.quantized_collectives``."""

    def __init__(self, d):
        d = d or {}
        if not isinstance(d, dict):
            raise ValueError(
                "comm.quantized_collectives must be a dict, got {}".format(
                    type(d).__name__))
        self.strict = bool(d.get(QC_STRICT, False))
        if QC_CUDA_AWARE in d:
            # the reference NcclBackend key: there is no CUDA transport
            # here and accepting it (even as a warning) would claim one
            raise ValueError(
                "comm.quantized_collectives.cuda_aware is a CUDA/NCCL "
                "transport key the TPU runtime cannot honor — the "
                "exchange rides ICI through shard_map collectives; "
                "remove the key (docs/onebit_adam.md)")
        unknown = sorted(k for k in d
                         if k not in KNOWN_QUANTIZED_COLLECTIVES_KEYS)
        if unknown:
            warn_or_raise_noop(
                "comm.quantized_collectives.{} has NO effect: unknown "
                "key(s) (accepted: {})".format(
                    ", ".join(unknown),
                    sorted(KNOWN_QUANTIZED_COLLECTIVES_KEYS)),
                self.strict, flag="comm.quantized_collectives.strict")
        self.enabled = bool(d.get(QC_ENABLED, QC_ENABLED_DEFAULT))
        dtype = str(d.get(QC_DTYPE, QC_DTYPE_DEFAULT)).lower()
        if dtype not in QC_DTYPES:
            raise ValueError(
                "comm.quantized_collectives.{} must be one of {}, got "
                "{!r}".format(QC_DTYPE, QC_DTYPES, dtype))
        self.dtype = dtype
        from .quantize import DEFAULT_BLOCK_SIZE
        block = d.get(QC_BLOCK_SIZE, DEFAULT_BLOCK_SIZE)
        if isinstance(block, bool) or not isinstance(block, int) or \
                block < 8:
            raise ValueError(
                "comm.quantized_collectives.{} must be an int >= 8, got "
                "{!r}".format(QC_BLOCK_SIZE, block))
        self.block_size = block
        hier = d.get(QC_HIERARCHICAL, QC_HIERARCHICAL_DEFAULT)
        if isinstance(hier, bool) or not isinstance(hier, int) or \
                hier < 0 or hier == 1:
            raise ValueError(
                "comm.quantized_collectives.{} must be 0 (follow the "
                "mesh) or an int >= 2 (factor the data axis that many "
                "ways), got {!r}".format(QC_HIERARCHICAL, hier))
        self.hierarchical = hier


class DeepSpeedCommConfig(object):
    """Typed view of the ``comm`` section of a ds_config dict."""

    def __init__(self, param_dict):
        d = (param_dict or {}).get(COMM, {}) or {}
        if not isinstance(d, dict):
            raise ValueError(
                "comm section must be a dict, got {}".format(
                    type(d).__name__))
        self.collective_matmul = CollectiveMatmulConfig(
            d.get(COLLECTIVE_MATMUL))
        self.quantized_collectives = QuantizedCollectivesConfig(
            d.get(QUANTIZED_COLLECTIVES))
