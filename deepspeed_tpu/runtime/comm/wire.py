"""Per-step collective bytes-on-wire estimator for ZeRO configs.

An analytic model of the per-device wire volume the training step's
ZeRO collectives move, so the communication win of the ZeRO++ modes
(qwZ/hpZ/qgZ) is visible in BENCH_*.json and the dryrun even on the CPU
fallback rung where nothing rides a real interconnect.

Ring-collective pricing (what GSPMD lowers to on a mesh axis of size g):
  all-gather / reduce-scatter move ``payload * (g-1)/g`` bytes per device;
  an all-reduce is a reduce-scatter + all-gather: ``2 * payload * (g-1)/g``.

Counted per optimizer step (gas = gradient-accumulation micro-steps):
  * stage 3: each data-sharded param leaf is all-gathered
    ``gathers_per_micro`` times per micro-step (default 2 — forward +
    backward re-materialization; the shard-lint HLO census (PR 10,
    analysis/hlo.py) confirmed XLA rematerializes the explicit ring
    gathers for the backward rather than keeping the gathered weight
    live) over its gather group — the FULL data axis flat, only the
    ``data_shard`` sub-axis under hpZ. Tensor-parallel leaves move only
    their model-axis SHARE per device (``numel / plan.tp_ways``) —
    census ground truth the earlier estimate missed;
  * stage >= 2: each micro-step's gradients reduce-scatter over the
    full data axis; stage 0-1 all-reduce instead. The census also
    ground-truthed the REDUCTION dtype: the wgrad matmuls accumulate in
    fp32 and XLA reduces BEFORE the convert back to the grad dtype
    lands, so the wire moves fp32 — except for leaves gathered through
    an explicit custom-vjp ring (cm/qwZ), whose cotangent is
    constrained at the compute dtype by the custom_vjp boundary
    (``explicit_gather_grad_itemsize``);
  * stage 1-2: the updated params re-replicate once per step (the
    all-gather of updated partitions).

Quantized payloads price the codec's wire format: 1 byte/lane + one
scale (in the buffer's dtype) per ``block_size`` lanes. For qgZ this
prices the quantized reduce-scatter transport
(``quantized_reduce_scatter_local``); the pure-GSPMD engine path models
its numerics while the wire stays in the compute dtype — the JSON keys
are explicit about being estimates.
"""
import numpy as np

import jax

from .quantize import DEFAULT_BLOCK_SIZE

_FP32_BYTES = 4

# Nominal aggregate per-chip ICI bandwidth (bytes/s) for the analytic
# overlap model in overlap_report(): order-of-magnitude public figures,
# one home like mfu.PEAK_TFLOPS. The CPU entry is a nominal 10 GB/s so
# CPU-rung overlap numbers stay nonzero and comparable across runs of
# the same box, never meaningful in absolute terms.
ICI_GBPS = {
    "TPU v2": 500.0, "TPU v3": 700.0, "TPU v4": 1200.0,
    "TPU v5 lite": 400.0, "TPU v5e": 400.0, "TPU v5": 1200.0,
    "TPU v5p": 1200.0, "TPU v6 lite": 700.0, "TPU v6e": 700.0,
    "cpu": 10.0,
}


def ici_bytes_per_s_for(device):
    """Nominal ICI bytes/s for one chip of ``device`` (a jax Device or a
    device-kind string); unknown kinds get the CPU nominal."""
    kind = device if isinstance(device, str) \
        else getattr(device, "device_kind", "cpu")
    for name, gbps in ICI_GBPS.items():
        if kind.lower().startswith(name.lower()):
            return gbps * 1e9
    return ICI_GBPS["cpu"] * 1e9


def _ring_factor(group):
    return (group - 1) / group if group > 1 else 0.0


def decomposed_collective_bytes(payload_bytes, group, chunks=1):
    """Per-device wire bytes of a ring-DECOMPOSED all-gather or
    reduce-scatter of ``payload_bytes``: ``group - 1`` ppermute hops of
    one shard each — in any number of ``chunks`` pieces per hop —
    moving exactly ``payload * (g-1)/g`` bytes, IDENTICAL to the
    one-shot collective's ring pricing. ``chunks`` only changes the
    grain the scheduler can overlap, never the bytes (pinned by
    tests/unit/test_collective_matmul.py), which is why
    ``estimate_step_comm_bytes`` needs no fusion-aware branch: the
    estimates stay honest with collective_matmul on."""
    del chunks  # granularity, not volume
    return int(round(payload_bytes * _ring_factor(group)))


def overlap_report(wire_est, step_time_s, fused_classes, device):
    """Per-collective-class overlap efficiency for ONE step — the
    T3-style scoreboard ``compute / (compute + exposed_collective)``,
    embedded in the StepRecord as ``comm_overlap``.

    ANALYTIC estimate, not a measurement: each class's collective time
    is its ``wire_est`` bytes over the chip's nominal ICI bandwidth
    (``ici_bytes_per_s_for``); a ring-fused class exposes none of it
    (the hops hide under the partial GEMMs), an unfused class exposes
    all of it, and compute is the measured step wall minus the exposed
    total. ``fused_classes``: {"allgather": bool, "reduce": bool}.
    """
    if wire_est is None or not step_time_s or step_time_s <= 0:
        return None
    bw = ici_bytes_per_s_for(device)
    classes = {
        "allgather": float(wire_est.get("allgather_bytes_per_step", 0) or 0),
        "reduce": float(wire_est.get("reduce_bytes_per_step", 0) or 0),
    }
    # the 1-bit momentum exchange is its own class when live (the
    # compressed-comm tier, docs/onebit_adam.md)
    opt_bytes = float(wire_est.get("optimizer_bytes_per_step", 0) or 0)
    if opt_bytes:
        classes["optimizer"] = opt_bytes
    # per-class fp32-baseline reduction ratios from the estimator
    # (wire_est["reduction_x"]: weight/gradient/optimizer vocabulary)
    red = wire_est.get("reduction_x") or {}
    red_by_class = {"allgather": red.get("weight"),
                    "reduce": red.get("gradient"),
                    "optimizer": red.get("optimizer")}
    est = {k: v / bw for k, v in classes.items()}
    exposed = {k: (0.0 if fused_classes.get(k) else est[k])
               for k in classes}
    compute = max(float(step_time_s) - sum(exposed.values()), 1e-9)
    out = {}
    for k in classes:
        out[k] = {
            "bytes": int(classes[k]),
            "fused": bool(fused_classes.get(k)),
            "est_collective_s": round(est[k], 9),
            "exposed_s": round(exposed[k], 9),
            "overlap_efficiency": round(compute / (compute + exposed[k]),
                                        6),
            "reduction_x": red_by_class.get(k),
        }
    return out


def quantized_allreduce_bytes(numel, world, block_size=DEFAULT_BLOCK_SIZE,
                              levels=None, scale_itemsize=_FP32_BYTES,
                              min_component=0):
    """Per-device wire bytes of ONE in-collective quantized all-reduce
    (``quantized_all_reduce_local`` /
    ``hierarchical_all_reduce_local``): a ring reduce-scatter whose
    every hop moves one int8 chunk + its fp32 block scales (two
    collective-permute instructions per hop), then an int8 all-gather
    (+ scales gather). ``levels=(shard, replica)`` prices the two-level
    decomposition (2504.18658): the full payload over the shard group,
    the 1/shard chunk over the replica group. ``min_component`` drops
    per-INSTRUCTION components below the HLO census threshold so the
    estimate reconciles instruction-for-instruction
    (analysis/hlo.py)."""
    from .quantize import qc_padded_size
    padded = qc_padded_size(numel, world, block_size)

    def keep(b):
        return int(b) if b >= min_component else 0

    def level(n, g):
        if g <= 1:
            return 0
        chunk = n // g
        nblocks = chunk // block_size
        total = 0
        # ring RS: g-1 hops, each one q-chunk ppermute + one scales
        # ppermute (census prices a collective-permute at its payload)
        total += (g - 1) * (keep(chunk) +
                            keep(nblocks * scale_itemsize))
        # int8 AG back: result g*chunk -> (g-1)*chunk on the wire
        total += keep((g - 1) * chunk)
        total += keep((g - 1) * nblocks * scale_itemsize)
        return total

    if levels:
        shard, replica = levels
        assert shard * replica == world, (levels, world)
        return level(padded, shard) + level(padded // shard, replica)
    return level(padded, world)


def onebit_exchange_bytes(numel, world, scale_itemsize=_FP32_BYTES,
                          min_component=0, itemsize_bits=1):
    """Per-device wire bytes of ONE compressed momentum allreduce
    (runtime/comm/onebit.py): the worker ``all_to_all`` of packed sign
    chunks + scalar-scale all-gather, then the server sign all-gather +
    its scales — the reference 2-phase pipeline. ``itemsize_bits=32``
    prices the SAME exchange uncompressed (the fp32-equivalent
    denominator of the optimizer-class reduction ratio)."""
    from .onebit import onebit_padded_size
    padded = onebit_padded_size(numel, world)
    ring = _ring_factor(world)
    payload = padded * itemsize_bits // 8

    def keep(b):
        return int(b) if b >= min_component else 0

    total = 0
    total += keep(int(round(payload * ring)))              # worker a2a
    total += keep(int(round(world * scale_itemsize * ring)))
    total += keep(int(round(payload * ring)))              # server AG
    total += keep(int(round(world * scale_itemsize * ring)))
    return total


def _payload(numel, itemsize, quantized, scale_itemsize, block_size):
    if not quantized:
        return numel * itemsize
    nblocks = -(-numel // block_size)
    return numel * 1 + nblocks * scale_itemsize


def _price_tree(params, eligible_fn, stage, dp, gather_group, gas,
                compute_itemsize, grad_itemsize, quantized_weights,
                quantized_gradients, block_size, gathers_per_micro=2,
                explicit_gather_grad_itemsize=None, tp_ways_fn=None,
                replicate_itemsize=None, min_component=0):
    """The one pricing body both entry points share.

    ``eligible_fn(path, shape, numel) -> bool``: is this leaf a stage-3
    data-sharded (per-micro-step-gathered) param. Weight gathers price
    the shape-preserving codec (blocks tile the last dim — what
    ``qwz_gather`` actually ships); gradient reduces price the FLAT
    codec (``quantize_with_error_feedback`` uses ``block_size``-lane
    flat blocks). ``explicit_gather_grad_itemsize``: when set, eligible
    stage-3 leaves' gradient reduces price THIS itemsize (the explicit
    cm/qwZ ring cotangent stays in the compute dtype) while every other
    leaf reduces at ``grad_itemsize``. ``tp_ways_fn(path, shape)``:
    tensor-parallel split degree — per-device data-axis wire moves only
    the leaf's model-axis share (census ground truth; eligibility still
    judges the GLOBAL leaf).
    """
    from .quantize import _lastdim_block
    from ..zero.partition import _path_str
    if replicate_itemsize is None:
        replicate_itemsize = compute_itemsize
    totals = {"allgather_bytes": 0.0, "reduce_bytes": 0.0}

    def leaf(path, p):
        shape = np.shape(p)
        numel = int(np.prod(shape)) if shape else 1
        wire_numel = numel
        if tp_ways_fn is not None:
            wire_numel = numel // max(int(tp_ways_fn(path, shape)), 1)
        eligible = stage >= 3 and eligible_fn(path, shape, numel)
        if eligible:
            wblk = _lastdim_block(shape[-1], block_size) if shape else 1
            per_gather = _payload(wire_numel, compute_itemsize,
                                  quantized_weights, compute_itemsize,
                                  wblk) * _ring_factor(gather_group)
            totals["allgather_bytes"] += \
                gathers_per_micro * gas * per_gather
        elif stage in (1, 2) and dp > 1 and numel >= dp and \
                any(d % dp == 0 for d in shape):
            # updated-partition re-replication, once per step (the plan
            # only shards — and thus re-gathers — leaves with a
            # dp-divisible dim; others stay replicated). Census ground
            # truth (PR 12, mirroring PR 10's reduce-dtype finding): the
            # partitioner gathers the MASTER-dtype value and the convert
            # to the compute dtype lands after, so the wire moves
            # ``replicate_itemsize`` (fp32 under mixed precision).
            # ``min_component`` drops per-leaf instructions below the
            # census threshold when reconciling.
            leaf_wire = wire_numel * replicate_itemsize * _ring_factor(dp)
            if leaf_wire >= min_component:
                totals["allgather_bytes"] += leaf_wire
        if dp > 1:
            gi = grad_itemsize
            if eligible and explicit_gather_grad_itemsize is not None:
                gi = explicit_gather_grad_itemsize
            grad_payload = _payload(wire_numel, gi, quantized_gradients,
                                    gi, block_size)
            factor = _ring_factor(dp) if stage >= 2 \
                else 2 * _ring_factor(dp)
            totals["reduce_bytes"] += gas * grad_payload * factor

    jax.tree_util.tree_map_with_path(
        lambda kp, p: leaf(_path_str(kp), p), params)
    out = {k: int(round(v)) for k, v in totals.items()}
    out["total_bytes"] = out["allgather_bytes"] + out["reduce_bytes"]
    return out


def estimate_step_comm_bytes(plan, params, gas=1, compute_itemsize=4,
                             grad_itemsize=4, quantized_weights=False,
                             quantized_gradients=False,
                             block_size=DEFAULT_BLOCK_SIZE,
                             gathers_per_micro=2,
                             explicit_gather_grad_itemsize=None,
                             replicate_itemsize=None, min_component=0,
                             _force_flat_fp32=False):
    """Per-device collective bytes for ONE optimizer step under ``plan``.

    Returns ``{"allgather_bytes", "reduce_bytes", "total_bytes"}``.
    ``gathers_per_micro``: stage-3 weight materializations per
    micro-step — 2 (forward + backward re-materialization, the census-
    confirmed default). ``_force_flat_fp32`` reprices as flat (full data
    axis) fp32 with no quantization — the comparison baseline —
    INCLUDING flat-plan leaf eligibility, so the baseline never bills
    gathers for a leaf flat ZeRO-3 would keep replicated (it keeps the
    caller's gather count: the baseline compares wire FORMATS, not
    schedules).
    """
    if _force_flat_fp32:
        compute_itemsize = grad_itemsize = _FP32_BYTES
        quantized_weights = quantized_gradients = False
        explicit_gather_grad_itemsize = None
        replicate_itemsize = _FP32_BYTES
    return _price_tree(
        params,
        lambda path, shape, numel: plan.param_is_data_sharded(
            path, shape, flat=_force_flat_fp32),
        stage=plan.stage, dp=plan.dp_size,
        gather_group=plan.dp_size if _force_flat_fp32
        else plan.param_shard_size,
        gas=gas, compute_itemsize=compute_itemsize,
        grad_itemsize=grad_itemsize,
        quantized_weights=quantized_weights,
        quantized_gradients=quantized_gradients, block_size=block_size,
        gathers_per_micro=gathers_per_micro,
        explicit_gather_grad_itemsize=explicit_gather_grad_itemsize,
        tp_ways_fn=plan.tp_ways, replicate_itemsize=replicate_itemsize,
        min_component=min_component)


def project_comm_bytes(params, stage, dp, gas=1, compute_itemsize=4,
                       grad_itemsize=4, quantized_weights=False,
                       hierarchical_partition=0, quantized_gradients=False,
                       persistence_threshold=100000,
                       block_size=DEFAULT_BLOCK_SIZE):
    """Price a param tree's ZeRO collectives at a HYPOTHETICAL dp degree
    — no mesh/plan needed. Leaf eligibility approximates
    ZeroShardingPlan's rule (numel >= max(threshold, group) and a
    group-divisible dim). Lets a single-device CPU bench still report
    what the config would move on a pod."""
    gather_group = hierarchical_partition \
        if stage >= 3 and hierarchical_partition > 1 else dp
    return _price_tree(
        params,
        lambda path, shape, numel: bool(shape) and
        numel >= max(persistence_threshold, gather_group) and
        any(d % gather_group == 0 for d in shape),
        stage=stage, dp=dp, gather_group=gather_group, gas=gas,
        compute_itemsize=compute_itemsize, grad_itemsize=grad_itemsize,
        quantized_weights=quantized_weights,
        quantized_gradients=quantized_gradients, block_size=block_size)


def _compressed_comm_classes(engine, min_component=0):
    """The compressed-comm tier's per-step byte classes, when live:
    returns (reduce_bytes, optimizer_bytes, fp32_equiv_optimizer_bytes,
    regime) or None on the GSPMD oracle path.

    OneBitAdam warmup / quantized-collectives: the gradient (reduce)
    class is the in-collective int8 exchange — per STEP under OneBitAdam
    (the engine averages the accumulated stacked grads once in the
    apply), per MICRO-step in pure exchange mode — or the fp32 stacked
    mean for onebit-without-qc warmup. OneBitAdam frozen: gradients
    never cross the wire (reduce = 0); the 1-bit momentum exchange is
    its own ``optimizer`` class."""
    mode_fn = getattr(engine, "_local_grad_mode", None)
    mode = mode_fn() if mode_fn is not None else None
    if mode is None:
        return None
    import jax
    params = engine.state["params"] if engine.state is not None and \
        engine.state.get("params") is not None else engine.model.params
    numel = sum(int(np.prod(np.shape(p))) if np.shape(p) else 1
                for p in jax.tree_util.tree_leaves(params))
    dp = engine.zero_plan.dp_size
    gas = engine.gradient_accumulation_steps()
    qc = getattr(engine, "_qc", None)
    levels = None
    if isinstance(engine._batch_axis, tuple):
        replica_axis, shard_axis = engine._batch_axis
        levels = (int(engine.mesh.shape[shard_axis]),
                  int(engine.mesh.shape[replica_axis]))

    def qc_bytes():
        return quantized_allreduce_bytes(
            numel, dp, qc.block_size, levels=levels,
            min_component=min_component)

    if mode == "exchange":
        return gas * qc_bytes(), 0, 0, None
    frozen = engine._onebit_frozen()
    if frozen:
        opt = onebit_exchange_bytes(numel, dp,
                                    min_component=min_component)
        equiv = onebit_exchange_bytes(numel, dp, itemsize_bits=32,
                                      min_component=min_component)
        return 0, opt, equiv, "frozen"
    if getattr(engine, "_qc_enabled", False):
        # one exchange per step: the engine averages the ACCUMULATED
        # stacked grads through the quantized ring in the apply step
        return qc_bytes(), 0, 0, "warmup"
    # uncompressed warmup: the per-leaf stacked mean lowers to fp32
    # all-reduces over the data axis
    return int(round(2 * _ring_factor(dp) * _FP32_BYTES * numel)), 0, 0, \
        "warmup"


def estimate_engine_comm_bytes(engine, min_component=0):
    """The engine's live config priced against the flat-fp32 baseline.

    JSON-ready dict: current-config and fp32-flat per-step bytes plus
    reduction ratios (>= 1 means the config moves fewer bytes).
    ``min_component`` drops per-instruction components below the HLO
    census threshold — pass the census ``min_bytes`` when reconciling
    (analysis/hlo.reconcile_wire); the default 0 reports full bytes.
    """
    import jax.numpy as jnp
    plan = engine.zero_plan
    params = engine.state["params"] if engine.state is not None \
        else engine.model.params
    compute_itemsize = jnp.dtype(engine.compute_dtype).itemsize
    gas = engine.gradient_accumulation_steps()
    # census-ground-truthed step model (see module docstring): weights
    # re-materialize in the backward (2 gathers/micro — XLA recomputes
    # the ring chains rather than keeping gathered weights live);
    # gradients reduce in the fp32 wgrad-accumulation dtype, except
    # leaves routed through an explicit custom-vjp ring (cm/qwZ) whose
    # cotangent the boundary pins to the compute dtype; TP leaves move
    # only their model-axis share per device
    explicit_gather = bool(getattr(engine, "_cm_zero3", False) or
                           getattr(engine, "_qwz_enabled", False))
    cur = estimate_step_comm_bytes(
        plan, params, gas=gas, compute_itemsize=compute_itemsize,
        grad_itemsize=_FP32_BYTES,
        quantized_weights=engine.zero_quantized_weights(),
        quantized_gradients=engine.zero_quantized_gradients(),
        explicit_gather_grad_itemsize=compute_itemsize
        if explicit_gather else None,
        # stage 1-2 re-replication moves the MASTER dtype (census ground
        # truth: the partitioner gathers before the compute-dtype
        # convert lands)
        replicate_itemsize=_FP32_BYTES if engine.mixed_precision
        else compute_itemsize,
        min_component=min_component)
    base = estimate_step_comm_bytes(plan, params, gas=gas,
                                    _force_flat_fp32=True)

    def ratio(b, c):
        return round(b / c, 2) if c else None

    # compressed-comm tier (OneBitAdam / quantized_collectives): the
    # gradient class is replaced by the live exchange's bytes, and the
    # frozen-regime 1-bit momentum exchange is its own class
    comp = _compressed_comm_classes(engine, min_component=min_component)
    opt_bytes = equiv_opt = 0
    onebit_regime = None
    if comp is not None:
        cur = dict(cur)
        cur["reduce_bytes"], opt_bytes, equiv_opt, onebit_regime = comp
        cur["total_bytes"] = cur["allgather_bytes"] + \
            cur["reduce_bytes"] + opt_bytes

    out = {
        "zero_stage": plan.stage,
        "quantized_weights": engine.zero_quantized_weights(),
        "hierarchical_partition": engine.zero_hierarchical_partition(),
        "quantized_gradients": engine.zero_quantized_gradients(),
        "allgather_bytes_per_step": cur["allgather_bytes"],
        "reduce_bytes_per_step": cur["reduce_bytes"],
        "optimizer_bytes_per_step": opt_bytes,
        "total_bytes_per_step": cur["total_bytes"],
        "fp32_flat_allgather_bytes_per_step": base["allgather_bytes"],
        "fp32_flat_reduce_bytes_per_step": base["reduce_bytes"],
        "fp32_equiv_optimizer_bytes_per_step": equiv_opt,
        "fp32_flat_total_bytes_per_step": base["total_bytes"],
        "allgather_reduction_x": ratio(base["allgather_bytes"],
                                       cur["allgather_bytes"]),
        "total_reduction_x": ratio(base["total_bytes"],
                                   cur["total_bytes"]),
        # per-class fp32-baseline ratios (the bench extra.comm block):
        # weight = the param all-gathers; gradient = every byte carrying
        # gradient information (the grad reduce + the frozen-regime
        # momentum exchange that replaces it); optimizer = the momentum
        # exchange vs the SAME exchange uncompressed
        "reduction_x": {
            "weight": ratio(base["allgather_bytes"],
                            cur["allgather_bytes"]),
            "gradient": ratio(base["reduce_bytes"],
                              cur["reduce_bytes"] + opt_bytes),
            "optimizer": ratio(equiv_opt, opt_bytes),
        },
    }
    if onebit_regime is not None:
        out["onebit_regime"] = onebit_regime
    if getattr(engine, "_qc_enabled", False):
        qc = engine._qc
        out["quantized_collectives"] = {
            "enabled": True,
            "dtype": qc.dtype,
            "block_size": int(qc.block_size),
            "hierarchical": isinstance(engine._batch_axis, tuple),
        }
    cm = getattr(engine, "_cm", None)
    if cm is not None and cm.enabled:
        # marker only: a ring-decomposed collective moves the bytes of
        # the one-shot collective (decomposed_collective_bytes), so the
        # byte totals above hold verbatim with fusion on
        out["collective_matmul"] = {
            "enabled": True,
            "zero_gather_fused": bool(getattr(engine, "_cm_zero3", False)),
            "tensor_parallel_fused": bool(getattr(engine, "_cm_tp",
                                                  False)),
            "chunks": int(cm.chunks),
        }
    if plan.dp_size <= 1:
        # single-device rung (the CPU bench fallback): nothing crosses a
        # wire, so also project the same config at a nominal pod scale to
        # keep the configured comm behavior visible in the artifact
        dp = 8
        zc = engine._config.zero_config
        proj = project_comm_bytes(
            params, plan.stage, dp, gas=gas,
            compute_itemsize=compute_itemsize,
            grad_itemsize=compute_itemsize,
            quantized_weights=bool(zc.quantized_weights),
            hierarchical_partition=int(zc.hierarchical_partition or 0),
            quantized_gradients=bool(zc.quantized_gradients),
            persistence_threshold=zc.param_persistence_threshold)
        proj_base = project_comm_bytes(
            params, plan.stage, dp, gas=gas,
            persistence_threshold=zc.param_persistence_threshold)
        out["projected_dp{}".format(dp)] = {
            "total_bytes_per_step": proj["total_bytes"],
            "fp32_flat_total_bytes_per_step": proj_base["total_bytes"],
            "total_reduction_x": ratio(proj_base["total_bytes"],
                                       proj["total_bytes"]),
        }
    return out
