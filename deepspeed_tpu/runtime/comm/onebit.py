"""1-bit sign+scale compressed collectives — the single codec home.

Reference parity: deepspeed/runtime/comm/nccl.py:43-178
(NcclBackend.compressed_allreduce) and its MPI twin. The reference's
2-phase algorithm is decomposed into its two collective stages so the
1-bit Adam optimizer (runtime/fp16/onebit_adam.py) can ride them as a
real reduce-scatter / all-gather pair inside ``shard_map``:

  * :func:`onebit_reduce_scatter_local` — the WORKER phase: add worker
    error feedback, take one scale ``||x||/sqrt(n)``, pack sign bits,
    ``all_to_all`` the sign chunks (+ ``all_gather`` the scalar scales),
    decompress and average my chunk across workers. The wire moves
    ``n/8`` uint8 bytes instead of ``4n`` fp32 — the reduce-scatter of
    the compressed allreduce.
  * :func:`onebit_all_gather_local` — the SERVER phase: add server error
    feedback to my averaged chunk, re-compress with a fresh scale,
    ``all_gather`` the sign bytes back to everyone — the broadcast half,
    again at ``n/8`` bytes on the wire.
  * :func:`compressed_allreduce_local` — the composition, preserved
    verbatim for ``CompressedBackend`` (runtime/comm/compressed.py).

All axis arguments accept a single mesh-axis name or a TUPLE of sub-axis
names (the hpZ-factored ``(data_replica, data_shard)`` mesh): jax's
collectives and ``axis_index`` treat the tuple as one flattened axis, so
the exchange composes with hierarchically partitioned meshes unchanged.

Everything stays in the input's dtype (a bf16 buffer gets a bf16 scale —
no mid-pipeline upcast), and pad lanes carry zero value AND zero error
feedback (see :func:`masked_compress`). Constants are explicitly typed
(``jnp.float32``) so the shard-lint weak-scalar rule stays silent on the
exchange bodies.

The bit-pack primitives (``pack_signs``/``unpack_signs``/``sign_scale``)
live with the blockwise codec in quantize.py and are shared here.
"""
import jax
import jax.numpy as jnp

from .quantize import pack_signs, sign_scale, unpack_signs


def onebit_padded_size(n, world_size):
    """Lanes the 1-bit exchange needs: a multiple of ``8 * world`` so
    every per-rank chunk packs to whole sign bytes."""
    mult = 8 * int(world_size)
    return ((int(n) + mult - 1) // mult) * mult


def masked_compress(x, mask, count):
    """Sign+scale quantize the lanes selected by ``mask`` (1.0/0.0 floats,
    ``count`` = number of real lanes). Pad lanes must carry zero value AND
    zero error feedback — quantizing a 0 lane to +scale would make its
    error oscillate at ±scale and pollute ``||x||/sqrt(n)`` (torch's
    sign(0)=0 gives the reference this for free). Returns (packed signs,
    scale, decompressed, error residual). Everything stays in ``x``'s
    dtype — a bf16 buffer gets a bf16 scale, no mid-pipeline upcast."""
    mask = mask.astype(x.dtype)
    masked = x * mask
    scale = sign_scale(masked, count)
    packed = pack_signs(x)
    signs = jnp.where(x >= 0, jnp.float32(1.0),
                      jnp.float32(-1.0)).astype(x.dtype)
    decompressed = scale * signs * mask
    return packed, scale, decompressed, (x - decompressed) * mask


def onebit_reduce_scatter_local(x, worker_error, axis_name, world_size,
                                real_size=None):
    """Worker phase per-device body (call inside shard_map over
    ``axis_name``): compress the error-corrected buffer, exchange sign
    chunks, decompress + average my chunk across workers.

    ``x``: this device's local buffer (flat fp32, size divisible by
    ``8 * world_size``; lanes >= ``real_size`` are padding).
    Returns ``(chunk_mean, chunk_mask, chunk_count, new_worker_error)``:
    ``chunk_mean`` is my rank's chunk of the worker-average (masked to
    real lanes, WITHOUT server error — the server phase owns that),
    ``chunk_mask``/``chunk_count`` describe my chunk's real lanes for the
    server compressor, ``new_worker_error`` is this device's residual.
    """
    n = x.size
    chunk = n // world_size
    if real_size is None:
        real_size = n
    mask = (jnp.arange(n) < real_size).astype(jnp.float32)

    corrected = x + worker_error
    packed, scale, _, new_worker_error = masked_compress(
        corrected, mask, jnp.float32(real_size))
    # rows: chunk destined to each server rank
    packed_rows = packed.reshape(world_size, chunk // 8)
    recv = jax.lax.all_to_all(packed_rows, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)
    scales = jax.lax.all_gather(scale, axis_name)

    # recv[w] = my chunk's sign bytes from worker w; my chunk's lane mask
    # and real-lane count depend on my position in the gather order
    rank = jax.lax.axis_index(axis_name)
    chunk_start = rank * chunk
    chunk_mask = (jnp.arange(chunk) + chunk_start <
                  real_size).astype(jnp.float32)
    chunk_count = jnp.clip(jnp.int32(real_size) - chunk_start, 0,
                           chunk).astype(jnp.float32)
    per_worker = jax.vmap(unpack_signs)(recv, scales)      # (world, chunk)
    chunk_mean = per_worker.mean(axis=0) * chunk_mask
    return chunk_mean, chunk_mask, chunk_count, new_worker_error


def onebit_all_gather_local(server_chunk, server_error, axis_name,
                            chunk_mask, chunk_count):
    """Server phase per-device body: error-compensate + re-compress my
    averaged chunk, all-gather the sign bytes, decompress the full
    buffer. Returns ``(full, new_server_error)`` — ``full`` is the
    world-concatenated result in rank order (pad lanes of OTHER chunks
    are NOT masked here; the caller applies its full-length mask)."""
    server_in = server_chunk + server_error
    server_packed, server_scale, _, new_server_error = masked_compress(
        server_in, chunk_mask, chunk_count)
    gathered = jax.lax.all_gather(server_packed, axis_name)
    gathered_scales = jax.lax.all_gather(server_scale, axis_name)
    full = jax.vmap(unpack_signs)(gathered, gathered_scales).reshape(-1)
    return full, new_server_error


def compressed_allreduce_local(x, worker_error, server_error, axis_name,
                               world_size, real_size=None):
    """The composed per-device body: worker reduce-scatter then server
    all-gather (reference nccl.py compressed_allreduce, both phases).

    ``x``: this device's local buffer (flat fp32, size divisible by
    8*world_size; lanes >= ``real_size`` are padding). Returns (averaged
    buffer, new worker_error, new server_error) — errors have the same
    shapes as the inputs (server_error is 1/world_size of the buffer).
    """
    n = x.size
    if real_size is None:
        real_size = n
    mask = (jnp.arange(n) < real_size).astype(jnp.float32)
    chunk_mean, chunk_mask, chunk_count, new_worker_error = \
        onebit_reduce_scatter_local(x, worker_error, axis_name, world_size,
                                    real_size)
    result, new_server_error = onebit_all_gather_local(
        chunk_mean, server_error, axis_name, chunk_mask, chunk_count)
    return result * mask, new_worker_error, new_server_error
