"""Compressed (1-bit) collectives: sign-pack allreduce with error feedback.

Reference parity: deepspeed/runtime/comm/nccl.py:43-178 (NcclBackend.
compressed_allreduce) and its MPI twin (comm/mpi.py). The reference's
2-phase algorithm is kept exactly; the transport changes:

  * cupy ``packbits`` -> a jnp bit-pack (uint8 dot with power-of-two
    weights) that XLA vectorizes on-device;
  * ``torch.distributed.all_to_all_single`` / ``all_gather`` ->
    ``jax.lax.all_to_all`` / ``all_gather`` inside ``shard_map`` over the
    ``data`` mesh axis, so the exchange rides ICI and XLA overlaps it;
  * CUDA stream juggling disappears (XLA schedules).

Phase 1 (worker): add worker error feedback, take one scale
``||x||/sqrt(n)``, pack sign bits, update the worker error, all_to_all the
sign chunks (+ all_gather scales).
Phase 2 (server): each rank decompresses & averages its chunk across
workers, adds server error feedback, re-compresses with a fresh scale,
updates server error, all_gathers the result to everyone.

Compression ratio is 32x on the wire minus two scalar scales per buffer —
the reference's "6.6x end-to-end at 40 Gb Ethernet" regime corresponds to
DCN-limited pods here.
"""
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...parallel.topology import DATA_AXIS, shard_map_compat
# The bit-pack/scale primitives live with the blockwise codec —
# re-exported here for the existing call sites (runtime.comm/__init__).
from .quantize import pack_signs, sign_scale, unpack_signs


def masked_compress(x, mask, count):
    """Sign+scale quantize the lanes selected by ``mask`` (1.0/0.0 floats,
    ``count`` = number of real lanes). Pad lanes must carry zero value AND
    zero error feedback — quantizing a 0 lane to +scale would make its
    error oscillate at ±scale and pollute ``||x||/sqrt(n)`` (torch's
    sign(0)=0 gives the reference this for free). Returns (packed signs,
    scale, decompressed, error residual). Everything stays in ``x``'s
    dtype — a bf16 buffer gets a bf16 scale, no mid-pipeline upcast."""
    mask = mask.astype(x.dtype)
    masked = x * mask
    scale = sign_scale(masked, count)
    packed = pack_signs(x)
    signs = jnp.where(x >= 0, 1.0, -1.0).astype(x.dtype)
    decompressed = scale * signs * mask
    return packed, scale, decompressed, (x - decompressed) * mask


def _compress(x):
    """One full buffer -> (packed signs, scalar scale, error residual)."""
    mask = jnp.ones(x.size, dtype=jnp.float32)
    packed, scale, _, err = masked_compress(x, mask, float(x.size))
    return packed, scale, err


def compressed_allreduce_local(x, worker_error, server_error, axis_name,
                               world_size, real_size=None):
    """The per-device body: call inside shard_map/pmap over ``axis_name``.

    ``x``: this device's local buffer (flat fp32, size divisible by
    8*world_size; lanes >= ``real_size`` are padding). Returns (averaged
    buffer, new worker_error, new server_error) — errors have the same
    shapes as the inputs (server_error is 1/world_size of the buffer).
    """
    n = x.size
    chunk = n // world_size
    if real_size is None:
        real_size = n
    mask = (jnp.arange(n) < real_size).astype(jnp.float32)

    # ---- phase 1: worker compression + exchange
    corrected = x + worker_error
    packed, scale, _, new_worker_error = masked_compress(
        corrected, mask, float(real_size))
    # rows: chunk destined to each server rank
    packed_rows = packed.reshape(world_size, chunk // 8)
    recv = jax.lax.all_to_all(packed_rows, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)
    scales = jax.lax.all_gather(scale, axis_name)

    # ---- phase 2: server decompress, average, re-compress, broadcast
    # recv[w] = my chunk's sign bytes from worker w; my chunk's lane mask
    # and real-lane count depend on my position in the gather order
    rank = jax.lax.axis_index(axis_name)
    chunk_start = rank * chunk
    chunk_mask = (jnp.arange(chunk) + chunk_start <
                  real_size).astype(jnp.float32)
    chunk_count = jnp.clip(real_size - chunk_start, 0, chunk).astype(
        jnp.float32)
    per_worker = jax.vmap(unpack_signs)(recv, scales)      # (world, chunk)
    server_chunk = per_worker.mean(axis=0) * chunk_mask + server_error
    server_packed, server_scale, _, new_server_error = masked_compress(
        server_chunk, chunk_mask, chunk_count)

    gathered = jax.lax.all_gather(server_packed, axis_name)  # (world, chunk/8)
    gathered_scales = jax.lax.all_gather(server_scale, axis_name)
    result = jax.vmap(unpack_signs)(gathered, gathered_scales).reshape(-1)
    return result * mask, new_worker_error, new_server_error


class CompressedBackend:
    """NcclBackend/MpiBackend equivalent over a JAX mesh.

    ``compressed_allreduce(per_rank_values, worker_error, server_error)``
    takes the *stacked* per-rank buffers — shape (world, n) sharded or
    shardable over the ``data`` axis — and returns (averaged (world, n),
    new worker errors, new server errors). Error state is carried by the
    caller, as the reference keeps it on the optimizer (onebit/adam.py).
    """

    def __init__(self, mesh, axis=DATA_AXIS):
        self.mesh = mesh
        self.axis = axis
        self.world_size = int(mesh.shape[axis])
        self._jit_cache = {}  # per-instance: padded size -> jitted exchange

    def padded_size(self, n):
        mult = 8 * self.world_size
        return ((n + mult - 1) // mult) * mult

    def _build(self, n, real_size):
        key = (n, real_size)
        if key in self._jit_cache:
            return self._jit_cache[key]
        world = self.world_size
        axis = self.axis

        @jax.jit
        def run(values, worker_error, server_error):
            body = functools.partial(compressed_allreduce_local,
                                     axis_name=axis, world_size=world,
                                     real_size=real_size)

            # shard_map splits the leading (world,) dim: each device sees
            # its own (1, n) row; drop/re-add the axis inside.
            def per_device(v, we, se):
                out, nwe, nse = body(v[0], we[0], se[0])
                return out[None], nwe[None], nse[None]

            sharded = shard_map_compat(
                per_device, mesh=self.mesh,
                in_specs=(P(axis), P(axis), P(axis)),
                out_specs=(P(axis), P(axis), P(axis)))
            return sharded(values, worker_error, server_error)

        self._jit_cache[key] = run
        return run

    def compressed_allreduce(self, values, worker_error=None,
                             server_error=None):
        world = self.world_size
        n = values.shape[-1]
        padded = self.padded_size(n)
        if padded != n:
            values = jnp.pad(values, ((0, 0), (0, padded - n)))
        if worker_error is None:
            worker_error = jnp.zeros((world, padded), dtype=jnp.float32)
        if server_error is None:
            server_error = jnp.zeros((world, padded // world),
                                     dtype=jnp.float32)
        out, we, se = self._build(padded, n)(values.astype(jnp.float32),
                                          worker_error, server_error)
        return out[:, :n], we, se
