"""CompressedBackend: the 1-bit allreduce facade over a JAX mesh.

Reference parity: deepspeed/runtime/comm/nccl.py (NcclBackend) and its
MPI twin. The codec and the per-device collective bodies live in ONE
place — runtime/comm/onebit.py (worker reduce-scatter + server
all-gather phases, composed as ``compressed_allreduce_local``) — shared
with the 1-bit Adam optimizer; this module only owns the host-side
facade: padding, error-state defaulting, and the per-size jit cache.

Compression ratio is 32x on the wire minus two scalar scales per buffer —
the reference's "6.6x end-to-end at 40 Gb Ethernet" regime corresponds to
DCN-limited pods here.
"""
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...parallel.topology import DATA_AXIS, shard_map_compat
# One sign+scale implementation: the pack/scale primitives live with the
# blockwise codec (quantize.py), the masked compressor and the exchange
# bodies with the 1-bit collectives (onebit.py). Re-exported here for
# the existing call sites (runtime.comm/__init__).
from .onebit import compressed_allreduce_local, masked_compress  # noqa: F401
from .quantize import pack_signs, sign_scale, unpack_signs  # noqa: F401


class CompressedBackend:
    """NcclBackend/MpiBackend equivalent over a JAX mesh.

    ``compressed_allreduce(per_rank_values, worker_error, server_error)``
    takes the *stacked* per-rank buffers — shape (world, n) sharded or
    shardable over the ``data`` axis — and returns (averaged (world, n),
    new worker errors, new server errors). Error state is carried by the
    caller, as the reference keeps it on the optimizer (onebit/adam.py).
    """

    def __init__(self, mesh, axis=DATA_AXIS):
        self.mesh = mesh
        self.axis = axis
        self.world_size = int(mesh.shape[axis])
        self._jit_cache = {}  # per-instance: padded size -> jitted exchange

    def padded_size(self, n):
        mult = 8 * self.world_size
        return ((n + mult - 1) // mult) * mult

    def _build(self, n, real_size):
        key = (n, real_size)
        if key in self._jit_cache:
            return self._jit_cache[key]
        world = self.world_size
        axis = self.axis

        @jax.jit
        def run(values, worker_error, server_error):
            body = functools.partial(compressed_allreduce_local,
                                     axis_name=axis, world_size=world,
                                     real_size=real_size)

            # shard_map splits the leading (world,) dim: each device sees
            # its own (1, n) row; drop/re-add the axis inside.
            def per_device(v, we, se):
                out, nwe, nse = body(v[0], we[0], se[0])
                return out[None], nwe[None], nse[None]

            sharded = shard_map_compat(
                per_device, mesh=self.mesh,
                in_specs=(P(axis), P(axis), P(axis)),
                out_specs=(P(axis), P(axis), P(axis)))
            return sharded(values, worker_error, server_error)

        self._jit_cache[key] = run
        return run

    def compressed_allreduce(self, values, worker_error=None,
                             server_error=None):
        world = self.world_size
        n = values.shape[-1]
        padded = self.padded_size(n)
        if padded != n:
            values = jnp.pad(values, ((0, 0), (0, padded - n)))
        if worker_error is None:
            worker_error = jnp.zeros((world, padded), dtype=jnp.float32)
        if server_error is None:
            server_error = jnp.zeros((world, padded // world),
                                     dtype=jnp.float32)
        out, we, se = self._build(padded, n)(values.astype(jnp.float32),
                                          worker_error, server_error)
        return out[:, :n], we, se
