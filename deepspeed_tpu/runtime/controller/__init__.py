"""Closed-loop runtime controller: retunes the running system from its
own telemetry, with every decision a schema-pinned, replayable ledger
event (docs/controller.md)."""
from .core import RuntimeController
from .ledger import (CONTROLLER_EVENT_TYPES, CONTROLLER_EVENTS_JSONL,
                     CONTROLLER_KNOBS, DECISION_KEYS,
                     KIND_CONTROLLER_EVENT, DecisionLedger,
                     make_controller_event, unreverted_regressions,
                     validate_controller_event)
from .policies import (CONTROLLER_POLICIES, POLICY_REGISTRY,
                       LaunchAheadPolicy, PrefillBucketsPolicy,
                       QuantizedCollectivesPolicy, SpeculationPolicy,
                       make_move)

__all__ = [
    "RuntimeController", "DecisionLedger", "DECISION_KEYS",
    "CONTROLLER_EVENT_TYPES", "CONTROLLER_EVENTS_JSONL",
    "CONTROLLER_KNOBS", "KIND_CONTROLLER_EVENT",
    "make_controller_event", "validate_controller_event",
    "unreverted_regressions", "CONTROLLER_POLICIES", "POLICY_REGISTRY",
    "LaunchAheadPolicy", "SpeculationPolicy",
    "QuantizedCollectivesPolicy", "PrefillBucketsPolicy", "make_move",
]
