"""The closed-loop runtime controller (docs/controller.md).

Observe -> decide -> act -> evaluate -> (revert): every tick the
controller folds the engine's objective sample (step wall) into its
rolling window, finalizes any override whose evaluation window
elapsed (the *measured* win lands in the ledger next to the pricer's
prediction — their ratio is the ``controller_drift`` gauge), and every
``interval_steps`` asks its policies for moves.

Observability is the contract, enforced structurally:

* actuation happens ONLY through :meth:`RuntimeController.apply_override`
  — the single audited seam (DSL012 flags knob writes anywhere else),
  and that seam cannot act without emitting a ledger ``decision``;
* the full ledger rides every crash bundle (``state.controller`` via
  the flight-recorder context registered at construction);
* a measured regression past ``guardrail_pct`` trips the ``controller``
  watchdog (dump by default — the bundle carries the ledger) and
  auto-reverts through the same seam, so the revert is a ledger event
  too.

The core is jax-free: engines adapt themselves by registering knob
bindings (getter/setter pairs) and assembling the signals dict from
``telemetry_snapshot()`` / ``ingest_fleet`` state (see the policy
module's signals vocabulary).
"""
from ...utils.logging import logger
from .ledger import DecisionLedger
from .policies import POLICY_REGISTRY


class _KnobBinding:
    __slots__ = ("knob", "getter", "setter")

    def __init__(self, knob, getter, setter):
        self.knob = knob
        self.getter = getter      # (target) -> current value
        self.setter = setter      # (target, value) -> None


class _Override:
    """One applied move awaiting its evaluation window."""

    __slots__ = ("decision_id", "policy", "knob", "target", "old",
                 "new", "applied_step", "eval_at_step", "baseline_s",
                 "predicted_win_s", "samples")

    def __init__(self, *, decision_id, policy, knob, target, old, new,
                 applied_step, eval_at_step, baseline_s,
                 predicted_win_s):
        self.decision_id = decision_id
        self.policy = policy
        self.knob = knob
        self.target = target
        self.old = old
        self.new = new
        self.applied_step = applied_step
        self.eval_at_step = eval_at_step
        self.baseline_s = baseline_s
        self.predicted_win_s = predicted_win_s
        self.samples = []         # objective samples after the move


class RuntimeController:
    """One per engine (train or serving). Construct only when the
    strict-validated ``controller`` config section enables it — a
    disabled controller is structurally absent (``engine.controller is
    None``): no ledger file, no policies, no per-step branch beyond
    one ``is not None``."""

    def __init__(self, cfg, telemetry=None, role="train",
                 output_dir=None):
        self.cfg = dict(cfg)
        self.role = role
        self.telemetry = telemetry
        if output_dir is None and telemetry is not None:
            output_dir = getattr(telemetry, "output_dir", None)
        self.ledger = DecisionLedger(output_dir)
        self.policies = [POLICY_REGISTRY[name]()
                         for name in self.cfg["policies"]]
        self._knobs = {}
        self._pending = []        # _Override awaiting evaluation
        self._cooldown = {}       # (knob, target) -> step it expires
        self._objective = []      # recent (step, objective_s)
        self._next_id = 0
        self._last_decide_step = None
        self.decisions = 0
        self.outcomes = 0
        self.reverts = 0
        self.drift = None         # last predicted/measured ratio
        recorder = getattr(telemetry, "recorder", None) \
            if telemetry is not None else None
        if recorder is not None:
            # the whole ledger in every crash bundle, resolved at dump
            # time — a dump alone replays every decision
            recorder.set_context("controller", self._bundle_context)

    # ------------------------------------------------------------ knobs
    def register_knob(self, knob, getter, setter):
        """Bind a controller-managed tunable. ``getter(target)`` reads
        the live value, ``setter(target, value)`` writes it — the
        setter is invoked ONLY from apply_override."""
        self._knobs[knob] = _KnobBinding(knob, getter, setter)

    @property
    def knobs(self):
        return sorted(self._knobs)

    # ---------------------------------------------------------- the seam
    def apply_override(self, *, policy, knob, target=None, new=None,
                       signal=None, predicted_win_s=None, reason="",
                       step=None):
        """THE single audited actuation seam: every knob write the
        controller ever performs goes through here, and none happens
        without its ledger ``decision`` event. Returns the event, or
        None when the knob has no binding / is cooling down."""
        binding = self._knobs.get(knob)
        if binding is None:
            return None
        step = self._last_step() if step is None else int(step)
        if self._cooldown.get((knob, target), -1) >= step:
            return None
        old = binding.getter(target)
        if old == new:
            return None
        decision_id = "{}-{:04d}".format(self.role, self._next_id)
        self._next_id += 1
        binding.setter(target, new)
        ev = self.ledger.emit(
            event="decision", decision_id=decision_id, policy=policy,
            knob=knob, target=target, old=old, new=new,
            signal=dict(signal or {}, step=step),
            predicted_win_s=predicted_win_s, reason=reason)
        self.decisions += 1
        self._metric("controller_decision", knob)
        self._pending.append(_Override(
            decision_id=decision_id, policy=policy, knob=knob,
            target=target, old=old, new=new, applied_step=step,
            eval_at_step=step + self.cfg["eval_steps"],
            baseline_s=self._objective_mean(self.cfg["interval_steps"]),
            predicted_win_s=predicted_win_s))
        self._cooldown[(knob, target)] = \
            step + self.cfg["cooldown_steps"]
        logger.info("controller[%s]: %s %s%s %r -> %r (%s)", self.role,
                    policy, knob, "" if target is None else
                    ":" + str(target), old, new, reason)
        return ev

    # ------------------------------------------------------------- tick
    def on_step(self, step, objective_s, signals=None):
        """The per-step tick, called from the engine's telemetry emit
        path: fold the objective sample, finalize due evaluations,
        and every ``interval_steps`` ask the policies for moves."""
        step = int(step)
        if objective_s is not None:
            self._objective.append((step, float(objective_s)))
            del self._objective[:-256]
            for ov in self._pending:
                if step > ov.applied_step:
                    ov.samples.append(float(objective_s))
        self._evaluate(step)
        if signals is None:
            return
        last = self._last_decide_step
        if last is not None and \
                step - last < self.cfg["interval_steps"]:
            return
        self._last_decide_step = step
        signals.setdefault(
            "step_time_s",
            self._objective_mean(self.cfg["interval_steps"]))
        budget = self.cfg["max_moves_per_tick"]
        for pol in self.policies:
            if budget <= 0:
                break
            try:
                moves = pol.propose(signals)
            except Exception:  # noqa: BLE001 - a policy bug must not
                logger.warning("controller policy %s failed on its "
                               "signals", pol.name, exc_info=True)
                continue      # kill the training step
            for move in moves:
                if budget <= 0:
                    break
                if self.apply_override(step=step, **move) is not None:
                    budget -= 1

    # ------------------------------------------------------- evaluation
    def _evaluate(self, step):
        due = [ov for ov in self._pending
               if step >= ov.eval_at_step and ov.samples]
        for ov in due:
            self._pending.remove(ov)
            measured = sum(ov.samples) / len(ov.samples)
            win = None if ov.baseline_s is None \
                else ov.baseline_s - measured
            drift = None
            if win and ov.predicted_win_s is not None:
                drift = ov.predicted_win_s / win if win != 0 else None
            cite = {"baseline_s": ov.baseline_s,
                    "measured_s": measured,
                    "n_samples": len(ov.samples),
                    "drift": drift}
            self.outcomes += 1
            self.ledger.emit(
                event="outcome", decision_id=ov.decision_id,
                policy=ov.policy, knob=ov.knob, target=ov.target,
                old=ov.old, new=ov.new, signal=cite,
                predicted_win_s=ov.predicted_win_s,
                measured_win_s=0.0 if win is None else win,
                reason="evaluation window closed")
            if drift is not None:
                self.drift = drift
                self._metric("controller_drift", drift)
            if win is not None and ov.baseline_s and win < 0 and \
                    -win > abs(ov.baseline_s) * \
                    self.cfg["guardrail_pct"]:
                self._regressed(ov, win, measured)

    def _regressed(self, ov, win, measured):
        """Guardrail trip: dump (the bundle carries the ledger), then
        auto-revert — the revert is a first-class ledger event."""
        detail = ("{}: {}{} {!r} -> {!r} regressed {:.1%} past the "
                  "{:.0%} guardrail (baseline {:.4f}s, measured "
                  "{:.4f}s)").format(
                      ov.decision_id, ov.knob,
                      "" if ov.target is None else ":" + str(ov.target),
                      ov.old, ov.new, -win / abs(ov.baseline_s),
                      self.cfg["guardrail_pct"], ov.baseline_s,
                      measured)
        watchdog = getattr(self.telemetry, "watchdog", None) \
            if self.telemetry is not None else None
        if watchdog is not None:
            watchdog.observe_controller(detail)
        binding = self._knobs.get(ov.knob)
        if binding is not None:
            binding.setter(ov.target, ov.old)
        self.reverts += 1
        self._metric("controller_revert", ov.knob)
        # cooldown so the reverted knob is not immediately re-proposed
        self._cooldown[(ov.knob, ov.target)] = \
            ov.eval_at_step + 2 * self.cfg["cooldown_steps"]
        self.ledger.emit(
            event="revert", decision_id=ov.decision_id,
            policy=ov.policy, knob=ov.knob, target=ov.target,
            old=ov.new, new=ov.old,
            signal={"baseline_s": ov.baseline_s,
                    "measured_s": measured},
            predicted_win_s=ov.predicted_win_s, measured_win_s=win,
            reason=detail)
        logger.warning("controller[%s]: reverted %s", self.role,
                       detail)

    # ---------------------------------------------------------- helpers
    def _last_step(self):
        return self._objective[-1][0] if self._objective else 0

    def _objective_mean(self, last_n):
        vals = [v for _, v in self._objective[-int(last_n):]]
        return sum(vals) / len(vals) if vals else None

    def _metric(self, what, arg):
        metrics = getattr(self.telemetry, "metrics", None) \
            if self.telemetry is not None else None
        if metrics is None:
            return
        try:
            if what == "controller_decision":
                metrics.controller_decision(arg)
            elif what == "controller_revert":
                metrics.controller_revert(arg)
            else:
                metrics.controller_drift(arg)
        except Exception:  # noqa: BLE001 - metrics must not kill steps
            logger.warning("controller metrics update failed",
                           exc_info=True)

    def overrides(self):
        """Currently-live overrides (awaiting evaluation) — surfaced
        in /healthz so an operator sees what the controller holds."""
        return [{"decision_id": ov.decision_id, "policy": ov.policy,
                 "knob": ov.knob, "target": ov.target, "old": ov.old,
                 "new": ov.new, "applied_step": ov.applied_step}
                for ov in self._pending]

    def snapshot(self):
        """CONTROLLER_SNAPSHOT_KEYS shape (telemetry/record.py):
        rides ``telemetry_snapshot()['controller']``, ``/healthz`` and
        the bench ``extra.controller`` block."""
        return {
            "enabled": True,
            "role": self.role,
            "policies": [pol.name for pol in self.policies],
            "decisions": self.decisions,
            "outcomes": self.outcomes,
            "reverts": self.reverts,
            "pending": len(self._pending),
            "overrides": self.overrides(),
            "drift": self.drift,
            "ledger_path": self.ledger.path,
        }

    def _bundle_context(self):
        return dict(self.snapshot(), events=self.ledger.snapshot())
