"""Engine adapters: bind the jax-free controller core to the live
engines. Every knob getter/setter the controller can actuate is
DEFINED here — textually inside ``runtime/controller/`` — so the
DSL012 lint (knob-write-outside-controller) keeps meaning: a knob
mutation anywhere else in the tree is a bypass of the audited
``apply_override`` seam, not an idiom.

``attach_train_controller`` / ``attach_serving_controller`` construct
the :class:`RuntimeController` (call them ONLY when the strict-
validated ``controller`` config section enables it — off must stay
structurally absent), register the engine's eligible knobs, and hook
the snapshot into the collector's ``/healthz`` /
``telemetry_snapshot()`` view. ``train_signals`` / ``serving_signals``
assemble the per-tick signals dict from the existing observability
seams: the plan executor's measured totals, the serving metrics'
speculative acceptance, the watchdog's TTFT burn rate, the fleet
state's ingested ICI health, the compile observatory's storm flags,
and the wire estimator as the quantized-collectives pricer.
"""
from ...utils.logging import logger
from .core import RuntimeController


def _set_window(engine, target, value):
    engine.plan_executor().windows[str(target)] = int(value)


def _set_h2d_bucket(engine, value):
    engine._h2d_bucket_elems = int(value)


def _set_quantized(engine, target, value):
    value = bool(value)
    if target == "weights":
        engine._qwz_enabled = value
    else:
        engine._qgz_enabled = value
        if value and "qg_error" not in engine.state:
            acc = engine.state.get("acc_grads")
            if acc is not None:
                engine._init_qg_error(acc)
    # the jitted step builders close over these bools — drop the cache
    # so the next step re-traces with the new collective decomposition
    engine._jit_cache.clear()


def _set_spec_k(engine, value):
    engine.spec_k = int(value)


def _set_prefill_chunk(engine, value):
    engine.inference_config.prefill_chunk_tokens = int(value)


def _set_prefill_buckets(engine, value):
    engine.prefill_buckets = [int(b) for b in value]


def _storm_flags(telemetry):
    try:
        return [f["key"] for f in telemetry.programs.flags
                if str(f["key"]).startswith("recompile_storm:")]
    except Exception:  # noqa: BLE001 - a malformed flag must not
        return []     # poison the tick


def attach_train_controller(engine, cfg):
    """Build the training engine's controller: launch-ahead windows,
    H2D transfer chunk, and (where the ZeRO config makes them
    eligible) quantized collectives per class."""
    ctrl = RuntimeController(cfg, telemetry=engine.telemetry,
                             role="train")
    ctrl.register_knob(
        "launch_ahead_window",
        lambda target: int(engine.plan_executor().windows.get(
            str(target), 1)),
        lambda target, value: _set_window(engine, target, value))
    if getattr(engine, "_h2d_bucket_elems", None):
        ctrl.register_knob(
            "h2d_bucket_elems",
            lambda target: int(engine._h2d_bucket_elems),
            lambda target, value: _set_h2d_bucket(engine, value))
    if _quantized_classes(engine):
        ctrl.register_knob(
            "quantized_collectives",
            lambda target: bool(engine._qwz_enabled
                                if target == "weights"
                                else engine._qgz_enabled),
            lambda target, value: _set_quantized(engine, target, value))
    if engine.telemetry is not None:
        engine.telemetry.set_controller_view(ctrl.snapshot)
    logger.info("controller[train]: attached (policies: %s; knobs: %s)",
                ", ".join(cfg["policies"]), ", ".join(ctrl.knobs))
    return ctrl


def _quantized_classes(engine):
    """The collective classes THIS config's machinery can actually
    quantize (toggling an ineligible class would silently no-op or
    break the step builders — observe_fleet never proposes it)."""
    stage = engine.zero_optimization_stage()
    classes = {}
    if stage >= 3 and getattr(engine.zero_plan, "param_data_axes",
                              ()) != ():
        classes["weights"] = bool(engine._qwz_enabled)
    if engine._config.zero_enabled and stage >= 2:
        classes["gradients"] = bool(engine._qgz_enabled)
    return classes


def _wire_win_s(engine):
    """The quantized-collectives pricer: the wire estimator's per-class
    bytes-on-wire over measured ICI nominal bandwidth, scaled by the
    int8 payload shrink (~3/4 of the full-precision bytes stay home).
    ``{}`` when the estimate is unavailable."""
    est = engine._telemetry_wire()
    if not est or engine.telemetry is None:
        return {}
    try:
        from ..comm.wire import ici_bytes_per_s_for
        bw = ici_bytes_per_s_for(engine.telemetry._device)
    except Exception:  # noqa: BLE001 - pricing must not kill the tick
        return {}
    if not bw:
        return {}
    out = {}
    for cls, key in (("weights", "allgather_bytes_per_step"),
                     ("gradients", "reduce_bytes_per_step")):
        nbytes = est.get(key) or 0
        if nbytes > 0:
            out[cls] = 0.75 * float(nbytes) / float(bw)
    return out


def train_signals(engine):
    """Signals dict (see policies.py vocabulary) for one training
    tick, assembled from the existing telemetry seams only."""
    tel = engine.telemetry
    sig = {"step": engine.global_steps}
    ex = engine._plan_executor
    if ex is not None:
        per_kind, busy, waits = ex.measured_totals()
        sig["exec_per_kind"] = per_kind
        sig["exec_busy_s"] = busy
        sig["exec_waits_s"] = waits
        sig["windows"] = dict(ex.windows)
    if getattr(engine, "_h2d_bucket_elems", None):
        sig["h2d_bucket_elems"] = int(engine._h2d_bucket_elems)
    quantized = _quantized_classes(engine)
    if quantized:
        sig["quantized"] = quantized
        sig["wire_win_s"] = _wire_win_s(engine)
    if tel is not None:
        if tel.fleet is not None and tel.fleet.ici_health:
            sig["ici_health"] = dict(tel.fleet.ici_health)
        sig["storm_flags"] = _storm_flags(tel)
    return sig


def attach_serving_controller(engine, cfg):
    """Build the serving engine's controller: speculative k (drafter
    configured), chunked-prefill size (chunking configured), and the
    prefill bucket list."""
    ctrl = RuntimeController(cfg, telemetry=engine.telemetry,
                             role="serve")
    if engine.drafter is not None:
        ctrl.register_knob(
            "spec_k",
            lambda target: int(engine.spec_k),
            lambda target, value: _set_spec_k(engine, value))
    if engine.inference_config.prefill_chunk_tokens:
        ctrl.register_knob(
            "prefill_chunk_tokens",
            lambda target: int(
                engine.inference_config.prefill_chunk_tokens),
            lambda target, value: _set_prefill_chunk(engine, value))
    ctrl.register_knob(
        "prefill_buckets",
        lambda target: list(engine.prefill_buckets),
        lambda target, value: _set_prefill_buckets(engine, value))
    if engine.telemetry is not None:
        engine.telemetry.set_controller_view(ctrl.snapshot)
    logger.info("controller[serve]: attached (policies: %s; knobs: %s)",
                ", ".join(cfg["policies"]), ", ".join(ctrl.knobs))
    return ctrl


def serving_signals(sched):
    """Signals dict for one serving-scheduler tick."""
    engine = sched.engine
    tel = engine.telemetry
    sig = {"step": engine.serving_record_steps,
           "spec_k": int(engine.spec_k),
           "prefill_buckets": list(engine.prefill_buckets)}
    chunk = engine.inference_config.prefill_chunk_tokens
    if chunk:
        sig["prefill_chunk_tokens"] = int(chunk)
    metrics = getattr(sched, "_record_metrics", None)
    if metrics is not None:
        dist = metrics.spec_dist()
        if dist is not None:
            sig["acceptance_rate"] = dist["acceptance_rate"]
    if tel is not None:
        if tel.watchdog is not None:
            sig["ttft_burn_rate"] = tel.watchdog.ttft_burn_rate()
        sig["storm_flags"] = _storm_flags(tel)
    return sig
