"""Decision-ledger schema for the closed-loop runtime controller.

Every move the controller takes lands as ONE schema-pinned JSON event
appended to ``controller_events.jsonl`` inside the telemetry job
directory: the ``decision`` (signal citation with the measured values
that triggered it, knob, old -> new, the pricer's predicted win), the
``outcome`` appended after the evaluation window (measured win,
predicted-vs-measured drift), and — when a guardrail trips — the
``revert`` (also a first-class event, so a doctored run reconstructs
the whole episode from the ledger alone). The fleet merger
(telemetry/fleet/aggregate.py) reads the per-host files the same way
it reads rescale/router events and surfaces them in the fleet report's
``controller`` section (bin/ds_fleet.py prints the DECISIONS table).

Stdlib-only by contract: ``aggregate.py`` and ``check_bench_schema.py``
carry local copies of :data:`DECISION_KEYS` /
:data:`CONTROLLER_EVENT_TYPES` / :data:`CONTROLLER_KNOBS` (pinned
equal by tests/unit/test_controller.py) so doctoring a crashed run
never needs jax importable.
"""
import json
import os
import time

KIND_CONTROLLER_EVENT = "controller_event"

# per-host file name inside a telemetry job directory (the rescale/
# router events discipline: one JSONL per host, merged wall-ordered)
CONTROLLER_EVENTS_JSONL = "controller_events.jsonl"

# the event vocabulary — one decision episode is decision -> outcome
# [-> revert]; the controller emits nothing outside this set
CONTROLLER_EVENT_TYPES = ("decision", "outcome", "revert")

# the knob vocabulary — every controller-managed tunable (DSL012 flags
# writes to these outside runtime/controller/ and the config parsers)
CONTROLLER_KNOBS = ("launch_ahead_window", "h2d_bucket_elems", "spec_k",
                    "prefill_chunk_tokens", "quantized_collectives",
                    "prefill_buckets")

# every controller_event carries exactly these top-level keys
DECISION_KEYS = ("kind", "wall", "seq", "event", "decision_id", "policy",
                 "knob", "target", "old", "new", "signal",
                 "predicted_win_s", "measured_win_s", "reason")


def make_controller_event(*, event, decision_id, policy, knob,
                          target=None, old=None, new=None, signal=None,
                          predicted_win_s=None, measured_win_s=None,
                          reason="", seq=0, wall=None):
    return {
        "kind": KIND_CONTROLLER_EVENT,
        "wall": float(wall if wall is not None else time.time()),
        "seq": int(seq),
        "event": str(event),
        "decision_id": str(decision_id),
        "policy": str(policy),
        "knob": str(knob),
        "target": None if target is None else str(target),
        "old": old,
        "new": new,
        "signal": signal,
        "predicted_win_s": (None if predicted_win_s is None
                            else float(predicted_win_s)),
        "measured_win_s": (None if measured_win_s is None
                           else float(measured_win_s)),
        "reason": str(reason),
    }


def validate_controller_event(ev):
    """Schema check for one controller_event dict. Returns a list of
    problem strings; empty list = valid."""
    problems = []
    if not isinstance(ev, dict):
        return ["controller event is not a dict: {!r}".format(
            type(ev).__name__)]
    for key in DECISION_KEYS:
        if key not in ev:
            problems.append("missing key {!r}".format(key))
    extra = sorted(set(ev) - set(DECISION_KEYS))
    if extra:
        problems.append("unexpected key(s) {}".format(extra))
    if problems:
        return problems
    if ev["kind"] != KIND_CONTROLLER_EVENT:
        problems.append("kind is {!r}, want {!r}".format(
            ev["kind"], KIND_CONTROLLER_EVENT))
    if ev["event"] not in CONTROLLER_EVENT_TYPES:
        problems.append("event {!r} not in {}".format(
            ev["event"], CONTROLLER_EVENT_TYPES))
    if ev["knob"] not in CONTROLLER_KNOBS:
        problems.append("knob {!r} not in {}".format(
            ev["knob"], CONTROLLER_KNOBS))
    for key in ("wall", "seq"):
        if isinstance(ev[key], bool) or \
                not isinstance(ev[key], (int, float)):
            problems.append("{} is not a number: {!r}".format(
                key, ev[key]))
    for key in ("decision_id", "policy", "reason"):
        if not isinstance(ev[key], str):
            problems.append("{} is not a string: {!r}".format(
                key, ev[key]))
    if ev["target"] is not None and not isinstance(ev["target"], str):
        problems.append("target is neither null nor a string: "
                        "{!r}".format(ev["target"]))
    if ev["signal"] is not None and not isinstance(ev["signal"], dict):
        problems.append("signal is neither null nor a dict: "
                        "{!r}".format(ev["signal"]))
    for key in ("predicted_win_s", "measured_win_s"):
        if ev[key] is not None and (
                isinstance(ev[key], bool) or
                not isinstance(ev[key], (int, float))):
            problems.append("{} is neither null nor a number: "
                            "{!r}".format(key, ev[key]))
    # a decision cites its trigger; an outcome/revert cites its measure
    if ev["event"] == "decision" and ev["signal"] is None:
        problems.append("decision event carries no signal citation")
    if ev["event"] in ("outcome", "revert") and \
            ev["measured_win_s"] is None:
        problems.append("{} event carries no measured_win_s".format(
            ev["event"]))
    return problems


def unreverted_regressions(events, guardrail_pct=0.0):
    """Decision ids whose ``outcome`` measured a regression past the
    guardrail with no later ``revert`` — reconstructable from the
    ledger alone (bin/ds_fleet.py --strict counts these)."""
    regressed, reverted = {}, set()
    for ev in events:
        if not isinstance(ev, dict) or \
                ev.get("kind") != KIND_CONTROLLER_EVENT:
            continue
        if ev.get("event") == "revert":
            reverted.add(ev.get("decision_id"))
        elif ev.get("event") == "outcome":
            win = ev.get("measured_win_s")
            base = (ev.get("signal") or {}).get("baseline_s")
            if isinstance(win, (int, float)) and not \
                    isinstance(win, bool) and win < 0:
                floor = abs(base) * float(guardrail_pct) \
                    if isinstance(base, (int, float)) and not \
                    isinstance(base, bool) else 0.0
                if -win >= floor:
                    regressed[ev.get("decision_id")] = win
    return sorted(d for d in regressed if d not in reverted and
                  d is not None)


class DecisionLedger:
    """In-memory event list + optional JSONL append (one line per
    event, flushed per event so a crashed controller leaves every
    decision it took on disk — the torn-tail tolerance lives in the
    merger's ``read_jsonl_tolerant``)."""

    def __init__(self, output_dir=None):
        self.events = []
        self.path = None
        self._seq = 0
        if output_dir is not None:
            os.makedirs(output_dir, exist_ok=True)
            self.path = os.path.join(output_dir, CONTROLLER_EVENTS_JSONL)

    def emit(self, **kwargs):
        kwargs.setdefault("seq", self._seq)
        ev = make_controller_event(**kwargs)
        problems = validate_controller_event(ev)
        assert not problems, "controller event failed its own schema: " \
            "{}".format(problems)
        self._seq = max(self._seq, ev["seq"]) + 1
        self.events.append(ev)
        if self.path is not None:
            with open(self.path, "a") as fh:
                fh.write(json.dumps(ev) + "\n")
                fh.flush()
        return ev

    def tally(self):
        """{event type: count} over everything emitted so far."""
        counts = {}
        for ev in self.events:
            counts[ev["event"]] = counts.get(ev["event"], 0) + 1
        return counts

    def snapshot(self):
        """The full ledger (crash bundles embed this under
        ``state.controller.events`` via the flight-recorder context,
        so a dump alone replays every decision)."""
        return list(self.events)
