"""The controller's policy catalog (docs/controller.md).

Each policy is a small stateful object: ``propose(signals)`` reads the
signals dict the engine adapter assembled from ``telemetry_snapshot()``
/ ``ingest_fleet`` state and returns a list of *proposed moves* — plain
dicts citing the measured values that triggered them and the pricer's
predicted win. Policies never actuate anything themselves: the
:class:`~deepspeed_tpu.runtime.controller.core.RuntimeController`
applies at most ``max_moves_per_tick`` of them through its single
audited ``apply_override()`` seam, which is also the only place the
ledger's ``decision`` events are born.

Signals dict vocabulary (absent keys = signal not available; policies
must tolerate every absence):

* ``step`` — current engine step
* ``step_time_s`` — rolling mean of the objective (step wall)
* ``exec_per_kind`` — ``{kind: {segments, run_s, wait_s}}`` lifetime
  executor totals (``PlanExecutor.measured_totals``)
* ``exec_busy_s`` / ``exec_waits_s`` — lifetime busy / exposed-wait
* ``windows`` — the executor's live launch-ahead windows dict
* ``h2d_bucket_elems`` — the H2D batcher's transfer chunk size
* ``acceptance_rate`` — speculative-decode acceptance (0..1)
* ``ttft_burn_rate`` — TTFT SLO burn rate (>1 = burning too fast)
* ``spec_k`` / ``prefill_chunk_tokens`` / ``prefill_buckets`` —
  current serving knob values
* ``ici_health`` — ``{"host:class": achieved/nominal}`` from
  ``ingest_fleet`` (1.0 = nominal, lower = degraded link)
* ``quantized`` — ``{"weights": bool, "gradients": bool}``
* ``wire_win_s`` — ``{class: predicted seconds saved per step}`` from
  the wire estimator's quantized-vs-full byte model
* ``storm_flags`` — recompile-storm program keys from the compile
  observatory (``telemetry.programs.flags``)
"""

# shared proposal shape (the controller turns one of these into a
# ledger ``decision`` event via apply_override)


def make_move(*, policy, knob, target=None, new=None, signal=None,
              predicted_win_s=None, reason=""):
    return {"policy": policy, "knob": knob, "target": target,
            "new": new, "signal": signal or {},
            "predicted_win_s": predicted_win_s, "reason": reason}


class LaunchAheadPolicy:
    """Executor launch-ahead windows and H2D transfer chunk size from
    measured exposed waits — the continuous version of the act-once
    ``widen`` rewrite pass. When the exposed-wait fraction of a step
    rises past ``wait_frac_hi`` the window of the waitiest segment kind
    widens by one (the pricer: the wait it would hide); when a widened
    window's kind shows ~no wait any more the window decays back toward
    its base so the schedule never ratchets. With the h2d window
    already at ``max_window`` and h2d still the waitiest kind, the
    transfer chunk size doubles instead (fewer, larger copies)."""

    name = "launch_ahead"

    def __init__(self, wait_frac_hi=0.10, wait_frac_lo=0.02,
                 max_window=16, max_bucket_growth=4):
        self.wait_frac_hi = float(wait_frac_hi)
        self.wait_frac_lo = float(wait_frac_lo)
        self.max_window = int(max_window)
        self.max_bucket_growth = int(max_bucket_growth)
        self._prev = None          # (per_kind wait_s, busy, waits)
        self._base_bucket = None

    def propose(self, signals):
        per_kind = signals.get("exec_per_kind")
        busy = signals.get("exec_busy_s")
        waits = signals.get("exec_waits_s")
        windows = signals.get("windows")
        if per_kind is None or busy is None or waits is None or \
                not windows:
            return []
        kind_waits = {k: float(v.get("wait_s", 0.0))
                      for k, v in per_kind.items()}
        prev = self._prev or ({}, 0.0, 0.0)
        self._prev = (kind_waits, float(busy), float(waits))
        d_busy = float(busy) - prev[1]
        d_waits = float(waits) - prev[2]
        if d_busy + d_waits <= 0:
            return []
        frac = d_waits / (d_busy + d_waits)
        d_kind = {k: w - prev[0].get(k, 0.0)
                  for k, w in kind_waits.items() if k in windows}
        moves = []
        if frac > self.wait_frac_hi and d_kind:
            kind = max(d_kind, key=d_kind.get)
            if d_kind[kind] <= 0:
                return []
            cur = int(windows.get(kind, 1))
            cite = {"wait_frac": round(frac, 4),
                    "kind_wait_delta_s": round(d_kind[kind], 6),
                    "busy_delta_s": round(d_busy, 6)}
            if cur < self.max_window:
                moves.append(make_move(
                    policy=self.name, knob="launch_ahead_window",
                    target=kind, new=cur + 1, signal=cite,
                    # the widen pricer: half the kind's exposed wait is
                    # hideable by one more in-flight slot
                    predicted_win_s=d_kind[kind] * 0.5,
                    reason="exposed-wait fraction {:.0%} past {:.0%}; "
                           "{} waitiest".format(frac, self.wait_frac_hi,
                                                kind)))
            elif kind == "h2d" and \
                    signals.get("h2d_bucket_elems") is not None:
                elems = int(signals["h2d_bucket_elems"])
                if self._base_bucket is None:
                    self._base_bucket = elems
                if elems < self._base_bucket * self.max_bucket_growth:
                    cite["h2d_window"] = cur
                    moves.append(make_move(
                        policy=self.name, knob="h2d_bucket_elems",
                        new=elems * 2, signal=cite,
                        predicted_win_s=d_kind[kind] * 0.25,
                        reason="h2d window at max {}; growing transfer "
                               "chunk".format(cur)))
        elif frac < self.wait_frac_lo:
            # decay: narrow the widest window whose kind shows no wait
            idle = [(k, int(w)) for k, w in windows.items()
                    if int(w) > 1 and d_kind.get(k, 0.0) <= 0.0]
            if idle:
                kind, cur = max(idle, key=lambda kv: kv[1])
                moves.append(make_move(
                    policy=self.name, knob="launch_ahead_window",
                    target=kind, new=cur - 1,
                    signal={"wait_frac": round(frac, 4)},
                    predicted_win_s=0.0,
                    reason="exposed-wait fraction {:.1%} below "
                           "{:.0%}; decaying".format(
                               frac, self.wait_frac_lo)))
        return moves


class SpeculationPolicy:
    """Speculative k and chunked-prefill size from acceptance rate and
    TTFT SLO burn. High acceptance means the drafter is cheap tokens on
    the table (raise k); low acceptance means wasted verify flops
    (lower k). A burning TTFT SLO shrinks the prefill chunk so decode
    interleaves sooner; a comfortably green SLO grows it back toward
    the configured base."""

    name = "speculation"

    def __init__(self, accept_hi=0.8, accept_lo=0.4, max_k=8,
                 burn_hi=1.0, burn_lo=0.5, min_chunk=64):
        self.accept_hi = float(accept_hi)
        self.accept_lo = float(accept_lo)
        self.max_k = int(max_k)
        self.burn_hi = float(burn_hi)
        self.burn_lo = float(burn_lo)
        self.min_chunk = int(min_chunk)
        self._base_chunk = None

    def propose(self, signals):
        moves = []
        accept = signals.get("acceptance_rate")
        k = signals.get("spec_k")
        step_s = signals.get("step_time_s") or 0.0
        if accept is not None and k:
            cite = {"acceptance_rate": round(float(accept), 4),
                    "spec_k": int(k)}
            if accept > self.accept_hi and k < self.max_k:
                moves.append(make_move(
                    policy=self.name, knob="spec_k", new=int(k) + 1,
                    signal=cite,
                    # one more draft token at this acceptance ~ its
                    # share of the verify step's wall back
                    predicted_win_s=step_s * float(accept) / (k + 1),
                    reason="acceptance {:.0%} past {:.0%}".format(
                        accept, self.accept_hi)))
            elif accept < self.accept_lo and k > 1:
                moves.append(make_move(
                    policy=self.name, knob="spec_k", new=int(k) - 1,
                    signal=cite,
                    predicted_win_s=step_s * (1.0 - float(accept)) / k,
                    reason="acceptance {:.0%} below {:.0%}".format(
                        accept, self.accept_lo)))
        burn = signals.get("ttft_burn_rate")
        chunk = signals.get("prefill_chunk_tokens")
        if burn is not None and chunk:
            chunk = int(chunk)
            if self._base_chunk is None:
                self._base_chunk = chunk
            cite = {"ttft_burn_rate": round(float(burn), 4),
                    "prefill_chunk_tokens": chunk}
            if burn > self.burn_hi and chunk // 2 >= self.min_chunk:
                moves.append(make_move(
                    policy=self.name, knob="prefill_chunk_tokens",
                    new=chunk // 2, signal=cite,
                    predicted_win_s=step_s * 0.5,
                    reason="TTFT SLO burn {:.2f} past {:.2f}; halving "
                           "prefill chunk".format(burn, self.burn_hi)))
            elif burn < self.burn_lo and chunk * 2 <= self._base_chunk:
                moves.append(make_move(
                    policy=self.name, knob="prefill_chunk_tokens",
                    new=chunk * 2, signal=cite, predicted_win_s=0.0,
                    reason="TTFT SLO burn {:.2f} below {:.2f}; growing "
                           "prefill chunk back".format(
                               burn, self.burn_lo)))
        return moves


class QuantizedCollectivesPolicy:
    """Quantized collectives on/off per class from ingested ICI health
    vs the wire estimator's predicted win (the EQuARX argument: the
    quantization win is link-health-dependent, so it must be decided
    from live measurement). A class quantizes when any link's
    achieved/nominal ratio sinks past ``health_lo`` AND the wire model
    predicts a positive win; it un-quantizes when every link is back
    above ``health_hi``."""

    name = "quantized_collectives"

    def __init__(self, health_lo=0.6, health_hi=0.9):
        self.health_lo = float(health_lo)
        self.health_hi = float(health_hi)

    def propose(self, signals):
        health = signals.get("ici_health") or {}
        quantized = signals.get("quantized") or {}
        wire_win = signals.get("wire_win_s") or {}
        vals = [v for v in health.values()
                if isinstance(v, (int, float))]
        if not vals or not quantized:
            return []
        worst_key = min(health, key=lambda k: health[k]
                        if isinstance(health[k], (int, float))
                        else float("inf"))
        worst = float(health[worst_key])
        moves = []
        for cls, on in sorted(quantized.items()):
            win = wire_win.get(cls)
            cite = {"worst_link": worst_key,
                    "worst_health": round(worst, 4),
                    "predicted_wire_win_s": win}
            if not on and worst < self.health_lo and win and win > 0:
                moves.append(make_move(
                    policy=self.name, knob="quantized_collectives",
                    target=cls, new=True, signal=cite,
                    predicted_win_s=win,
                    reason="link {} at {:.0%} of nominal (< {:.0%}); "
                           "wire model predicts {:.3f}s/step".format(
                               worst_key, worst, self.health_lo,
                               win)))
            elif on and worst > self.health_hi:
                moves.append(make_move(
                    policy=self.name, knob="quantized_collectives",
                    target=cls, new=False, signal=cite,
                    predicted_win_s=0.0,
                    reason="links recovered to {:.0%} of nominal "
                           "(> {:.0%})".format(worst, self.health_hi)))
        return moves


class PrefillBucketsPolicy:
    """Prefill buckets from compile-observatory storm flags: a
    recompile storm on the prefill program family means the bucket
    list admits too many distinct shapes, so coarsen it (drop every
    other bucket, always keeping the largest — admission correctness
    depends on the top bucket covering max_seq_len). Acts at most once
    per distinct storm flag set."""

    name = "prefill_buckets"

    def __init__(self, min_buckets=2):
        self.min_buckets = int(min_buckets)
        self._seen_flags = set()

    def propose(self, signals):
        flags = tuple(sorted(signals.get("storm_flags") or ()))
        buckets = signals.get("prefill_buckets")
        if not flags or not buckets or flags in self._seen_flags:
            return []
        self._seen_flags.add(flags)
        buckets = list(buckets)
        if len(buckets) <= self.min_buckets:
            return []
        coarse = buckets[::2]
        if coarse[-1] != buckets[-1]:
            coarse.append(buckets[-1])
        step_s = signals.get("step_time_s") or 0.0
        return [make_move(
            policy=self.name, knob="prefill_buckets", new=coarse,
            signal={"storm_flags": list(flags),
                    "n_buckets": len(buckets)},
            # the pricer: each avoided executable is roughly one step
            # wall of compile amortization saved
            predicted_win_s=step_s * (len(buckets) - len(coarse)),
            reason="recompile storm on {}; coarsening {} -> {} "
                   "buckets".format(", ".join(flags), len(buckets),
                                    len(coarse)))]


# registry: config "policies" list entries -> classes
POLICY_REGISTRY = {
    LaunchAheadPolicy.name: LaunchAheadPolicy,
    SpeculationPolicy.name: SpeculationPolicy,
    QuantizedCollectivesPolicy.name: QuantizedCollectivesPolicy,
    PrefillBucketsPolicy.name: PrefillBucketsPolicy,
}

CONTROLLER_POLICIES = tuple(sorted(POLICY_REGISTRY))
