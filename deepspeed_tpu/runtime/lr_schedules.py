"""LR schedules: LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR.

Reference parity: deepspeed/runtime/lr_schedules.py (:301, :408, :677, :761).
Schedules step per optimizer step and write ``lr`` (and OneCycle momentum)
onto the optimizer handle; the engine feeds those host scalars into the jitted
train step as arguments, so schedule changes never trigger recompilation.
"""
import math
from argparse import ArgumentParser

from ..utils.logging import logger

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"
CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"
CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
TOTAL_NUM_STEPS = "total_num_steps"


def add_tuning_arguments(parser):
    """CLI args for schedule tuning (reference :54-154)."""
    group = parser.add_argument_group("Convergence Tuning",
                                      "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001,
                       help="Starting lr value.")
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0,
                       help="scaling rate for LR range test.")
    group.add_argument("--lr_range_test_step_size", type=int, default=1000,
                       help="training steps per LR change.")
    group.add_argument("--lr_range_test_staircase", type=bool, default=False,
                       help="use staircase scaling for LR range test.")
    group.add_argument("--cycle_first_step_size", type=int, default=1000,
                       help="size of first step of 1Cycle schedule (training steps).")
    group.add_argument("--cycle_first_stair_count", type=int, default=-1,
                       help="first stair count for 1Cycle schedule.")
    group.add_argument("--cycle_second_step_size", type=int, default=-1,
                       help="size of second step of 1Cycle schedule (default first_step_size).")
    group.add_argument("--cycle_second_stair_count", type=int, default=-1,
                       help="second stair count for 1Cycle schedule.")
    group.add_argument("--decay_step_size", type=int, default=1000,
                       help="size of intervals for applying post cycle decay (training steps).")
    group.add_argument("--cycle_min_lr", type=float, default=0.01,
                       help="1Cycle LR lower bound.")
    group.add_argument("--cycle_max_lr", type=float, default=0.1,
                       help="1Cycle LR upper bound.")
    group.add_argument("--decay_lr_rate", type=float, default=0.0,
                       help="post cycle LR decay rate.")
    group.add_argument("--cycle_momentum", type=bool, default=False,
                       help="enable 1Cycle momentum schedule.")
    group.add_argument("--cycle_min_mom", type=float, default=0.8,
                       help="1Cycle momentum lower bound.")
    group.add_argument("--cycle_max_mom", type=float, default=0.9,
                       help="1Cycle momentum upper bound.")
    group.add_argument("--decay_mom_rate", type=float, default=0.0,
                       help="post cycle momentum decay rate.")
    group.add_argument("--warmup_min_lr", type=float, default=0,
                       help="WarmupLR minimum/initial LR value.")
    group.add_argument("--warmup_max_lr", type=float, default=0.001,
                       help="WarmupLR maximum LR value.")
    group.add_argument("--warmup_num_steps", type=int, default=1000,
                       help="WarmupLR step count for LR warmup.")
    return parser


def parse_arguments():
    parser = ArgumentParser()
    parser = add_tuning_arguments(parser)
    lr_sched_args, unknown_args = parser.parse_known_args()
    return lr_sched_args, unknown_args


class _ScheduleBase:
    """Common machinery: tracks last_batch_iteration, pushes lr to the
    optimizer handle (any object with a mutable ``lr`` attribute)."""

    def __init__(self, optimizer, last_batch_iteration=-1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        raise NotImplementedError

    def get_last_lr(self):
        assert getattr(self, "_last_lr", None) is not None, \
            "need to call step() first"
        return self._last_lr

    def _update_optimizer(self, lrs):
        if self.optimizer is not None:
            self.optimizer.lr = lrs[0]

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        lrs = self.get_lr()
        self._update_optimizer(lrs)
        self._last_lr = list(lrs)

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_ScheduleBase):
    """LR range test (Smith): grow lr from a base at a constant rate
    (reference :301)."""

    def __init__(self, optimizer, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        if isinstance(lr_range_test_min_lr, (list, tuple)):
            lr_range_test_min_lr = lr_range_test_min_lr[0]
        self.min_lr = [lr_range_test_min_lr]
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        if last_batch_iteration == -1:
            self._update_optimizer(self.min_lr)

    def _interval(self):
        frac = float(self.last_batch_iteration + 1) / self.step_size
        return math.floor(frac) if self.staircase else frac

    def get_lr(self):
        increase = 1 + self.step_rate * self._interval()
        return [lr * increase for lr in self.min_lr]


class OneCycle(_ScheduleBase):
    """1Cycle schedule: lr rises then falls over one cycle, optional inverse
    momentum cycle, then post-cycle decay (reference :408)."""

    def __init__(self, optimizer, cycle_min_lr, cycle_max_lr, decay_lr_rate=0.0,
                 cycle_first_step_size=2000, cycle_second_step_size=None,
                 cycle_first_stair_count=0, cycle_second_stair_count=None,
                 decay_step_size=0, cycle_momentum=True, cycle_min_mom=0.8,
                 cycle_max_mom=0.9, decay_mom_rate=0.0,
                 last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        first = float(cycle_first_step_size)
        second = float(cycle_second_step_size
                       if cycle_second_step_size is not None else first)
        self.total_size = first + second
        self.step_ratio = first / self.total_size
        self.decay_step_size = decay_step_size

        self.min_lrs = [cycle_min_lr]
        self.max_lrs = [cycle_max_lr]
        self.decay_lr_rate = decay_lr_rate

        self.cycle_momentum = cycle_momentum
        self.min_moms = [(cycle_min_mom, 0.99)]
        self.max_moms = [(cycle_max_mom, 0.99)]
        self.decay_mom_rate = decay_mom_rate

        if last_batch_iteration == -1:
            self._update_optimizer(self.min_lrs)
            if cycle_momentum and self.optimizer is not None:
                self.optimizer.betas = self.min_moms[0]

    def _get_scale_factor(self):
        batch_iteration = self.last_batch_iteration + 1
        cycle = math.floor(1 + batch_iteration / self.total_size)
        x = 1.0 + batch_iteration / self.total_size - cycle
        if x <= self.step_ratio:
            return x / self.step_ratio
        return (x - 1) / (self.step_ratio - 1)

    def _get_cycle_lr(self):
        scale = self._get_scale_factor()
        return [min_lr + (max_lr - min_lr) * scale
                for min_lr, max_lr in zip(self.min_lrs, self.max_lrs)]

    def _get_decay_lr(self, decay_batch_iteration):
        decay_interval = decay_batch_iteration / max(self.decay_step_size, 1)
        factor = 1 + self.decay_lr_rate * decay_interval
        return [min_lr / factor for min_lr in self.min_lrs]

    def _get_cycle_mom(self):
        scale = self._get_scale_factor()
        return [(max_m[0] - (max_m[0] - min_m[0]) * scale, min_m[1])
                for min_m, max_m in zip(self.min_moms, self.max_moms)]

    def _get_decay_mom(self, decay_batch_iteration):
        decay_interval = decay_batch_iteration / max(self.decay_step_size, 1)
        factor = 1 + self.decay_mom_rate * decay_interval
        return [(beta0 * factor, beta1) for beta0, beta1 in self.max_moms]

    def get_lr(self):
        if self.last_batch_iteration < self.total_size:
            return self._get_cycle_lr()
        return self._get_decay_lr(self.last_batch_iteration - self.total_size + 1)

    def get_mom(self):
        if not self.cycle_momentum:
            return None
        if self.last_batch_iteration < self.total_size:
            return self._get_cycle_mom()
        return self._get_decay_mom(self.last_batch_iteration - self.total_size + 1)

    def step(self, batch_iteration=None):
        super().step(batch_iteration)
        if self.cycle_momentum and self.optimizer is not None:
            self.optimizer.betas = self.get_mom()[0]


class WarmupLR(_ScheduleBase):
    """Log-warmup from min lr to max lr over warmup_num_steps, then constant
    (reference :677)."""

    def __init__(self, optimizer, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        if isinstance(warmup_min_lr, (list, tuple)):
            warmup_min_lr = warmup_min_lr[0]
        if isinstance(warmup_max_lr, (list, tuple)):
            warmup_max_lr = warmup_max_lr[0]
        self.min_lrs = [warmup_min_lr]
        self.max_lrs = [warmup_max_lr]
        self.delta_lrs = [warmup_max_lr - warmup_min_lr]
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _get_gamma(self):
        if self.last_batch_iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(
                self.last_batch_iteration + 1)
        return 1.0

    def get_lr(self):
        if self.last_batch_iteration < 0:
            logger.warning("Attempting to get learning rate from scheduler "
                           "before it has started")
            return [0.0]
        gamma = self._get_gamma()
        return [min_lr + (delta * gamma)
                for min_lr, delta in zip(self.min_lrs, self.delta_lrs)]


class WarmupDecayLR(WarmupLR):
    """WarmupLR followed by linear decay to 0 at total_num_steps
    (reference :761)."""

    def __init__(self, optimizer, total_num_steps, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000,
                 last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr,
                         warmup_num_steps, last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            logger.warning(
                "total_num_steps {} is less than warmup_num_steps {}".format(
                    total_num_steps, warmup_num_steps))

    def _get_gamma(self):
        if self.last_batch_iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(
                self.last_batch_iteration + 1)
        return max(
            0.0,
            float(self.total_num_steps - self.last_batch_iteration) /
            float(max(1.0, self.total_num_steps - self.warmup_num_steps)))


SCHEDULE_CLASSES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_lr_schedule_class(name):
    if name not in SCHEDULE_CLASSES:
        raise ValueError("{} is not a valid LR schedule, valid: {}".format(
            name, VALID_LR_SCHEDULES))
    return SCHEDULE_CLASSES[name]
