"""Progressive Layer Dropping.

Reference parity: deepspeed/runtime/progressive_layer_drop.py. Keep-prob
theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar, updated per global
step and passed into the model forward as a kwarg.
"""
import numpy as np

from ..utils.logging import log_dist


class ProgressiveLayerDrop(object):
    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist("Enabled progressive layer dropping (theta = {})".format(
            self.theta), ranks=[0])

    def get_state(self):
        kwargs = {"progressive_layer_drop": True, "pld_theta": self.get_theta()}
        return kwargs

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        def _prob(x, gamma, p):
            return (1.0 - p) * np.exp(-gamma * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
