"""Elastic self-healing training: preemption-native rescale.

Detection (:mod:`.monitor`), execution with resharded restore and
bounded retry (:mod:`.rescale`), and the rescale-event schema every
surface shares (:mod:`.events`)."""
from .events import (KIND_RESCALE_EVENT, RESCALE_EVENT_KEYS,
                     RESCALE_EVENT_NAMES, RESCALE_EVENTS_JSONL,
                     append_rescale_event, make_rescale_event,
                     read_rescale_events, validate_rescale_event)
from .monitor import ElasticDecision, ElasticityMonitor, EvictionPolicy
from .rescale import (ElasticRunner, EnrollmentRefused, RescaleError,
                      enroll_check)

__all__ = [
    "KIND_RESCALE_EVENT", "RESCALE_EVENT_KEYS", "RESCALE_EVENT_NAMES",
    "RESCALE_EVENTS_JSONL", "append_rescale_event", "make_rescale_event",
    "read_rescale_events", "validate_rescale_event",
    "ElasticDecision", "ElasticityMonitor", "EvictionPolicy",
    "ElasticRunner", "EnrollmentRefused", "RescaleError", "enroll_check",
]
