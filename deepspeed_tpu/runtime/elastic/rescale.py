"""Preemption-native rescale: the execution half of elastic training.

:class:`ElasticRunner` wraps an engine lifecycle so "a host died" is a
recorded topology change instead of a crash:

1. **detection** is delegated to :class:`~.monitor.ElasticityMonitor`
   (SIGTERM/notice file, straggler eviction, world change at re-init);
   the guarded ``train_step``/``checkpoint`` paths additionally catch
   a hard preemption (``SimulatedKill`` in the fault harness, or any
   configured preemption exception) mid-step;
2. **resharded restore**: teardown, ``build_mesh`` for the new world,
   a fresh engine whose ``ZeroShardingPlan`` matches the new topology,
   and ``load_checkpoint`` from the last crash-safe manifest (PR 1's
   fallback scan picks the newest COMPLETE tag, so a kill mid-save or
   mid-load falls back instead of wedging). World-size-dependent
   optimizer state (1-bit Adam error feedback) is canonicalised by the
   optimizer's ``reshard_state`` hook in the engine load path;
3. **safe resume**: the target world is validated against the
   elasticity config BEFORE any teardown
   (``ElasticityIncompatibleWorldSize`` refuses the rescale with the
   old engine untouched), and an optional fingerprint gate re-derives
   the PR 15 program fingerprint and refuses to enroll a divergent
   host by name (:class:`EnrollmentRefused`);
4. **bounded retry**: each rescale rides ``utils/retry.py`` with every
   attempt recorded as a rescale event (events.py) — in the runner's
   shared history (crash-bundle ``topology`` section), in
   ``rescale_events.jsonl`` (fleet doctor), and in the log ring.
"""
import copy
import os
import socket

from ...elasticity import (ElasticityIncompatibleWorldSize,
                           compute_elastic_config, elasticity_enabled)
from ...utils.fault_injection import SimulatedKill
from ...utils.logging import logger
from ...utils.retry import RetryPolicy, retry_call
from ...version import __version__ as ds_version
from .events import append_rescale_event, make_rescale_event
from .monitor import ElasticityMonitor, EvictionPolicy


class RescaleError(RuntimeError):
    """A rescale attempt failed in a way worth retrying (restore found
    no checkpoint, engine rebuild failed transiently)."""


class EnrollmentRefused(RuntimeError):
    """A host's program fingerprint diverges from the fleet's — it must
    not enroll (the mesh would hang at its first divergent collective).
    ``host`` names the refused host."""

    def __init__(self, host, message):
        super().__init__(message)
        self.host = host


def enroll_check(run_dir, host, fingerprint):
    """Fingerprint gate at enrollment: compare ``host``'s freshly
    derived ``fingerprint`` against every fingerprint published in the
    run directory's host manifests (PR 15 / fleet contract). Raises
    :class:`EnrollmentRefused` naming the host when it diverges from
    the fleet majority; returns the comparison payload otherwise."""
    from ...telemetry.fleet.aggregate import (MANIFEST_FINGERPRINT_KEY,
                                              MANIFEST_NAME,
                                              compare_fingerprints,
                                              load_host)
    fingerprints = {}
    if run_dir and os.path.isdir(run_dir):
        for name in sorted(os.listdir(run_dir)):
            path = os.path.join(run_dir, name)
            if not os.path.isfile(os.path.join(path, MANIFEST_NAME)):
                continue
            view = load_host(path, name=name)
            if view.manifest is not None:
                fingerprints[name] = view.manifest.get(
                    MANIFEST_FINGERPRINT_KEY)
    fingerprints[host] = fingerprint
    comparison = compare_fingerprints(fingerprints)
    if comparison["mismatch"] and host in comparison["divergent_hosts"]:
        from ...analysis.concurrency.divergence import divergence_findings
        try:
            detail = "; ".join(
                f.message for f in divergence_findings(comparison)
                if host in f.message)
        except Exception:  # noqa: BLE001 - families may be raw counts
            detail = ""
        detail = detail or "digest {} != reference host {}".format(
            comparison["digests"].get(host), comparison["reference"])
        raise EnrollmentRefused(
            host,
            "host {!r} refused enrollment: program fingerprint "
            "diverges from the fleet ({})".format(host, detail))
    return comparison


class ElasticRunner:
    """Owns one engine at a time and rebuilds it across topologies.

    ``model_factory`` is a zero-arg callable returning a FRESH model
    (params are restored from the checkpoint, so the factory's init
    values never survive a rescale). ``config`` is the ds_config dict;
    the runner adapts its batch parameters per world so the GLOBAL
    batch is preserved (elastic configs re-solve grad-accum via the
    HCN candidates, non-elastic ones re-derive it from
    train_batch/micro)."""

    def __init__(self, model_factory, config, checkpoint_dir,
                 candidate_worlds=None, monitor=None, retry_policy=None,
                 fingerprint_gate=None, preemption_exceptions=None,
                 mesh_kwargs=None, world=None, events_dir=None,
                 sleep=None):
        import jax

        self.model_factory = model_factory
        self.base_config = copy.deepcopy(config)
        self.checkpoint_dir = str(checkpoint_dir)
        self.mesh_kwargs = dict(mesh_kwargs or {})
        self.events = []
        self.rescales = 0
        self._events_dir_override = events_dir
        self._sleep = sleep

        elas = dict(self.base_config.get("elasticity") or {})
        if candidate_worlds is None and elasticity_enabled(
                self.base_config):
            _batch, valid = compute_elastic_config(
                self.base_config, ds_version)[:2]
            candidate_worlds = valid
        self.candidate_worlds = sorted(int(w) for w in candidate_worlds) \
            if candidate_worlds else None
        self.retry_policy = retry_policy or RetryPolicy(
            retries=int(elas.get("rescale_retries", 2)),
            backoff_seconds=float(elas.get("rescale_backoff_seconds",
                                           0.5)))
        self.fingerprint_gate = bool(elas.get("fingerprint_gate", False)
                                     if fingerprint_gate is None
                                     else fingerprint_gate)
        self.preemption_exceptions = tuple(
            preemption_exceptions
            if preemption_exceptions is not None else (SimulatedKill,))
        self.monitor = monitor or ElasticityMonitor(
            notice_file=elas.get("preemption_notice_file"),
            eviction=EvictionPolicy(
                severity=float(elas.get("eviction_severity", 2.0)),
                windows=int(elas.get("eviction_windows", 3))))
        if world is None:
            world = len(jax.devices())
        self.engine = self._build(int(world))

    # ------------------------------------------------------- topology
    @property
    def world(self):
        return int(dict(self.engine.mesh.shape).get("data", 1))

    def _mesh_shape(self, engine=None):
        engine = engine or self.engine
        if engine is None:
            return None
        return {k: int(v) for k, v in dict(engine.mesh.shape).items()}

    def _config_for_world(self, world):
        """Per-world ds_config: global batch preserved, grad-accum
        re-derived. Elastic configs re-solve through
        ``_configure_elasticity``; non-elastic ones drop a pinned
        grad-accum so train_batch/micro re-derive it for the new
        world (an indivisible combination is caught by preflight)."""
        cfg = copy.deepcopy(self.base_config)
        if not elasticity_enabled(cfg) and \
                cfg.get("train_batch_size") is not None and \
                cfg.get("train_micro_batch_size_per_gpu") is not None:
            cfg.pop("gradient_accumulation_steps", None)
        return cfg

    def _build(self, world):
        from ...parallel.topology import build_mesh
        from ..engine import DeepSpeedEngine
        mesh = build_mesh(data=world, **self.mesh_kwargs)
        engine = DeepSpeedEngine(model=self.model_factory(),
                                 config_params=self._config_for_world(
                                     world),
                                 mesh=mesh)
        # share ONE history across every engine generation so the
        # flight recorder's topology section always carries the full
        # rescale trail, whichever engine is live at crash time
        engine._rescale_history = self.events
        tel = getattr(engine, "telemetry", None)
        if tel is not None:
            # the live ds_fleet seam: every ingested fleet report also
            # feeds the eviction policy (telemetry/collector.py)
            tel.set_elastic_observer(self.observe_fleet)
        return engine

    # --------------------------------------------------------- events
    def _events_dir(self):
        if self._events_dir_override:
            return self._events_dir_override
        tel = getattr(self.engine, "telemetry", None) \
            if self.engine is not None else None
        return getattr(tel, "output_dir", None)

    def _record(self, event, reason, **kw):
        evt = make_rescale_event(event, reason, **kw)
        self.events.append(evt)
        logger.warning("elastic: %s (%s)", event, reason)
        out_dir = self._events_dir()
        if out_dir:
            try:
                append_rescale_event(out_dir, evt)
            except OSError as err:
                logger.warning("elastic: could not persist rescale "
                               "event (%s)", err)
        return evt

    # ------------------------------------------------------ guarded io
    def checkpoint(self, tag=None, client_state=None):
        """Guarded save: a preemption mid-save becomes a rescale-down
        restored from the last COMPLETE manifest (the torn tag is
        skipped by the PR 1 fallback scan — no data beyond the last
        durable checkpoint is lost, which is all a hard kill can
        promise)."""
        try:
            return self.engine.save_checkpoint(
                self.checkpoint_dir, tag=tag,
                client_state=client_state or {})
        except self.preemption_exceptions as kill:
            self._on_preemption("preempted during checkpoint: "
                                "{}".format(kill))
            return None

    def train_step(self, fn):
        """Guarded step: ``fn(engine)`` runs the caller's forward/
        backward/step; a preemption mid-step triggers the same
        rescale-down path as a mid-save kill. Returns ``(result,
        rescaled)``."""
        try:
            return fn(self.engine), False
        except self.preemption_exceptions as kill:
            self._on_preemption("preempted during step: "
                                "{}".format(kill))
            return None, True

    def _on_preemption(self, reason):
        self.monitor.notice_preemption(reason)
        self.monitor.poll()       # consume: this handler IS the react
        self._record("preemption_notice", reason,
                     old_world=self.world,
                     old_mesh=self._mesh_shape())
        target = self._downscale_target()
        self.rescale(target, reason, save_first=False)

    def _downscale_target(self, current=None):
        import jax
        current = self.world if current is None else current
        avail = len(jax.devices())
        candidates = self.candidate_worlds or \
            [w for w in (current // 2, current // 4, 1) if w >= 1]
        smaller = [w for w in candidates if w < current and w <= avail]
        if not smaller:
            raise RescaleError(
                "no candidate world below {} to rescale down to "
                "(candidates: {})".format(current, candidates))
        return max(smaller)

    # ------------------------------------------------------ monitoring
    def observe_fleet(self, report):
        """Feed a fleet observation (merged report or snapshot) to the
        eviction policy; see ``maybe_rescale`` for acting on it."""
        return self.monitor.observe_fleet(report)

    def maybe_rescale(self):
        """Training-loop seam: poll the monitor and execute any pending
        decision. Graceful paths (notice file, eviction) checkpoint
        FIRST — rescale without data loss; returns the decision acted
        on, or None."""
        decision = self.monitor.poll()
        if decision is None:
            return None
        if decision.action == "evict":
            self._record("eviction", decision.reason,
                         old_world=self.world,
                         old_mesh=self._mesh_shape(),
                         detail="evicting host(s): {}".format(
                             ", ".join(decision.hosts)))
            target = decision.target_world or self._downscale_target()
            self.rescale(target, decision.reason, save_first=True)
            return decision
        target = decision.target_world
        if target is None:
            self._record("preemption_notice", decision.reason,
                         old_world=self.world,
                         old_mesh=self._mesh_shape())
            target = self._downscale_target()
            self.rescale(target, decision.reason, save_first=True)
        elif target != self.world:
            self.rescale(target, decision.reason, save_first=True)
        return decision

    # --------------------------------------------------------- rescale
    def rescale(self, new_world, reason, save_first=True):
        """Change topology to ``new_world`` with bounded retry. The
        target is validated BEFORE any teardown: an incompatible world
        is recorded as ``rescale_refused`` and raised with the current
        engine untouched."""
        new_world = int(new_world)
        old_world = self.world
        old_mesh = self._mesh_shape()
        try:
            self._preflight(new_world)
        except ElasticityIncompatibleWorldSize as err:
            self._record("rescale_refused", reason,
                         old_world=old_world, new_world=new_world,
                         old_mesh=old_mesh, outcome="refused",
                         detail=str(err))
            raise
        attempts = {"n": 0}

        def _attempt():
            attempts["n"] += 1
            self._record("rescale_attempt", reason,
                         attempt=attempts["n"], old_world=old_world,
                         new_world=new_world, old_mesh=old_mesh)
            return self._attempt_rescale(new_world, save_first
                                         and attempts["n"] == 1)

        def _on_retry(attempt, exc, delay):
            self._record("rescale_attempt", reason, attempt=attempt + 1,
                         old_world=old_world, new_world=new_world,
                         old_mesh=old_mesh, outcome="retrying",
                         detail="{}; retry in {:.2f}s".format(exc,
                                                              delay))

        kw = {}
        if self._sleep is not None:
            kw["sleep"] = self._sleep
        engine = retry_call(_attempt, policy=self.retry_policy,
                            retry_on=(RescaleError, OSError),
                            on_retry=_on_retry, **kw)
        self.engine = engine
        self.rescales += 1
        self._record("rescale", reason, attempt=attempts["n"],
                     old_world=old_world, new_world=new_world,
                     old_mesh=old_mesh,
                     new_mesh=self._mesh_shape(engine),
                     outcome="ok",
                     detail="resumed at step {}".format(
                         engine.global_steps))
        return engine

    def _preflight(self, new_world):
        import jax
        if new_world < 1:
            raise ElasticityIncompatibleWorldSize(
                "world size {} is not positive".format(new_world))
        if new_world > len(jax.devices()):
            raise ElasticityIncompatibleWorldSize(
                "world size {} exceeds the {} visible device(s)".format(
                    new_world, len(jax.devices())))
        if self.candidate_worlds and new_world not in \
                self.candidate_worlds:
            raise ElasticityIncompatibleWorldSize(
                "world size {} is not an elastic candidate "
                "(valid: {})".format(new_world, self.candidate_worlds))
        config = getattr(self.engine, "_config", None)
        if config is not None:
            config.validate_elastic_world_size(new_world)

    def _attempt_rescale(self, new_world, save_first):
        if self.engine is not None:
            if save_first:
                self.engine.save_checkpoint(self.checkpoint_dir)
            close = getattr(self.engine, "close", None)
            if callable(close):
                close()       # releases the telemetry dir claim so the
            self.engine = None  # new engine reuses THIS host's dir
        engine = self._build(new_world)
        load_path, _client = engine.load_checkpoint(self.checkpoint_dir)
        if load_path is None:
            raise RescaleError(
                "restore found no loadable checkpoint under "
                "{!r}".format(self.checkpoint_dir))
        if self.fingerprint_gate:
            self._enroll(engine)
        return engine

    def _enroll(self, engine):
        from ...analysis.concurrency.divergence import (
            fingerprint_engine, publish_fingerprint)
        fingerprint = fingerprint_engine(engine)
        publish_fingerprint(engine, fingerprint)
        tel = getattr(engine, "telemetry", None)
        host_dir = getattr(tel, "output_dir", None) if tel is not None \
            else None
        run_dir = os.path.dirname(host_dir) if host_dir else None
        host = os.path.basename(host_dir) if host_dir \
            else socket.gethostname()
        try:
            return enroll_check(run_dir, host, fingerprint)
        except EnrollmentRefused as err:
            self._record("enroll_refused", str(err),
                         new_world=self._mesh_shape(engine).get("data"),
                         new_mesh=self._mesh_shape(engine),
                         outcome="refused", detail=err.host)
            raise

    def close(self):
        if self.engine is not None:
            close = getattr(self.engine, "close", None)
            if callable(close):
                close()
            self.engine = None
