"""Rescale-event schema + JSONL plumbing for elastic training.

Every topology change an :class:`~.rescale.ElasticRunner` performs (or
refuses) is one validated event: preemption notices, proactive
evictions, each bounded-retry attempt, the completed rescale with the
old/new topology, and enrollment refusals. Events land in THREE
surfaces so a post-mortem can always reconstruct the topology history:

* the runner's in-memory ``events`` list (shared with every engine it
  builds, so the flight recorder's ``topology`` bundle section carries
  the full rescale history at crash time — telemetry/recorder.py);
* ``rescale_events.jsonl`` in the host's telemetry directory, one JSON
  object per line, append-only — the fleet doctor
  (telemetry/fleet/aggregate.py, stdlib-only, which duplicates the
  names below under its import contract) merges them into the fleet
  report and ``bin/ds_fleet.py`` prints them;
* the engine log at warning level, so the flight recorder's log-event
  ring sees them too.
"""
import json
import os
import time

# file name + schema duplicated in telemetry/fleet/aggregate.py
# (stdlib-import contract); pinned equal by tests/unit/test_elastic_rescale.py
RESCALE_EVENTS_JSONL = "rescale_events.jsonl"
KIND_RESCALE_EVENT = "rescale_event"

# every rescale event carries exactly these keys
RESCALE_EVENT_KEYS = (
    "kind", "event", "wall", "reason", "attempt",
    "old_world", "new_world", "old_mesh", "new_mesh",
    "outcome", "detail",
)

# the event vocabulary: what happened at this point of the lifecycle
RESCALE_EVENT_NAMES = (
    "preemption_notice",   # SIGTERM / notice file / injected kill seen
    "eviction",            # straggler/ICI policy evicted a host
    "rescale_attempt",     # one bounded-retry attempt started/failed
    "rescale",             # a completed topology change
    "rescale_refused",     # world size rejected before any teardown
    "enroll_refused",      # divergent fingerprint refused at enrollment
)


def make_rescale_event(event, reason, old_world=None, new_world=None,
                       old_mesh=None, new_mesh=None, attempt=None,
                       outcome=None, detail=None, wall=None):
    """Build one schema-complete rescale event dict."""
    return {
        "kind": KIND_RESCALE_EVENT,
        "event": event,
        "wall": float(time.time() if wall is None else wall),
        "reason": str(reason),
        "attempt": attempt,
        "old_world": old_world,
        "new_world": new_world,
        "old_mesh": dict(old_mesh) if old_mesh else None,
        "new_mesh": dict(new_mesh) if new_mesh else None,
        "outcome": outcome,
        "detail": detail,
    }


def validate_rescale_event(event):
    """Schema check for one rescale event. Returns a list of problem
    strings; empty list = valid."""
    problems = []
    if not isinstance(event, dict):
        return ["event is not a dict: {!r}".format(type(event).__name__)]
    if event.get("kind") != KIND_RESCALE_EVENT:
        return ["unknown event kind {!r}".format(event.get("kind"))]
    for key in RESCALE_EVENT_KEYS:
        if key not in event:
            problems.append("missing key {!r}".format(key))
    if problems:
        return problems
    if event["event"] not in RESCALE_EVENT_NAMES:
        problems.append("event {!r} not one of {}".format(
            event["event"], RESCALE_EVENT_NAMES))
    if isinstance(event["wall"], bool) or \
            not isinstance(event["wall"], (int, float)):
        problems.append("wall is not a number")
    if not isinstance(event["reason"], str) or not event["reason"]:
        problems.append("reason is not a non-empty string")
    for key in ("old_world", "new_world", "attempt"):
        val = event[key]
        if val is not None and (isinstance(val, bool)
                                or not isinstance(val, int)):
            problems.append("{} is neither null nor an int".format(key))
    for key in ("old_mesh", "new_mesh"):
        val = event[key]
        if val is not None and not isinstance(val, dict):
            problems.append("{} is neither null nor a dict".format(key))
    for key in ("outcome", "detail"):
        val = event[key]
        if val is not None and not isinstance(val, str):
            problems.append("{} is neither null nor a string".format(key))
    return problems


def append_rescale_event(output_dir, event):
    """Append one validated event to ``rescale_events.jsonl`` under
    ``output_dir`` (a host telemetry directory). Returns the path.
    Line-at-a-time append + flush: a crash mid-run leaves whole JSON
    lines behind, which the fleet merger reads tolerantly."""
    problems = validate_rescale_event(event)
    if problems:
        raise ValueError("invalid rescale event: {}".format(problems))
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, RESCALE_EVENTS_JSONL)
    with open(path, "a") as fh:
        fh.write(json.dumps(event, sort_keys=True) + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    return path


def read_rescale_events(output_dir):
    """Tolerant read of a host directory's rescale events (torn last
    line skipped, like the fleet merger's JSONL reader)."""
    path = os.path.join(output_dir, RESCALE_EVENTS_JSONL)
    if not os.path.isfile(path):
        return []
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and \
                    rec.get("kind") == KIND_RESCALE_EVENT:
                out.append(rec)
    return out
