"""Mesh-change detection: when should an elastic run change topology?

Three signal classes feed one decision type (:class:`ElasticDecision`):

* **preemption** — a SIGTERM (chained behind the flight recorder's own
  handler), a cloud preemption-notice file appearing on disk
  (``elasticity.preemption_notice_file``), or an injected
  ``SimulatedKill`` surfacing through the guarded step/checkpoint
  paths (utils/fault_injection.py);
* **proactive eviction** — the PR 14 straggler/ICI attribution
  (telemetry/fleet/) flagging the same host at or above a configured
  severity for ``k`` consecutive observation windows
  (``elasticity.eviction_severity`` / ``elasticity.eviction_windows``);
* **device-count change** — the world the scheduler hands us at
  (re)init differs from the engine's mesh.

The monitor only *decides*; :class:`~.rescale.ElasticRunner` executes
(checkpoint, teardown, rebuild, resharded restore, fingerprint gate).
"""
import os
import signal
import threading
from typing import NamedTuple, Optional, Tuple

from ...utils.logging import logger


class ElasticDecision(NamedTuple):
    """One detection outcome: what to do, why, and to which topology.
    ``target_world`` of None means "next smaller candidate" — the
    runner resolves it against the elasticity config's valid counts."""
    action: str                       # "rescale" | "evict"
    reason: str
    target_world: Optional[int] = None
    hosts: Tuple[str, ...] = ()       # hosts being evicted, if any


class EvictionPolicy:
    """Turn straggler/ICI flags into an eviction decision once the same
    host stays flagged at/above ``severity`` (worst host/median ratio)
    for ``windows`` CONSECUTIVE observations. A window where the host
    is clean resets its streak — one noisy window never evicts."""

    def __init__(self, severity=0.0, windows=3):
        if windows < 1:
            raise ValueError("eviction windows must be >= 1, got "
                             "{}".format(windows))
        self.severity = float(severity)
        self.windows = int(windows)
        self.streaks = {}
        self.evicted = []

    def observe(self, report):
        """Feed one fleet observation (a merged fleet report, a
        ``telemetry_snapshot()["fleet"]`` sub-dict, or a bare flags
        list); returns an "evict" :class:`ElasticDecision` when a host
        crosses the streak threshold, else None."""
        if isinstance(report, (list, tuple)):
            flags = list(report)
        else:
            flags = report.get("straggler_flags") \
                or report.get("straggler", {}).get("flags", [])
        worst = {}
        for flag in flags:
            host = flag.get("host")
            if host is None:
                continue
            ratio = flag.get("worst_ratio")
            ratio = float("inf") if ratio is None else float(ratio)
            worst[host] = max(worst.get(host, 0.0), ratio)
        for host in list(self.streaks):
            if host not in worst:
                del self.streaks[host]      # clean window resets
        offenders = []
        for host, ratio in worst.items():
            if ratio < self.severity:
                self.streaks.pop(host, None)
                continue
            self.streaks[host] = self.streaks.get(host, 0) + 1
            if self.streaks[host] >= self.windows and \
                    host not in self.evicted:
                offenders.append((host, ratio, self.streaks[host]))
        if not offenders:
            return None
        offenders.sort(key=lambda t: -t[1])
        hosts = tuple(h for h, _, _ in offenders)
        self.evicted.extend(hosts)
        detail = ", ".join(
            "{} ({:.2f}x for {} window(s))".format(h, r, s)
            for h, r, s in offenders)
        return ElasticDecision(
            action="evict",
            reason="straggler flagged {} consecutive window(s): {}".format(
                self.windows, detail),
            hosts=hosts)


class ElasticityMonitor:
    """Aggregates the preemption + eviction + world-change signals.

    Thread/signal-safe by construction: signal handlers and watcher
    threads only SET flags; ``poll()`` (called from the training loop)
    reads and consumes them — no locks are taken in the handler, the
    exact discipline the concurrency sanitizer enforces on the flight
    recorder's SIGTERM path."""

    def __init__(self, notice_file=None, eviction=None):
        self.notice_file = notice_file
        self.eviction = eviction or EvictionPolicy()
        self._preempted = threading.Event()
        self._preempt_reason = "preemption"
        self._prev_sigterm = None
        self._pending = []

    # ------------------------------------------------------- preemption
    def notice_preemption(self, reason="preemption"):
        """Flag a preemption (SIGTERM handler, notice file, or the
        guarded step path catching an injected kill)."""
        self._preempt_reason = str(reason)
        self._preempted.set()

    def preemption_requested(self):
        return self._preempted.is_set()

    def install_sigterm(self):
        """Chain a preemption-notice handler behind whatever SIGTERM
        handler is installed (the flight recorder dumps first — its
        handler chains to us, ours chains to whatever preceded it).
        Main-thread only; a no-op off it."""
        if threading.current_thread() is not threading.main_thread():
            return False
        prev = signal.getsignal(signal.SIGTERM)

        def _handler(signum, frame):
            self.notice_preemption("sigterm")
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        self._prev_sigterm = prev
        signal.signal(signal.SIGTERM, _handler)
        return True

    # ------------------------------------------------------------ polls
    def check_world(self, current_world, desired_world):
        """Device-count change at (re)init: the scheduler says
        ``desired_world`` but the engine's mesh has ``current_world``."""
        if desired_world is None or desired_world == current_world:
            return None
        return ElasticDecision(
            action="rescale",
            reason="device count changed: {} -> {}".format(
                current_world, desired_world),
            target_world=int(desired_world))

    def observe_fleet(self, report):
        """Feed one fleet observation to the eviction policy; a
        resulting decision is queued for the next ``poll()``."""
        decision = self.eviction.observe(report)
        if decision is not None:
            logger.warning("elastic monitor: %s", decision.reason)
            self._pending.append(decision)
        return decision

    def poll(self):
        """The training-loop seam: returns the next pending decision
        (preemption first, then queued evictions), or None."""
        if self.notice_file and os.path.exists(self.notice_file):
            self.notice_preemption(
                "preemption notice file {}".format(self.notice_file))
        if self._preempted.is_set():
            self._preempted.clear()
            return ElasticDecision(action="rescale",
                                   reason=self._preempt_reason)
        if self._pending:
            return self._pending.pop(0)
        return None
