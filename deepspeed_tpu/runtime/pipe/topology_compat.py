"""Re-export topology types under the reference's import path
(deepspeed.runtime.pipe.topology)."""
from ...parallel.topology import (ProcessTopology, PipeDataParallelTopology,
                                  PipeModelDataParallelTopology, MeshGrid,
                                  _prime_factors)

PipelineParallelGrid = MeshGrid
